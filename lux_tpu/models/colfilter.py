"""Collaborative Filtering: batch-gradient matrix factorization on a
weighted bipartite rating graph, on the pull engine.

Math parity with the reference app (col_filter/):
  * Vertex state = K-dim latent vector, K = 20, initialized to sqrt(1/K)
    (col_filter/app.h:28-43, colfilter_gpu.cu:260-264);
  * per edge (src -> dst, rating w):  err = w - <v_src, v_dst>
    (cf_kernel dot product, colfilter_gpu.cu:85-87);
  * per destination: accErr = sum_in-edges err * v_src  (:88-89);
  * update: v_dst += GAMMA * (accErr - LAMBDA * v_dst)  (:96-101), with
    LAMBDA = 0.001, GAMMA = 3.5e-7 (col_filter/app.h:26-27);
  * fixed iteration count (colfilter.cc driver), weighted pull engine
    (core/pull_model.inl EDGE_WEIGHT path).

Every vertex in range is updated each iteration, including those with no
ratings (pure weight decay) — same as the kernel's unconditional tail write.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import pull
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import PullShards, build_pull_shards
from lux_tpu.parallel.mesh import Mesh
from lux_tpu.program import SpecBacked, library

K = 20
LAMBDA = 1e-3
GAMMA = 3.5e-7


def err_dot(src: jnp.ndarray, dst: jnp.ndarray, mode: str = "vpu"):
    """The per-edge K-dim rating prediction <v_src, v_dst> (the CF
    error-dot, cf_kernel's dot product loop, colfilter_gpu.cu:85-87).

    "vpu": elementwise multiply + lane-axis ``jnp.sum`` — the shipped
    form.  "mxu" (ISSUE 7): the K-contraction as a TRUE matmul tile,
    ``(rows, K) @ (K, 1)`` via dot_general with f32 accumulation — on
    TPU this rides the MXU while the VPU form serializes K lane adds.
    Both are exact per-term f32; only the f32 ACCUMULATION order
    differs (last-ulp association, like mxsum vs scan), so the default
    stays "vpu" until the micro race (tools/tpu_micro_race.py cfdot)
    banks a measured winner under ``tpu:cf_err_dot``."""
    import jax

    prod = src * dst
    if mode == "mxu":
        ones = jnp.ones((prod.shape[-1], 1), jnp.float32)
        out = jax.lax.dot_general(
            prod, ones, (((prod.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return out[..., 0]
    if mode != "vpu":
        raise ValueError(f"err_dot mode must be 'vpu' or 'mxu', got {mode!r}")
    return jnp.sum(prod, axis=-1)


def _resolve_err_dot(mode: str | None) -> str:
    """None follows the chip-measured ``tpu:cf_err_dot`` overlay winner
    (engine/methods.cf_err_dot_mode); a concrete mode passes through."""
    if mode is not None:
        return mode
    from lux_tpu.engine import methods

    return methods.cf_err_dot_mode()


@dataclasses.dataclass(frozen=True)
class CFProgram(SpecBacked):
    """CF as a named parameter bundle over the declarative spec
    (lux_tpu.program.library.COLFILTER — ISSUE 13): per edge
    err = rating - <v_src, v_dst> (the spec's ``dot_lanes`` is the
    ``err_dot`` helper above, so the banked ``tpu:cf_err_dot`` winner
    flows through unchanged), value pushed to dst = err * v_src, update
    v += GAMMA * (accErr - LAMBDA * v).  Gathers arrive in the storage
    dtype; compute + reduce stay float32.  The error term reads the
    destination's current vector per edge (``needs_dst_state`` via the
    spec), so exchanges that pre-combine remotely (reduce_scatter)
    can't run CF."""

    k: int = K
    lam: float = LAMBDA
    gamma: float = GAMMA
    #: state storage dtype.  "bfloat16" halves the (V, K) latent-state HBM
    #: footprint and per-iteration exchange volume — the wide-state memory
    #: case SURVEY.md §7.3 flags (10.7 GB f32 at RMAT27).  Per-edge error
    #: terms and the segmented accumulation stay float32.
    dtype: str = "float32"
    #: error-dot lowering ("vpu" | "mxu", see ``err_dot``).  A STATIC
    #: program attribute: it participates in jit compile caches like
    #: any other program field, and the default keeps every existing
    #: caller bitwise-unchanged.
    err_dot: str = "vpu"

    @property
    def spec(self):
        return library.COLFILTER

    def _env(self):
        return {"k": self.k, "lam": self.lam, "gamma": self.gamma,
                "dtype": self.dtype, "err_dot": self.err_dot}


def colfilter(
    g: HostGraph | PullShards,
    num_iters: int = 10,
    num_parts: int = 1,
    mesh: Mesh | None = None,
    k: int = K,
    lam: float = LAMBDA,
    gamma: float = GAMMA,
    method: str = "auto",
    dtype: str = "float32",
    route=None,
    err_dot: str | None = None,
) -> np.ndarray:
    """Run CF; returns the (nv, k) latent-vector matrix.  ``route``: a
    plan from ops.expand.plan_cf_route_shards (routed src+dst load).
    ``err_dot``: error-dot lowering; the None default follows the
    measured ``tpu:cf_err_dot`` overlay winner ("vpu" until a window
    banks one), so an unattended measurement changes the driver with
    no code edit — same contract as the method winners."""
    shards = g if isinstance(g, PullShards) else build_pull_shards(g, num_parts)
    assert shards.spec.weighted, "CF requires a weighted (rating) graph"
    prog = CFProgram(k=k, lam=lam, gamma=gamma, dtype=dtype,
                     err_dot=_resolve_err_dot(err_dot))
    state0 = pull.init_state(prog, shards.arrays)
    if mesh is None:
        final = pull.run_pull_fixed(
            prog, shards.spec, shards.arrays, state0, num_iters,
            method=method, route=route,
        )
    else:
        from lux_tpu.parallel import dist

        final = dist.run_pull_fixed_dist(
            prog, shards.spec, shards.arrays, state0, num_iters, mesh,
            method=method, route=route,
        )
    return shards.scatter_to_global(np.asarray(final))


def make_pallas_runner(g: HostGraph, k: int = K, lam: float = LAMBDA,
                       gamma: float = GAMMA, interpret: bool = False,
                       v_blk: int | None = None, t_chunk: int | None = None,
                       dtype: str = "float32",
                       err_dot_mode: str | None = None):
    """Single-chip CF on the fused 2-D Pallas kernel: the err·srcVec
    accumulation becomes a (V_BLK, T) x (T, K) MXU matmul per chunk,
    and with ``err_dot_mode="mxu"`` the error-dot itself lowers as a
    (C*T, K) @ (K, 1) MXU matmul tile too (None = the measured
    ``tpu:cf_err_dot`` winner), so BOTH K-contractions of the CF
    recurrence ride the systolic unit.  Returns
    (run(state, num_iters), state0)."""
    import functools

    import jax

    from lux_tpu.ops import pallas_spmv as ps

    assert g.weights is not None, "CF requires a weighted graph"
    kw = {}
    if v_blk:
        kw["v_blk"] = v_blk
    if t_chunk:
        kw["t_chunk"] = t_chunk
    ed_mode = _resolve_err_dot(err_dot_mode)
    bc = ps.build_blockcsr(g, **kw)
    nvp = bc.num_vblocks * bc.v_blk
    state0 = np.zeros((nvp, k), np.float32)
    state0[: g.nv] = np.sqrt(1.0 / k)
    e_src = jnp.asarray(bc.e_src_pos)
    e_dst = jnp.asarray(bc.e_dst_rel)
    w = jnp.asarray(bc.e_weight)
    cb = jnp.asarray(bc.chunk_block)
    cf = jnp.asarray(bc.chunk_first)
    # per-edge destination in the padded global range (clip padding slots)
    dst_global = jnp.clip(
        cb[:, None] * bc.v_blk + e_dst, 0, nvp - 1
    )

    @functools.partial(jax.jit, static_argnames="num_iters")
    def run(state, num_iters):
        def body(_, s):
            # state stored in `dtype` (bf16 halves the (V,K) HBM footprint,
            # SURVEY.md §7.3's memory case); error math + reduce stay f32
            src_vec = s[e_src].astype(jnp.float32)  # (C, T, K)
            dst_vec = s[dst_global].astype(jnp.float32)
            err = w - err_dot(src_vec, dst_vec, ed_mode)  # (C, T)
            vals = err[..., None] * src_vec
            acc = ps.spmv_blockcsr_2d(
                vals, e_dst, cb, cf, v_blk=bc.v_blk,
                num_vblocks=bc.num_vblocks, interpret=interpret,
            )
            new = s.astype(jnp.float32) + jnp.float32(gamma) * (
                acc - jnp.float32(lam) * s.astype(jnp.float32)
            )
            return new.astype(dtype)

        return jax.lax.fori_loop(0, num_iters, body, state)

    return run, jnp.asarray(state0).astype(dtype)


def colfilter_pallas(g: HostGraph, num_iters: int = 10, interpret: bool = False,
                     **kw) -> np.ndarray:
    run, s0 = make_pallas_runner(g, interpret=interpret, **kw)
    return np.asarray(run(s0, num_iters))[: g.nv]


def colfilter_reference(
    g: HostGraph, num_iters: int, k: int = K, lam: float = LAMBDA,
    gamma: float = GAMMA,
) -> np.ndarray:
    """NumPy oracle of the identical recurrence."""
    v = np.full((g.nv, k), np.sqrt(1.0 / k), np.float32)
    dst = g.dst_of_edges()
    for _ in range(num_iters):
        src_vec = v[g.col_idx]  # (ne, k)
        dst_vec = v[dst]
        err = g.weights.astype(np.float32) - np.sum(src_vec * dst_vec, axis=-1)
        acc = np.zeros_like(v)
        np.add.at(acc, dst, err[:, None] * src_vec)
        v = v + gamma * (acc - lam * v)
    return v


def rmse(g: HostGraph, v: np.ndarray) -> float:
    """Root-mean-square rating reconstruction error (training metric)."""
    dst = g.dst_of_edges()
    pred = np.sum(v[g.col_idx] * v[dst], axis=-1)
    return float(np.sqrt(np.mean((g.weights - pred) ** 2)))


def init_rmse(g: HostGraph) -> float:
    """Closed-form RMSE of the untrained state: every latent vector is
    sqrt(1/K) (colfilter_gpu.cu:260-264), so every prediction is exactly
    K * (1/K) = 1."""
    return float(np.sqrt(np.mean((np.asarray(g.weights, np.float64) - 1.0) ** 2)))


def check_training(g: HostGraph, v: np.ndarray) -> int:
    """Training-progress validation for `-check` — an EXTENSION (the
    reference ships no CF check task): gradient descent on the factor
    model must not move the training RMSE ABOVE the untrained closed
    form, and the state must stay finite.  Both sides are computed in
    float64 and the band is 1e-4 relative: at the app-default
    GAMMA=3.5e-7 the true improvement after a few iterations is tiny,
    so the check catches divergence/corruption, not slow progress.
    Returns a violation count in the [PASS]/[FAIL] contract: 1 if RMSE
    regressed (diverged), plus the number of non-finite entries."""
    v = np.asarray(v)
    bad = int((~np.isfinite(v)).sum())
    if rmse(g, v.astype(np.float64)) > init_rmse(g) * (1 + 1e-4):
        bad += 1
    return bad
