"""Multi-host (multi-process) initialization.

The reference scales across nodes through GASNet underneath Realm — no
code in Lux itself touches the network; launching N processes with
`-ll:gpu` per node is the whole story (README.md:33-37, SURVEY.md §2.4).
The TPU equivalent: one Python process per host, `jax.distributed`
bootstraps the cross-host runtime, and the SAME shard_map programs then
run with a global mesh whose axes span hosts — XLA routes all_gather /
psum / ppermute over ICI within a slice and DCN across slices.  No
lux_tpu code changes between single-host and multi-host.

Per-host data loading: each host builds only its own parts
(`read_lux_range` does the partial file read, the pull_load_task_impl
equivalent) and `jax.make_array_from_process_local_data` assembles the
globally-sharded stacked arrays.
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np

log = logging.getLogger("lux_tpu")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Bootstrap the multi-host runtime (no-op when single-host or already
    initialized).

    On TPU pods the three arguments auto-detect from the environment;
    elsewhere pass them explicitly.  Returns the process index.
    """
    # guard with a module flag, NOT jax.process_count(): querying the
    # backend would initialize it and forbid jax.distributed.initialize
    if getattr(initialize, "_done", False):
        return jax.process_index()
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif coordinator_address is not None:
        jax.distributed.initialize(coordinator_address=coordinator_address)
    # mark done only after a successful bootstrap so a transient failure
    # (coordinator not yet listening) stays retryable
    initialize._done = True
    log.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return jax.process_index()


def global_parts_mesh():
    """1-D mesh over ALL devices of ALL hosts (parts axis)."""
    from lux_tpu.parallel.mesh import PARTS_AXIS

    return jax.sharding.Mesh(np.asarray(jax.devices()), (PARTS_AXIS,))


def local_part_range(num_parts: int) -> Sequence[int]:
    """The part indices this host owns under a one-part-per-device layout
    (the analog of the mapper's node-major slice placement,
    lux_mapper.cc:112-121).  The split arithmetic lives in ONE place —
    ``placement.PlacementTree.build`` (balanced: the first ``num_parts %
    process_count`` hosts take one extra part) — so the dist engines and
    the fleet agree on ownership by construction."""
    from lux_tpu.parallel.placement import local_tree

    return local_tree(num_parts).parts_of(jax.process_index())


def assemble_global(mesh, stacked_local: np.ndarray, num_parts: int):
    """Build a globally-sharded stacked (P, ...) array from this host's
    local parts (host-sharded loading path)."""
    return jax.make_array_from_process_local_data(
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(mesh.axis_names[0])),
        stacked_local,
        (num_parts,) + stacked_local.shape[1:],
    )
