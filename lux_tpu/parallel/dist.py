"""Distributed (multi-chip) pull-engine drivers via shard_map.

This is the communication backend of the framework — the role Legion +
GASNet play in the reference, where declaring a whole-region read
(core/pull_model.inl:454-461) makes the runtime all-gather every part's
vertex state into each node's zero-copy memory per iteration
(SURVEY.md §2.5, §5).  Here the exchange is explicit and rides ICI:

    full_state = lax.all_gather(local_state, "parts", tiled=True)

inside `shard_map` over a 1-D mesh, with the iteration loop staying
on-device (`lax.fori_loop` / `lax.while_loop`) and convergence decided by a
`lax.psum` of per-part active counts — the analog of the FutureMap
reduction at sssp/sssp.cc:116-129, minus the 4-iteration host lag.

The per-part compute is byte-identical to the single-device path
(lux_tpu.engine.pull.local_pull_step): only the state exchange differs.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import PullProgram, local_pull_step
from lux_tpu.graph.shards import ShardArrays, ShardSpec
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked


def _squeeze0(tree):
    return jax.tree.map(lambda a: a[0], tree)


def _arrays_specs():
    return ShardArrays(*([P(PARTS_AXIS)] * len(ShardArrays._fields)))


@lru_cache(maxsize=64)
def _compile_fixed(prog, mesh, num_iters: int, method: str):
    """Build (once per config) the jitted shard_map program.  Cached so
    repeated calls don't retrace; all keys are hashable statics."""

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), P(PARTS_AXIS)),
        out_specs=P(PARTS_AXIS),
    )
    def run(arr_blk, state_blk):
        arr = _squeeze0(arr_blk)

        def body(_, local):
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            return local_pull_step(prog, arr, full, local, method)

        out = jax.lax.fori_loop(0, num_iters, body, state_blk[0])
        return out[None]

    return run


def run_pull_fixed_dist(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
):
    """Fixed-iteration distributed pull (PageRank/CF).  ``arrays`` and
    ``state0`` are stacked (P, ...) with P == mesh size; returns the final
    stacked state (sharded)."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    assert spec.num_parts == mesh.devices.size, (spec.num_parts, mesh.shape)
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, arrays))
    state0 = shard_stacked(mesh, state0)
    return _compile_fixed(prog, mesh, num_iters, method)(arrays, state0)


def compile_pull_step_dist(prog, mesh, method: str = "auto"):
    """ONE distributed pull iteration (all_gather + local step) — the
    step-wise observability mode for `-verbose --distributed`: the host
    fences per iteration (like the reference's per-iteration kernel
    timers), trading the fused on-device loop for stats.  The state is
    donated — ping-pong double buffering like the single-device
    compile_pull_step.

    Resolution happens OUTSIDE the compile cache: caching on "auto" would
    pin the first platform resolution for the process."""
    from lux_tpu.engine import methods

    return _compile_step_dist_cached(
        prog, mesh, methods.resolve(method, prog.reduce)
    )


@lru_cache(maxsize=64)
def _compile_step_dist_cached(prog, mesh, method: str):

    @partial(jax.jit, donate_argnums=1)
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), P(PARTS_AXIS)),
        out_specs=P(PARTS_AXIS),
    )
    def step(arr_blk, state_blk):
        arr = _squeeze0(arr_blk)
        local = state_blk[0]
        full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
        return local_pull_step(prog, arr, full, local, method)[None]

    return step


@lru_cache(maxsize=64)
def _compile_until(prog, mesh, max_iters: int, active_fn, method: str):
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), P(PARTS_AXIS)),
        out_specs=(P(PARTS_AXIS), P()),
    )
    def run(arr_blk, state_blk):
        arr = _squeeze0(arr_blk)

        def cond(carry):
            _, it, active = carry
            return (active > 0) & (it < max_iters)

        def body(carry):
            local, it, _ = carry
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            new = local_pull_step(prog, arr, full, local, method)
            active = jax.lax.psum(
                active_fn(local, new).astype(jnp.int32), PARTS_AXIS
            )
            return new, it + 1, active

        local, iters, _ = jax.lax.while_loop(
            cond, body, (state_blk[0], jnp.int32(0), jnp.int32(1))
        )
        return local[None], iters

    return run


def run_pull_until_dist(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    max_iters: int,
    active_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    method: str = "auto",
):
    """Convergence-driven distributed pull (CC/SSSP): iterate until the
    global active count (psum over parts) reaches zero.

    active_fn(old_local, new_local) -> scalar active count for this part
    (must be a hashable top-level function, not a per-call lambda, so the
    compiled program can be cached).
    Returns (final stacked state, iterations run).
    """
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    assert spec.num_parts == mesh.devices.size, (spec.num_parts, mesh.shape)
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, arrays))
    state0 = shard_stacked(mesh, state0)
    return _compile_until(prog, mesh, max_iters, active_fn, method)(
        arrays, state0
    )
