"""Distributed (multi-chip) pull-engine drivers via shard_map.

This is the communication backend of the framework — the role Legion +
GASNet play in the reference, where declaring a whole-region read
(core/pull_model.inl:454-461) makes the runtime all-gather every part's
vertex state into each node's zero-copy memory per iteration
(SURVEY.md §2.5, §5).  Here the exchange is explicit and rides ICI:

    full_state = lax.all_gather(local_state, "parts", tiled=True)

inside `shard_map` over a 1-D mesh, with the iteration loop staying
on-device (`lax.fori_loop` / `lax.while_loop`) and convergence decided by a
`lax.psum` of per-part active counts — the analog of the FutureMap
reduction at sssp/sssp.cc:116-129, minus the 4-iteration host lag.

The per-part compute is byte-identical to the single-device path
(lux_tpu.engine.pull.local_pull_step): only the state exchange differs.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import (
    PullProgram, local_pull_step, pull_gather_part, pull_reduce_part,
)
from lux_tpu.graph.shards import ShardArrays, ShardSpec
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked
from lux_tpu.parallel.placement import halo_all_gather


def _arrays_specs():
    return ShardArrays(*([P(PARTS_AXIS)] * len(ShardArrays._fields)))


@lru_cache(maxsize=64)
def _compile_fixed(prog, mesh, num_iters: int, method: str,
                   route_static=None, interpret: bool = False):
    """Build (once per config) the jitted shard_map program.  Cached so
    repeated calls don't retrace; all keys are hashable statics.

    ``route_static``: ExpandStatic to run each resident part's LOAD
    phase through the routed-shuffle expand (parts share ONE static by
    construction, so the vmapped lanes stay uniform; the per-part index
    arrays ride in as a sharded pytree operand)."""
    routed = route_static is not None
    in_specs = (_arrays_specs(), P(PARTS_AXIS))
    kw = {}
    if routed:
        in_specs = in_specs + (P(PARTS_AXIS),)
        # pallas_call's out_shape carries no varying-mesh-axes
        # annotation (see parallel/pallas_dist.py): the routed lane
        # gathers run under this shard_map, so the vma check must be off
        kw["check_vma"] = False

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(PARTS_AXIS),
        **kw,
    )
    def run(arr_blk, state_blk, *route_blk):
        # each device holds k = P/D resident parts (k == 1 when P == D);
        # the per-part step vmaps over the resident lanes — the mapper-
        # slicing analog (core/lux_mapper.cc:102-122)
        def body(_, block):
            full = halo_all_gather(block)
            if routed:
                return jax.vmap(
                    lambda arr, loc, ra: local_pull_step(
                        prog, arr, full, loc, method,
                        route=(route_static, ra), interpret=interpret)
                )(arr_blk, block, route_blk[0])
            return jax.vmap(
                lambda arr, loc: local_pull_step(prog, arr, full, loc, method)
            )(arr_blk, block)

        return jax.lax.fori_loop(0, num_iters, body, state_blk)

    return run


def run_pull_fixed_dist(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
    route=None,
):
    """Fixed-iteration distributed pull (PageRank/CF).  ``arrays`` and
    ``state0`` are stacked (P, ...) with P == mesh size; returns the final
    stacked state (sharded).  P may be any multiple of the mesh size
    (k parts resident per device).  ``route`` runs each part's hot loop
    through the routed pipelines (ops/expand.py: ExpandStatic = routed
    LOAD, bitwise; CFRouteStatic = wide src+dst routed LOAD, bitwise;
    FusedStatic = routed load AND reduce); the all_gather exchange is
    unchanged."""
    from lux_tpu.engine import methods
    from lux_tpu.engine.pull import _route_interpret

    method = methods.resolve_sum(method, prog.reduce)
    assert spec.num_parts % mesh.devices.size == 0, (spec.num_parts, mesh.shape)
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, arrays))
    state0 = shard_stacked(mesh, state0)
    if route is None:
        return _compile_fixed(prog, mesh, num_iters, method)(arrays, state0)
    rs, ra = route
    ra = shard_stacked(mesh, jax.tree.map(jnp.asarray, ra))
    fn = _compile_fixed(prog, mesh, num_iters, method, route_static=rs,
                        interpret=_route_interpret())
    return fn(arrays, state0, ra)


def compile_pull_phases_dist(prog, mesh, method: str = "auto"):
    """One DISTRIBUTED pull iteration as THREE separately-jitted,
    fence-able shard_map sub-steps — the multi-GPU `-verbose` phase
    breakdown of the reference (per-GPU loadTime/compTime/updateTime,
    sssp_gpu.cu:513-518, printed on multi-GPU runs too):

      load(arrays, state)        -> per-edge gathered (src, dst) states;
                                    carries THE exchange (all_gather of
                                    every part's state over ICI — the
                                    Legion/GASNet whole-region read,
                                    core/pull_model.inl:454-461)
      comp(arrays, gathered)     -> per-destination reduced accumulators
      update(arrays, state, acc) -> new state (apply; state donated)

    The per-part bodies are the SAME pull_gather_part/pull_reduce_part
    the fused engines use.  Observability path: fencing between phases
    costs dispatch latency; run_pull_fixed_dist is the perf path."""
    from lux_tpu.engine import methods

    return _compile_phases_dist_cached(
        prog, mesh, methods.resolve_sum(method, prog.reduce)
    )


@lru_cache(maxsize=64)
def _compile_phases_dist_cached(prog, mesh, method: str):
    Pp = P(PARTS_AXIS)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), Pp),
        out_specs=(Pp, Pp),
    )
    def load(arr_blk, state_blk):
        full = halo_all_gather(state_blk)  # the ICI exchange
        return jax.vmap(
            lambda arr, loc: pull_gather_part(arr, full, loc)
        )(arr_blk, state_blk)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), (Pp, Pp)),
        out_specs=Pp,
    )
    def comp(arr_blk, gath_blk):
        return jax.vmap(
            lambda arr, gath: pull_reduce_part(prog, arr, gath, method)
        )(arr_blk, gath_blk)

    @partial(jax.jit, donate_argnums=1)
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), Pp, Pp),
        out_specs=Pp,
    )
    def update(arr_blk, state_blk, acc_blk):
        return jax.vmap(lambda arr, loc, a: prog.apply(loc, a, arr))(
            arr_blk, state_blk, acc_blk
        )

    return load, comp, update


@lru_cache(maxsize=64)
def _compile_until(prog, mesh, max_iters: int, active_fn, method: str):
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_arrays_specs(), P(PARTS_AXIS)),
        out_specs=(P(PARTS_AXIS), P()),
    )
    def run(arr_blk, state_blk):

        def cond(carry):
            _, it, active = carry
            return (active > 0) & (it < max_iters)

        def body(carry):
            block, it, _ = carry
            full = halo_all_gather(block)
            new = jax.vmap(
                lambda arr, loc: local_pull_step(prog, arr, full, loc, method)
            )(arr_blk, block)
            # per-lane counts summed locally, then one psum over devices
            counts = jax.vmap(active_fn)(block, new)
            active = jax.lax.psum(
                jnp.sum(counts.astype(jnp.int32)), PARTS_AXIS
            )
            return new, it + 1, active

        block, iters, _ = jax.lax.while_loop(
            cond, body, (state_blk, jnp.int32(0), jnp.int32(1))
        )
        return block, iters

    return run


def run_pull_until_dist(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    max_iters: int,
    active_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    method: str = "auto",
):
    """Convergence-driven distributed pull (CC/SSSP): iterate until the
    global active count (psum over parts) reaches zero.

    active_fn(old_local, new_local) -> scalar active count for this part
    (must be a hashable top-level function, not a per-call lambda, so the
    compiled program can be cached).
    Returns (final stacked state, iterations run).
    """
    from lux_tpu.engine import methods

    method = methods.resolve_sum(method, prog.reduce)
    assert spec.num_parts % mesh.devices.size == 0, (spec.num_parts, mesh.shape)
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, arrays))
    state0 = shard_stacked(mesh, state0)
    return _compile_until(prog, mesh, max_iters, active_fn, method)(
        arrays, state0
    )
