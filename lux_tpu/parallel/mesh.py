"""Device mesh helpers.

The TPU replacement for the reference's machine model + mapper
(core/lux_mapper.cc): where LuxMapper discovers GPUs/framebuffers and slices
index launches one point per GPU round-robin across nodes
(lux_mapper.cc:102-140), we declare a 1-D `jax.sharding.Mesh` over all chips
and let GSPMD/shard_map place one graph part per chip.  Memory placement
(the FB vs zero-copy tags, core/graph.h:33-34) needs no analog: sharded
arrays live in HBM; the all-gathered state is XLA-managed.

Axis naming convention:
  * ``parts`` — the graph partition axis (one contiguous vertex range per
    chip; the sequence/context-parallel analog, SURVEY.md §2.5).
  * ``feat``  — optional second axis for feature-dimension sharding of
    wide vertex states (CF latent vectors; tensor-parallel analog) —
    see parallel/feat.py.
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("lux_tpu")

PARTS_AXIS = "parts"
FEAT_AXIS = "feat"


def make_mesh(num_parts: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``num_parts`` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if num_parts is None:
        num_parts = len(devices)
    assert len(devices) >= num_parts, (len(devices), num_parts)
    return Mesh(np.asarray(devices[:num_parts]), (PARTS_AXIS,))


def make_mesh_for_parts(num_parts: int, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh sized for ``num_parts`` graph parts on however many devices
    exist: if parts exceed devices, pick the largest mesh size that
    divides the part count, leaving k = parts/size parts RESIDENT per
    device — the analog of the reference mapper slicing up to
    MAX_NUM_PARTS=64 parts across whatever processors exist
    (core/graph.h:31, core/lux_mapper.cc:102-122)."""
    if devices is None:
        devices = jax.devices()
    d = min(len(devices), num_parts)
    while num_parts % d:
        d -= 1
    if num_parts > len(devices) and d < len(devices):
        log.warning(
            "num_parts=%d shares no divisor with the %d available devices"
            " above %d: running a %d-device mesh (%d idle). Pick -ng as a"
            " multiple of the device count to use every chip.",
            num_parts, len(devices), d, d, len(devices) - d,
        )
    return make_mesh(d, devices)


def parts_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (part) axis; replicate the rest."""
    return NamedSharding(mesh, P(PARTS_AXIS))


def shard_stacked(mesh: Mesh, tree):
    """Place a pytree of stacked (P, ...) arrays with axis 0 on the mesh."""
    sh = parts_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)


def flatten_gather(block):
    """all_gather a (k, V, ...) resident block over the parts axis and
    flatten to the (P*V, ...) gathered-coordinate state.  Thin alias of
    ``placement.halo_all_gather`` — the canonical halo-exchange leg
    (parallel/placement.py owns the ordering invariant and the LUX-J3
    audit); kept here for the historical import path."""
    from lux_tpu.parallel.placement import halo_all_gather

    return halo_all_gather(block)


def routed_run_args(mesh, route):
    """Shared tail for routed exchange drivers: device-shard the plan
    arrays over the parts axis and resolve interpret mode.  Returns
    (route_static, sharded_arrays, interpret)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine.pull import _route_interpret

    rs, ra = route
    ra = shard_stacked(mesh, jax.tree.map(jnp.asarray, ra))
    return rs, ra, _route_interpret()
