"""Device mesh helpers.

The TPU replacement for the reference's machine model + mapper
(core/lux_mapper.cc): where LuxMapper discovers GPUs/framebuffers and slices
index launches one point per GPU round-robin across nodes
(lux_mapper.cc:102-140), we declare a 1-D `jax.sharding.Mesh` over all chips
and let GSPMD/shard_map place one graph part per chip.  Memory placement
(the FB vs zero-copy tags, core/graph.h:33-34) needs no analog: sharded
arrays live in HBM; the all-gathered state is XLA-managed.

Axis naming convention:
  * ``parts`` — the graph partition axis (one contiguous vertex range per
    chip; the sequence/context-parallel analog, SURVEY.md §2.5).
  * ``feat``  — optional second axis for feature-dimension sharding of
    wide vertex states (CF latent vectors; tensor-parallel analog).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PARTS_AXIS = "parts"
FEAT_AXIS = "feat"


def make_mesh(num_parts: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``num_parts`` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if num_parts is None:
        num_parts = len(devices)
    assert len(devices) >= num_parts, (len(devices), num_parts)
    return Mesh(np.asarray(devices[:num_parts]), (PARTS_AXIS,))


def parts_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (part) axis; replicate the rest."""
    return NamedSharding(mesh, P(PARTS_AXIS))


def shard_stacked(mesh: Mesh, tree):
    """Place a pytree of stacked (P, ...) arrays with axis 0 on the mesh."""
    sh = parts_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)
