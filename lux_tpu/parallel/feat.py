"""Feature-dimension (tensor-parallel) sharding for wide vertex states.

CF's latent state is (V, K): the one app state SURVEY.md §7.3 flags for
memory (10.7 GB f32 at RMAT27 K=20).  The 1-D engines shard V over
``parts`` and replicate K; this module adds the second mesh axis
promised in parallel/mesh.py — ``feat`` — and runs CF on a 2-D
(parts × feat) mesh with K split across FEAT_AXIS, the tensor-parallel
analog of the reference's one-axis GPU slicing (SURVEY.md §2.5):

  * every device holds a (k_parts, V, K/F) state block: per-chip HBM for
    the state AND the per-iteration all-gathered exchange shrink ×F
    (the all_gather rides only the parts axis, within a feat column);
  * the one cross-feat term in CF's math is the K-dim error dot product
      err = w - <v_src, v_dst>
    which becomes a local partial dot + one (E,)-sized
    ``lax.psum(..., FEAT_AXIS)`` per iteration — O(E) wire instead of
    O(E·K) gradient traffic, because the err·srcVec outer product and
    the segmented per-destination reduction are feat-local;
  * apply (GAMMA/LAMBDA update) is elementwise over K: feat-local.

Math parity: identical recurrence to models/colfilter.CFProgram
(col_filter/colfilter_gpu.cu:85-101); the only reassociation is the
K-sum splitting into F partial sums, so results match the 1-D engines
to float addition-order tolerance (exact when F divides the dot's
addition tree evenly — tests compare allclose + RMSE).
"""
from __future__ import annotations

import logging
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lux_tpu.graph.shards import ShardArrays, ShardSpec
from lux_tpu.ops import segment
from lux_tpu.parallel.mesh import FEAT_AXIS, PARTS_AXIS, flatten_gather

_REDUCERS = segment.reducers()
log = logging.getLogger("lux_tpu")


def make_mesh_feat(num_parts: int, feat_shards: int, devices=None) -> Mesh:
    """2-D (parts × feat) mesh over num_parts * feat_shards devices.
    Feat is the MINOR axis so a feat column's all_gather stays between
    mesh-adjacent devices (ICI-neighbor rings, like edge2d's layout)."""
    if devices is None:
        devices = jax.devices()
    need = num_parts * feat_shards
    assert len(devices) >= need, (len(devices), need)
    devs = np.asarray(devices[:need]).reshape(num_parts, feat_shards)
    return Mesh(devs, (PARTS_AXIS, FEAT_AXIS))


def make_mesh_feat_for_parts(num_parts: int, feat_shards: int,
                             devices=None) -> Mesh:
    """(parts × feat) mesh for ``num_parts`` graph parts on however many
    devices exist: the parts extent is the largest divisor of num_parts
    that fits devices // feat_shards, leaving k = parts/extent parts
    RESIDENT per device — mesh.make_mesh_for_parts extended to the 2-D
    feat mesh."""
    if devices is None:
        devices = jax.devices()
    assert len(devices) >= feat_shards, (len(devices), feat_shards)
    slots = len(devices) // feat_shards
    d = min(slots, num_parts)
    while num_parts % d:
        d -= 1
    if d < slots and num_parts > slots:
        log.warning(
            "num_parts=%d shares no divisor with the %d parts slots "
            "(%d devices / %d feat shards) above %d: running a %dx%d "
            "mesh (%d devices idle). Pick -ng as a multiple of the "
            "slot count to use every chip.",
            num_parts, slots, len(devices), feat_shards, d, d,
            feat_shards, len(devices) - d * feat_shards,
        )
    return make_mesh_feat(d, feat_shards, devices)


def _arrays_specs():
    return ShardArrays(*([P(PARTS_AXIS)] * len(ShardArrays._fields)))


def shard_feat(mesh: Mesh, arrays: ShardArrays, state0):
    """Place stacked arrays (parts-sharded, feat-replicated) and the
    (P, V, K) state (parts × feat sharded) on the 2-D mesh.  device_put
    straight from host per leaf — no default-device staging; an
    already-correctly-sharded state passes through copy-free."""
    arr_sh = NamedSharding(mesh, P(PARTS_AXIS))
    st_sh = NamedSharding(mesh, P(PARTS_AXIS, None, FEAT_AXIS))
    arrays = jax.tree.map(lambda a: jax.device_put(a, arr_sh), arrays)
    return arrays, jax.device_put(state0, st_sh)


def init_state_feat(prog, arrays: ShardArrays, mesh: Mesh):
    """(P, V, K) initial latent state created DIRECTLY sharded over the
    2-D mesh: only the small (P, V) vertex inputs ever exist whole; the
    K-wide state is born (parts × feat)-sharded, so no single chip holds
    the full (V, K) matrix — the point of feat sharding at the RMAT27
    scale the module docstring cites."""
    arr_sh = NamedSharding(mesh, P(PARTS_AXIS))
    st_sh = NamedSharding(mesh, P(PARTS_AXIS, None, FEAT_AXIS))
    gv = jax.device_put(np.asarray(arrays.global_vid), arr_sh)
    dg = jax.device_put(np.asarray(arrays.degree), arr_sh)
    vm = jax.device_put(np.asarray(arrays.vtx_mask), arr_sh)
    return jax.jit(jax.vmap(prog.init_state), out_shardings=st_sh)(gv, dg, vm)


@lru_cache(maxsize=64)
def _compile_cf_feat(prog, mesh, num_iters: int, method: str,
                     route_static=None, interpret: bool = False):
    routed = route_static is not None
    in_specs = (_arrays_specs(), P(PARTS_AXIS, None, FEAT_AXIS))
    kw = {}
    if routed:
        # plans shard over parts, replicate over the feat axis (the
        # same gather serves every feat slice)
        in_specs = in_specs + (P(PARTS_AXIS),)
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(PARTS_AXIS, None, FEAT_AXIS),
        **kw,
    )
    def run(arr_blk, state_blk, *route_blk):
        # block: (k_parts, V, Kf).  One iteration = parts-axis gather of
        # the LOCAL feat slice, partial dots, one cross-feat psum for the
        # error term, then feat-local accumulate + apply (module docstring;
        # math from models/colfilter.CFProgram.edge_value/apply).
        def body(_, block):
            full = flatten_gather(block)  # (P*V, Kf) over parts only

            def gather(arr, loc, ra=None):
                if ra is not None:
                    from lux_tpu.ops import expand as _expand

                    src, dst = _expand.apply_cf_route(
                        full, loc, route_static, ra, interpret=interpret)
                    src = src.astype(jnp.float32)
                    dst = dst.astype(jnp.float32)
                else:
                    src = full[arr.src_pos].astype(jnp.float32)  # (E, Kf)
                    dst = loc[
                        jnp.clip(arr.dst_local, 0, loc.shape[0] - 1)
                    ].astype(jnp.float32)
                return src, jnp.sum(src * dst, axis=-1)

            if routed:
                src_vecs, part_dot = jax.vmap(gather)(
                    arr_blk, block, route_blk[0])
            else:
                src_vecs, part_dot = jax.vmap(gather)(arr_blk, block)
            # the ONLY cross-feat exchange: (k_parts, E) error dots
            err = arr_blk.weights - jax.lax.psum(part_dot, FEAT_AXIS)
            vals = err[..., None] * src_vecs  # (k_parts, E, Kf)

            def reduce_apply(arr, v, loc):
                acc = _REDUCERS[prog.reduce](
                    v, arr.row_ptr, arr.head_flag, arr.dst_local,
                    method=method,
                )
                return prog.apply(loc, acc, arr)

            return jax.vmap(reduce_apply)(arr_blk, vals, block)

        return jax.lax.fori_loop(0, num_iters, body, state_blk)

    return run


@lru_cache(maxsize=64)
def _compile_cf_feat_ring(prog, mesh, num_parts: int, num_iters: int,
                          method: str):
    """CF on the (parts × feat) mesh with the RING dense exchange: the
    largest-config composition (SURVEY.md §7.3 — RMAT27 K=20 state too
    big for replication on BOTH axes).  Each feat column circulates
    (k, V, K/F) state blocks over the parts ring (O(nv/P · K/F) resident
    per chip); the cross-feat error-dot psum happens per fold step on
    (k, B)-sized partial dots — O(part edges) wire per iteration, never
    O(E·K)."""
    from lux_tpu.parallel.ring import RingArrays, neutral_like, ring_sweep

    D = mesh.shape[PARTS_AXIS]
    k = num_parts // D

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            RingArrays(*([P(PARTS_AXIS)] * len(RingArrays._fields))),
            P(PARTS_AXIS),  # vtx_mask
            P(PARTS_AXIS, None, FEAT_AXIS),  # state
        ),
        out_specs=P(PARTS_AXIS, None, FEAT_AXIS),
    )
    def run(rarr_blk, vtx_mask_blk, state_blk):
        my = jax.lax.axis_index(PARTS_AXIS)

        def iteration(_, block):
            V = block.shape[1]

            def fold(s, acc, stream):
                dev = (my + s) % D
                qs = [dev * k + j for j in range(k)]  # streamed lane ids

                def dots(rarr_i, local_i):
                    # (k_stream, B, Kf) src vectors and (k_stream, B)
                    # partial dots for ONE resident lane, all streamed
                    # lanes stacked — so the cross-feat exchange below is
                    # one psum per fold step, not one per lane
                    src = jnp.stack(
                        [stream[j][rarr_i.src_local[q]] for j, q in
                         enumerate(qs)]
                    ).astype(jnp.float32)
                    dst = jnp.stack(
                        [local_i[jnp.clip(rarr_i.dst_local[q], 0, V - 1)]
                         for q in qs]
                    ).astype(jnp.float32)
                    return src, jnp.sum(src * dst, axis=-1)

                srcs, part_dot = jax.vmap(dots)(rarr_blk, block)
                # the ONE cross-feat exchange: (k_res, k_stream, B) dots
                w = jnp.stack([rarr_blk.weights[:, q] for q in qs], axis=1)
                err = w - jax.lax.psum(part_dot, FEAT_AXIS)
                vals = err[..., None] * srcs  # (k_res, k_stream, B, Kf)

                def red(rarr_i, v, acc_i):
                    for j, q in enumerate(qs):
                        part = segment.segment_reduce_by_ends(
                            v[j], rarr_i.head_flag[q], rarr_i.dst_local[q],
                            V, reduce="sum", method=method,
                        )
                        acc_i = acc_i + part
                    return acc_i

                return jax.vmap(red)(rarr_blk, vals, acc)

            acc = ring_sweep(block, neutral_like(block, "sum"), fold, D)

            def apply_one(loc, a, vm):
                return prog.apply(loc, a, _FeatArrView(vtx_mask=vm))

            return jax.vmap(apply_one)(block, acc, vtx_mask_blk)

        return jax.lax.fori_loop(0, num_iters, iteration, state_blk)

    return run


class _FeatArrView:
    """Duck-typed ShardArrays view for CFProgram.apply (reads vtx_mask
    only)."""

    def __init__(self, vtx_mask):
        self.vtx_mask = vtx_mask


def run_cf_feat_ring(
    prog,
    shards,
    state0,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
):
    """Fixed-iteration CF on the (parts × feat) mesh with ring-streamed
    state blocks (``shards`` from ring.build_ring_shards).  Per-chip
    state: O(nv/P × K/F) — both big-axes compositions at once."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    spec = shards.spec
    assert mesh.axis_names == (PARTS_AXIS, FEAT_AXIS), mesh.axis_names
    d_parts = mesh.shape[PARTS_AXIS]
    assert spec.num_parts % d_parts == 0, (spec.num_parts, d_parts)
    assert state0.shape[-1] % mesh.shape[FEAT_AXIS] == 0
    assert prog.reduce == "sum"
    assert len(shards.parts_subset) == spec.num_parts
    assert method in ("scan", "scatter"), (
        segment.BUCKETED_METHODS_NOTE
    )
    arr_sh = NamedSharding(mesh, P(PARTS_AXIS))
    st_sh = NamedSharding(mesh, P(PARTS_AXIS, None, FEAT_AXIS))
    rarrays = jax.tree.map(
        lambda a: jax.device_put(a, arr_sh), shards.rarrays
    )
    vtx_mask = jax.device_put(np.asarray(shards.arrays.vtx_mask), arr_sh)
    state0 = jax.device_put(state0, st_sh)
    run = _compile_cf_feat_ring(
        prog, mesh, spec.num_parts, num_iters, method
    )
    return run(rarrays, vtx_mask, state0)


def run_cf_feat_dist(
    prog,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
    route=None,
):
    """Fixed-iteration CF on the (parts × feat) mesh.  ``state0`` is the
    stacked (P, V, K) latent state; K must divide by the feat extent and
    P by the parts extent (k resident parts per device).  ``route``
    (plan_cf_route_shards) replays the src AND dst gathers per feat
    column — bitwise-identical; the scalar plans serve every feat slice,
    so they shard over parts and replicate over the feat axis.  Returns
    the final stacked state (sharded)."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    assert mesh.axis_names == (PARTS_AXIS, FEAT_AXIS), mesh.axis_names
    d_parts = mesh.shape[PARTS_AXIS]
    d_feat = mesh.shape[FEAT_AXIS]
    assert spec.num_parts % d_parts == 0, (spec.num_parts, d_parts)
    k = state0.shape[-1]
    assert k % d_feat == 0, (k, d_feat)
    assert prog.reduce == "sum", "feat sharding is CF's sum-reduce path"
    arrays, state0 = shard_feat(mesh, arrays, state0)
    if route is None:
        return _compile_cf_feat(prog, mesh, num_iters, method)(
            arrays, state0)
    from lux_tpu.engine.pull import _route_interpret

    rs, ra = route
    ra = jax.tree.map(
        lambda a: jax.device_put(jnp.asarray(a),
                                 NamedSharding(mesh, P(PARTS_AXIS))), ra)
    run = _compile_cf_feat(prog, mesh, num_iters, method,
                           route_static=rs, interpret=_route_interpret())
    return run(arrays, state0, ra)
