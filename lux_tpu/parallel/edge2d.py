"""2-D (parts x edge) parallelism: edge-dim sharding WITHIN a part.

The reference binds one part to one GPU (`MAX_NUM_PARTS=64`,
core/graph.h:31) — a part whose in-edge slice exceeds one device's memory
simply cannot run.  On a TPU mesh the natural fix is a second mesh axis
(SURVEY.md §2.5: "optional edge-dim sharding within a part", the
tensor-parallel analog of this workload): the 1-D edge-balanced partition
assigns each part a contiguous destination range, and each part's CSC edge
slice is split edge-wise over the ``edge`` axis.  Every edge-shard computes
a PARTIAL per-destination reduction for the same destination range (its
chunk may start/stop mid-destination — partial sums/mins are exactly what
`psum`/`pmin`/`pmax` combine), and `apply` runs replicated across the edge
axis on the combined accumulator.

Layout (P parts, EP edge-shards, E2 = padded chunk edges):
  src_pos:   (P, EP, E2) int32  positions in the (P*V,) gathered state
  dst_local: (P, EP, E2) int32  part-local destination; padding holds V
  head_flag: (P, EP, E2) bool   per-chunk destination-segment starts
  weights:   (P, EP, E2) float32
plus per-part vertex arrays replicated over EP.  Reductions use the
row_ptr-free end-scatter encoding (ops.segment.segment_reduce_by_ends).

Exchange: `all_gather` of the part-sharded state over the ``parts`` axis
(each edge-column holds a replica), then one `psum`/`pmin`/`pmax` over
``edge`` per iteration — both ride ICI.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lux_tpu.engine.pull import PullProgram
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import LANE, ShardSpec, _round_up, shard_geometry
from lux_tpu.ops import segment
from lux_tpu.parallel.ring import _slice_dst_local, mark_bucket_heads

PARTS_AXIS = "parts"
EDGE_AXIS = "edge"


class Edge2DArrays(NamedTuple):
    src_pos: np.ndarray
    dst_local: np.ndarray
    head_flag: np.ndarray
    weights: np.ndarray
    #: per-part vertex arrays, shared by every edge-shard of the part
    vtx_mask: np.ndarray  # (P, V)
    degree: np.ndarray  # (P, V)
    global_vid: np.ndarray  # (P, V)


@dataclasses.dataclass
class Edge2DShards:
    spec: ShardSpec
    cuts: np.ndarray
    arrays2d: Edge2DArrays
    num_edge_shards: int
    e2_pad: int

    @property
    def arrays(self):
        """Vertex-array view for engine.pull.init_state (which reads only
        global_vid/degree/vtx_mask — all present on arrays2d).  The 1-D
        pull layout's O(E) edge arrays are deliberately NOT kept: the
        whole point of edge sharding is parts whose edge slice doesn't
        fit one device, so the host must not hold a second edge copy."""
        return self.arrays2d

    def scatter_to_global(self, stacked):
        P_ = self.spec.num_parts
        out = []
        for p in range(P_):
            n = int(self.cuts[p + 1] - self.cuts[p])
            out.append(np.asarray(stacked[p])[:n])
        return np.concatenate(out, axis=0)


def make_mesh2d(num_parts: int, num_edge_shards: int) -> Mesh:
    """(parts, edge) mesh over num_parts * num_edge_shards devices."""
    n = num_parts * num_edge_shards
    devs = jax.devices()
    if len(devs) < n:
        raise ValueError(
            f"2-D mesh needs {num_parts} x {num_edge_shards} = {n} devices; "
            f"only {len(devs)} available"
        )
    devs = np.asarray(devs[:n]).reshape(num_parts, num_edge_shards)
    return Mesh(devs, (PARTS_AXIS, EDGE_AXIS))


def build_edge2d_shards(
    g: HostGraph, num_parts: int, num_edge_shards: int
) -> Edge2DShards:
    """Split each part's CSC edge slice into ``num_edge_shards`` contiguous
    chunks (chunk boundaries may fall mid-destination — the partial
    reductions are psum-combined).  Never materializes the 1-D pull
    layout's O(E) arrays."""
    cuts, nv_pad, e_pad = shard_geometry(
        np.asarray(g.row_ptr), num_parts, g.nv
    )
    spec = ShardSpec(
        num_parts=num_parts, nv=g.nv, ne=g.ne, nv_pad=nv_pad, e_pad=e_pad,
        weighted=g.weights is not None,
    )
    Pn, EP, V = num_parts, num_edge_shards, spec.nv_pad

    # global padded chunk size from per-part edge counts (formula shared
    # with the preflight hint, graph/shards.edge2d_chunk_pad)
    from lux_tpu.graph.shards import edge2d_chunk_pad

    e_counts = np.asarray(g.row_ptr)[cuts[1:]] - np.asarray(g.row_ptr)[cuts[:-1]]
    E2 = edge2d_chunk_pad(int(e_counts.max()) if len(e_counts) else 1, EP)

    src_pos = np.zeros((Pn, EP, E2), np.int32)
    dst_local = np.full((Pn, EP, E2), V, np.int32)
    head_flag = np.zeros((Pn, EP, E2), bool)
    weights = np.zeros((Pn, EP, E2), np.float32)
    vtx_mask = np.zeros((Pn, V), bool)
    degree = np.zeros((Pn, V), np.int32)
    global_vid = np.full((Pn, V), g.nv - 1, np.int32)
    degrees = g.out_degrees()
    for p in range(Pn):
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        n = vhi - vlo
        vtx_mask[p, :n] = True
        degree[p, :n] = degrees[vlo:vhi]
        global_vid[p, :n] = np.arange(vlo, vhi, dtype=np.int32)
        elo, ehi = int(g.row_ptr[vlo]), int(g.row_ptr[vhi])
        m_part = ehi - elo
        srcs = np.asarray(g.col_idx[elo:ehi]).astype(np.int64)
        own = np.searchsorted(cuts, srcs, side="right") - 1
        spos = (own * V + (srcs - cuts[own])).astype(np.int32)
        dl_slice = _slice_dst_local(g, vlo, vhi)
        step = -(-m_part // EP) if m_part else 0
        for e in range(EP):
            lo = min(e * step, m_part)
            hi = min(lo + step, m_part)
            m = hi - lo
            src_pos[p, e, :m] = spos[lo:hi]
            dl = dl_slice[lo:hi]
            dst_local[p, e, :m] = dl
            mark_bucket_heads(head_flag[p, e], dl)
            if g.weights is not None:
                weights[p, e, :m] = g.weights[elo + lo : elo + hi].astype(
                    np.float32
                )
    return Edge2DShards(
        spec=spec,
        cuts=cuts,
        arrays2d=Edge2DArrays(
            src_pos, dst_local, head_flag, weights,
            vtx_mask, degree, global_vid,
        ),
        num_edge_shards=EP,
        e2_pad=E2,
    )


_PCOMBINE = {
    "sum": jax.lax.psum,
    "min": jax.lax.pmin,
    "max": jax.lax.pmax,
}


@lru_cache(maxsize=64)
def _compile_edge2d_fixed(prog, mesh, num_iters: int, method: str,
                          route_static=None, interpret: bool = False):
    edge_specs = P(PARTS_AXIS, EDGE_AXIS)
    vtx_specs = P(PARTS_AXIS)  # replicated over the edge axis
    in_specs = Edge2DArrays(
        edge_specs, edge_specs, edge_specs, edge_specs,
        vtx_specs, vtx_specs, vtx_specs,
    )
    routed = route_static is not None
    all_specs = (in_specs, P(PARTS_AXIS))
    kw = {}
    if routed:
        all_specs = all_specs + (P(PARTS_AXIS, EDGE_AXIS),)
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=all_specs,
        out_specs=P(PARTS_AXIS),
        **kw,
    )
    def run(arr_blk, state_blk, *route_blk):
        src_pos = arr_blk.src_pos[0, 0]
        dst_loc = arr_blk.dst_local[0, 0]
        head = arr_blk.head_flag[0, 0]
        w = arr_blk.weights[0, 0]
        vtx_mask = arr_blk.vtx_mask[0]
        degree = arr_blk.degree[0]
        V = vtx_mask.shape[0]

        def iteration(_, local):
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            dst_state = local[jnp.clip(dst_loc, 0, V - 1)]
            if routed:
                from lux_tpu.ops import expand as _expand

                src_vals = _expand.apply_expand(
                    full, route_static,
                    jax.tree.map(lambda a: a[0, 0], route_blk[0]),
                    interpret=interpret)
            else:
                src_vals = full[src_pos]
            vals = prog.edge_value(src_vals, w, dst_state)
            part = segment.segment_reduce_by_ends(
                vals, head, dst_loc, V, reduce=prog.reduce, method=method
            )
            # combine the edge-shards' partial reductions; the result is
            # replicated over EDGE_AXIS, so apply runs identically on
            # every replica and the out_specs stay parts-only
            acc = _PCOMBINE[prog.reduce](part, EDGE_AXIS)
            from lux_tpu.parallel.ring import _RingArrView

            return prog.apply(
                local, acc, _RingArrView(vtx_mask=vtx_mask, degree=degree)
            )

        return jax.lax.fori_loop(0, num_iters, iteration, state_blk[0])[None]

    return run


@lru_cache(maxsize=64)
def _compile_edge2d_until(prog, mesh, max_iters: int, active_fn, method: str):
    edge_specs = P(PARTS_AXIS, EDGE_AXIS)
    vtx_specs = P(PARTS_AXIS)
    in_specs = Edge2DArrays(
        edge_specs, edge_specs, edge_specs, edge_specs,
        vtx_specs, vtx_specs, vtx_specs,
    )

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(in_specs, P(PARTS_AXIS)),
        out_specs=(P(PARTS_AXIS), P()),
    )
    def run(arr_blk, state_blk):
        src_pos = arr_blk.src_pos[0, 0]
        dst_loc = arr_blk.dst_local[0, 0]
        head = arr_blk.head_flag[0, 0]
        w = arr_blk.weights[0, 0]
        vtx_mask = arr_blk.vtx_mask[0]
        degree = arr_blk.degree[0]
        V = vtx_mask.shape[0]
        from lux_tpu.parallel.ring import _RingArrView

        def cond(carry):
            _, it, active = carry
            return (active > 0) & (it < max_iters)

        def body(carry):
            local, it, _ = carry
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            dst_state = local[jnp.clip(dst_loc, 0, V - 1)]
            vals = prog.edge_value(full[src_pos], w, dst_state)
            part = segment.segment_reduce_by_ends(
                vals, head, dst_loc, V, reduce=prog.reduce, method=method
            )
            acc = _PCOMBINE[prog.reduce](part, EDGE_AXIS)
            new = prog.apply(
                local, acc, _RingArrView(vtx_mask=vtx_mask, degree=degree)
            )
            # each part's count is replicated over EDGE after the combine;
            # psum over PARTS alone gives the global count everywhere
            active = jax.lax.psum(
                active_fn(local, new).astype(jnp.int32), PARTS_AXIS
            )
            return new, it + 1, active

        local, iters, _ = jax.lax.while_loop(
            cond, body, (state_blk[0], jnp.int32(0), jnp.int32(1))
        )
        return local[None], iters

    return run


def run_pull_until_2d(
    prog: PullProgram,
    shards: Edge2DShards,
    state0,
    max_iters: int,
    active_fn,
    mesh: Mesh,
    method: str = "auto",
):
    """Convergence-driven pull over the 2-D mesh (CC-style): iterate until
    the global active count reaches zero.  active_fn must be a hashable
    top-level function (compiled-program cache key)."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    arrays, state0 = _place_edge2d(shards, state0, mesh, method)
    run = _compile_edge2d_until(prog, mesh, max_iters, active_fn, method)
    return run(arrays, state0)


def _place_edge2d(shards: Edge2DShards, state0, mesh: Mesh, method: str):
    """Validate geometry and device_put the 2-D arrays + stacked state."""
    spec = shards.spec
    assert mesh.axis_names == (PARTS_AXIS, EDGE_AXIS)
    assert mesh.shape[PARTS_AXIS] == spec.num_parts
    assert mesh.shape[EDGE_AXIS] == shards.num_edge_shards
    assert method in ("scan", "scatter"), (
        "edge-sharded chunks carry no row_ptr: method='scan' or "
        "'scatter' only (--method / LUX_BENCH_METHOD; LUX_SUM_MODE "
        "winners downgrade to 'scan' on this layout)"
    )
    edge_sh = NamedSharding(mesh, P(PARTS_AXIS, EDGE_AXIS))
    vtx_sh = NamedSharding(mesh, P(PARTS_AXIS))
    a = shards.arrays2d
    arrays = Edge2DArrays(
        jax.device_put(a.src_pos, edge_sh),
        jax.device_put(a.dst_local, edge_sh),
        jax.device_put(a.head_flag, edge_sh),
        jax.device_put(a.weights, edge_sh),
        jax.device_put(np.asarray(a.vtx_mask), vtx_sh),
        jax.device_put(np.asarray(a.degree), vtx_sh),
        jax.device_put(np.asarray(a.global_vid), vtx_sh),
    )
    return arrays, jax.device_put(np.asarray(state0), vtx_sh)


def run_pull_fixed_2d(
    prog: PullProgram,
    shards: Edge2DShards,
    state0,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
    route=None,
):
    """Fixed-iteration pull over the 2-D (parts, edge) mesh.  ``state0`` is
    the stacked (P, V, ...) state (engine.pull.init_state).  ``route``
    (plan_edge2d_route_shards) replays each chunk's gathered-state read
    as routed lane shuffles — bitwise-identical."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    arrays, state0 = _place_edge2d(shards, state0, mesh, method)
    if route is None:
        run = _compile_edge2d_fixed(prog, mesh, num_iters, method)
        return run(arrays, state0)
    from lux_tpu.engine.pull import _route_interpret

    rs, ra = route
    sh = NamedSharding(mesh, P(PARTS_AXIS, EDGE_AXIS))
    ra = jax.tree.map(
        lambda a: jax.device_put(jnp.asarray(a), sh), ra)
    run = _compile_edge2d_fixed(prog, mesh, num_iters, method,
                                route_static=rs,
                                interpret=_route_interpret())
    return run(arrays, state0, ra)
