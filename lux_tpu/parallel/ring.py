"""Ring-streamed state exchange: the large-graph alternative to all_gather.

The all_gather drivers (lux_tpu.parallel.dist) materialize the WHOLE vertex
state on every chip per iteration — the reference's own exchange model
(whole-region zero-copy reads, core/pull_model.inl:454-461), fine for
Twitter-scale state (~170 MB) but not for RMAT27 CF-style wide state
(SURVEY.md §7.3).  This module streams instead: each chip keeps only one
part-sized block resident, passing blocks around the ring with
`lax.ppermute` and folding in each block's edge contributions as it
arrives — the ring-attention communication shape applied to vertex state
(SURVEY.md §5 long-context analog).  Peak per-chip state memory drops from
O(nv) to O(nv / P), and XLA overlaps the neighbor transfer with the
current block's compute.

Host-side, each part's edges are bucketed by the SOURCE's owning part
(P buckets, padded to the largest bucket).  Power-law skew can inflate
padding up to the largest bucket size; the edge-balanced partitioner keeps
per-part totals even, which bounds the common case.

Supports the full PullProgram contract including destination-state gathers
(CF's error term) — destinations are always local, so dst state comes from
the resident local block, never the ring.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import PullProgram
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import LANE, PullShards, _round_up, build_pull_shards
from lux_tpu.ops import segment
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked


class RingArrays(NamedTuple):
    """Per-part, per-source-bucket edge structure.  Shapes (R = number of
    built parts — all P, or this host's subset; B = e_bucket_pad):
      src_local: (R, P, B) int32  source index WITHIN the streamed block
      dst_local: (R, P, B) int32  local destination (for dst-state gathers
                 and segment-end scatters); padding holds V
      head_flag: (R, P, B) bool   segment starts by destination; the first
                 padding slot is also flagged so the last real edge reads
                 as a segment END (ops.segment.segment_reduce_by_ends)
      weights:   (R, P, B) float32

    Deliberately NO per-bucket (V+1) row_ptr: dense offsets would cost
    O(P^2 * V) (~35 GB at the RMAT27/P=64 target, SURVEY.md §7.3); every
    array here is edge-aligned, so total bucket memory is O(part edges).
    """

    src_local: np.ndarray
    dst_local: np.ndarray
    head_flag: np.ndarray
    weights: np.ndarray


@dataclasses.dataclass
class RingShards:
    pull: PullShards
    rarrays: RingArrays
    e_bucket_pad: int
    #: part indices materialized in rarrays' leading axis (multi-host
    #: builds give each host multihost.local_part_range(P))
    parts_subset: list

    @property
    def spec(self):
        return self.pull.spec

    @property
    def arrays(self):
        return self.pull.arrays

    @property
    def cuts(self):
        return self.pull.cuts

    def scatter_to_global(self, stacked):
        return self.pull.scatter_to_global(stacked)


def bucket_counts(g: HostGraph, cuts, num_parts: int):
    """(P, P) bucket edge counts: [p, q] = edges into part p's destinations
    from part q's sources.  One O(slice) pass per part — nothing ne-sized
    is ever materialized (col_idx may be an mmap view; slicing reads only
    that byte range), so subset builds on big graphs stay O(local edges)
    resident.  Every host computes this so padded bucket shapes agree
    globally."""
    counts = np.zeros((num_parts, num_parts), np.int64)
    for p in range(num_parts):
        elo = int(g.row_ptr[cuts[p]])
        ehi = int(g.row_ptr[cuts[p + 1]])
        own = np.searchsorted(cuts, g.col_idx[elo:ehi], side="right") - 1
        counts[p] = np.bincount(own, minlength=num_parts)
    return counts


def _slice_dst_local(g: HostGraph, vlo: int, vhi: int) -> np.ndarray:
    """Part-local destination ids for edge slice [row_ptr[vlo], row_ptr[vhi])
    derived from row_ptr alone — no global dst_of_edges() materialization."""
    rp = np.asarray(g.row_ptr[vlo : vhi + 1])
    return np.repeat(
        np.arange(vhi - vlo, dtype=np.int32), np.diff(rp).astype(np.int64)
    )


def _owner_split(srcs: np.ndarray, cuts) -> tuple:
    """Stable owner-bucketing of an edge slice: (order, counts).  Native
    counting sort (lux_io.lux_bucket_split, O(m log P)) when the library
    is built; NumPy argsort fallback otherwise — identical permutations
    (both stable by owner, original order within a bucket)."""
    from lux_tpu import native

    res = native.bucket_split(srcs, cuts)
    if res is not None:
        return res
    own = np.searchsorted(cuts, srcs, side="right") - 1
    counts = np.bincount(own, minlength=len(cuts) - 1)
    return np.argsort(own, kind="stable"), counts


def _native_bucket_fill_ok(w_in) -> bool:
    """The native fill consumes int32 weights (reference WeightType=int);
    any dtype that an int32 cast could truncate (floats, int64, uint32+)
    takes the NumPy path so both paths stay bit-identical."""
    return w_in is None or w_in.dtype in (
        np.int8, np.int16, np.int32, np.uint8, np.uint16,
    )


def native_bucket_fill(*args):
    """Shim so the builders read as one call; see native.bucket_fill."""
    from lux_tpu import native

    return native.bucket_fill(*args)


def mark_bucket_heads(hf_row: np.ndarray, dl: np.ndarray) -> None:
    """Destination-segment starts for one bucket (edges CSC-ordered).  The
    first padding slot is flagged too, so segment_reduce_by_ends sees the
    last real edge as an end."""
    m = len(dl)
    if m:
        hf_row[0] = True
        hf_row[1:m] = dl[1:] != dl[:-1]
    if m < hf_row.shape[0]:
        hf_row[m] = True


def build_ring_shards(
    g: HostGraph, num_parts: int, parts_subset=None, pull=None,
    counts=None, placement=None, host: int = 0,
) -> RingShards:
    """Bucket the graph for ring streaming.  ``parts_subset`` builds only
    those parts' (P, B) bucket rows (the sharded_load pattern: each host
    materializes O(its edges), not O(ne)).  Pass an existing ``pull``
    build to avoid repartitioning, and/or precomputed ``bucket_counts``
    to avoid an extra O(ne) pass (tools/biggraph_check.py does both).
    ``placement``/``host`` derive the subset from a PlacementTree slice
    instead — the one ownership map shared with the fleet."""
    if placement is not None:
        assert parts_subset is None, "pass placement OR parts_subset"
        assert placement.num_parts == num_parts, (
            placement.num_parts, num_parts)
        parts_subset = placement.parts_of(host)
    pull = pull if pull is not None else build_pull_shards(g, num_parts)
    spec, cuts = pull.spec, pull.cuts
    Pn, V = num_parts, spec.nv_pad
    counts = counts if counts is not None else bucket_counts(g, cuts, Pn)
    B = _round_up(max(1, int(counts.max())), LANE)

    rows = list(range(Pn) if parts_subset is None else parts_subset)
    src_local = np.zeros((len(rows), Pn, B), np.int32)
    dst_local = np.full((len(rows), Pn, B), V, np.int32)
    head_flag = np.zeros((len(rows), Pn, B), bool)
    weights = np.zeros((len(rows), Pn, B), np.float32)
    identity = np.arange(Pn, dtype=np.int64)
    blk = Pn * B
    for i, p in enumerate(rows):
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        elo, ehi = int(g.row_ptr[vlo]), int(g.row_ptr[vhi])
        w_in = None if g.weights is None else np.asarray(g.weights[elo:ehi])
        if _native_bucket_fill_ok(w_in) and native_bucket_fill(
            np.asarray(g.col_idx[elo:ehi]),
            np.asarray(g.row_ptr[vlo : vhi + 1]), w_in, cuts, B,
            identity, B,
            src_local.reshape(-1)[i * blk : (i + 1) * blk],
            dst_local.reshape(-1)[i * blk : (i + 1) * blk],
            head_flag.view(np.uint8).reshape(-1)[i * blk : (i + 1) * blk],
            weights.reshape(-1)[i * blk : (i + 1) * blk],
        ):
            continue
        srcs = np.asarray(g.col_idx[elo:ehi]).astype(np.int64)
        dl_slice = _slice_dst_local(g, vlo, vhi)
        # stable owner-bucketing keeps CSC (by-destination) order within
        # each bucket
        order, _ = _owner_split(srcs, cuts)
        splits = np.split(order, np.cumsum(counts[p])[:-1])
        for q in range(Pn):
            eids = splits[q]
            m = len(eids)
            src_local[i, q, :m] = (srcs[eids] - cuts[q]).astype(np.int32)
            dl = dl_slice[eids]
            dst_local[i, q, :m] = dl
            mark_bucket_heads(head_flag[i, q], dl)
            if w_in is not None:
                weights[i, q, :m] = w_in[eids].astype(np.float32)
    return RingShards(
        pull=pull,
        rarrays=RingArrays(src_local, dst_local, head_flag, weights),
        e_bucket_pad=B,
        parts_subset=rows,
    )


_FOLD = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def ring_sweep(block, acc0, fold, D: int):
    """The ring schedule shared by every streaming engine (this module's
    pull/push dense rounds and parallel/feat's ring × feat CF): D-1 fold
    steps each overlapped with a ppermute of the stream to the next chip,
    then the final resident fold without the (dead) last transfer.
    ``fold(s, acc, stream) -> acc`` consumes the block that started s
    hops clockwise; D is the parts-axis extent (compile-time)."""
    perm = [(i, (i - 1) % D) for i in range(D)]

    def fold_block(s, carry):
        acc, stream = carry
        acc = fold(s, acc, stream)
        return acc, jax.lax.ppermute(stream, PARTS_AXIS, perm)

    acc, stream = jax.lax.fori_loop(0, D - 1, fold_block, (acc0, block))
    return fold(D - 1, acc, stream)


def neutral_like(local, reduce):
    """Neutral-element fold accumulator.  Dtype = the REDUCTION dtype, not
    the storage dtype: programs storing bf16 state still reduce in f32
    (e.g. PageRankProgram.edge_value casts), and the fori_loop carry must
    keep one dtype across folds.  Integer programs reduce in their own
    dtype."""
    dt = (
        local.dtype
        if jnp.issubdtype(local.dtype, jnp.integer)
        else jnp.promote_types(local.dtype, jnp.float32)
    )
    # *_like keeps `local`'s varying-axes type (shard_map VMA): a fresh
    # constant would be unvarying and break the fori_loop carry
    if reduce == "sum":
        return jnp.zeros_like(local, dtype=dt)
    if jnp.issubdtype(dt, jnp.integer):
        v = jnp.iinfo(dt).max if reduce == "min" else jnp.iinfo(dt).min
    else:
        v = jnp.inf if reduce == "min" else -jnp.inf
    return jnp.full_like(local, v, dtype=dt)


@lru_cache(maxsize=64)
def _compile_ring_fixed(prog, mesh, num_parts: int, num_iters: int,
                        method: str, route_static=None,
                        interpret: bool = False):
    D = mesh.devices.size
    k = num_parts // D
    routed = route_static is not None
    in_specs = (
        RingArrays(*([P(PARTS_AXIS)] * len(RingArrays._fields))),
        P(PARTS_AXIS),  # vtx_mask
        P(PARTS_AXIS),  # degree
        P(PARTS_AXIS),  # state
    )
    kw = {}
    if routed:
        in_specs = in_specs + (P(PARTS_AXIS),)  # (P, P_src, ...) plans
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(PARTS_AXIS),
        **kw,
    )
    def run(rarr_blk, vtx_mask_blk, degree_blk, state_blk, *route_blk):
        # k = P/D resident parts per device (k == 1 when parts == devices);
        # the ring circulates (k, V, ...) blocks over the D devices, and
        # each arriving block's k streamed lanes fold into every resident
        # lane (static unroll over j: compile-time geometry)
        my = jax.lax.axis_index(PARTS_AXIS)

        def iteration(_, block):
            V = block.shape[1]

            def fold(s, acc, stream):
                dev = (my + s) % D
                for j in range(k):
                    q = dev * k + j  # global part id of streamed lane j

                    def one(rarr_i, local_i, acc_i, ra_i=None, q=q):
                        dst_state = local_i[
                            jnp.clip(rarr_i.dst_local[q], 0, V - 1)
                        ]
                        if ra_i is not None:
                            # bucket-local routed expand of the streamed
                            # block (ops/expand.py) — bitwise vs the
                            # flat gather; q is traced, so the (i, q)
                            # plan slice is a dynamic leading-axis index
                            from lux_tpu.ops import expand as _expand

                            src_vals = _expand.apply_expand(
                                stream[j], route_static,
                                jax.tree.map(lambda a: a[q], ra_i),
                                interpret=interpret)
                        else:
                            src_vals = stream[j][rarr_i.src_local[q]]
                        vals = prog.edge_value(
                            src_vals, rarr_i.weights[q], dst_state,
                        )
                        part = segment.segment_reduce_by_ends(
                            vals, rarr_i.head_flag[q], rarr_i.dst_local[q],
                            V, reduce=prog.reduce, method=method,
                        )
                        return _FOLD[prog.reduce](acc_i, part)

                    if routed:
                        acc = jax.vmap(one)(rarr_blk, block, acc,
                                            route_blk[0])
                    else:
                        acc = jax.vmap(one)(rarr_blk, block, acc)
                return acc

            acc = ring_sweep(block, neutral_like(block, prog.reduce), fold, D)
            return jax.vmap(
                lambda loc, a, vm, dg: _apply(prog, loc, a, vm, dg)
            )(block, acc, vtx_mask_blk, degree_blk)

        return jax.lax.fori_loop(0, num_iters, iteration, state_blk)

    return run


class _RingArrView(NamedTuple):
    """Duck-typed ShardArrays view for PullProgram.apply inside the ring
    driver (only the fields apply() implementations read)."""

    vtx_mask: jnp.ndarray
    degree: jnp.ndarray


def _apply(prog, local, acc, vtx_mask, degree):
    return prog.apply(local, acc, _RingArrView(vtx_mask=vtx_mask, degree=degree))


@dataclasses.dataclass
class PushRingShards:
    """Push-engine shards with the RING dense exchange: frontier CSR
    buckets (sparse rounds exchange queues) + per-source-owner ring
    buckets (dense rounds fold ppermute-streamed state blocks instead of
    all-gathering the whole state).  The O(E) pull arrays inside ``push``
    stay host-side; the push-ring driver never device-places them."""

    push: "object"  # PushShards (engine-facing; avoids a circular import)
    rarrays: RingArrays
    e_bucket_pad: int

    @property
    def spec(self):
        return self.push.spec

    @property
    def pspec(self):
        return self.push.pspec

    @property
    def parrays(self):
        return self.push.parrays

    @property
    def arrays(self):
        return self.push.arrays

    @property
    def pull(self):
        return self.push.pull

    @property
    def cuts(self):
        return self.push.cuts

    def scatter_to_global(self, stacked):
        return self.push.scatter_to_global(stacked)


def build_push_ring_shards(
    g: HostGraph, num_parts: int, parts_subset=None, cuts=None
) -> PushRingShards:
    """Push shards + ring buckets over the SAME partition (one build).
    ``cuts`` selects a custom contiguous partition (adaptive
    repartitioning rebuilds, engine/repartition.py)."""
    from lux_tpu.graph.push_shards import build_push_shards

    push = build_push_shards(g, num_parts, cuts=cuts)
    rs = build_ring_shards(g, num_parts, parts_subset, pull=push.pull)
    return PushRingShards(push=push, rarrays=rs.rarrays,
                          e_bucket_pad=rs.e_bucket_pad)


def run_pull_fixed_ring(
    prog: PullProgram,
    shards: RingShards,
    state0,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
    route=None,
):
    """Distributed fixed-iteration pull with ring-streamed state blocks.
    Signature-compatible with dist.run_pull_fixed_dist: pass the stacked
    (P, V, ...) initial state (e.g. from engine.pull.init_state).
    ``route`` (plan_ring_route_shards) replays each bucket's streamed-
    block gather as routed lane shuffles — bitwise-identical."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    spec = shards.spec
    assert spec.num_parts % mesh.devices.size == 0
    assert len(shards.parts_subset) == spec.num_parts, (
        "subset-built ring shards: assemble the full stacked arrays across "
        "hosts (multihost.assemble_global) before driving"
    )
    assert method in ("scan", "scatter"), (
        segment.BUCKETED_METHODS_NOTE
    )
    rarrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.rarrays))
    vtx_mask = shard_stacked(mesh, jnp.asarray(shards.arrays.vtx_mask))
    degree = shard_stacked(mesh, jnp.asarray(shards.arrays.degree))
    state0 = shard_stacked(mesh, state0)
    if route is None:
        run = _compile_ring_fixed(prog, mesh, spec.num_parts, num_iters,
                                  method)
        return run(rarrays, vtx_mask, degree, state0)
    from lux_tpu.parallel.mesh import routed_run_args

    rs, ra, interp = routed_run_args(mesh, route)
    run = _compile_ring_fixed(prog, mesh, spec.num_parts, num_iters,
                              method, route_static=rs, interpret=interp)
    return run(rarrays, vtx_mask, degree, state0, ra)
