"""Reduce-scatter state exchange: push-partials + psum_scatter.

Third exchange strategy of the communication backend (SURVEY.md §5 plan:
"reduce_scatter where updates can be pre-combined"), complementing
all_gather (parallel/dist.py) and the ppermute ring (parallel/ring.py):

  * each chip keeps only its OWN state block resident (like the ring);
  * chip q computes, from its local sources, partial per-destination
    accumulations for EVERY destination part p — using the transposed
    bucket layout (bucket (p, q) = edges from q's sources into p's
    destinations, the same host build as the ring, distributed by q);
  * one `lax.psum_scatter` sums partials across chips and hands each chip
    exactly its own destination block.

Only SUM-reducible programs qualify (PageRank, CF): XLA's fused
reduce-scatter is addition.  min/max programs use the ring or all_gather.

Compared to all_gather: same wire volume, but no nv-sized gathered buffer
is ever materialized (peak state O(nv/P + nv partials... the (P, V)
partial stack is the transient), and the reduction happens inside the
collective where XLA can fuse it with the transfer.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import PullProgram
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import LANE, PullShards, _round_up, build_pull_shards
from lux_tpu.ops import segment
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked
from lux_tpu.parallel.placement import halo_reduce_scatter
from lux_tpu.parallel.ring import _RingArrView


class ScatterArrays(NamedTuple):
    """Chip q's view: for each destination part p, the edges from q's own
    sources into p.  Shapes (R = number of built chips, all P or a host's
    subset; B = e_bucket_pad):
      src_local: (R, P, B) int32  source index within MY resident block
                 (axis 1 = destination part p)
      dst_local: (R, P, B) int32  p-LOCAL destination index; padding holds V
      head_flag: (R, P, B) bool   destination-segment starts (first padding
                 slot flagged, see ring.mark_bucket_heads)
      weights:   (R, P, B) float32

    No per-bucket (V+1) row_ptr — dense offsets are O(P^2 * V)
    (SURVEY.md §7.3); dst_local + head_flag give the same segmentation in
    O(bucket edges) via segment_reduce_by_ends.
    """

    src_local: np.ndarray
    dst_local: np.ndarray
    head_flag: np.ndarray
    weights: np.ndarray


@dataclasses.dataclass
class ScatterShards:
    pull: PullShards
    sarrays: ScatterArrays
    e_bucket_pad: int
    #: chip (source-owner) indices materialized in sarrays' leading axis
    parts_subset: list

    @property
    def spec(self):
        return self.pull.spec

    @property
    def arrays(self):
        return self.pull.arrays

    @property
    def cuts(self):
        return self.pull.cuts

    def scatter_to_global(self, stacked):
        return self.pull.scatter_to_global(stacked)


def build_scatter_shards(
    g: HostGraph, num_parts: int, parts_subset=None, pull=None,
    counts=None, placement=None, host: int = 0,
) -> ScatterShards:
    """Transposed bucket build: axis 0 = SOURCE owner q (the chip that
    stores and computes the bucket), axis 1 = destination part p.
    ``parts_subset`` selects which chips' rows to materialize (per-host
    builds hold O(their edges), not O(ne)).  Pass an existing ``pull``
    build (e.g. sharded_load.load_pull_shards) to avoid repartitioning,
    and/or precomputed ``bucket_counts`` to skip an extra O(ne) pass.
    ``placement``/``host`` derive the subset from a PlacementTree slice."""
    if placement is not None:
        assert parts_subset is None, "pass placement OR parts_subset"
        assert placement.num_parts == num_parts, (
            placement.num_parts, num_parts)
        parts_subset = placement.parts_of(host)
    from lux_tpu.parallel.ring import (
        _owner_split,
        _slice_dst_local,
        bucket_counts,
        mark_bucket_heads,
    )

    pull = pull if pull is not None else build_pull_shards(g, num_parts)
    spec, cuts = pull.spec, pull.cuts
    Pn, V = num_parts, spec.nv_pad
    counts = counts if counts is not None else bucket_counts(g, cuts, Pn)
    B = _round_up(max(1, int(counts.max())), LANE)

    rows = list(range(Pn) if parts_subset is None else parts_subset)
    row_of = {q: i for i, q in enumerate(rows)}
    src_local = np.zeros((len(rows), Pn, B), np.int32)
    dst_local = np.full((len(rows), Pn, B), V, np.int32)
    head_flag = np.zeros((len(rows), Pn, B), bool)
    weights = np.zeros((len(rows), Pn, B), np.float32)
    from lux_tpu.parallel.ring import _native_bucket_fill_ok, native_bucket_fill

    row_map = np.full(Pn, -1, np.int64)
    for q in rows:
        row_map[q] = row_of[q]
    for p in range(Pn):  # destination part: one slice scan, split by owner
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        elo, ehi = int(g.row_ptr[vlo]), int(g.row_ptr[vhi])
        w_in = None if g.weights is None else np.asarray(g.weights[elo:ehi])
        if _native_bucket_fill_ok(w_in) and native_bucket_fill(
            np.asarray(g.col_idx[elo:ehi]),
            np.asarray(g.row_ptr[vlo : vhi + 1]), w_in, cuts, B,
            # transposed layout: owner q's bucket for destination p lives
            # at flat row_of[q]*(Pn*B) + p*B — base the views at column p
            row_map, Pn * B,
            src_local.reshape(-1)[p * B :],
            dst_local.reshape(-1)[p * B :],
            head_flag.view(np.uint8).reshape(-1)[p * B :],
            weights.reshape(-1)[p * B :],
        ):
            continue
        srcs = np.asarray(g.col_idx[elo:ehi]).astype(np.int64)
        dl_slice = _slice_dst_local(g, vlo, vhi)
        order, _ = _owner_split(srcs, cuts)
        splits = np.split(order, np.cumsum(counts[p])[:-1])
        for q in rows:  # source owner — only this host's chips materialize
            i = row_of[q]
            eids = splits[q]
            m = len(eids)
            src_local[i, p, :m] = (srcs[eids] - cuts[q]).astype(np.int32)
            dl = dl_slice[eids]
            dst_local[i, p, :m] = dl
            mark_bucket_heads(head_flag[i, p], dl)
            if w_in is not None:
                weights[i, p, :m] = w_in[eids].astype(np.float32)
    return ScatterShards(
        pull=pull,
        sarrays=ScatterArrays(src_local, dst_local, head_flag, weights),
        e_bucket_pad=B,
        parts_subset=rows,
    )


@lru_cache(maxsize=64)
def _compile_scatter_fixed(prog, mesh, num_parts: int, num_iters: int,
                           method: str, route_static=None,
                           interpret: bool = False):
    assert prog.reduce == "sum", (
        "reduce_scatter exchange requires a sum-reducible program; "
        "use the ring or all_gather drivers for min/max"
    )
    assert not getattr(prog, "needs_dst_state", False), (
        "program reads destination state per edge (e.g. CF's error term); "
        "pre-combined reduce_scatter cannot supply it — use ring/all_gather"
    )

    routed = route_static is not None
    in_specs = (
        ScatterArrays(*([P(PARTS_AXIS)] * len(ScatterArrays._fields))),
        P(PARTS_AXIS),  # vtx_mask
        P(PARTS_AXIS),  # degree
        P(PARTS_AXIS),  # state
    )
    kw = {}
    if routed:
        in_specs = in_specs + (P(PARTS_AXIS),)  # (P, P_dst, ...) plans
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(PARTS_AXIS),
        **kw,
    )
    def run(sarr_blk, vtx_mask_blk, degree_blk, state_blk, *route_blk):
        # k = P/D resident source parts per device (k == 1 when parts ==
        # devices) — the leading axis of every block, like the ring/dist
        # engines.  Lane j holds global source part dev*k + j.
        k = state_blk.shape[0]

        def iteration(_, local):  # local: (k, V, ...)
            V = local.shape[1]

            def partial_for(p):
                # partials into destination part p from ALL my resident
                # source parts, pre-summed before the collective (legal:
                # sum programs only — the assert above)
                def lane(loc, src, w, hf, dl, ra=None):
                    # dst_state unavailable pre-combination (remote);
                    # sum programs don't use it
                    if ra is not None:
                        from lux_tpu.ops import expand as _expand

                        src_vals = _expand.apply_expand(
                            loc, route_static, ra, interpret=interpret)
                    else:
                        src_vals = loc[src]
                    vals = prog.edge_value(src_vals, w, None)
                    return segment.segment_reduce_by_ends(
                        vals, hf, dl, V, reduce="sum", method=method,
                    )

                if routed:
                    return jax.vmap(lane)(
                        local, sarr_blk.src_local[:, p],
                        sarr_blk.weights[:, p], sarr_blk.head_flag[:, p],
                        sarr_blk.dst_local[:, p],
                        jax.tree.map(lambda a: a[:, p], route_blk[0]),
                    ).sum(axis=0)
                return jax.vmap(lane)(
                    local, sarr_blk.src_local[:, p], sarr_blk.weights[:, p],
                    sarr_blk.head_flag[:, p], sarr_blk.dst_local[:, p],
                ).sum(axis=0)

            partials = jnp.stack(
                [partial_for(p) for p in range(num_parts)]
            )  # (P, V, ...)
            # the placement tree's reduce-scatter halo leg: device d gets
            # its k resident parts' summed destination blocks
            acc = halo_reduce_scatter(partials, k)
            return jax.vmap(
                lambda loc, a, vm, dg: prog.apply(
                    loc, a, _RingArrView(vtx_mask=vm, degree=dg)
                )
            )(local, acc, vtx_mask_blk, degree_blk)

        return jax.lax.fori_loop(0, num_iters, iteration, state_blk)

    return run


def run_pull_fixed_scatter(
    prog: PullProgram,
    shards: ScatterShards,
    state0,
    num_iters: int,
    mesh: Mesh,
    method: str = "auto",
    route=None,
):
    """Distributed fixed-iteration pull with reduce_scatter exchange.
    P may be any multiple of the mesh size (k parts resident per device,
    like the ring/dist drivers).  ``route``
    (plan_scatter_route_shards) replays each bucket's resident-block
    gather as routed lane shuffles — bitwise-identical."""
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    spec = shards.spec
    assert spec.num_parts % mesh.devices.size == 0, (
        spec.num_parts, mesh.shape,
    )
    assert len(shards.parts_subset) == spec.num_parts, (
        "subset-built scatter shards: assemble the full stacked arrays "
        "across hosts (multihost.assemble_global) before driving"
    )
    assert method in ("scan", "scatter"), (
        segment.BUCKETED_METHODS_NOTE
    )
    sarrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.sarrays))
    vtx_mask = shard_stacked(mesh, jnp.asarray(shards.arrays.vtx_mask))
    degree = shard_stacked(mesh, jnp.asarray(shards.arrays.degree))
    state0 = shard_stacked(mesh, state0)
    if route is None:
        run = _compile_scatter_fixed(prog, mesh, spec.num_parts, num_iters,
                                     method)
        return run(sarrays, vtx_mask, degree, state0)
    from lux_tpu.parallel.mesh import routed_run_args

    rs, ra, interp = routed_run_args(mesh, route)
    run = _compile_scatter_fixed(prog, mesh, spec.num_parts, num_iters,
                                 method, route_static=rs, interpret=interp)
    return run(sarrays, vtx_mask, degree, state0, ra)
