"""Distributed pull engine with the Pallas one-hot MXU reduce.

The single-chip Pallas path (models.pagerank.make_pallas_runner) covers
the bench; this module makes ``method=pallas`` a first-class DISTRIBUTED
strategy: the same per-iteration contract as parallel.dist (all_gather
the state over ICI, reduce locally, write only the own slice — the
reference's whole-region read at core/pull_model.inl:454-461) but the
per-destination reduction is the block-CSR one-hot contraction
(ops.pallas_spmv) instead of an XLA segmented reduce.  On TPU the XLA
scatter serializes (measured 264 ms/iter at rmat20/ef16 — docs/PERF.md),
so the MXU kernel is the scalable dense-round reduce.

Scope: sum-reduce programs whose ``edge_value`` is elementwise in
(src_state, weight) — PageRank and weighted-sum programs.  CF needs the
destination state per edge (error term) and keeps its dedicated 2-D
kernel path.

Host layout: each part's padded vertex range (nv_pad, the stacked-shard
row) is tiled into v_blk-wide blocks; every part gets the same
num_vblocks and chunk count (padded with no-op chunks: dst_rel == v_blk
matches no one-hot row), so the per-part arrays stack into (P, C, T)
and shard over the mesh like every other engine's.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import build_pull_shards, ShardSpec, stacked_to_global
from lux_tpu.ops import pallas_spmv as ps
from lux_tpu.parallel.mesh import PARTS_AXIS, flatten_gather, shard_stacked


class PallasArrays(NamedTuple):
    """Stacked (P, ...) device arrays for the distributed Pallas pull."""

    e_src_pos: Any  # (P, C, T) int32 — gathered-coordinate sources
    e_dst_rel: Any  # (P, C, T) int32 — dst - block_base; v_blk == padding
    e_weight: Any  # (P, C, T) float32 (zeros when unweighted)
    chunk_block: Any  # (P, C) int32
    chunk_first: Any  # (P, C) int32
    global_vid: Any  # (P, V) int32   — vertex view for init/apply
    degree: Any  # (P, V) int32
    vtx_mask: Any  # (P, V) bool


@dataclasses.dataclass
class PallasParts:
    spec: ShardSpec
    cuts: np.ndarray
    num_vblocks: int
    v_blk: int
    t_chunk: int
    arrays: PallasArrays

    def scatter_to_global(self, stacked: np.ndarray) -> np.ndarray:
        return stacked_to_global(self.cuts, stacked)


class _LocalView:
    """The HostGraph surface build_blockcsr reads, for ONE part's padded
    row: local row_ptr over the full nv_pad domain (empty tail rows) and
    gathered-coordinate sources."""

    def __init__(self, row_ptr, nv, weights):
        self.row_ptr = row_ptr
        self.nv = nv
        self.weights = weights

    def dst_of_edges(self) -> np.ndarray:
        return np.repeat(
            np.arange(self.nv, dtype=np.int64), np.diff(self.row_ptr)
        )


def build_pallas_parts(
    g: HostGraph,
    num_parts: int,
    v_blk: Optional[int] = None,
    t_chunk: Optional[int] = None,
    base=None,
) -> PallasParts:
    """Partition + block-CSR re-layout for the distributed Pallas pull.

    Reuses the edge-balanced shard geometry (same cuts/padding as
    build_pull_shards, so states are interchangeable across engines).
    ``base`` optionally supplies already-built pull shards (the push
    variant shares them with its CSR layout instead of re-partitioning).
    """
    if base is None:
        base = build_pull_shards(g, num_parts)
    spec, cuts, arr = base.spec, base.cuts, base.arrays
    kw = {}
    if v_blk:
        kw["v_blk"] = v_blk
    if t_chunk:
        kw["t_chunk"] = t_chunk

    parts = []
    for p in range(num_parts):
        rp = arr.row_ptr[p].astype(np.int64)
        m = int(rp[-1])
        w = arr.weights[p][:m] if spec.weighted else None
        view = _LocalView(rp, spec.nv_pad, w)
        parts.append(
            ps.build_blockcsr(view, src_pos=arr.src_pos[p][:m], **kw)
        )

    nb = parts[0].num_vblocks
    vb, tc = parts[0].v_blk, parts[0].t_chunk
    c_max = max(bc.num_chunks for bc in parts)
    P_ = num_parts
    e_src = np.zeros((P_, c_max, tc), np.int32)
    e_dst = np.full((P_, c_max, tc), vb, np.int32)
    # unweighted graphs carry a broadcastable (P,1,1) zero placeholder —
    # PageRank-style edge_values ignore it and HBM never holds an O(E)
    # zero array (preflight counts the weight term only when weighted)
    e_w = (
        np.zeros((P_, c_max, tc), np.float32)
        if spec.weighted
        else np.zeros((P_, 1, 1), np.float32)
    )
    cb = np.zeros((P_, c_max), np.int32)
    cf = np.zeros((P_, c_max), np.int32)
    for p, bc in enumerate(parts):
        c = bc.num_chunks
        e_src[p, :c] = bc.e_src_pos
        e_dst[p, :c] = bc.e_dst_rel
        if bc.e_weight is not None:
            e_w[p, :c] = bc.e_weight
        cb[p, :c] = bc.chunk_block
        cf[p, :c] = bc.chunk_first
        # padding chunks: keep routing to the last real block with no
        # first-flag — the kernel accumulates nothing (dst == v_blk)
        cb[p, c:] = bc.chunk_block[-1] if c else 0

    arrays = PallasArrays(
        e_src_pos=e_src,
        e_dst_rel=e_dst,
        e_weight=e_w,
        chunk_block=cb,
        chunk_first=cf,
        global_vid=arr.global_vid,
        degree=arr.degree,
        vtx_mask=arr.vtx_mask,
    )
    return PallasParts(
        spec=spec, cuts=cuts, num_vblocks=nb, v_blk=vb, t_chunk=tc,
        arrays=arrays,
    )


def init_state_pallas(prog, pp: PallasParts) -> jnp.ndarray:
    """Stacked (P, V) initial state (same contract as pull.init_state)."""
    return jax.vmap(prog.init_state)(
        jnp.asarray(pp.arrays.global_vid),
        jnp.asarray(pp.arrays.degree),
        jnp.asarray(pp.arrays.vtx_mask),
    )


def _guard(prog):
    if prog.reduce != "sum" or getattr(prog, "needs_dst_state", False):
        raise ValueError(
            "pallas distributed pull: sum-reduce programs without "
            "destination-state edge terms only"
        )


@lru_cache(maxsize=64)
def _compile_fixed_pallas(prog, mesh, num_iters: int, num_vblocks: int,
                          v_blk: int, nv_pad: int, interpret: bool,
                          compute_dtype: str):
    arr_specs = PallasArrays(*([P(PARTS_AXIS)] * len(PallasArrays._fields)))

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(arr_specs, P(PARTS_AXIS)),
        out_specs=P(PARTS_AXIS),
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # shard_map's vma check has no way to infer it (jax 0.9 requires
        # an explicit vma or check_vma=False for pallas under shard_map)
        check_vma=False,
    )
    def run(arr_blk, state_blk):
        arr = jax.tree.map(lambda a: a[0], arr_blk)

        def body(_, local):
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            # (C, T) gather in XLA; the kernel does the reduce on the MXU
            vals = prog.edge_value(full[arr.e_src_pos], arr.e_weight)
            acc = ps.spmv_blockcsr(
                vals, arr.e_dst_rel, arr.chunk_block, arr.chunk_first,
                op="sum", v_blk=v_blk, num_vblocks=num_vblocks,
                interpret=interpret, compute_dtype=compute_dtype,
            )[:nv_pad]
            return prog.apply(local, acc, arr)

        out = jax.lax.fori_loop(0, num_iters, body, state_blk[0])
        return out[None]

    return run


def run_pull_fixed_pallas_dist(
    prog,
    pp: PallasParts,
    state0: jnp.ndarray,
    num_iters: int,
    mesh: Mesh,
    interpret: bool = False,
):
    """Fixed-iteration distributed pull on the Pallas reduce.  ``state0``
    stacked (P, V); returns the final stacked (sharded) state."""
    _guard(prog)
    assert pp.spec.num_parts == mesh.devices.size
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, pp.arrays))
    state0 = shard_stacked(mesh, state0)
    # bf16 state programs also feed the MXU at the bf16 rate (f32
    # accumulation either way) — match the single-chip runner's contract
    compute_dtype = getattr(prog, "dtype", "float32")
    return _compile_fixed_pallas(
        prog, mesh, num_iters, pp.num_vblocks, pp.v_blk, pp.spec.nv_pad,
        interpret, compute_dtype,
    )(arrays, state0)


@lru_cache(maxsize=64)
def _compile_fixed_pallas_2d(prog, mesh, num_iters: int, num_vblocks: int,
                             v_blk: int, nv_pad: int, interpret: bool):
    arr_specs = PallasArrays(*([P(PARTS_AXIS)] * len(PallasArrays._fields)))

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(arr_specs, P(PARTS_AXIS)),
        out_specs=P(PARTS_AXIS),
        check_vma=False,  # pallas out_shape carries no vma (see above)
    )
    def run(arr_blk, state_blk):
        arr = jax.tree.map(lambda a: a[0], arr_blk)
        # per-edge destination within THIS part's padded row: dsts are
        # always local in the pull layout, so the error term's dst vector
        # gathers from the resident slice, never the exchanged buffer
        dst_local = jnp.clip(
            arr.chunk_block[:, None] * v_blk + arr.e_dst_rel, 0, nv_pad - 1
        )

        def body(_, local):
            full = jax.lax.all_gather(local, PARTS_AXIS, tiled=True)
            src_vec = full[arr.e_src_pos]  # (C, T, K)
            dst_vec = local[dst_local]
            vals = prog.edge_value(src_vec, arr.e_weight, dst_vec)
            acc = ps.spmv_blockcsr_2d(
                vals, arr.e_dst_rel, arr.chunk_block, arr.chunk_first,
                v_blk=v_blk, num_vblocks=num_vblocks, interpret=interpret,
            )[:nv_pad]
            return prog.apply(local, acc, arr)

        out = jax.lax.fori_loop(0, num_iters, body, state_blk[0])
        return out[None]

    return run


@dataclasses.dataclass
class PushPallasShards:
    """Push-engine layout whose DENSE rounds reduce on the Pallas kernel:
    the sparse-round CSR/queues come from build_push_shards, the dense
    rounds use the block-CSR chunk arrays (gathered-coordinate sources)
    instead of the pull layout's O(E) stacked arrays — the per-part hot
    loop the reference tunes in components_gpu.cu:85-130, on the VPU/MXU.
    """

    push: Any  # PushShards (pspec, spec, cuts, parrays, arrays)
    pl: PallasArrays
    num_vblocks: int
    v_blk: int
    t_chunk: int

    @property
    def spec(self):
        return self.push.spec

    @property
    def pspec(self):
        return self.push.pspec

    @property
    def cuts(self):
        return self.push.cuts

    @property
    def pull(self):
        return self.push.pull

    def scatter_to_global(self, stacked: np.ndarray) -> np.ndarray:
        return self.push.scatter_to_global(stacked)


def build_push_pallas_shards(
    g: HostGraph,
    num_parts: int,
    v_blk: Optional[int] = None,
    t_chunk: Optional[int] = None,
    cuts=None,
) -> PushPallasShards:
    """Push shards + the block-CSR dense-round layout, sharing one
    edge-balanced partitioning (states interchangeable with every other
    push engine)."""
    from lux_tpu.graph.push_shards import build_push_shards

    push_sh = build_push_shards(g, num_parts, cuts=cuts)
    pp = build_pallas_parts(
        g, num_parts, v_blk=v_blk, t_chunk=t_chunk, base=push_sh.pull
    )
    return PushPallasShards(
        push=push_sh, pl=pp.arrays, num_vblocks=pp.num_vblocks,
        v_blk=pp.v_blk, t_chunk=pp.t_chunk,
    )


@lru_cache(maxsize=64)
def _compile_push_pallas(prog, mesh, pspec, spec, num_vblocks: int,
                         v_blk: int, interpret: bool):
    """Direction-optimizing push whose dense rounds run the Pallas min/max
    reduce: same sparse-round queue exchange + global mode predicate as
    push._compile_push_dist (the shared _spmd_push_iter body); the dense
    branch all_gathers the state and reduces each part's in-edges with the
    masked-VPU one-hot kernel instead of an XLA segmented reduce."""
    from lux_tpu.engine import push as pe
    from lux_tpu.graph.push_shards import PushArrays

    pl_specs = PallasArrays(*([P(PARTS_AXIS)] * len(PallasArrays._fields)))
    parr_specs = PushArrays(*([P(PARTS_AXIS)] * len(PushArrays._fields)))
    view_specs = pe.VertexView(*([P(PARTS_AXIS)] * len(pe.VertexView._fields)))
    carry_specs = pe._carry_specs()

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(pl_specs, parr_specs, view_specs, carry_specs, P()),
        out_specs=carry_specs,
        check_vma=False,  # pallas out_shape carries no vma (see above)
    )
    def run(pl_blk, parr_blk, view_blk, carry_blk, it_stop):
        # the pallas push engine keeps one part per device (driver asserts
        # P == mesh size): blocks carry a unit lane axis
        pl = jax.tree.map(lambda a: a[0], pl_blk)
        op = jnp.minimum if prog.reduce == "min" else jnp.maximum

        def dense_fn(block):  # (1, V)
            local = block[0]
            full = flatten_gather(block)
            # (C, T) gather + relax in XLA; dtype-preserving kernel reduce
            vals = prog.relax(full[pl.e_src_pos], pl.e_weight)
            acc = ps.spmv_blockcsr(
                vals, pl.e_dst_rel, pl.chunk_block, pl.chunk_first,
                op=prog.reduce, v_blk=v_blk, num_vblocks=num_vblocks,
                interpret=interpret,
            )[: spec.nv_pad]
            mask = view_blk.vtx_mask[0]
            return jnp.where(mask, op(local, acc), local)[None]

        def cond(c):
            return (c.active > 0) & (c.it < it_stop)

        def body(c):
            return pe._spmd_push_iter(
                prog, pspec, spec, parr_blk, view_blk, dense_fn, c
            )

        return jax.lax.while_loop(cond, body, carry_blk)

    return run


def run_push_pallas_dist(
    prog,
    shards: PushPallasShards,
    mesh: Mesh,
    max_iters: int = 10_000,
    interpret: bool = False,
):
    """Distributed push driver with Pallas dense rounds (min/max frontier
    programs: SSSP/CC).  Only the block-CSR chunks, the sparse CSR, and
    the O(V) vertex view touch the devices — never the pull layout's O(E)
    stacked arrays.  Returns (stacked state, iters, edge counter)."""
    from lux_tpu.engine import push as pe

    if prog.reduce not in ("min", "max"):
        raise ValueError(
            "pallas push drives min/max frontier programs; sum programs "
            "use the pull engines"
        )
    spec, pspec = shards.spec, shards.pspec
    assert spec.num_parts == mesh.devices.size
    pl = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.pl))
    parrays = shard_stacked(
        mesh, jax.tree.map(jnp.asarray, shards.push.parrays)
    )
    view_h = jax.tree.map(jnp.asarray, pe.vertex_view(shards.push.arrays))
    view = shard_stacked(mesh, view_h)
    carry0 = pe.shard_carry(mesh, pe._init_carry(prog, pspec, view_h))
    run = _compile_push_pallas(
        prog, mesh, pspec, spec, shards.num_vblocks, shards.v_blk, interpret
    )
    out = run(pl, parrays, view, carry0, jnp.int32(max_iters))
    return out.state, out.it, out.edges


def run_cf_pallas_dist(
    prog,
    pp: PallasParts,
    state0: jnp.ndarray,
    num_iters: int,
    mesh: Mesh,
    interpret: bool = False,
):
    """Distributed CF on the 2-D Pallas kernel: the err·srcVec
    accumulation is a (V_BLK, T) x (T, K) MXU matmul per chunk
    (colfilter_gpu.cu:85-101's role), with the (V, K) latent state
    sharded over the mesh and all-gathered per iteration."""
    if prog.reduce != "sum" or not getattr(prog, "needs_dst_state", False):
        raise ValueError(
            "pallas 2-D distributed pull is the CF shape: sum-reduce with "
            "a destination-state edge term"
        )
    if not pp.spec.weighted:
        raise ValueError("CF requires a weighted graph")
    assert pp.spec.num_parts == mesh.devices.size
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, pp.arrays))
    state0 = shard_stacked(mesh, state0)
    return _compile_fixed_pallas_2d(
        prog, mesh, num_iters, pp.num_vblocks, pp.v_blk, pp.spec.nv_pad,
        interpret,
    )(arrays, state0)
