"""PlacementTree — the one partition→(host, device, slice) map.

The reference's machine model lives in core/lux_mapper.cc: LuxMapper
discovers nodes and GPUs, then slices index launches node-major so part
p lands on GPU ``p % gpus`` of node ``p / gpus_per_node``
(lux_mapper.cc:102-140).  lux_tpu previously encoded that same layout
three times — ``multihost.local_part_range`` (host split),
``mesh.make_mesh_for_parts`` (device split), and the fleet's implicit
"one worker = one whole graph" replica assumption.  This module is the
single source of truth for all of them:

* **dist engines** (``parallel/dist.py``, ``ring.py``, ``scatter.py``,
  ``multihost.py``) take their parts_subset / mesh / halo-exchange legs
  from the tree;
* **fleet** (``serve/fleet/worker.py``, ``controller.py``, ``pod.py``)
  exchanges the SAME tree over the wire in the hello handshake, so a
  "replica" and a "mesh slice" are one object: a worker that owns
  parts [lo, hi) of an N-part graph is routed exactly like a loopback
  worker that owns all of it.

The tree is deliberately small and wire-friendly: a contiguous
part-range per host (the balanced split every layer already used),
serialized as plain JSON lists.  jax is only imported inside the mesh /
halo functions, so the fleet side (controller, wire tools) can hold and
ship trees without pulling in an accelerator runtime — the same
jax-free-leaf contract as ``fleet/wire.py`` (tools/_jaxfree.py).

Halo exchange
-------------
The two collective legs every dist engine uses live here, named for
what they move rather than which engine calls them:

* ``halo_all_gather``     — resident (k, V, ...) block → full (P*V, ...)
  gathered state (pull/push all_gather engines).  Donation-safe: the
  gathered buffer is a fresh XLA temporary; the resident block can be
  donated across iterations.
* ``halo_reduce_scatter`` — per-destination (P, V, ...) partials →
  this chip's summed (k, V, ...) block (scatter engine).  The reduction
  happens inside the collective where XLA fuses it with the transfer.

Both rely on the ``shard_stacked`` ordering invariant (device d holds
parts [d*k, (d+1)*k)); ``tiled=True`` concatenates/splits in device
order, so flattened axes are in global part order.  LUX-J3 audits both
legs (analysis/ir/targets.py "placement/halo-*").
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

#: wire-schema version for PlacementTree.to_wire (bump on layout change)
WIRE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class HostSlice:
    """One host's contiguous part range [lo, hi) of an N-part graph.

    ``devices`` is the host's local device count (0 = unknown/any): the
    fleet uses it for capacity accounting only; the dist engines size
    their local mesh from the actual jax.local_devices() at run time.
    """

    host: int
    lo: int
    hi: int
    devices: int = 0

    def __post_init__(self):
        if self.lo > self.hi or self.lo < 0:
            raise ValueError(f"bad part range [{self.lo}, {self.hi})")

    @property
    def num_parts(self) -> int:
        return self.hi - self.lo

    @property
    def parts(self) -> range:
        return range(self.lo, self.hi)

    def to_wire(self) -> Dict:
        return {"host": self.host, "lo": self.lo, "hi": self.hi,
                "devices": self.devices}

    @classmethod
    def from_wire(cls, d: Dict) -> "HostSlice":
        return cls(host=int(d["host"]), lo=int(d["lo"]), hi=int(d["hi"]),
                   devices=int(d.get("devices", 0)))


@dataclasses.dataclass(frozen=True)
class PlacementTree:
    """How ``num_parts`` graph partitions map onto hosts (and, within a
    host, onto devices via ``local_mesh``).  Slices are contiguous,
    ordered, and tile [0, num_parts) exactly — checked at construction
    so a tree received over the wire cannot describe overlapping or
    gapped ownership."""

    num_parts: int
    slices: Tuple[HostSlice, ...]

    def __post_init__(self):
        if self.num_parts < 1:
            raise ValueError(f"num_parts must be >= 1, got {self.num_parts}")
        if not self.slices:
            raise ValueError("placement tree needs at least one host slice")
        cursor = 0
        for i, s in enumerate(self.slices):
            if s.host != i:
                raise ValueError(
                    f"slice {i} carries host id {s.host}; hosts must be "
                    "dense 0..H-1 in slice order")
            if s.lo != cursor:
                raise ValueError(
                    f"host {i} starts at part {s.lo}, expected {cursor}: "
                    "slices must tile [0, num_parts) contiguously")
            cursor = s.hi
        if cursor != self.num_parts:
            raise ValueError(
                f"slices cover [0, {cursor}) but num_parts={self.num_parts}")

    # ---------------------------------------------------------- build
    @classmethod
    def build(cls, num_parts: int, num_hosts: int = 1,
              devices_per_host: int = 0) -> "PlacementTree":
        """Balanced node-major split: the first ``num_parts % num_hosts``
        hosts take one extra part (the historical
        ``multihost.local_part_range`` arithmetic, now defined once).
        Hosts beyond ``num_parts`` get empty slices rather than erroring
        so a fixed fleet can serve a small graph."""
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        base, extra = divmod(num_parts, num_hosts)
        slices = []
        for h in range(num_hosts):
            lo = h * base + min(h, extra)
            hi = lo + base + (1 if h < extra else 0)
            slices.append(HostSlice(host=h, lo=lo, hi=hi,
                                    devices=devices_per_host))
        return cls(num_parts=num_parts, slices=tuple(slices))

    @classmethod
    def single_host(cls, num_parts: int,
                    devices: int = 0) -> "PlacementTree":
        """The degenerate tree every existing single-host path implies."""
        return cls.build(num_parts, 1, devices)

    # ---------------------------------------------------------- lookup
    @property
    def num_hosts(self) -> int:
        return len(self.slices)

    def parts_of(self, host: int) -> range:
        """Part indices host ``host`` owns."""
        return self.slices[host].parts

    def host_of(self, part: int) -> int:
        """Which host owns ``part`` (binary search over slice bounds)."""
        if not 0 <= part < self.num_parts:
            raise IndexError(f"part {part} outside [0, {self.num_parts})")
        lo, hi = 0, len(self.slices) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if part >= self.slices[mid].hi:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def slice_of(self, host: int) -> HostSlice:
        return self.slices[host]

    # ------------------------------------------------------------ wire
    def to_wire(self) -> Dict:
        """JSON-safe dict for the fleet hello handshake / pod ops."""
        return {
            "version": WIRE_VERSION,
            "num_parts": self.num_parts,
            "slices": [s.to_wire() for s in self.slices],
        }

    @classmethod
    def from_wire(cls, d: Dict) -> "PlacementTree":
        v = int(d.get("version", 1))
        if v > WIRE_VERSION:
            raise ValueError(
                f"placement tree wire version {v} > supported "
                f"{WIRE_VERSION}")
        return cls(
            num_parts=int(d["num_parts"]),
            slices=tuple(HostSlice.from_wire(s) for s in d["slices"]),
        )

    # ------------------------------------------------------------ mesh
    def mesh(self, devices: Optional[Sequence] = None):
        """Global 1-D parts mesh for this tree (all hosts' devices when
        jax.distributed is live, or the local devices on a virtual
        mesh).  Delegates to ``make_mesh_for_parts`` so k = P/D parts
        stay resident per device when parts exceed devices."""
        from lux_tpu.parallel.mesh import make_mesh_for_parts

        return make_mesh_for_parts(self.num_parts, devices)

    def local_mesh(self, host: int, devices: Optional[Sequence] = None):
        """Mesh over ONE host's slice — what a pod worker runs its local
        lanes on (parts [lo, hi) resident, k = slice/D per device)."""
        n = self.slices[host].num_parts
        if n == 0:
            raise ValueError(f"host {host} owns no parts")
        from lux_tpu.parallel.mesh import make_mesh_for_parts

        return make_mesh_for_parts(n, devices)


def local_tree(num_parts: int) -> PlacementTree:
    """The tree for the CURRENT jax multi-process runtime (process-count
    hosts; single-host tree when jax.distributed was never initialized).
    """
    import jax

    return PlacementTree.build(
        num_parts, jax.process_count(),
        devices_per_host=jax.local_device_count())


# ---------------------------------------------------------------- halo
def halo_all_gather(block):
    """all_gather a (k, V, ...) resident block over the parts axis and
    flatten to the (P*V, ...) gathered-coordinate state.  Must run
    inside a shard_map body on a parts mesh whose inputs were placed by
    ``shard_stacked`` — that placement IS the ordering invariant:
    device d holds parts [d*k, (d+1)*k), and tiled=True concatenates in
    device order, so the flattened axis is in global part order."""
    import jax

    from lux_tpu.parallel.mesh import PARTS_AXIS

    full = jax.lax.all_gather(block, PARTS_AXIS, tiled=True)
    return full.reshape((-1,) + full.shape[2:])


def halo_reduce_scatter(partials, k: int):
    """Sum (P, V, ...) per-destination partials across chips and hand
    this chip its own (k, V, ...) destination block.  Only SUM-reducible
    programs qualify (XLA's fused reduce-scatter is addition) — callers
    assert prog.reduce == "sum".  Same shard_stacked ordering contract
    as ``halo_all_gather``: tiled psum_scatter over D devices hands
    device d the contiguous [d*k*V, (d+1)*k*V) slice = its k resident
    parts' summed destinations."""
    import jax

    from lux_tpu.parallel.mesh import PARTS_AXIS

    P, V = partials.shape[0], partials.shape[1]
    flat = partials.reshape((P * V,) + partials.shape[2:])
    return jax.lax.psum_scatter(
        flat, PARTS_AXIS, scatter_dimension=0, tiled=True
    ).reshape((k, V) + partials.shape[2:])
