"""luxwire-trace: fleet-wide distributed request tracing (host half).

PR 6's flight recorder attributes time WITHIN one process; the fleet
built since (controller/workers, the live write path, failover) crosses
process and wire boundaries where a request's latency was invisible —
p99 rose and nothing said whether it was queue wait, wire, engine,
catch-up stream or a retry.  This module is the Dapper-shaped answer:

* a :class:`TraceContext` — ``(trace_id, span_id, parent_span_id,
  flags)`` — minted at the fleet entry points (``submit`` /
  ``admit_writes`` / ``takeover`` / ``republish``), carried on every
  fleet frame as a compact ``tc`` header, and propagated client ->
  controller -> worker -> replica and through retries, failovers,
  catch-up streams and the two-phase republish;
* every hop records ordinary luxtrace spans INTO ITS OWN per-process
  event log with the context as span attrs (``trace``/``span``/
  ``parent_span``) — no collector, no extra wire traffic: the log files
  a run already writes ARE the trace store, and ``tools/luxstitch.py``
  merges them into one causally-ordered fleet timeline;
* the wire layer stamps ``dtrace.send``/``dtrace.recv`` points for
  every traced frame — the (send, recv) pairs luxstitch uses to correct
  per-process clock skew (same-host CLOCK_MONOTONIC is shared, but
  multi-machine workers — ROADMAP item 2's next step — are not).

**Identity is deterministic where retries need it to be**: a context
minted from a key (the client ``request_id``, a write's ``write_id``)
derives its trace id — and its ROOT span id — from a keyed blake2b, so
a client retrying the same logical request against a PROMOTED
controller lands in the SAME trace: the kill-mid-write drill's original
attempt, the failover takeover, the re-hello and the dedup-acked replay
stitch into one timeline because their ids were never random.

**Cost contract**: one ``None`` check when disabled (``LUX_DTRACE=0``);
a sampled context costs two hashes at mint + a handful of JSONL lines
per hop.  ``LUX_DTRACE_SAMPLE`` (0..1) head-samples at the root — an
unsampled context still PROPAGATES (flags bit clear) so a downstream
hop never half-records a trace, it just stays silent.  The sampling
decision is derived from the trace id, so every process of the fleet
agrees on it without coordination.

Pure stdlib, like the recorder: the stitch/view tools load event logs
jax-free, and the controller process never imports jax.
"""
from __future__ import annotations

import hashlib
import itertools
import os
import threading
from typing import Optional

# NOTE: importing the ``recorder`` MODULE through the package would
# resolve to the package attribute of that name — the singleton
# accessor FUNCTION re-exported by __init__ — so pull the three
# needed symbols straight from the submodule instead
from lux_tpu.obs.recorder import point as _point
from lux_tpu.obs.recorder import recorder as _recorder_fn
from lux_tpu.obs.recorder import span as _span

ENABLE_ENV = "LUX_DTRACE"
SAMPLE_ENV = "LUX_DTRACE_SAMPLE"

#: flags bit 0: this trace is sampled (hops record spans/points)
FLAG_SAMPLED = 1

_STATE_LOCK = threading.Lock()
#: tri-state override: None = follow the env, True/False = forced (the
#: trace-overhead probe flips this mid-run; tests scope it)
_FORCED: Optional[bool] = None


def enabled() -> bool:
    """Tracing master switch: ``LUX_DTRACE`` (default on; ``0``/``off``
    disables minting entirely — frames carry no header, hops cost one
    ``None`` check).  ``set_enabled`` overrides the env for the
    process (the overhead probe's A/B lever)."""
    with _STATE_LOCK:
        forced = _FORCED
    if forced is not None:
        return forced
    return os.environ.get(ENABLE_ENV, "1").strip().lower() not in (
        "0", "off", "false")


def set_enabled(value: Optional[bool]) -> None:
    """Force tracing on/off for this process (None = back to the env).
    Locked: the saturation bench's overhead probe flips it between
    closed-loop slices while worker threads are serving."""
    global _FORCED
    with _STATE_LOCK:
        _FORCED = value


def sample_rate() -> float:
    """Root head-sampling probability, ``LUX_DTRACE_SAMPLE`` in [0, 1]
    (default 1.0 — every request traced; a million-user fleet dials
    this down and keeps the deterministic keyed traces reproducible)."""
    from lux_tpu.utils.config import env_float

    return env_float(SAMPLE_ENV, 1.0, minimum=0.0, maximum=1.0)


def _hex_hash(text: str, nbytes: int) -> str:
    return hashlib.blake2b(text.encode("utf-8"),
                           digest_size=nbytes).hexdigest()


#: unkeyed ids: a per-process random prefix + an atomic counter —
#: unique across the fleet's processes without an os.urandom syscall
#: per id (ids are metadata, like run ids; never results — LUX-D003's
#: concern is engine determinism, and these never feed it)
_ID_PREFIX = os.urandom(4).hex()
_ID_SEQ = itertools.count(1)


def _next_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_SEQ) & 0xFFFFFFFF:08x}"


def _sampled_for(trace_id: str, rate: float) -> bool:
    """Deterministic per-trace sampling decision: hash the trace id
    into [0, 1) and compare — every process (and every RETRY of a
    keyed trace) agrees without coordination, and no process-global
    RNG is consulted (LUX-D003)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    draw = int(hashlib.blake2b(trace_id.encode("utf-8"),
                               digest_size=8).hexdigest(), 16)
    return (draw / float(1 << 64)) < rate


class TraceContext:
    """One position in one trace: the header a fleet frame carries.

    ``trace_id`` names the logical request end to end; ``span_id``
    names THIS hop's span; ``parent_span_id`` is the causal link the
    stitcher follows.  Contexts are immutable — ``child()`` mints the
    next hop."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "flags")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None,
                 flags: int = FLAG_SAMPLED):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_span_id = (None if parent_span_id is None
                               else str(parent_span_id))
        self.flags = int(flags)

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    def child(self) -> "TraceContext":
        """The next hop: fresh span id, this span as parent, same trace
        and flags."""
        return TraceContext(self.trace_id, _next_id(),
                            parent_span_id=self.span_id,
                            flags=self.flags)

    # -- wire form ------------------------------------------------------

    def to_wire(self) -> dict:
        out = {"t": self.trace_id, "s": self.span_id, "f": self.flags}
        if self.parent_span_id is not None:
            out["p"] = self.parent_span_id
        return out

    @classmethod
    def from_wire(cls, d) -> Optional["TraceContext"]:
        if not isinstance(d, dict) or "t" not in d or "s" not in d:
            return None
        return cls(d["t"], d["s"], d.get("p"), int(d.get("f", 0)))

    def attrs(self) -> dict:
        """The span-attr triple every traced hop records — what
        luxstitch keys the cross-process links on."""
        out = {"trace": self.trace_id, "span": self.span_id}
        if self.parent_span_id is not None:
            out["parent_span"] = self.parent_span_id
        return out

    def __repr__(self) -> str:  # drill failure reports print these
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_span_id} f={self.flags})")


def mint(key: Optional[str] = None) -> Optional[TraceContext]:
    """A ROOT context, or None when tracing is disabled.

    ``key`` (a request_id / ``w:<write_id>``) derives trace AND root
    span ids deterministically, so every retry of one logical request —
    across attempts, envelopes, and controller incarnations — is ONE
    trace.  ``key=None`` mints random ids (an untraceable one-off)."""
    if not enabled():
        return None
    if key is not None:
        trace_id = _hex_hash(f"lux:{key}", 8)
        span_id = _hex_hash(f"lux:{key}/root", 6)
    else:
        trace_id = _next_id()
        span_id = _next_id()
    flags = FLAG_SAMPLED if _sampled_for(trace_id, sample_rate()) else 0
    return TraceContext(trace_id, span_id, flags=flags)


def incident(key: str) -> Optional[TraceContext]:
    """A keyed ROOT context for an operational INCIDENT (ISSUE 16): an
    autopilot scale action, a controller election, a policy-mode
    switch.  Identical to ``mint(key)`` except the head-sampling
    decision is forced ON: ``LUX_DTRACE_SAMPLE`` exists to thin the
    per-REQUEST trace store, and autonomous control actions are orders
    of magnitude rarer than requests — a fleet that scaled itself or
    elected a controller must ALWAYS be able to render that incident
    as one stitched timeline, whatever the request sampling dial says.
    Still None when tracing is disabled outright (``LUX_DTRACE=0``)."""
    if not enabled():
        return None
    return TraceContext(_hex_hash(f"lux:{key}", 8),
                        _hex_hash(f"lux:{key}/root", 6),
                        flags=FLAG_SAMPLED)


def wire_ctx(msg: dict) -> Optional[TraceContext]:
    """The context a received frame carries (``msg['tc']``), or None."""
    tc = msg.get("tc")
    return TraceContext.from_wire(tc) if tc is not None else None


def child_of(msg: dict) -> Optional[TraceContext]:
    """The context THIS hop should record under: a child of the frame's
    header (the sender's span is the causal parent)."""
    ctx = wire_ctx(msg)
    return ctx.child() if ctx is not None else None


class _NullSpan:
    """No-op stand-in so call sites write one line whether or not the
    request is traced."""

    __slots__ = ()
    dur = 0.0
    ok = True

    def set(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL = _NullSpan()


def tspan(name: str, ctx: Optional[TraceContext], always: bool = False,
          **attrs):
    """An ordinary recorder span enriched with ``ctx``'s trace attrs.
    ``ctx=None`` records the plain span (existing single-process
    behavior); an UNSAMPLED context records nothing (the null span) —
    propagate silently, never half-trace.

    ``always=True`` is for OPERATIONAL spans (takeover, republish,
    delta install, hello) that predate tracing as unconditional
    recorder spans: sampling exists to thin the request-rate TRACE
    store, not the local flight recorder, so an unsampled operational
    span still records PLAIN (no trace attrs — the trace stays
    untouched) instead of vanishing from the post-mortem."""
    attrs = {k: v for k, v in attrs.items() if v is not None}
    if ctx is None:
        return _span(name, **attrs)
    if not ctx.sampled:
        return _span(name, **attrs) if always else _NULL
    return _span(name, **{**ctx.attrs(), **attrs})


def emit_span(name: str, ctx: Optional[TraceContext], t0: float,
              t1: float, ok: bool = True, **attrs) -> None:
    """Retroactive traced span (begin/end measured on different
    threads — the fleet request/attempt shape); see
    ``Recorder.emit_span`` for why this bypasses the nesting stack."""
    if ctx is None or not ctx.sampled:
        return
    attrs = {k: v for k, v in attrs.items() if v is not None}
    _recorder_fn().emit_span(name, t0, t1, ok=ok,
                             attrs={**ctx.attrs(), **attrs})


def wire_point(direction: str, tc: dict, op, peer, owner) -> None:
    """The skew-correction stamp the wire layer drops per traced frame:
    ``dtrace.send`` on the sender, ``dtrace.recv`` on the receiver,
    paired by the header's span id.  Only sampled frames stamp (bit
    check on the RAW wire dict — the hot path never builds a
    TraceContext)."""
    if not (int(tc.get("f", 0)) & FLAG_SAMPLED):
        return
    _point(f"dtrace.{direction}", trace=tc.get("t"),
           span=tc.get("s"), op=op, peer=peer, owner=owner)
