"""Declarative SLOs evaluated as multi-window burn rates — the layer
that turns the serving metrics stream into a yes/no production answer.

ROADMAP item 2 said "the Prometheus surface and flight recorder make
the SLO story measurable end to end"; this module is the SLO story
itself.  A spec is DATA (JSON round-trip, like FaultPlans and
VertexProgramSpecs):

    SLOSpec(name="reads",   kind="availability",  objective=0.999)
    SLOSpec(name="read_p99", kind="latency",      objective=0.99,
            threshold_ms=250.0)
    SLOSpec(name="fresh",   kind="staleness",     objective=0.99)
    SLOSpec(name="write_ack", kind="write_latency", objective=0.99,
            threshold_ms=500.0)

Semantics (the Google-SRE multiwindow shape):

* every observed event is GOOD or BAD per spec — ``availability``: any
  errored/timed-out/shed query is bad; ``latency``: a query slower
  than ``threshold_ms``; ``staleness``: a read served below its
  ``min_generation`` bound (the explicit stale-degrade tag);
  ``write_latency``: an admit->acked write slower than ``threshold_ms``;
* **burn rate** over a window = (bad/total in the window) / (1 -
  objective) — burn 1.0 spends the error budget exactly at the rate
  the objective allows; burn 14.4 over an hour-class window is the
  classic page threshold;
* a spec is **burning** when EVERY one of its windows exceeds its burn
  threshold (the long window proves it is real, the short window
  proves it is still happening); the verdict is ``ok`` / ``warn``
  (some window hot) / ``burning`` / ``no_data``.

**Exemplars**: each observation may carry the request's distributed
trace id (``obs/dtrace.py``); the engine keeps the most recent BAD
traces per spec — plus the WORST (slowest) observed trace as a
fallback — so a burning SLO links directly to offending timelines a
``tools/luxstitch.py`` stitch can open.  That is the whole point of
co-designing the two layers: the verdict names the traces.

Implementation: the engine snapshots its cumulative (bad, total)
counters on a min-gap cadence into a bounded ring; a window's burn is
the delta against the newest snapshot at least ``window_s`` old (or
the oldest available — a young engine reports over the span it has).
Pure stdlib: the fleet controller (which never imports jax) owns one.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

KINDS = ("availability", "latency", "staleness", "write_latency")

#: kinds whose good/bad split needs a latency threshold
_THRESHOLD_KINDS = ("latency", "write_latency")

#: default multiwindow burn thresholds: (window seconds, burn-rate
#: threshold).  Scaled-down analogs of the SRE 1h/6h pair — serving
#: windows here are minutes, not days, and tests drive them with a
#: fake clock anyway.
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = (
    (60.0, 14.4), (300.0, 6.0))

#: how many bad-event trace ids each spec retains
MAX_EXEMPLARS = 4


class SLOSpecError(ValueError):
    """Malformed spec (unknown kind, bad objective/threshold/windows)."""


class SLOSpec:
    """One declarative objective.  ``objective`` is the good fraction
    promised (0 < objective < 1); ``threshold_ms`` splits good from bad
    for the latency kinds; ``windows`` is a ((seconds, burn_threshold),
    ...) tuple — ALL windows must burn for the spec to page."""

    def __init__(self, name: str, kind: str, objective: float = 0.99,
                 threshold_ms: Optional[float] = None,
                 windows: Sequence[Sequence[float]] = DEFAULT_WINDOWS,
                 description: str = ""):
        self.name = str(name)
        self.kind = str(kind)
        self.objective = float(objective)
        self.threshold_ms = (None if threshold_ms is None
                             else float(threshold_ms))
        self.windows = tuple((float(w), float(b)) for w, b in windows)
        self.description = str(description)
        self.validate()

    def validate(self) -> None:
        if self.kind not in KINDS:
            raise SLOSpecError(
                f"unknown SLO kind {self.kind!r}; expected one of "
                f"{KINDS}")
        if not (0.0 < self.objective < 1.0):
            raise SLOSpecError(
                f"objective must be in (0, 1), got {self.objective} "
                f"(spec {self.name!r})")
        if self.kind in _THRESHOLD_KINDS and (
                self.threshold_ms is None or self.threshold_ms <= 0):
            raise SLOSpecError(
                f"{self.kind} spec {self.name!r} needs threshold_ms > 0")
        if not self.windows:
            raise SLOSpecError(f"spec {self.name!r} needs >= 1 window")
        for w, b in self.windows:
            if w <= 0 or b <= 0:
                raise SLOSpecError(
                    f"spec {self.name!r}: windows need positive "
                    f"(seconds, burn threshold), got ({w}, {b})")

    # -- data form ------------------------------------------------------

    def to_dict(self) -> dict:
        out = {"name": self.name, "kind": self.kind,
               "objective": self.objective,
               "windows": [list(w) for w in self.windows]}
        if self.threshold_ms is not None:
            out["threshold_ms"] = self.threshold_ms
        if self.description:
            out["description"] = self.description
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        known = {"name", "kind", "objective", "threshold_ms", "windows",
                 "description"}
        unknown = set(d) - known
        if unknown:
            raise SLOSpecError(
                f"unknown spec fields {sorted(unknown)} (known: "
                f"{sorted(known)})")
        if "name" not in d or "kind" not in d:
            raise SLOSpecError(f"spec needs name + kind: {d}")
        return cls(**d)


def specs_from_json(text: str) -> List[SLOSpec]:
    """A JSON list of spec objects -> [SLOSpec] (the file/env form)."""
    try:
        data = json.loads(text)
    except ValueError as e:
        raise SLOSpecError(f"bad SLO JSON: {e}") from None
    if not isinstance(data, list):
        raise SLOSpecError(f"SLO JSON must be a list of specs: {data!r}")
    return [SLOSpec.from_dict(d) for d in data]


class _SpecState:
    __slots__ = ("spec", "bad", "total", "bad_traces", "worst")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.bad = 0
        self.total = 0
        #: most recent bad-event trace ids (the offending timelines)
        self.bad_traces: "collections.deque" = collections.deque(
            maxlen=MAX_EXEMPLARS)
        #: (value, trace_id) of the worst traced observation — the
        #: exemplar of last resort, so a green latency SLO still links
        #: SOMETHING a human can open
        self.worst: Optional[Tuple[float, str]] = None


class SLOEngine:
    """Evaluates a set of :class:`SLOSpec` over an observation stream.

    Observations arrive via ``observe_query`` / ``observe_write`` (the
    fleet controller calls these from its resolve paths); ``status()``
    returns one verdict row per spec.  Thread-safe; bounded memory
    (snapshot ring capped to the longest window, exemplar deques
    capped)."""

    #: minimum seconds between counter snapshots (bounds ring growth
    #: under a hot observe stream)
    SNAPSHOT_MIN_GAP_S = 0.05

    def __init__(self, specs: Sequence[SLOSpec], clock=time.monotonic):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise SLOSpecError(f"duplicate spec names: {names}")
        self.clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, _SpecState] = {
            s.name: _SpecState(s) for s in specs}
        self._horizon_s = max(
            (w for s in specs for w, _ in s.windows), default=60.0)
        #: (t, {name: (bad, total)}) ring; capacity sized so the oldest
        #: retained snapshot always predates the longest window
        cap = max(int(self._horizon_s / self.SNAPSHOT_MIN_GAP_S) + 8, 64)
        self._snaps: "collections.deque" = collections.deque(maxlen=cap)
        self._last_snap_t: Optional[float] = None

    @property
    def specs(self) -> List[SLOSpec]:
        with self._lock:
            return [st.spec for st in self._states.values()]

    # -- the observation stream ----------------------------------------

    def observe_query(self, latency_s: Optional[float], ok: bool = True,
                      stale: bool = False,
                      trace_id: Optional[str] = None) -> None:
        """One resolved fleet query: ``ok=False`` for errors/timeouts/
        sheds (availability-bad), ``stale`` for an answer served below
        its read bound (staleness-bad), ``latency_s`` scored against
        every ``latency`` spec."""
        self._observe(("availability", "latency", "staleness"),
                      latency_s, ok=ok, stale=stale, trace_id=trace_id)

    def observe_write(self, latency_s: Optional[float], ok: bool = True,
                      trace_id: Optional[str] = None) -> None:
        """One admitted write: admit->all-acked wall time vs the
        ``write_latency`` threshold; a failed admit is bad outright."""
        self._observe(("write_latency",), latency_s, ok=ok, stale=False,
                      trace_id=trace_id)

    def _observe(self, kinds, latency_s, ok, stale, trace_id) -> None:
        now = self.clock()
        with self._lock:
            for st in self._states.values():
                spec = st.spec
                if spec.kind not in kinds:
                    continue
                if spec.kind in ("availability", "write_latency") \
                        and not ok:
                    bad = True
                elif not ok:
                    # errored queries carry no meaningful latency/
                    # staleness signal; availability owns them
                    continue
                elif spec.kind == "staleness":
                    bad = bool(stale)
                elif spec.kind in _THRESHOLD_KINDS:
                    if latency_s is None:
                        continue
                    bad = latency_s * 1e3 > spec.threshold_ms
                else:  # availability, ok event
                    bad = False
                st.total += 1
                st.bad += int(bad)
                if trace_id is not None:
                    if bad:
                        st.bad_traces.append(str(trace_id))
                    v = latency_s if latency_s is not None else 0.0
                    if st.worst is None or v > st.worst[0]:
                        st.worst = (v, str(trace_id))
            self._maybe_snapshot(now)

    def _maybe_snapshot(self, now: float) -> None:
        if (self._last_snap_t is not None
                and now - self._last_snap_t < self.SNAPSHOT_MIN_GAP_S):
            return
        self._last_snap_t = now
        self._snaps.append((now, {n: (st.bad, st.total)
                                  for n, st in self._states.items()}))

    # -- evaluation -----------------------------------------------------

    def _window_base(self, now: float, window_s: float):
        """The newest snapshot at least ``window_s`` old (or the oldest
        we have — a young engine scores over its whole life)."""
        base = None
        for t, counts in self._snaps:
            if now - t >= window_s:
                base = (t, counts)
            else:
                break
        if base is None and self._snaps:
            base = self._snaps[0]
        return base

    def status(self, now: Optional[float] = None) -> List[dict]:
        """One verdict row per spec:

        ``{name, kind, objective, threshold_ms, total, bad, windows:
        {"60s": {burn, bad, total, burning}}, verdict, exemplar_traces}``

        ``verdict``: ``no_data`` (nothing observed), ``burning`` (every
        window over its threshold), ``warn`` (some window over), else
        ``ok``."""
        now = self.clock() if now is None else now
        out: List[dict] = []
        with self._lock:
            self._maybe_snapshot(now)
            for name, st in self._states.items():
                spec = st.spec
                budget = 1.0 - spec.objective
                windows = {}
                hot = 0
                for window_s, burn_thresh in spec.windows:
                    base = self._window_base(now, window_s)
                    b0, t0 = base[1].get(name, (0, 0)) if base else (0, 0)
                    dbad, dtot = st.bad - b0, st.total - t0
                    frac = (dbad / dtot) if dtot else 0.0
                    burn = frac / budget if budget > 0 else 0.0
                    burning = bool(dtot and burn > burn_thresh)
                    hot += int(burning)
                    windows[f"{window_s:g}s"] = {
                        "burn": round(burn, 3), "bad": dbad,
                        "total": dtot, "threshold": burn_thresh,
                        "burning": burning}
                if not st.total:
                    verdict = "no_data"
                elif hot == len(spec.windows):
                    verdict = "burning"
                elif hot:
                    verdict = "warn"
                else:
                    verdict = "ok"
                exemplars = list(st.bad_traces)
                if not exemplars and st.worst is not None:
                    exemplars = [st.worst[1]]
                row = {"name": name, "kind": spec.kind,
                       "objective": spec.objective,
                       "total": st.total, "bad": st.bad,
                       "windows": windows, "verdict": verdict,
                       "exemplar_traces": exemplars}
                if spec.threshold_ms is not None:
                    row["threshold_ms"] = spec.threshold_ms
                out.append(row)
        return out

    def verdicts(self, now: Optional[float] = None) -> Dict[str, str]:
        """``{spec name: verdict}`` — the compact form the autopilot
        policy/autoscaler layers (ISSUE 16) match rules against."""
        return {r["name"]: r["verdict"] for r in self.status(now=now)}

    def prom_lines(self) -> List[str]:
        """The verdicts as Prometheus gauges (merged into the
        controller's own exposition): burn per (slo, window), and a
        0/1/2 verdict code (ok/warn/burning; no_data absent)."""
        rows = self.status()
        lines: List[str] = []
        burn_rows = [(r["name"], w, d["burn"]) for r in rows
                     for w, d in r["windows"].items() if r["total"]]
        if burn_rows:
            name = "lux_slo_burn_rate"
            lines.extend([f"# HELP {name} error-budget burn rate per "
                          "SLO window", f"# TYPE {name} gauge"])
            lines.extend(
                f'{name}{{slo="{s}",window="{w}"}} {v}'
                for s, w, v in burn_rows)
        code = {"ok": 0, "warn": 1, "burning": 2}
        verd = [(r["name"], code[r["verdict"]]) for r in rows
                if r["verdict"] in code]
        if verd:
            name = "lux_slo_verdict"
            lines.extend([f"# HELP {name} SLO verdict "
                          "(0 ok, 1 warn, 2 burning)",
                          f"# TYPE {name} gauge"])
            lines.extend(f'{name}{{slo="{s}"}} {v}' for s, v in verd)
        return lines


#: verdict severity order, mildest first — ``worst_verdict`` and the
#: autoscaler's hot/idle decision rank against this
VERDICT_ORDER = ("no_data", "ok", "warn", "burning")


def worst_verdict(rows) -> str:
    """The most severe verdict across status rows (``"no_data"`` for an
    empty set) — the one-word fleet health the autopilot layers key
    their decisions on."""
    worst = 0
    for r in rows:
        v = r.get("verdict") if isinstance(r, dict) else str(r)
        if v in VERDICT_ORDER:
            worst = max(worst, VERDICT_ORDER.index(v))
    return VERDICT_ORDER[worst]


def default_fleet_slos(read_p99_ms: float = 500.0,
                       write_ack_ms: float = 1000.0,
                       windows: Sequence[Sequence[float]] = (
                           (15.0, 10.0), (60.0, 2.0))) -> List[SLOSpec]:
    """The standing serving objectives the benches evaluate: request
    availability, read latency, read freshness, write-ack latency.
    Bench-scale windows (seconds, not hours — a bench window must fit
    inside its own run)."""
    return [
        SLOSpec("read_availability", "availability", objective=0.99,
                windows=windows,
                description="queries answered (not shed/errored/"
                            "timed out)"),
        SLOSpec("read_latency", "latency", objective=0.95,
                threshold_ms=read_p99_ms, windows=windows,
                description="queries under the latency bound"),
        SLOSpec("read_freshness", "staleness", objective=0.99,
                windows=windows,
                description="bounded reads served at-or-above their "
                            "generation bound"),
        SLOSpec("write_ack", "write_latency", objective=0.95,
                threshold_ms=write_ack_ms, windows=windows,
                description="writes journaled + replica-acked under "
                            "the bound"),
    ]
