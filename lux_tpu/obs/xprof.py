"""XProf kernel attribution: who got the device time?

``jax.profiler.start_trace`` writes a TensorBoard profile bundle under
``<dir>/plugins/profile/<run>/``; the piece this module reads is the
Chrome/Perfetto ``*.trace.json.gz`` (stdlib gzip+json — no tensorboard
or profile-proto dependency, per the no-new-deps rule).  Every complete
event ("ph" == "X") carries (name, dur µs, pid); pid metadata rows name
the device lanes, so device time separates from host threads.

The attribution question this answers is the routed-pf one: of a
window's device time, how much ran inside the ``fused_pass_gather``
Pallas kernels vs ordinary gathers/scatters vs collectives vs everything
else — the measured counterpart of the static HBM-sweep accounting
(roofline.routed_hbm_passes, audited by LUX-J5).
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, List, Optional, Tuple

#: kernel-name classification, first match wins (lowercase substrings).
#: "routed-pf" names the pass-fused Pallas family (ops/pallas_shuffle
#: fused_pass_gather + the group-reduce kernels); "route" the unfused
#: lane shuffles; collectives cover the ICI exchange.
CLASSES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("routed-pf", ("fused_pass_gather", "pass_gather", "group_reduce")),
    ("route", ("lane_gather", "lane_shuffle", "shuffle_kernel")),
    ("collective", ("all-gather", "all-reduce", "all-to-all",
                    "collective-permute", "reduce-scatter", "psum",
                    "ppermute", "allgather", "allreduce")),
    ("gather", ("gather",)),
    ("scatter", ("scatter",)),
    ("fusion", ("fusion", "loop_fusion")),
    ("copy", ("copy", "transpose", "bitcast", "memset")),
)


def classify(name: str) -> str:
    low = name.lower()
    for cls, needles in CLASSES:
        if any(n in low for n in needles):
            return cls
    return "other"


#: per-file on-disk size cap for synchronous parsing (MB).  trace() runs
#: attribution in its exit path, INSIDE the chip-day step whose timeout
#: the battery enforces — a multi-hundred-MB Perfetto bundle (gigabytes
#: decoded) must not stall or OOM the step that just finished its
#: measured work.  Oversized files are skipped and reported in the
#: emitted event; render them offline with a raised LUX_OBS_XPROF_MAX_MB.
MAX_MB_ENV = "LUX_OBS_XPROF_MAX_MB"
DEFAULT_MAX_MB = 64


def _max_bytes() -> int:
    from lux_tpu.utils.config import env_int

    try:
        mb = env_int(MAX_MB_ENV, DEFAULT_MAX_MB, minimum=1)
    except ValueError:
        mb = DEFAULT_MAX_MB
    return int(mb) * (1 << 20)


def _trace_files(trace_dir: str) -> List[str]:
    """Trace files of the NEWEST capture under ``trace_dir``.  jax's
    profiler writes one ``plugins/profile/<timestamp>/`` bundle per
    start_trace, and the apps reuse one ``--profile-dir`` across runs —
    attributing the union of history would inflate every total and mix
    runs into one frac denominator, so only the latest bundle counts."""
    runs = [d for d in glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*"))
        if os.path.isdir(d)]
    root = max(runs, key=os.path.getmtime) if runs else trace_dir
    return sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(root, "**", "*.trace.json"),
                    recursive=True))


def _load_events(path: str) -> list:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    return doc.get("traceEvents", []) if isinstance(doc, dict) else doc


def _device_pids(events: list) -> set:
    """pids whose process_name metadata looks like a device lane.  The
    tunnel-side TPU lanes name themselves '/device:TPU:0'-style; plain
    CPU traces keep XLA ops under 'TensorFlow Op'/'XLA Ops' threads —
    when nothing matches, attribution falls back to ALL pids (a host
    trace is still a real time breakdown, labeled as such by caller)."""
    pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = str(ev.get("args", {}).get("name", "")).lower()
            if any(k in name for k in ("device", "tpu", "gpu", "xla",
                                       "accelerator")):
                pids.add(ev.get("pid"))
    return pids


def kernel_table(trace_dir: str, top: int = 0,
                 skipped: Optional[List[str]] = None,
                 meta: Optional[Dict] = None) -> List[Dict]:
    """Aggregate device time per kernel name over every trace file under
    ``trace_dir``.  Returns rows sorted by total time desc:
    {"name", "class", "total_ms", "calls", "frac"} — frac of the summed
    kernel time.  Empty list when no trace file exists.  Files over the
    LUX_OBS_XPROF_MAX_MB on-disk cap are not parsed; their paths are
    appended to ``skipped`` when the caller passes a list.  When a file
    has no device-lane pids (a host/CPU capture) the fallback sums ALL
    pids — ``meta["host_only"]`` is set so consumers can label the table
    as host wall time rather than device time."""
    totals: Dict[str, List[float]] = {}
    cap = _max_bytes()
    loaded = []
    for path in _trace_files(trace_dir):
        try:
            if os.path.getsize(path) > cap:
                if skipped is not None:
                    skipped.append(path)
                continue
            events = _load_events(path)
        except (OSError, ValueError):
            continue
        loaded.append((events, _device_pids(events)))
    # the all-pids fallback is BUNDLE-wide, not per-file: when any file
    # has device lanes, a host-only sibling file contributes nothing
    # (host wall time must never silently sum into device ms)
    any_dev = any(dev for _, dev in loaded)
    if not any_dev and meta is not None and any(
            ev.get("ph") == "X" for events, _ in loaded for ev in events):
        meta["host_only"] = True
    for events, dev in loaded:
        if any_dev and not dev:
            continue
        for ev in events:
            if ev.get("ph") != "X":
                continue
            if dev and ev.get("pid") not in dev:
                continue
            name = str(ev.get("name", ""))
            dur_us = float(ev.get("dur", 0.0))
            t = totals.setdefault(name, [0.0, 0])
            t[0] += dur_us
            t[1] += 1
    grand = sum(t[0] for t in totals.values()) or 1.0
    rows = [
        {"name": name, "class": classify(name),
         "total_ms": round(t[0] / 1e3, 3), "calls": t[1],
         "frac": round(t[0] / grand, 4)}
        for name, t in totals.items()
    ]
    rows.sort(key=lambda r: (-r["total_ms"], r["name"]))
    return rows[:top] if top else rows


def class_summary(rows: List[Dict]) -> Dict[str, float]:
    """{class: total_ms} rollup of a kernel_table."""
    out: Dict[str, float] = {}
    for r in rows:
        out[r["class"]] = round(out.get(r["class"], 0.0)
                                + r["total_ms"], 3)
    return out


def emit_kernel_table(trace_dir: str, rec=None,
                      top: int = 40) -> Optional[List[Dict]]:
    """Parse ``trace_dir`` and write the attribution into the event log
    as one point event; returns the rows (None when no trace found).
    Never raises: attribution is bookkeeping, not a run dependency."""
    skipped: List[str] = []
    meta: Dict = {}
    try:
        rows = kernel_table(trace_dir, top=top, skipped=skipped, meta=meta)
    except Exception:  # noqa: BLE001 — attribution must never cost a run
        return None
    if not rows and not skipped:
        return None
    from lux_tpu import obs

    r = rec if rec is not None else obs.recorder()
    ev = {"trace_dir": trace_dir, "rows": rows,
          "classes": class_summary(rows)}
    if meta.get("host_only"):  # no device lanes: host wall, not device ms
        ev["host_only"] = True
    if skipped:  # over-cap files: named, not silently absent
        ev["skipped_over_cap"] = skipped
        ev["cap_mb"] = _max_bytes() >> 20
    r.point("xprof.kernels", **ev)
    return rows
