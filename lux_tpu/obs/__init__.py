"""lux_tpu.obs — luxtrace, the always-on flight recorder.

Three layers, one event log per run:

* **recorder** (pure stdlib) — ``span()``/``point()`` context managers
  writing an append-only JSONL event log under a uid-checked 0o700 dir;
  nested span ids, monotonic timestamps, a ``run_id`` that bench rows
  and AUDIT/PROGRESS entries also carry.  Crash-safe by construction:
  begin events are on disk before the work they cover runs.
* **ring** (jax) — fixed-capacity on-device iteration telemetry carried
  in the hot-loop carry (static shapes, donated with the state, fetched
  once at run end); bitwise no-op on results, enforced by LUX-J1/J2/J5
  and the LUX-O checker family.
* **xprof** (stdlib) — parses the captured XProf/Perfetto trace and
  attributes device time to the routed-pf kernels vs gather/scatter/
  collectives.

``tools/luxview.py`` renders any event log into the human report;
``tools/chip_day.sh`` spans every battery step so an aborted window
still leaves a complete post-mortem artifact.  Schema + design notes:
docs/OBSERVABILITY.md.

This ``__init__`` (and recorder) stays jax-free so the tools can import
it under the same bare-package stub luxcheck uses; ``ring``/``xprof``
import lazily where needed.

``dtrace`` (also stdlib-only) is the distributed-tracing layer on top:
trace contexts minted at the fleet entry points, carried on every fleet
frame, recorded as span attrs each hop — ``tools/luxstitch.py`` merges
the per-process logs into one causally-ordered fleet timeline, and
``obs/slo.py`` evaluates declarative SLOs as multi-window burn rates
over the serving metrics with trace-id exemplars.
"""
from lux_tpu.obs.recorder import (  # noqa: F401
    Recorder,
    Span,
    install,
    new_run_id,
    point,
    recorder,
    run_id,
    span,
)
from lux_tpu.obs import dtrace  # noqa: F401  (stdlib-only, like recorder)
