"""luxtrace recorder: the always-on flight recorder's host half.

The reference ships observability it never uses (Legion Prof/Spy behind
-lg:* flags, SURVEY.md §5); this repo's gap was the same shape — five
VERDICT rounds of "a window closed and we cannot reconstruct where the
time went".  The recorder turns every run into an attributable artifact:
one append-only JSONL event log per run, written THROUGH crashes (begin
events hit disk before the work they cover runs, so a process killed
mid-step leaves an unfinished span, not a blank file).

Design constraints, in order:

* pure stdlib — importing this module must never pull in jax/numpy
  (tools/luxview.py runs it under the same jax-free package stub as
  luxcheck, on hosts whose tunnel is in ANY state);
* always-on, never load-bearing — a full disk, an untrusted log dir, or
  LUX_OBS=0 degrade to in-memory aggregation only; no caller branches on
  recorder health, and recorder failure can never fail a run;
* cheap — one span is two dict->JSON lines on a line-buffered fd plus a
  lock'd counter bump; the hot loops themselves carry their telemetry
  ON DEVICE (lux_tpu.obs.ring) and the recorder only sees the single
  end-of-run fetch.

Event vocabulary (one JSON object per line):

  {"e":"m", "run":..,"pid":..,"wall":..,"mono":..,"argv":[..]}   file meta
  {"e":"b", "n":name,"s":sid,"p":parent_sid|null,"t":mono,"a":{..}}
  {"e":"e", "s":sid,"t":mono,"ok":bool,"a":{..}}                 span end
  {"e":"p", "n":name,"t":mono,"a":{..}}                          point

Span ids are "<pid>-<token>-<counter>" (the token is per-process random:
a long battery recycles pids, and two processes issuing "1234-1" would
let a later begin overwrite an earlier span in luxview's merge — masking
exactly the OPEN span a post-mortem exists to show) so events from
different processes of the same run (bench orchestrator + workers, every
chip_day step) merge into one timeline: CLOCK_MONOTONIC is system-wide
on Linux, so cross-process ``t`` values are directly comparable and the
meta event's (wall, mono) pair anchors them to calendar time.

The run directory is vetted exactly like the plan cache
(ops/expand._cache_dir_trusted): 0o700, owned by this uid, no symlink —
and the log is JSON-only by construction (luxcheck LUX-P001 scans this
package like any other).
"""
from __future__ import annotations

import binascii
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from typing import Optional

#: LUX_OBS=0 disables FILE writes (in-memory span totals still
#: aggregate: plan_build_seconds and the bench phases view depend on
#: them, and they must never depend on log-dir health)
ENABLE_ENV = "LUX_OBS"
DIR_ENV = "LUX_OBS_DIR"
RUN_ENV = "LUX_OBS_RUN_ID"
#: retention: the recorder is always-on, so without a bound the root
#: accumulates one run dir per bench/serve/test/ci invocation until the
#: disk fills — and a full disk silently disables the post-mortem
#: logging the feature exists for.  Keep the newest N run dirs (the plan
#: cache's analogous bounded contract); <= 0 disables the sweep.
KEEP_ENV = "LUX_OBS_KEEP"
DEFAULT_KEEP = 64
#: never sweep a dir whose newest file was written in the last hour —
#: a live run beyond the keep horizon must not lose its log mid-write
SWEEP_MIN_AGE_S = 3600.0


def default_root() -> str:
    """Per-user event-log root, the plan cache's sibling."""
    uid = os.getuid() if hasattr(os, "getuid") else "na"
    return os.environ.get(DIR_ENV) or os.path.join(
        tempfile.gettempdir(), f"lux_obs_{uid}")


def _dir_trusted(path: str) -> bool:
    """Create (0o700) and vet an event-log dir: refuse symlinks, foreign
    owners, and group/other access — same contract as the plan cache."""
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        st = os.lstat(path)
    except OSError:
        return False
    if os.path.islink(path) or not os.path.isdir(path):
        return False
    if hasattr(os, "getuid") and st.st_uid != os.getuid():
        return False
    if st.st_mode & 0o077:
        try:  # repair a pre-existing loose dir we own
            os.chmod(path, 0o700)
        except OSError:
            return False
    return True


def _sweep_old_runs(root: str, current_dir: str) -> None:
    """Delete the oldest run dirs beyond the keep horizon (newest file
    mtime orders them); the current run and anything written within
    SWEEP_MIN_AGE_S are never touched.  All failures are absorbed —
    retention, like everything else here, can never fail a run."""
    raw = os.environ.get(KEEP_ENV, "")
    try:
        keep = int(raw) if raw.strip() else DEFAULT_KEEP
    except ValueError:
        keep = DEFAULT_KEEP
    if keep <= 0:
        return
    try:
        cur = os.path.realpath(current_dir)
        now = time.time()
        entries = []
        with os.scandir(root) as it:
            for de in it:
                if not de.is_dir(follow_symlinks=False):
                    continue
                if os.path.realpath(de.path) == cur:
                    continue
                try:
                    newest = de.stat(follow_symlinks=False).st_mtime
                    with os.scandir(de.path) as files:
                        for f in files:
                            try:
                                st = f.stat(follow_symlinks=False)
                            except OSError:
                                continue
                            newest = max(newest, st.st_mtime)
                except OSError:
                    continue
                entries.append((newest, de.path))
        entries.sort(reverse=True)
        # the current run dir occupies one keep slot
        for newest, path in entries[max(keep - 1, 0):]:
            if now - newest < SWEEP_MIN_AGE_S:
                continue
            shutil.rmtree(path, ignore_errors=True)
    except OSError:
        pass


def new_run_id() -> str:
    """Collision-proof human-sortable run id.  Wall clock + pid + random
    suffix; never feeds results or cache keys (luxcheck LUX-D002 scopes
    wall-clock out of engine code — this is the metadata layer)."""
    stamp = time.strftime("%Y%m%d_%H%M%S")
    rand = binascii.hexlify(os.urandom(3)).decode()
    return f"{stamp}_{os.getpid()}_{rand}"


class Span:
    """One live span.  Use via ``Recorder.span`` / module-level ``span``:

        with span("plan.build", parts=4) as sp:
            ...
            sp.set(bytes=n)        # attrs attached to the END event
        sp.dur                     # seconds, available after exit
    """

    __slots__ = ("_rec", "name", "sid", "parent", "attrs", "_end_attrs",
                 "t0", "dur", "ok")

    def __init__(self, rec: "Recorder", name: str, attrs: dict):
        self._rec = rec
        self.name = name
        self.attrs = attrs
        self._end_attrs: dict = {}
        self.sid = ""
        self.parent: Optional[str] = None
        self.t0 = 0.0
        self.dur = 0.0
        self.ok = True

    def set(self, **attrs) -> "Span":
        self._end_attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._rec._begin(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.ok = exc_type is None
        self._rec._end(self)
        return False


class Recorder:
    """Process-wide flight recorder; one JSONL file per (run, process).

    Thread-safe: the span stack is per-thread (nesting follows each
    thread's own call structure), the file and the aggregation table are
    lock-guarded.  All failures are absorbed — a recorder can degrade to
    memory-only but can never raise into the instrumented code path.
    """

    def __init__(self, run_id: Optional[str] = None,
                 root: Optional[str] = None,
                 enabled: Optional[bool] = None,
                 clock=time.monotonic):
        self.run_id = (run_id or os.environ.get(RUN_ENV) or new_run_id())
        self.root = root or default_root()
        if enabled is None:
            enabled = os.environ.get(ENABLE_ENV, "1") != "0"
        self.enabled = enabled
        self.clock = clock
        self._lock = threading.Lock()
        self._local = threading.local()
        self._file = None
        self._file_failed = False
        self._swept = False
        # pid reuse over a long battery must not collide sids (luxview
        # merges all of a run's files into one flat span table)
        self._sid_prefix = (
            f"{os.getpid()}-{binascii.hexlify(os.urandom(2)).decode()}")
        self._next_sid = 0
        #: span name -> [count, total_seconds]; the single clock behind
        #: plan_build_seconds AND the bench ``phases`` dict (no drift:
        #: both are views over the same span durations)
        self._totals: dict[str, list] = {}
        self.log_path: Optional[str] = None

    # -- file plumbing --------------------------------------------------

    def run_dir(self) -> str:
        return os.path.join(self.root, self.run_id)

    def _open(self):
        """Lazy line-buffered open; one failure disables file output for
        the process (memory aggregation continues)."""
        if self._file is not None or self._file_failed or not self.enabled:
            return self._file
        d = self.run_dir()
        if not _dir_trusted(self.root) or not _dir_trusted(d):
            self._file_failed = True
            return None
        if not self._swept:
            self._swept = True
            _sweep_old_runs(self.root, d)
        try:
            path = os.path.join(d, f"events-{os.getpid()}.jsonl")
            # block-buffered, flushed explicitly: span begins/points
            # flush (the crash-safety contract — a killed process must
            # leave its OPEN span on disk), while retroactive spans
            # (emit_span — the work already finished) ride the buffer
            # so high-rate distributed tracing is not one syscall per
            # event
            self._file = open(path, "a", buffering=8192,
                              encoding="utf-8")
            # buffered tail events must survive a normal exit even when
            # nobody closes the recorder (CLI tools, bench workers)
            import atexit

            atexit.register(self.close)
            self.log_path = path
            self._file.write(json.dumps({
                "e": "m", "run": self.run_id, "pid": os.getpid(),
                "wall": time.time(), "mono": self.clock(),
                "argv": sys.argv[:4],
            }, default=str) + "\n")
        except OSError:
            self._file_failed = True
            self._file = None
        return self._file

    def _write(self, obj: dict) -> None:
        self._write_lines((obj,), flush=True)

    def _write_lines(self, objs, flush: bool = False) -> None:
        try:
            # serialize OUTSIDE the lock: the recorder is process-wide
            # and high-rate tracing writes from every serving thread —
            # holding the lock across json.dumps serializes them all
            text = "".join(json.dumps(o, separators=(",", ":"),
                                      default=str) + "\n"
                           for o in objs)
        except (ValueError, TypeError):
            text = None
        with self._lock:
            f = self._open()
            if f is None:
                return
            try:
                if text is None:
                    raise ValueError("unserializable event")
                f.write(text)
                if flush:
                    f.flush()
            except (OSError, ValueError):
                self._file_failed = True
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def flush(self) -> None:
        """Push buffered (retroactive-span) events to disk — readers
        of a LIVE log (tests, a mid-run luxstitch) call this; close()
        flushes implicitly."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- spans ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def _begin(self, sp: Span) -> None:
        with self._lock:
            self._next_sid += 1
            sp.sid = f"{self._sid_prefix}-{self._next_sid}"
        st = self._stack()
        sp.parent = st[-1] if st else None
        st.append(sp.sid)
        sp.t0 = self.clock()
        ev = {"e": "b", "n": sp.name, "s": sp.sid, "p": sp.parent,
              "t": sp.t0}
        if sp.attrs:
            ev["a"] = sp.attrs
        self._write(ev)

    def _end(self, sp: Span) -> None:
        t1 = self.clock()
        sp.dur = t1 - sp.t0
        st = self._stack()
        if st and st[-1] == sp.sid:
            st.pop()
        elif sp.sid in st:  # mis-nested exit: drop through to it
            del st[st.index(sp.sid):]
        if sp.ok:
            # only completed spans feed the aggregate: the totals are
            # the ONE clock behind plan_build_seconds and the bench
            # phases dict, and a failed plan.load (rebuilt under
            # plan.build) must not drift the two numbers apart.
            # Failure timings stay in the event log, ok=false.
            with self._lock:
                tot = self._totals.setdefault(sp.name, [0, 0.0])
                tot[0] += 1
                tot[1] += sp.dur
        ev = {"e": "e", "s": sp.sid, "t": t1, "ok": sp.ok}
        if sp._end_attrs:
            ev["a"] = sp._end_attrs
        self._write(ev)

    def point(self, name: str, **attrs) -> None:
        ev = {"e": "p", "n": name, "t": self.clock()}
        if attrs:
            ev["a"] = attrs
        self._write(ev)

    def emit_span(self, name: str, t0: float, t1: float, ok: bool = True,
                  attrs: Optional[dict] = None,
                  end_attrs: Optional[dict] = None) -> str:
        """Record a span RETROACTIVELY — begin+end in one call, never
        touching the per-thread nesting stack.  This exists for work
        whose begin and end happen on different threads (a fleet query
        submitted on the caller's thread resolves on the connection
        reader): a stack-based ``span()`` begun there would become the
        phantom parent of every later span on the submitting thread.
        The two events carry the timestamps the caller measured; the
        aggregate totals count it like any completed span.  Returns the
        minted sid (the distributed-tracing layer links across
        processes via its own span attrs, not this id)."""
        with self._lock:
            self._next_sid += 1
            sid = f"{self._sid_prefix}-{self._next_sid}"
            if ok:
                tot = self._totals.setdefault(name, [0, 0.0])
                tot[0] += 1
                tot[1] += float(t1) - float(t0)
        b = {"e": "b", "n": name, "s": sid, "p": None, "t": float(t0)}
        if attrs:
            b["a"] = dict(attrs)
        e = {"e": "e", "s": sid, "t": float(t1), "ok": bool(ok)}
        if end_attrs:
            e["a"] = dict(end_attrs)
        # both halves are already known and the work already ENDED, so
        # one UNFLUSHED buffered write — per-event write syscalls are
        # the dominant cost of high-rate tracing, and a crash loses
        # nothing a post-mortem needs (open spans always flush)
        self._write_lines((b, e))
        return sid

    # -- aggregation (the "one clock" view) -----------------------------

    def total_seconds(self, name: str) -> float:
        with self._lock:
            tot = self._totals.get(name)
            return tot[1] if tot else 0.0

    def total_count(self, name: str) -> int:
        with self._lock:
            tot = self._totals.get(name)
            return tot[0] if tot else 0

    def totals(self, prefix: str = "") -> dict:
        """{name: (count, seconds)} snapshot for names under prefix."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._totals.items()
                    if k.startswith(prefix)}

    def reset_totals(self, prefix: str = "") -> None:
        with self._lock:
            for k in list(self._totals):
                if k.startswith(prefix):
                    del self._totals[k]


# ---------------------------------------------------------------------------
# process-wide singleton + module-level convenience API
# ---------------------------------------------------------------------------

_RECORDER: Optional[Recorder] = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> Recorder:
    """The process recorder (created on first use, honoring
    LUX_OBS_RUN_ID / LUX_OBS_DIR / LUX_OBS)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = Recorder()
        return _RECORDER


def install(rec: Optional[Recorder]) -> Optional[Recorder]:
    """Swap the process recorder (tests; chip-day children inherit the
    run id via env instead).  Returns the previous one."""
    global _RECORDER
    with _RECORDER_LOCK:
        old, _RECORDER = _RECORDER, rec
        return old


def span(name: str, **attrs) -> Span:
    return recorder().span(name, **attrs)


def point(name: str, **attrs) -> None:
    recorder().point(name, **attrs)


def run_id() -> str:
    return recorder().run_id
