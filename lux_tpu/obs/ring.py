"""On-device iteration telemetry: a fixed-capacity ring in the hot-loop
carry.

The reference prints per-iteration activeNodes/loadTime/... by fencing
every iteration on the host (-verbose, sssp_gpu.cu:513-518).  Here the
hot loop lives entirely on device (lax.fori/while), so per-iteration
host reads would serialize dispatch — the exact failure mode luxcheck's
LUX-O family rejects.  Instead each engine pushes one small row per
iteration into a static-shape ring CARRIED IN THE LOOP STATE:

* static shapes (capacity x columns, fixed dtype) — the loop's jaxpr is
  identical for every run length, so the LUX-J1 retrace audit holds;
* carried and (optionally) donated with the rest of the state — the
  LUX-J2 donation audit sees one more aliased leaf, not a second copy;
* pure additional OUTPUT — the engine's state math never reads the
  ring, so telemetry-on is bitwise-identical to telemetry-off on every
  result array, and the plan-derived ``roofline.routed_hbm_passes``
  accounting is untouched (LUX-J5's claim cross-check still balances);
* fetched to host ONCE, after the loop completes (``ring_rows``) —
  never inside it.

Capacity semantics: the ring keeps the LAST ``cap`` rows (wrap-around),
with ``n`` counting every push, so a 10k-iteration convergence run still
reports its tail behavior and its exact iteration count.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

#: default ring capacity (rows); per-run override is an ordinary
#: function argument, not an env knob — rings are built by drivers
DEFAULT_CAP = 512

#: column schemas, by ring kind (luxview renders these headers)
SCHEMAS = {
    "pull_fixed": ("it", "residual_l1"),
    "pull_until": ("it", "active"),
    "push": ("it", "frontier", "edges_lo", "dense"),
}


class IterRing(NamedTuple):
    """The carried telemetry ring: ``buf`` is (cap, cols) of one fixed
    dtype, ``n`` the int32 count of rows ever pushed (> cap = wrapped)."""

    buf: jnp.ndarray
    n: jnp.ndarray


def new_ring(kind: str, cap: int = DEFAULT_CAP) -> IterRing:
    """Fresh ring for one of the SCHEMAS kinds.  float32 everywhere:
    every recorded quantity (iteration index, counts < 2^32 per round)
    is telemetry, not arithmetic — 24-bit precision on a 268M-edge dense
    round is a rounding of the CURVE, never of a result."""
    cols = len(SCHEMAS[kind])
    return IterRing(jnp.zeros((int(cap), cols), jnp.float32),
                    jnp.int32(0))


def ring_push(ring: IterRing, *vals) -> IterRing:
    """Append one row (traced; static shapes in, static shapes out)."""
    cap = ring.buf.shape[0]
    row = jnp.stack([jnp.asarray(v).astype(jnp.float32) for v in vals])
    idx = jnp.mod(ring.n, cap)
    buf = jax.lax.dynamic_update_index_in_dim(ring.buf, row, idx, 0)
    return IterRing(buf, ring.n + 1)


def ring_rows(ring: IterRing):
    """The ONE host fetch, after the loop: (rows ndarray in push order,
    total pushes).  Keeps the last ``cap`` rows when wrapped."""
    import numpy as np

    buf = np.asarray(ring.buf)
    n = int(ring.n)
    cap = buf.shape[0]
    if n <= cap:
        return buf[:n], n
    start = n % cap
    return np.concatenate([buf[start:], buf[:start]]), n


def emit_ring(kind: str, ring: IterRing, rec=None, **attrs) -> None:
    """Fetch the ring and write it into the event log as one point event
    (the run-end flush; luxview's per-iteration curves read these)."""
    from lux_tpu import obs

    rows, n = ring_rows(ring)
    r = rec if rec is not None else obs.recorder()
    r.point("telemetry.ring", kind=kind, cols=list(SCHEMAS[kind]),
            n=n, rows=[[float(x) for x in row] for row in rows], **attrs)
