"""LUX-G: guarded-by race inference (jax-free, AST only).

The fleet's shared-state discipline is *conventions*: every serving
class pairs its mutable fields with a ``threading.Lock`` and every
access is supposed to happen under ``with self._lock``.  LUX-L checks
the locks' *order*; nothing checked that guarded fields are actually
*accessed under their guard* — the discipline bug class that shipped
twice (PR 16, PR 19) before this family existed.

Inference model (deliberately lexical — see docs/ANALYSIS.md):

* per-class scope: a guard map is inferred for each ``class`` in
  isolation; fields and locks are lexical identities (``Cls._field``).
* a ``self._x`` that is *assigned* (plain, augmented, or through a
  subscript) at least once inside ``with self._lock:`` in any method
  other than ``__init__`` is **guarded by** ``_lock``.  Only the
  *innermost* held lock at the write attributes the guard, so nested
  acquisitions do not fabricate mixed-guard findings.
* ``threading.Condition(self._lock)`` ALIASES its lock: acquiring the
  condition is acquiring ``_lock``, so a field written under the
  condition and read under the lock is one coherent guard, not two.
* init window: ``__init__`` runs before any thread exists, so its
  writes neither establish nor violate a guard.
* ``*_locked`` naming convention: a method whose name ends in
  ``_locked`` declares "my caller holds the lock" (the repo-wide idiom:
  ``_op_commit_locked``); its accesses are exempt from G001 — the
  CALLER's with-block is the checked site.

Rules:

* G001 — read or write of a guarded field outside its guard, in a
  method reachable by a second thread (thread targets plus the
  transitive closure of same-class ``self.m()`` calls and bound-method
  references — dispatcher tables, RPC handlers, heartbeat loops).
* G002 — mixed guards: one field written under two DIFFERENT locks;
  whichever lock a reader picks, the other writer races it.
* G003 — compound check-then-act: within one method, a guarded field
  is read under one ``with`` block and written under a LATER, separate
  one — the guard was dropped across the read-modify-write.

Stated limits: identities are lexical (a lock reached through a
helper object is invisible), scope is per-class (a second thread
driving this class from ANOTHER class's loop is not discovered), and
reachability is per-module.  Those are the same limits LUX-L carries,
documented in docs/ANALYSIS.md; the suppression contract covers the
deliberate exceptions (single-reference reads that ride the GIL, etc).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, Module, call_name
from .locks import _ctor_kind
from .threads import _thread_target_names

#: entry-point call shapes whose callable argument runs on a new thread
#: (mirrors threads._thread_target_names, plus the Attribute form —
#: ``Thread(target=self._run)`` — that per-class analysis needs)
_SPAWN_LAST = {"Thread", "submit", "Timer"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'_x' for a ``self._x`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassGuards:
    """Guard inference for ONE class: lock fields (alias-resolved),
    per-access held-lock sets, the second-thread-reachable method set."""

    def __init__(self, mod: Module, cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        #: methods defined directly on the class body
        self.methods: Dict[str, ast.FunctionDef] = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        #: lock field -> canonical lock field (Condition(self._l) -> _l)
        self.lock_alias: Dict[str, str] = {}
        self._collect_locks()
        #: (attr node, field, is_write, innermost held guard or None,
        #:  full held set, method name)
        self.accesses: List[Tuple[ast.AST, str, bool, Optional[str],
                                  Set[str], str]] = []
        self._collect_accesses()
        self.reachable: Set[str] = self._reachable_methods()

    # -- lock fields ----------------------------------------------------

    def _collect_locks(self) -> None:
        raw: Dict[str, Optional[str]] = {}  # field -> aliased field|None
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            kind = _ctor_kind(node.value)
            if not kind:
                continue
            alias = None
            if kind == "Condition" and node.value.args:
                alias = _self_attr(node.value.args[0])
            for t in node.targets:
                f = _self_attr(t)
                if f:
                    raw[f] = alias
        for f in raw:
            seen = {f}
            cur = f
            while raw.get(cur) in raw and raw[cur] not in seen:
                cur = raw[cur]
                seen.add(cur)
            self.lock_alias[f] = raw[cur] or cur

    def canonical(self, field: str) -> str:
        return self.lock_alias.get(field, field)

    # -- accesses -------------------------------------------------------

    def _held_at(self, node: ast.AST, method: ast.AST
                 ) -> Tuple[Optional[str], Set[str]]:
        """(innermost guard, all guards) lexically held at ``node``,
        walking ancestors up to (not past) the method def."""
        innermost: Optional[str] = None
        held: Set[str] = set()
        for anc in self.mod.ancestors(node):
            if anc is method:
                break
            if not isinstance(anc, (ast.With, ast.AsyncWith)):
                continue
            for item in anc.items:
                g = self._guard_of(item.context_expr)
                if g:
                    held.add(g)
                    if innermost is None:
                        innermost = g
        return innermost, held

    def _guard_of(self, expr: ast.AST) -> Optional[str]:
        f = _self_attr(expr)
        if f is not None:
            if f in self.lock_alias:
                return self.canonical(f)
            low = f.lower()
            if any(k in low for k in ("lock", "mutex", "cond", "wake")):
                return self.canonical(f)
            return None
        src = ast.unparse(expr).lower()
        if any(k in src for k in ("lock", "mutex", "cond", "flock",
                                  "wake")):
            return ast.unparse(expr)
        return None

    def _is_write(self, node: ast.Attribute) -> bool:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        # self._x[k] = v / self._x[k] += v mutate the guarded object;
        # follow nested subscripts (self._x[i][j] = v) up the chain
        cur: ast.AST = node
        p = self.mod.parent(node)
        while isinstance(p, ast.Subscript) and p.value is cur:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                return True
            cur = p
            p = self.mod.parent(p)
        return False

    def _collect_accesses(self) -> None:
        for name, meth in self.methods.items():
            for node in ast.walk(meth):
                f = _self_attr(node)
                if f is None or f in self.lock_alias:
                    continue
                inner, held = self._held_at(node, meth)
                self.accesses.append(
                    (node, f, self._is_write(node), inner, held, name))

    # -- reachability ---------------------------------------------------

    def _seed_methods(self) -> Set[str]:
        seeds: Set[str] = set()
        nested_defs: Dict[str, ast.AST] = {}
        for meth in self.methods.values():
            for n in ast.walk(meth):
                if (isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))
                        and n is not meth):
                    nested_defs[n.name] = n

        def seed_refs_in(scope: ast.AST) -> None:
            for n in ast.walk(scope):
                g = _self_attr(n)
                if g and g in self.methods:
                    seeds.add(g)

        def note_callable(expr: ast.AST, spawner: ast.AST) -> None:
            f = _self_attr(expr)
            if f and f in self.methods:
                seeds.add(f)
            elif isinstance(expr, ast.Name) and expr.id in nested_defs:
                # a nested def run on a thread: its self.* references
                # seed reachability (``Thread(target=loop)`` where
                # ``loop`` calls ``self.step()``)
                seed_refs_in(nested_defs[expr.id])
            elif isinstance(expr, ast.Name):
                # target bound through a local we cannot resolve (a loop
                # variable over ``(self._accept_loop, self._respond_loop)``
                # tuples, a conditional alias): seed every self-method
                # the SPAWNING method references — conservative toward
                # checking, since one of those references is the target
                seed_refs_in(spawner)

        for meth in self.methods.values():
            for node in ast.walk(meth):
                if not isinstance(node, ast.Call):
                    continue
                last = call_name(node).split(".")[-1]
                if last in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            note_callable(kw.value, meth)
                elif last == "submit" and node.args:
                    note_callable(node.args[0], meth)
        # module-level spawns targeting this class's methods by bare
        # name (reuse the LUX-C discovery so both families agree)
        for name in _thread_target_names(self.mod):
            if name in self.methods:
                seeds.add(name)
        return seeds

    def _reachable_methods(self) -> Set[str]:
        reach = self._seed_methods()
        work = list(reach)
        while work:
            m = work.pop()
            meth = self.methods.get(m)
            if meth is None:
                continue
            for n in ast.walk(meth):
                f = _self_attr(n)
                # ANY reference counts: dispatcher dicts hold bound
                # methods (``{"step": self._op_step}``), so a bare
                # ``self._op_step`` in thread context marks it reachable
                if f and f in self.methods and f not in reach:
                    reach.add(f)
                    work.append(f)
        reach.discard("__init__")
        return reach


class GuardedByChecker(Checker):
    family = "guarded-by"
    name = "guards"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(mod, cls))
        return out

    def _check_class(self, mod: Module, cls: ast.ClassDef
                     ) -> Iterable[Finding]:
        cg = _ClassGuards(mod, cls)
        if not cg.lock_alias:
            return []

        # guard inference: locked writes outside the init window
        guards: Dict[str, Set[str]] = {}
        guard_site: Dict[Tuple[str, str], ast.AST] = {}
        for node, field, is_write, inner, _held, meth in cg.accesses:
            if not is_write or inner is None or meth == "__init__":
                continue
            guards.setdefault(field, set()).add(inner)
            guard_site.setdefault((field, inner), node)

        out: List[Finding] = []

        # G002: one field, two guards — report at the second guard's
        # write site, naming both
        mixed: Set[str] = set()
        for field, gset in sorted(guards.items()):
            if len(gset) < 2:
                continue
            mixed.add(field)
            names = sorted(gset)
            site = guard_site[(field, names[1])]
            out.append(self.finding(
                mod, site, "LUX-G002",
                f"field '{cls.name}.{field}' is written under "
                f"{len(names)} different locks ({', '.join(names)}) — "
                "readers holding either one race the other writer"))

        single = {f: next(iter(g)) for f, g in guards.items()
                  if len(g) == 1 and f not in mixed}

        # G001: unguarded access from a second-thread-reachable method
        flagged: Set[Tuple[str, str, int]] = set()
        for node, field, is_write, _inner, held, meth in cg.accesses:
            guard = single.get(field)
            if guard is None or meth == "__init__":
                continue
            if meth not in cg.reachable or meth.endswith("_locked"):
                continue
            if guard in held:
                continue
            key = (meth, field, getattr(node, "lineno", 0))
            if key in flagged:
                continue
            flagged.add(key)
            kind = "write" if is_write else "read"
            out.append(self.finding(
                mod, node, "LUX-G001",
                f"{kind} of '{cls.name}.{field}' (guarded by "
                f"'{guard}') outside the lock in thread-reachable "
                f"method '{meth}'"))

        # G003: read under one with-block, write under a later separate
        # one — the guard was dropped mid read-modify-write
        out.extend(self._check_then_act(mod, cls, cg, single))
        return out

    def _check_then_act(self, mod: Module, cls: ast.ClassDef,
                        cg: _ClassGuards,
                        single: Dict[str, str]) -> Iterable[Finding]:
        # per (method, field): accesses keyed by their innermost
        # with-block NODE; a block that both reads and writes the field
        # is an atomic RMW and absolves the method for that field
        per: Dict[Tuple[str, str],
                  Dict[int, List[Tuple[bool, int, ast.AST]]]] = {}
        writing_blocks: Set[int] = set()
        for node, field, is_write, inner, _held, meth in cg.accesses:
            if meth == "__init__" or single.get(field) != inner \
                    or inner is None:
                continue
            w = self._with_block(mod, node, cg.methods[meth])
            if w is None:
                continue
            if is_write:
                # a block that writes ANY guarded field commits its
                # decision inside the acquisition — its reads are a
                # check-AND-act, not a stale check (``if token_ok:
                # self._staged = ...`` must not flag on the token read)
                writing_blocks.add(id(w))
            per.setdefault((meth, field), {}).setdefault(
                id(w), []).append(
                    (is_write, getattr(node, "lineno", 0), node))
        out: List[Finding] = []
        for (meth, field), by_block in sorted(per.items()):
            if len(by_block) < 2:
                continue
            reads = [(ln, n) for wid, acc in by_block.items()
                     if wid not in writing_blocks
                     for w, ln, n in acc if not w]
            writes = [(ln, n) for acc in by_block.values()
                      for w, ln, n in acc if w]
            for rln, _rn in sorted(reads):
                later = [(wln, wn) for wln, wn in sorted(writes)
                         if wln > rln]
                if later:
                    wln, wn = later[0]
                    out.append(self.finding(
                        mod, wn, "LUX-G003",
                        f"check-then-act on '{cls.name}.{field}': read "
                        f"under the lock at line {rln}, write under a "
                        f"SEPARATE acquisition here — the guard was "
                        "dropped mid read-modify-write"))
                    break
        return out

    @staticmethod
    def _with_block(mod: Module, node: ast.AST,
                    method: ast.AST) -> Optional[ast.AST]:
        for anc in mod.ancestors(node):
            if anc is method:
                return None
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                return anc
        return None


#: synthetic positives — each MUST fire (tools/luxcheck.py --twins and
#: tests/test_luxguard.py keep the family honest: a checker edit that
#: silently stops firing fails the suite, same as luxproto's twins)
TWINS = (
    ("g001_unlocked_read", """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def run(self):
        while self._n < 10:
            self.bump()

    def start(self):
        threading.Thread(target=self.run).start()
""", ("LUX-G001",)),
    ("g002_mixed_guards", """
import threading

class Split:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux_lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._n = 1

    def b(self):
        with self._aux_lock:
            self._n = 2
""", ("LUX-G002",)),
    ("g003_check_then_act", """
import threading

class Bank:
    def __init__(self):
        self._lock = threading.Lock()
        self._bal = 0

    def set(self, v):
        with self._lock:
            self._bal = v

    def withdraw(self, amount):
        with self._lock:
            ok = self._bal >= amount
        if ok:
            with self._lock:
                self._bal = self._bal - amount
        return ok
""", ("LUX-G003",)),
)
