"""Synthetic-positive twins for the LUX-G/LUX-R families (jax-free).

Same philosophy as luxproto's broken twins: a checker that silently
stops firing is worse than no checker, because the repo-clean gate
keeps passing while the invariant rots.  Every entry here is a minimal
KNOWN-BAD snippet paired with the code(s) it must produce; ``run_twins``
re-checks each through the real pipeline and a twin that comes back
clean is a FAILURE — of the checker, not the snippet.

Gated three ways: ``tools/luxcheck.py --twins`` (ci_check guard_smoke,
chip_day step -3d) and ``tests/test_luxguard.py`` (tier-1).
"""
from __future__ import annotations

import textwrap
from typing import List, Tuple

from lux_tpu.analysis.core import Module, check_module
from lux_tpu.analysis.guards import GuardedByChecker
from lux_tpu.analysis.guards import TWINS as _GUARD_TWINS
from lux_tpu.analysis.resources import ResourceLifecycleChecker
from lux_tpu.analysis.resources import TWINS as _RESOURCE_TWINS

#: (name, source, codes that MUST fire) across both new families
ALL_TWINS: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = (
    _GUARD_TWINS + _RESOURCE_TWINS
)

_CHECKERS = (GuardedByChecker(), ResourceLifecycleChecker())


def run_twins() -> List[Tuple[str, Tuple[str, ...], frozenset, bool]]:
    """[(twin name, expected codes, fired codes, ok)] — ``ok`` means
    every expected code fired (extra codes are fine; a twin may well be
    broken in more ways than the one it pins)."""
    results = []
    for name, source, expected in ALL_TWINS:
        mod = Module(path=f"<twin:{name}>",
                     relpath=f"twins/{name}.py",
                     source=textwrap.dedent(source))
        fired = frozenset(f.code for f in check_module(mod, _CHECKERS))
        results.append((name, expected, fired,
                        all(c in fired for c in expected)))
    return results
