"""Tracing-safety checkers (LUX-T*): Python control flow and host
concretization on traced values inside jit / shard_map / Pallas / lax
control-flow bodies.

The engine's whole performance contract is ONE compiled program per
(app, layout) replayed for every iteration (docs/PERF.md).  A Python
``if``/``bool()``/``.item()`` on a traced value either raises a
ConcretizationTypeError at trace time (best case) or — when the value
happens to be weakly typed or the branch is shape-dependent — silently
forces a retrace per distinct value, which on a chip window is the most
expensive bug class we have.  These lints reject the PATTERN statically
instead of waiting for the tracer.

Traced contexts recognized (per module, no cross-module dataflow):

* functions decorated ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``
  / ``@functools.partial(jax.jit, ...)`` (``static_argnames`` /
  ``static_argnums`` params are exempt — branching on a static is the
  supported recompile-by-design path);
* functions decorated with / wrapped in ``shard_map`` the same way;
* local ``def``s passed to ``jax.jit(f)``, ``shard_map(f, ...)``,
  ``lax.scan(f, ...)``, ``lax.while_loop(cond, body, ...)``,
  ``lax.fori_loop(lo, hi, f, ...)``, ``lax.cond(p, t, f, ...)``,
  ``pl.pallas_call(kernel, ...)``.

Within a traced body, a NON-static parameter is a traced value; we flag
direct uses only (no aliasing) — precision over recall, because every
false positive costs a justified suppression.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from lux_tpu.analysis.core import (
    Checker, Finding, Module, call_name, dotted_name,
)

_JIT_CALLEES = {"jit", "jax.jit", "shard_map", "jax.experimental."
                "shard_map.shard_map"}
_PARTIAL_CALLEES = {"partial", "functools.partial"}
#: callee -> argument positions whose function operand is traced
_TRACED_ARG_POS = {
    "scan": (0,), "lax.scan": (0,), "jax.lax.scan": (0,),
    "while_loop": (0, 1), "lax.while_loop": (0, 1),
    "jax.lax.while_loop": (0, 1),
    "fori_loop": (2,), "lax.fori_loop": (2,), "jax.lax.fori_loop": (2,),
    "cond": (1, 2), "lax.cond": (1, 2), "jax.lax.cond": (1, 2),
    "pallas_call": (0,), "pl.pallas_call": (0,),
    "jit": (0,), "jax.jit": (0,),
    "shard_map": (0,),
}

_CAST_BUILTINS = {"bool", "int", "float"}
_HOST_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                       "numpy.array"}


def _is_jit_like(call: str) -> bool:
    return call in _JIT_CALLEES or call.endswith(".jit")


def _static_params(fn: ast.FunctionDef, deco: ast.Call) -> Set[str]:
    """static_argnames/static_argnums of a ``partial(jax.jit, ...)``
    decorator resolved to parameter names (best effort on literals)."""
    statics: Set[str] = set()
    argnames = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in deco.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    statics.add(node.value)
        elif kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, int) and 0 <= node.value < len(argnames):
                    statics.add(argnames[node.value])
    return statics


def traced_functions(mod: Module) -> Dict[ast.FunctionDef, Set[str]]:
    """Map of traced FunctionDef -> set of STATIC parameter names."""
    by_name: Dict[str, ast.FunctionDef] = {}
    out: Dict[ast.FunctionDef, Set[str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            by_name[node.name] = node  # last definition wins, like Python
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call):
                    cn = call_name(deco)
                    if _is_jit_like(cn):
                        out[node] = set()
                    elif cn in _PARTIAL_CALLEES and deco.args:
                        first = deco.args[0]
                        fname = (call_name(first)
                                 if isinstance(first, ast.Call)
                                 else dotted_name(first))
                        if _is_jit_like(fname):
                            out[node] = _static_params(node, deco)
                elif _is_jit_like(dotted_name(deco)):
                    out[node] = set()
    # local defs passed by name into tracing entry points
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        positions = _TRACED_ARG_POS.get(cn)
        if positions is None and cn.split(".")[-1] in (
                "scan", "while_loop", "fori_loop", "cond", "pallas_call"):
            positions = _TRACED_ARG_POS.get(cn.split(".")[-1])
        if positions is None:
            continue
        for pos in positions:
            if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                fn = by_name.get(node.args[pos].id)
                if fn is not None and fn not in out:
                    out[fn] = set()
    return out


def _traced_params(fn: ast.FunctionDef, statics: Set[str]) -> Set[str]:
    params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs)}
    if fn.args.vararg:
        params.add(fn.args.vararg.arg)
    params -= statics
    params.discard("self")
    params.discard("cls")
    # ``interpret``-style trailing flags are Python bools at trace time
    # in this codebase's idiom; a traced bool would be flagged at the
    # call site it is concretized, not at every mention
    return params


def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` are trace-time constants."""
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


def _is_shape_access(node: ast.AST) -> bool:
    """References like ``x.shape`` / ``x.ndim`` / ``x.dtype`` are static
    under trace; a Name that only appears under such an attribute is not
    a traced-value use."""
    return isinstance(node, ast.Attribute) and node.attr in (
        "shape", "ndim", "dtype", "size", "sharding")


def _traced_name_used(mod: Module, expr: ast.AST, params: Set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in params:
            parent = mod.parent(n)
            if parent is not None and _is_shape_access(parent):
                continue
            if isinstance(parent, ast.Call) and parent.func is n:
                continue  # calling a param: a callee, not a traced array
            return True
    return False


class TracingSafetyChecker(Checker):
    family = "tracing-safety"
    name = "tracing"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn, statics in traced_functions(mod).items():
            params = _traced_params(fn, statics)
            if not params:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.If) and not _is_none_check(
                        node.test) and _traced_name_used(
                            mod, node.test, params):
                    out.append(self.finding(
                        mod, node, "LUX-T001",
                        f"Python `if` on traced value in `{fn.name}` — "
                        "use jnp.where/lax.cond, or declare the argument "
                        "static (recompile-by-design)"))
                elif isinstance(node, ast.While) and _traced_name_used(
                        mod, node.test, params):
                    out.append(self.finding(
                        mod, node, "LUX-T002",
                        f"Python `while` on traced value in `{fn.name}` — "
                        "use lax.while_loop (a traced bound retraces "
                        "per value)"))
                elif isinstance(node, ast.Call):
                    cn = call_name(node)
                    if (cn in _CAST_BUILTINS and len(node.args) == 1
                            and isinstance(node.args[0], ast.Name)
                            and node.args[0].id in params):
                        out.append(self.finding(
                            mod, node, "LUX-T003",
                            f"`{cn}()` concretizes traced value "
                            f"`{node.args[0].id}` in `{fn.name}` — forces "
                            "a host sync / trace error"))
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "item"
                          and isinstance(node.func.value, ast.Name)
                          and node.func.value.id in params):
                        out.append(self.finding(
                            mod, node, "LUX-T004",
                            f"`.item()` on traced value "
                            f"`{node.func.value.id}` in `{fn.name}` — "
                            "host sync inside the compiled body"))
                    elif (cn in _HOST_MATERIALIZERS and node.args
                          and isinstance(node.args[0], ast.Name)
                          and node.args[0].id in params):
                        out.append(self.finding(
                            mod, node, "LUX-T005",
                            f"`{cn}()` materializes traced value "
                            f"`{node.args[0].id}` on host in `{fn.name}` "
                            "— device->host copy per call"))
        return out
