"""luxcheck core: the repo-native static-analysis engine.

Lux's contract is a deterministic, recompile-free hot loop.  The
reference gets its race-freedom checked by construction (SURVEY §5); this
port re-asserts it dynamically via bitwise-rerun tests — which only catch
a violation AFTER it has cost a run.  This engine encodes the invariants
that have actually bitten this codebase as AST lints, so a retrace, a
nondeterministic ordering, or a planner-thread race is rejected before
any chip budget is spent (tools/chip_day.sh step -3).

Design: pure stdlib ``ast`` — importing this package must never pull in
jax/numpy (the preflight gate has to run in milliseconds on a cold
host).  Checkers are small classes registered in
``lux_tpu.analysis.ALL_CHECKERS``; each yields ``Finding``s against a
parsed ``Module``.  Two suppression layers, both requiring a written
justification (an unexplained suppression is itself a finding):

* inline — ``# luxcheck: disable=LUX-T001 -- <why this is safe>`` on the
  flagged line, or on a comment-only line directly above it;
* baseline — ``tools/luxcheck_baseline.txt`` entries
  ``<relpath>:<code>:<fingerprint>  # <why>`` (shipped EMPTY: the
  baseline exists for emergencies mid-chip-window, not as a dumping
  ground — stale entries are themselves findings).
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

#: scanned by ``--all`` / the repo-clean test, relative to the repo root.
#: tests/ is deliberately excluded: tests seed violations on purpose
#: (fixtures) and monkeypatch global state under pytest's isolation.
DEFAULT_TARGETS = ("lux_tpu", "tools", "bench.py")

#: path parts never scanned (native build artifacts, bytecode)
EXCLUDE_PARTS = frozenset({"__pycache__", "build", ".git"})

#: a suppression justification must carry at least this many characters —
#: enough to force a real sentence, short enough not to be ceremony
MIN_JUSTIFICATION = 8

_SUPPRESS_RE = re.compile(
    r"#\s*luxcheck:\s*disable=([A-Za-z0-9_,\-]+)\s*(?:--|—|:)?\s*(.*?)\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit.  ``text`` is the stripped source line — it joins the
    fingerprint so baseline entries survive line-number drift but die
    when the flagged code itself changes (a stale suppression must not
    silently cover NEW code)."""

    path: str  # repo-relative, forward slashes
    line: int
    col: int
    code: str
    message: str
    text: str = ""

    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.path}:{self.code}:{self.text}".encode()
        )
        return h.hexdigest()[:12]

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


class Module:
    """One parsed source file + the per-line suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Optional[dict] = None
        # line -> (codes | {"all"}, justification, suppression line no)
        self.suppressions: dict[int, Tuple[frozenset, str, int]] = {}
        self._scan_suppressions()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _scan_suppressions(self) -> None:
        # tokenize, don't regex raw lines: the suppression syntax quoted
        # inside a docstring/string literal (e.g. this engine's own docs)
        # must neither register a live suppression nor emit a phantom
        # LUX-X001
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # ast.parse succeeded, so this is vanishingly rare
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            codes = frozenset(
                c.strip() for c in m.group(1).split(",") if c.strip()
            )
            just = m.group(2).strip()
            i = tok.start[0]
            entry = (codes, just, i)
            self.suppressions[i] = entry
            # a comment-only suppression line covers the NEXT line
            if self.lines[i - 1][: tok.start[1]].strip() == "":
                self.suppressions.setdefault(i + 1, entry)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        if self._parents is None:
            self._parents = {}
            for p in ast.walk(self.tree):
                for c in ast.iter_child_nodes(p):
                    self._parents[c] = p
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    def under_lock(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside a ``with`` whose context
        expression names a lock (``with _LOCK:``, ``with self._lock:``,
        ``with cv:`` via a name containing lock/mutex/cond)."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    src = ast.unparse(item.context_expr).lower()
                    if any(k in src for k in ("lock", "mutex", "cond",
                                              "flock", "wake")):
                        return True
        return False

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None


class Checker:
    """Base class: subclasses set ``family``/``name`` and implement
    ``run(mod) -> Iterable[Finding]``."""

    family = "unset"
    name = "unset"

    def run(self, mod: Module) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: Module, node: ast.AST, code: str,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(path=mod.relpath, line=line, col=col, code=code,
                       message=message, text=mod.line_text(line))


# ---------------------------------------------------------------------------
# shared AST helpers used by several checker families
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return dotted_name(node.func)


def names_in(node: ast.AST) -> frozenset:
    return frozenset(
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    )


# ---------------------------------------------------------------------------
# suppression + baseline application
# ---------------------------------------------------------------------------


def _apply_inline(mod: Module, findings: List[Finding]) -> List[Finding]:
    """Filter findings through the module's inline suppressions; emit
    LUX-X001 for suppressions whose justification is missing/too thin.
    A suppression with a bad justification does NOT suppress."""
    out: List[Finding] = []
    bad_lines = set()
    for line, (codes, just, sline) in sorted(mod.suppressions.items()):
        if len(just) < MIN_JUSTIFICATION and sline not in bad_lines:
            bad_lines.add(sline)
            out.append(Finding(
                path=mod.relpath, line=sline, col=0, code="LUX-X001",
                message="suppression without a justification — write why "
                        "the finding is safe after '--'",
                text=mod.line_text(sline)))
    for f in findings:
        sup = mod.suppressions.get(f.line)
        if sup is not None:
            codes, just, sline = sup
            if (("all" in codes or f.code in codes)
                    and len(just) >= MIN_JUSTIFICATION):
                continue
        out.append(f)
    return out


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    path: str
    code: str
    fingerprint: str
    justification: str
    lineno: int


def load_baseline(path: str) -> Tuple[List[BaselineEntry], List[Finding]]:
    """Parse the baseline file.  Malformed or unjustified entries are
    findings (LUX-X002) — the baseline must never rot silently."""
    entries: List[BaselineEntry] = []
    problems: List[Finding] = []
    if not os.path.exists(path):
        return entries, problems
    rel = os.path.basename(path)
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, just = line.partition("#")
            just = just.strip()
            parts = body.strip().rsplit(":", 2)
            if len(parts) != 3 or len(just) < MIN_JUSTIFICATION:
                problems.append(Finding(
                    path=rel, line=i, col=0, code="LUX-X002",
                    message="malformed or unjustified baseline entry "
                            "(want '<path>:<code>:<fingerprint>  # why')",
                    text=line))
                continue
            entries.append(BaselineEntry(
                path=parts[0], code=parts[1], fingerprint=parts[2],
                justification=just, lineno=i))
    return entries, problems


def _apply_baseline(findings: List[Finding], baseline_path: Optional[str]
                    ) -> List[Finding]:
    if not baseline_path:
        return findings
    entries, problems = load_baseline(baseline_path)
    # ONE-SHOT consumption: each entry suppresses at most one finding.
    # Fingerprints hash (path, code, line text), so two identical lines
    # in a file collide — without this, one justified entry would also
    # cover every FUTURE identical occurrence, unreviewed.
    keyed: dict[tuple, List[BaselineEntry]] = {}
    for e in entries:
        keyed.setdefault((e.path, e.code, e.fingerprint), []).append(e)
    out: List[Finding] = []
    for f in findings:
        k = (f.path, f.code, f.fingerprint())
        if keyed.get(k):
            keyed[k].pop()
            continue
        out.append(f)
    rel = os.path.basename(baseline_path)
    for k, stale in keyed.items():
        for e in stale:
            out.append(Finding(
                path=rel, line=e.lineno, col=0, code="LUX-X003",
                message=f"stale baseline entry ({e.path}:{e.code}:"
                        f"{e.fingerprint}) matches no current finding — "
                        "delete it",
                text=""))
    return out + problems


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def iter_py_files(root: str, targets: Sequence[str] = DEFAULT_TARGETS
                  ) -> Iterator[str]:
    for t in targets:
        full = os.path.join(root, t)
        if os.path.isfile(full):
            if full.endswith(".py"):
                yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(
                d for d in dirnames if d not in EXCLUDE_PARTS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_module(mod: Module, checkers: Sequence[Checker]) -> List[Finding]:
    """All checkers over one parsed module, inline suppressions applied."""
    raw: List[Finding] = []
    for ch in checkers:
        raw.extend(ch.run(mod))
    return _apply_inline(mod, raw)


def check_file(path: str, root: str, checkers: Sequence[Checker]
               ) -> List[Finding]:
    rel = os.path.relpath(path, root)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        mod = Module(path, rel, source)
    except (OSError, SyntaxError, ValueError) as e:
        return [Finding(path=rel.replace(os.sep, "/"), line=1, col=0,
                        code="LUX-X000",
                        message=f"file not analyzable: {e}", text="")]
    return check_module(mod, checkers)


def check_paths(paths: Sequence[str], root: str,
                checkers: Optional[Sequence[Checker]] = None,
                baseline_path: Optional[str] = None) -> List[Finding]:
    """The full gate: every .py under ``paths``, inline suppressions and
    the baseline applied; returns the surviving findings sorted by
    location.  Exit-0 == empty list."""
    if checkers is None:
        from lux_tpu.analysis import ALL_CHECKERS

        checkers = ALL_CHECKERS
    findings: List[Finding] = []
    seen: set = set()  # overlapping targets (--all + an explicit subdir)
    # must scan each FILE once: duplicates double-report and break the
    # baseline's one-shot consumption

    def one_file(f: str) -> None:
        key = os.path.realpath(f)
        if key not in seen:
            seen.add(key)
            findings.extend(check_file(f, root, checkers))

    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(full):
            for f in iter_py_files(root, [os.path.relpath(full, root)]):
                one_file(f)
        elif os.path.isfile(full):
            one_file(full)
        else:
            # a typo'd or renamed target must FAIL the gate, not shrink
            # it: "clean" after scanning zero files is how a preflight
            # silently stops preflighting
            findings.append(Finding(
                path=p.replace(os.sep, "/"), line=1, col=0,
                code="LUX-X000",
                message="target path does not exist — fix the path (a "
                        "missing target must never pass as clean)",
                text=""))
    findings = _apply_baseline(findings, baseline_path)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


def repo_root() -> str:
    """The repo root this package is installed in (two levels above)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
