"""Policy checkers (LUX-P*): repo contracts that past PRs established
after incidents, enforced so they can never quietly regress.

* LUX-P001 — ``pickle`` (import or use) and ``allow_pickle=True``.  The
  plan disk cache was MOVED OFF pickle in PR 1 (npz + a typed JSON
  decoder — loading a cache entry cannot execute code; ops/expand.py
  PLAN_FORMAT history).  Any reintroduction reopens arbitrary-code
  execution through a world-readable temp dir.
* LUX-P002 — raw ``int(os.environ...)``/``float(os.environ...)`` casts.
  ``LUX_PLAN_THREADS=garbage`` used to raise a bare ValueError deep in
  the planner fan-out; every env knob must parse through
  ``lux_tpu.utils.config.env_int`` (clear error naming the variable,
  positivity enforced at the boundary).
* LUX-P003 — ``.astype(np.uint8)`` index narrowing outside
  ``ops/expand._narrow_idx``.  The u8 routed-pass indices rely on a
  strictly-<128 digit-local invariant that ``_narrow_idx`` asserts;
  an unchecked cast would gather out of bounds under
  ``promise_in_bounds`` on chip (silent garbage, not an error).
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from lux_tpu.analysis.core import Checker, Finding, Module, call_name

_UINT8_NAMES = {"np.uint8", "numpy.uint8", "jnp.uint8"}


def _is_environ_expr(node: ast.AST) -> bool:
    """``os.environ.get(...)`` / ``os.environ[...]`` / ``environ.get``."""
    if isinstance(node, ast.Call):
        cn = call_name(node)
        return cn in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv")
    if isinstance(node, ast.Subscript):
        return ast.unparse(node.value) in ("os.environ", "environ")
    return False


class PolicyChecker(Checker):
    family = "policy"
    name = "policy"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            # --- P001: pickle ---
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] in ("pickle", "cPickle",
                                                    "dill", "shelve"):
                        out.append(self.finding(
                            mod, node, "LUX-P001",
                            f"`import {alias.name}` — the plan cache is "
                            "npz+JSON by contract (PLAN_FORMAT 4+); "
                            "pickle in a cache path is code execution "
                            "from a temp dir"))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] in ("pickle",
                                                         "cPickle", "dill"):
                    out.append(self.finding(
                        mod, node, "LUX-P001",
                        f"`from {node.module} import ...` — pickle is "
                        "banned in cache/serving paths"))
            elif isinstance(node, ast.Call):
                cn = call_name(node)
                for kw in node.keywords:
                    if (kw.arg == "allow_pickle"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True):
                        out.append(self.finding(
                            mod, kw.value, "LUX-P001",
                            "allow_pickle=True — a cache file must never "
                            "be able to execute code"))
                # --- P002: raw env int/float cast ---
                if (cn in ("int", "float") and len(node.args) >= 1
                        and _is_environ_expr(node.args[0])):
                    out.append(self.finding(
                        mod, node, "LUX-P002",
                        f"raw `{cn}(os.environ...)` — parse env knobs "
                        "through lux_tpu.utils.config.env_int (clear "
                        "error naming the variable, bounds enforced at "
                        "the boundary, not deep in the planner)"))
                # --- P003: u8 index narrowing outside _narrow_idx ---
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype"
                        and mod.relpath.startswith("lux_tpu/")
                        and node.args):
                    a = node.args[0]
                    is_u8 = (
                        (isinstance(a, (ast.Attribute, ast.Name))
                         and ast.unparse(a) in _UINT8_NAMES)
                        or (isinstance(a, ast.Constant)
                            and a.value in ("uint8", "u1"))
                    )
                    fn = mod.enclosing_function(node)
                    if is_u8 and (fn is None
                                  or fn.name != "_narrow_idx"):
                        out.append(self.finding(
                            mod, node, "LUX-P003",
                            "uint8 index narrowing outside "
                            "ops/expand._narrow_idx — the <128 "
                            "digit-local invariant must be asserted, "
                            "or the u8 gather reads out of bounds "
                            "on chip"))
        return out
