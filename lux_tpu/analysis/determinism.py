"""Determinism checkers (LUX-D*): orderings and entropy sources that can
change result bytes between two runs of the same program.

Lux's verification story is bitwise-rerun equality (tests/test_determinism
and the parallel==serial plan-build tests) — atomic-free determinism by
construction, which Tascade (PAPERS.md, arxiv 2311.15810) argues must be
VERIFIED rather than assumed.  The dynamic tests catch a violation only
on the inputs they run; these lints reject the generating patterns:

* LUX-D001 — iterating a ``set`` into ordered data.  Python set order is
  hash-seed-dependent across processes (PYTHONHASHSEED): any array,
  list, or loop built from raw set iteration can differ between the two
  halves of a bitwise A/B run.  Wrapping in ``sorted()`` (or an
  order-insensitive consumer: len/min/max/sum/any/all) is the fix.
* LUX-D002 — wall-clock reads (``time.time``/``datetime.now``) inside
  engine/ops/graph/parallel/models code.  Timing belongs in
  utils/timing + bench/serve metrics; a wall-clock read in engine code
  either leaks into results or masquerades as one (perf_counter /
  monotonic are exempt: they cannot produce calendar values that leak
  into cache keys or filenames).
* LUX-D003 — process-global RNG (``np.random.*`` legacy API, stdlib
  ``random.*`` module functions) in package code.  Every draw must go
  through an explicitly seeded ``np.random.default_rng(seed)`` /
  ``random.Random(seed)`` so reruns replay (graph/generate.py idiom).

Float accumulation-order hazards (the reduce strategies' sum
association) are intentionally NOT linted: association is a documented
per-method contract (docs/PARITY.md) enforced by the bitwise tests —
a static rule would only restate `jnp.sum` exists.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from lux_tpu.analysis.core import Checker, Finding, Module, call_name

#: consumers for which set iteration order cannot matter
_ORDER_INSENSITIVE = {"sorted", "len", "min", "max", "sum", "any", "all",
                      "set", "frozenset"}

#: direct consumers that bake iteration order into data
_ORDERED_BUILDERS = {"list", "tuple", "np.array", "np.asarray",
                     "numpy.array", "numpy.asarray", "np.fromiter",
                     "jnp.array", "jnp.asarray", "np.stack",
                     "np.concatenate", "enumerate"}

_WALL_CLOCK = {"time.time", "time.time_ns", "datetime.now",
               "datetime.datetime.now", "datetime.utcnow",
               "datetime.datetime.utcnow", "datetime.today",
               "datetime.date.today"}

#: modules where wall-clock reads are a determinism hazard (results /
#: cache keys); timing+metrics layers are exempt by scope
_ENGINE_SCOPES = ("lux_tpu/engine/", "lux_tpu/ops/", "lux_tpu/graph/",
                  "lux_tpu/parallel/", "lux_tpu/models/")

_LEGACY_NP_RANDOM = {"seed", "rand", "randn", "randint", "random",
                     "choice", "shuffle", "permutation", "uniform",
                     "normal", "binomial", "poisson", "random_sample"}
_STDLIB_RANDOM_FNS = {"random", "randint", "randrange", "choice",
                      "choices", "shuffle", "sample", "uniform",
                      "gauss", "getrandbits", "seed"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return isinstance(node, ast.Call) and call_name(node) in (
        "set", "frozenset")


class DeterminismChecker(Checker):
    family = "determinism"
    name = "determinism"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        in_engine = any(mod.relpath.startswith(s) for s in _ENGINE_SCOPES)
        in_pkg = mod.relpath.startswith("lux_tpu/")
        for node in ast.walk(mod.tree):
            # --- D001: set iteration into ordered data ---
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp)):
                iters.extend(g.iter for g in node.generators)
            elif isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in _ORDERED_BUILDERS:
                    iters.extend(a for a in node.args)
            for it in iters:
                if _is_set_expr(it):
                    # exempt when the whole construct feeds an
                    # order-insensitive consumer directly
                    parent = mod.parent(node)
                    if (isinstance(parent, ast.Call) and call_name(parent)
                            in _ORDER_INSENSITIVE):
                        continue
                    if (isinstance(node, ast.Call) and call_name(node)
                            in _ORDER_INSENSITIVE):
                        continue
                    out.append(self.finding(
                        mod, it, "LUX-D001",
                        "iteration over a set feeds ordered data — set "
                        "order is hash-seed-dependent across processes; "
                        "wrap in sorted()"))
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            # --- D002: wall clock in engine scopes ---
            if in_engine and cn in _WALL_CLOCK:
                out.append(self.finding(
                    mod, node, "LUX-D002",
                    f"wall-clock read `{cn}()` in engine/ops code — "
                    "timing belongs in utils/timing; results and cache "
                    "keys must not depend on the calendar"))
            # --- D003: process-global RNG in package code ---
            if in_pkg:
                parts = cn.split(".")
                if (len(parts) == 3 and parts[0] in ("np", "numpy")
                        and parts[1] == "random"
                        and parts[2] in _LEGACY_NP_RANDOM):
                    out.append(self.finding(
                        mod, node, "LUX-D003",
                        f"legacy global RNG `{cn}()` — use an explicitly "
                        "seeded np.random.default_rng(seed) so reruns "
                        "replay bitwise"))
                elif (len(parts) == 2 and parts[0] == "random"
                        and parts[1] in _STDLIB_RANDOM_FNS):
                    out.append(self.finding(
                        mod, node, "LUX-D003",
                        f"process-global RNG `{cn}()` — use a seeded "
                        "random.Random(seed) instance"))
        return out
