"""luxproto — exhaustive protocol model checking for the distributed
fleet.

Four executable models of the fleet's coordination protocols, each
checked EXHAUSTIVELY (full reachable state space, BFS, shortest
counterexamples) at small-but-covering configurations, plus the
conformance bridge that keeps the models honest against the real code:

========  ==================================================  =========
protocol  real code                                           model
========  ==================================================  =========
election  serve/autopilot/election.py incarnation fencing     election_model
publish   serve/fleet controller↔worker two-phase tokens      publish_model
genline   serve/live generation line / read-your-writes       genline_model
journal   mutate/deltalog.py batch-then-marker atomicity      journal_model
========  ==================================================  =========

Every protocol registers a *clean* model (must check clean — CI fails
otherwise) and one or more *broken twins*: the same model with one
guard removed, which must PRODUCE a counterexample (a clean broken
twin means the model lost the guard's coverage — also a CI failure).
Twins double as the counterexample→FaultPlan source
(``proto/export.py``).

Pure stdlib + the jax-free protocol-surface modules
(``pubproto``/``live.errors``/``deltalog`` constants): everything here
imports under ``tools/_jaxfree.bare_package()``.
"""
from __future__ import annotations

from typing import Callable, Dict, List

from lux_tpu.analysis.proto.election_model import ElectionModel
from lux_tpu.analysis.proto.genline_model import GenLineModel
from lux_tpu.analysis.proto.journal_model import JournalModel
from lux_tpu.analysis.proto.mc import (
    CheckResult,
    Model,
    Violation,
    check,
)
from lux_tpu.analysis.proto.publish_model import PublishModel


class Protocol:
    """One registered protocol: the clean model factory plus its
    broken twins (guard-name → factory)."""

    def __init__(self, name: str, clean: Callable[[], Model],
                 broken: Dict[str, Callable[[], Model]],
                 summary: str):
        self.name = name
        self.clean = clean
        self.broken = dict(broken)
        self.summary = summary


#: the shipped registry, in report order — built once at import as a
#: literal (read-only thereafter: luxproto readers never mutate it)
PROTOCOLS: Dict[str, Protocol] = {p.name: p for p in (
    Protocol(
        "election",
        clean=lambda: ElectionModel(n_standbys=3, fenced=True,
                                    max_restarts=1),
        broken={"unfenced": lambda: ElectionModel(
            n_standbys=2, fenced=False, max_restarts=1)},
        summary="controller election incarnation fencing (split-brain "
                "guard) over the real StandbyGroup",
    ),
    Protocol(
        "publish",
        clean=lambda: PublishModel(n_workers=2, checked=True),
        broken={"unchecked_tokens": lambda: PublishModel(
            n_workers=2, checked=False)},
        summary="two-phase publish tokens across controller failover "
                "(exact-match commit, latest-prepare-wins)",
    ),
    Protocol(
        "genline",
        clean=lambda: GenLineModel(max_writes=3, mode="monotonic_max"),
        broken={
            "stale_heartbeat": lambda: GenLineModel(
                mode="stale_heartbeat"),
            "optimistic_send": lambda: GenLineModel(
                mode="optimistic_send"),
        },
        summary="generation line: read-your-writes bounds, stale "
                "tags, monotonic view folding",
    ),
    Protocol(
        "journal",
        clean=lambda: JournalModel(n_batches=3, marker_first=False),
        broken={"marker_first": lambda: JournalModel(
            marker_first=True)},
        summary="journal crash-atomicity: durable batch npz before "
                "the .ok marker, replay keeps the committed prefix",
    ),
)}


def check_protocol(name: str, max_states: int = 1_000_000) -> CheckResult:
    """Exhaustively check one protocol's CLEAN model."""
    return check(PROTOCOLS[name].clean(), max_states=max_states)


def check_broken(name: str, twin: str,
                 max_states: int = 1_000_000) -> CheckResult:
    """Check a broken twin — callers EXPECT a violation here."""
    return check(PROTOCOLS[name].broken[twin](), max_states=max_states)


def check_all(max_states: int = 1_000_000) -> List[CheckResult]:
    """Clean models for every registered protocol, in registry order."""
    return [check_protocol(n, max_states=max_states) for n in PROTOCOLS]


__all__ = [
    "CheckResult",
    "Model",
    "PROTOCOLS",
    "Protocol",
    "Violation",
    "check",
    "check_all",
    "check_broken",
    "check_protocol",
]
