"""Protocol model 2: two-phase publish tokens across controller
failover (``serve/fleet/controller.py`` republish ↔
``serve/fleet/worker.py`` prepare/commit/discard).

Conformance bridge: tokens are minted by the REAL
:func:`~lux_tpu.serve.fleet.pubproto.publish_token` (so the
incarnation-fencing property being checked is the property of the real
token format), and the refusal labels on refused transitions are the
real ``pubproto`` strings the worker sends on the wire.

The model covers the full failure surface of one republish + one
failover:

* controller c0 (incarnation A) runs prepare → commit over 2 workers
  and may CRASH at any step, leaving prepare/commit RPCs in flight;
* successor c1 (incarnation B) takes over: discard fan-out re-arms
  worker token state, then its own republish — while c0's stale
  messages are still being delivered;
* workers follow the real rules: latest prepare wins (the in-flight
  build re-checks the token before staging), commit installs only on
  an EXACT token match, discard/commit clears staged.

Safety invariants:

1. **no mismatched install** — a worker never serves a cache under a
   commit token different from the token it was staged with;
2. **barrier means uniform** — when the active controller has observed
   its barrier complete, every worker serves that controller's token.

The broken twin (``checked=False``) disables the worker-side token
checks (stale-prepare re-check and commit exact-match) — the checker
then finds the shortest failover schedule in which a dead controller's
delayed commit installs a cache staged for the successor's republish.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from lux_tpu.analysis.proto.mc import Action, Model, State
from lux_tpu.serve.fleet.pubproto import (
    ERR_NOTHING_STAGED,
    ERR_PREPARE_SUPERSEDED,
    publish_token,
    token_mismatch,
)

# controller incarnations (c1 is the post-failover successor)
INCARNATIONS = ("A", "B")

# controller phases
C_START = "start"       # elected, republish not yet fanned out
C_PREP = "preparing"    # prepares sent, awaiting staged acks
C_COMMIT = "committing"  # commits sent, awaiting install acks
C_DONE = "done"         # barrier observed complete
C_ABORTED = "aborted"   # a refusal/timeout aborted the republish
C_DEAD = "dead"         # crashed (c0 only)
C_OFF = "off"           # not yet elected (c1 before takeover)

#: serving marker for the pre-republish cache
OLD = "old"


class PublishModel(Model):
    """State:
    ``(c0_phase, c1_phase, workers)`` with per-worker
    ``(wtok, builds, staged, serving, pend_prep, pend_commit)``:

    * ``wtok`` — the worker's ``_publish_token`` (latest prepare wins);
    * ``builds`` — tokens with an in-flight staged-cache build
      (frozenset: each prepare RPC builds on its own handler thread);
    * ``staged`` — token of the staged cache, if any;
    * ``serving`` — ``OLD`` or ``(staged_token, commit_token)`` for the
      install that produced the serving cache (the pair is what
      invariant 1 inspects);
    * ``pend_prep`` / ``pend_commit`` — in-flight RPC tokens, delivered
      in any order, surviving their sender's crash.
    """

    name = "publish"

    def __init__(self, n_workers: int = 2, checked: bool = True):
        self.n = int(n_workers)
        self.checked = bool(checked)
        # rid=1: one republish per incarnation in the small scope
        self.tokens = tuple(
            publish_token(inc, 1) for inc in INCARNATIONS)

    def config(self) -> Dict[str, object]:
        return {"workers": self.n, "checked": self.checked,
                "incarnations": list(INCARNATIONS),
                "tokens": list(self.tokens)}

    def initial(self) -> Iterable[State]:
        w0 = (None, frozenset(), None, OLD, frozenset(), frozenset())
        yield (C_START, C_OFF, (w0,) * self.n)

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _w(workers: tuple, i: int, **kw) -> tuple:
        wtok, builds, staged, serving, pp, pc = workers[i]
        cur = {"wtok": wtok, "builds": builds, "staged": staged,
               "serving": serving, "pp": pp, "pc": pc}
        cur.update(kw)
        nw = (cur["wtok"], cur["builds"], cur["staged"], cur["serving"],
              cur["pp"], cur["pc"])
        return workers[:i] + (nw,) + workers[i + 1:]

    def _controller_actions(self, cidx: int, state: State) -> List[Action]:
        c0, c1, workers = state
        phase = (c0, c1)[cidx]
        tok = self.tokens[cidx]
        out: List[Action] = []

        def with_phase(p: str, ws: tuple = None) -> State:
            nc0, nc1 = (p, c1) if cidx == 0 else (c0, p)
            return (nc0, nc1, workers if ws is None else ws)

        if phase == C_START:
            ws = workers
            for i in range(self.n):
                ws = self._w(ws, i, pp=ws[i][4] | {tok})
            out.append((f"send_prepares(c{cidx})", with_phase(C_PREP, ws)))
        if phase == C_PREP and all(w[2] == tok for w in workers):
            ws = workers
            for i in range(self.n):
                ws = self._w(ws, i, pc=ws[i][5] | {tok})
            out.append((f"send_commits(c{cidx})",
                        with_phase(C_COMMIT, ws)))
        if phase == C_COMMIT and all(
                w[3] != OLD and w[3][1] == tok for w in workers):
            out.append((f"barrier_done(c{cidx})", with_phase(C_DONE)))
        if phase in (C_PREP, C_COMMIT):
            # refusal ack or timeout → abort + synchronous discard
            # fan-out (stale RPCs already in flight stay in flight)
            ws = workers
            for i in range(self.n):
                ws = self._w(ws, i, wtok=None, staged=None)
            out.append((f"abort_discard(c{cidx})",
                        with_phase(C_ABORTED, ws)))
        return out

    # -- transition system ----------------------------------------------

    def actions(self, state: State) -> Iterable[Action]:
        c0, c1, workers = state
        out: List[Action] = []
        out += self._controller_actions(0, state)
        if c1 != C_OFF:
            out += self._controller_actions(1, state)
        # crash/failover interleave with everything above
        if c0 != C_DEAD:
            out.append(("crash(c0)", (C_DEAD, c1, workers)))
        if c0 == C_DEAD and c1 == C_OFF:
            # takeover re-arms every worker via the real discard
            # semantics (clear staged + token; in-flight builds strand)
            ws = workers
            for i in range(self.n):
                ws = self._w(ws, i, wtok=None, staged=None)
            out.append(("takeover_discard(c1)", (c0, C_START, ws)))
        # worker-side message deliveries and build completions
        for i, (wtok, builds, staged, serving, pp, pc) in enumerate(workers):
            for t in sorted(pp):
                # prepare arrives: record token FIRST (latest prepare
                # wins), then start the staged-cache build
                ws = self._w(workers, i, wtok=t, builds=builds | {t},
                             pp=pp - {t})
                out.append((f"prepare_arrive(w{i},{t})", (c0, c1, ws)))
            for t in sorted(builds):
                if wtok == t or not self.checked:
                    # build done; the pre-stage token re-check passes
                    # (or is DISABLED in the broken twin)
                    ws = self._w(workers, i, builds=builds - {t},
                                 staged=t)
                    out.append((f"stage(w{i},{t})", (c0, c1, ws)))
                else:
                    # real refusal: ERR_PREPARE_SUPERSEDED
                    ws = self._w(workers, i, builds=builds - {t})
                    out.append((
                        f"stage_refused(w{i},{t}) "
                        f"[{ERR_PREPARE_SUPERSEDED}]", (c0, c1, ws)))
            for t in sorted(pc):
                if staged is None:
                    # real refusal: ERR_NOTHING_STAGED (post-discard /
                    # duplicate commit) — never installs
                    ws = self._w(workers, i, pc=pc - {t})
                    out.append((
                        f"commit_refused(w{i},{t}) "
                        f"[{ERR_NOTHING_STAGED}]", (c0, c1, ws)))
                elif staged == t or not self.checked:
                    # exact-match install (broken twin installs ANY
                    # staged cache — the mismatch the checker hunts)
                    ws = self._w(workers, i, staged=None,
                                 serving=(staged, t), pc=pc - {t})
                    out.append((f"commit(w{i},{t})", (c0, c1, ws)))
                else:
                    ws = self._w(workers, i, pc=pc - {t})
                    out.append((
                        f"commit_refused(w{i},{t}) "
                        f"[{token_mismatch(staged, t)}]", (c0, c1, ws)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        c0, c1, workers = state
        for i, (_wtok, _builds, _staged, serving, _pp, _pc) in \
                enumerate(workers):
            if serving != OLD and serving[0] != serving[1]:
                return (f"worker w{i} serves a cache staged under "
                        f"{serving[0]!r} installed by commit "
                        f"{serving[1]!r} — " +
                        token_mismatch(serving[0], serving[1]))
        # active controller: the successor once takeover happened
        active = 1 if c1 != C_OFF else 0
        phase = (c0, c1)[active]
        if phase == C_DONE:
            tok = self.tokens[active]
            for i, w in enumerate(workers):
                if w[3] == OLD or w[3][1] != tok:
                    return (f"controller c{active} observed its publish "
                            f"barrier complete but worker w{i} serves "
                            f"{w[3]!r}, not token {tok!r}")
        return None

    def accepting(self, state: State) -> bool:
        # action-less ⇒ c0 dead (crash is enabled otherwise), c1
        # terminal, all messages/builds drained: a finished incident —
        # acceptable whether c1's republish committed or aborted
        # (safety, not liveness, is the model's contract)
        c0, c1, workers = state
        drained = all(not w[1] and not w[4] and not w[5]
                      for w in workers)
        return (c0 == C_DEAD and c1 in (C_DONE, C_ABORTED) and drained)
