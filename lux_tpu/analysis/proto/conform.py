"""Trace-replay conformance: recorded soak event logs checked against
the protocol models.

The models (``election_model``/``publish_model``/``genline_model``/
``journal_model``) prove the protocols safe in the abstract; this
module closes the loop in the other direction — it feeds the event
logs the REAL fleet emits (``fault.chaos.chaos_soak`` and
``fault.chaos.autopilot_soak`` return them under ``"events"``) through
the models' legality rules and flags every observed transition the
models would not allow.  A soak that passes its own assertions but
emits a model-illegal transition is a conformance bug: either the
fleet drifted from the protocol or the model drifted from the fleet,
and both are findings.

The rules are the models' invariants projected onto the event
vocabulary:

* **genline** — write generations grow without gaps
  (``gen <= max_gen + 1``); a non-stale-tagged read satisfies its
  read-your-writes bound (``tag >= bound``); no tag ever leads the
  journal generation (``tag <= max_gen``) — the view-never-leads-
  reality invariant;
* **election** — every failover names a winner; at most ONE failover
  per incumbent incarnation (the elections<=1 split-brain guard);
* **journal/failover durability** — a promoted controller's journal
  generation covers every acked write (``gen >= max_gen``);
* **liveness bookkeeping** — a worker is not killed twice without an
  intervening rejoin (refresh rejoins the dead in ``chaos_soak``).

An EMPTY trace is itself a nonconformance: a soak that recorded
nothing proves nothing, and a conformance pass over it must not read
as coverage.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional


@dataclasses.dataclass(frozen=True)
class Nonconformance:
    """One model-illegal observed transition."""

    index: int          # position in the event log (-1: whole-log)
    rule: str           # short rule id, e.g. "genline.ryw"
    message: str
    event: Optional[dict] = None

    def format(self) -> str:
        at = "log" if self.index < 0 else f"event[{self.index}]"
        return f"{at} {self.rule}: {self.message}"


def _bad(out: List[Nonconformance], i: int, rule: str, msg: str,
         ev: Optional[dict] = None) -> None:
    out.append(Nonconformance(index=i, rule=rule, message=msg,
                              event=ev))


#: event vocabularies, used both for validation and for kind detection
CHAOS_EVS = ("write", "read", "read_stale", "refresh", "kill",
             "failover")
PILOT_EVS = ("write", "read", "sub", "scale", "failover")


def detect_kind(events: List[dict]) -> str:
    """``chaos_soak`` events carry the step index ``"i"``;
    ``autopilot_soak`` events do not."""
    for ev in events:
        return "chaos_soak" if "i" in ev else "autopilot_soak"
    return "empty"


def replay_chaos_soak(events: List[dict]) -> List[Nonconformance]:
    out: List[Nonconformance] = []
    if not events:
        _bad(out, -1, "trace.empty",
             "empty event log — a conformance pass over nothing is "
             "not coverage")
        return out
    max_gen = 0        # journal generation = max acked write gen
    dead: set = set()  # killed workers awaiting a rejoining refresh
    failovers = 0
    last_i = -1
    for idx, ev in enumerate(events):
        kind = ev.get("ev")
        if kind not in CHAOS_EVS:
            _bad(out, idx, "trace.unknown_event",
                 f"unknown event kind {kind!r}", ev)
            continue
        i = ev.get("i", -1)
        if not isinstance(i, int) or i < last_i:
            _bad(out, idx, "trace.step_order",
                 f"step index {i!r} regressed (last {last_i})", ev)
        else:
            last_i = i
        if kind == "write":
            gen = int(ev.get("gen", -1))
            if gen < 1:
                _bad(out, idx, "genline.gen_positive",
                     f"write at generation {gen} (< 1)", ev)
            elif gen > max_gen + 1:
                _bad(out, idx, "genline.gen_gap",
                     f"write jumped the generation line: {gen} > "
                     f"{max_gen} + 1", ev)
            max_gen = max(max_gen, gen)
        elif kind in ("read", "read_stale"):
            bound = int(ev.get("bound", 0))
            tag = int(ev.get("tag", -1))
            stale = bool(ev.get("stale", False))
            if tag > max_gen:
                _bad(out, idx, "genline.view_leads",
                     f"read tag {tag} leads the journal generation "
                     f"{max_gen} — view-never-leads-reality violated",
                     ev)
            if not stale and tag < bound:
                _bad(out, idx, "genline.ryw",
                     f"non-stale read at bound {bound} answered with "
                     f"tag {tag} — read-your-writes violated without "
                     "the stale tag", ev)
            if kind == "read" and stale:
                _bad(out, idx, "genline.fresh_required",
                     "stale answer on a stale_ok=False read", ev)
        elif kind == "refresh":
            gen = int(ev.get("gen", -1))
            if gen != max_gen:
                _bad(out, idx, "genline.refresh_gen",
                     f"refresh at generation {gen}, acked line is "
                     f"{max_gen}", ev)
            dead.clear()  # refresh rejoins every dead worker first
        elif kind == "kill":
            wid = ev.get("wid")
            if not wid:
                _bad(out, idx, "fleet.kill_unnamed",
                     "kill event without a worker id", ev)
            elif wid in dead:
                _bad(out, idx, "fleet.double_kill",
                     f"worker {wid} killed twice with no rejoin "
                     "between", ev)
            else:
                dead.add(wid)
        elif kind == "failover":
            failovers += 1
            if ev.get("winner") is None:
                _bad(out, idx, "election.no_winner",
                     "failover completed without a recorded winner",
                     ev)
            if failovers > 1:
                _bad(out, idx, "election.refenced",
                     f"failover #{failovers} for one incumbent "
                     "incarnation — elections<=1 violated", ev)
            gen = int(ev.get("gen", -1))
            if gen < max_gen:
                _bad(out, idx, "journal.promotion_lost_writes",
                     f"promoted controller journal at generation "
                     f"{gen} < acked line {max_gen}", ev)
            max_gen = max(max_gen, gen)
    return out


def replay_autopilot_soak(events: List[dict]) -> List[Nonconformance]:
    out: List[Nonconformance] = []
    if not events:
        _bad(out, -1, "trace.empty",
             "empty event log — a conformance pass over nothing is "
             "not coverage")
        return out
    max_gen = 0      # journal generation (write/failover line)
    last_seq = -1    # autoscaler action sequence
    failovers = 0
    for idx, ev in enumerate(events):
        kind = ev.get("ev")
        if kind not in PILOT_EVS:
            _bad(out, idx, "trace.unknown_event",
                 f"unknown event kind {kind!r}", ev)
            continue
        if kind == "write":
            gen = int(ev.get("gen", -1))
            if gen < 1:
                _bad(out, idx, "genline.gen_positive",
                     f"write at generation {gen} (< 1)", ev)
            elif gen > max_gen + 1:
                _bad(out, idx, "genline.gen_gap",
                     f"write jumped the generation line: {gen} > "
                     f"{max_gen} + 1", ev)
            max_gen = max(max_gen, gen)
        elif kind == "read":
            # autopilot reads always carry the full read-your-writes
            # bound (min_generation = acked_gen at submit time)
            tag = int(ev.get("tag", -1))
            if tag > max_gen:
                _bad(out, idx, "genline.view_leads",
                     f"read tag {tag} leads the journal generation "
                     f"{max_gen}", ev)
            if tag < max_gen:
                _bad(out, idx, "genline.ryw",
                     f"read tag {tag} below the acked line {max_gen} "
                     "— autopilot reads are bound to the full acked "
                     "generation", ev)
        elif kind == "sub":
            gen = int(ev.get("gen", -1))
            if gen > max_gen:
                _bad(out, idx, "genline.sub_leads",
                     f"subscription update at generation {gen} leads "
                     f"the journal generation {max_gen}", ev)
        elif kind == "scale":
            action = ev.get("action")
            if action not in ("scale_up", "scale_down"):
                _bad(out, idx, "pilot.scale_action",
                     f"unknown autoscaler action {action!r}", ev)
            seq = int(ev.get("seq", -1))
            if seq <= last_seq:
                _bad(out, idx, "pilot.scale_seq",
                     f"autoscaler seq {seq} did not advance (last "
                     f"{last_seq})", ev)
            last_seq = max(last_seq, seq)
            frac = float(ev.get("moved_frac", 0.0))
            if not (0.0 <= frac <= 1.0):
                _bad(out, idx, "pilot.moved_frac",
                     f"moved_frac {frac} outside [0, 1]", ev)
        elif kind == "failover":
            failovers += 1
            if ev.get("winner") is None:
                _bad(out, idx, "election.no_winner",
                     "failover completed without a recorded winner",
                     ev)
            if failovers > 1:
                _bad(out, idx, "election.refenced",
                     f"failover #{failovers} for one incumbent "
                     "incarnation — elections<=1 violated", ev)
            gen = int(ev.get("gen", -1))
            if gen < max_gen:
                _bad(out, idx, "journal.promotion_lost_writes",
                     f"promoted controller journal at generation "
                     f"{gen} < acked line {max_gen}", ev)
            max_gen = max(max_gen, gen)
    return out


def replay(events: Iterable[dict],
           kind: str = "auto") -> List[Nonconformance]:
    """Conformance-check one recorded soak log.  ``kind`` is
    ``"chaos_soak"``, ``"autopilot_soak"`` or ``"auto"`` (detect by
    event shape)."""
    evs = list(events)
    if kind == "auto":
        kind = detect_kind(evs)
    if kind == "chaos_soak":
        return replay_chaos_soak(evs)
    if kind == "autopilot_soak":
        return replay_autopilot_soak(evs)
    if kind == "empty":
        return replay_chaos_soak(evs)  # emits the trace.empty finding
    return [Nonconformance(
        index=-1, rule="trace.unknown_kind",
        message=f"unknown trace kind {kind!r} (expected chaos_soak / "
                "autopilot_soak / auto)")]
