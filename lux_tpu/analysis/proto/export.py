"""Counterexample → FaultPlan export: a model checker trace becomes a
seeded, replayable PR-14 fault schedule.

A broken-twin counterexample is a SCHEDULE — an ordering of detect /
claim / promote / crash steps that breaks an invariant.  The fleet's
fault engine (``lux_tpu.fault.plan``) already knows how to impose
schedules on the real code: ``delay`` rules stretch a window open,
``kill`` rules crash a thread at a named process point.  This module
translates a :class:`~lux_tpu.analysis.proto.mc.Violation` trace into
exactly those rules, so the abstract counterexample replays against
the real implementation (``fault.chaos.election_drill`` for the
election protocol — the round-trip the tests pin: unfenced group +
exported plan ⇒ a REAL second election; real fenced group + the same
plan ⇒ one election).

The plan's seed is derived deterministically from the trace, so the
exported JSON is bit-stable for a given counterexample — a failing
check prints a plan that IS its reproduction recipe.

Per-protocol mappings:

* **election** — the first ``claim_win(sA)`` becomes a ``delay`` at
  ``election.promote`` for standby A (hold the promotion window open);
  a later ``claim_win(sB)``/``detect(sB)`` becomes a ``delay`` at
  ``election.detect`` for standby B (make it the late TOCTOU
  detector).  Replayed by ``election_drill``.
* **journal** — each ``crash(#N)`` becomes a ``kill`` at
  ``journal.before_marker`` (the canonical batch-durable/marker-absent
  window; ``after`` staggers successive crashes).
* **genline** — the regressing ``deliver_report``/``heartbeat`` step
  becomes a ``delay`` at ``controller.heartbeat`` (stale heartbeats
  are delayed heartbeats).
* **publish** — a mid-barrier ``crash(c0)`` becomes a ``kill`` at
  ``controller.heartbeat`` for the incumbent (crash between prepare
  and commit fan-outs).
"""
from __future__ import annotations

import hashlib
import re
from typing import List, Optional

from lux_tpu.analysis.proto.mc import CheckResult, Violation
from lux_tpu.fault.plan import FaultPlan, FaultRule

#: how long the exported schedule holds the winner's promotion open /
#: stalls the late detector — generous multiples of the drill's probe
#: cadence (hb 10ms, death 30ms) so the replay is schedule-stable
PROMOTE_HOLD_MS = 1500.0
DETECT_STALL_MS = 500.0


def trace_seed(violation: Violation) -> int:
    """Deterministic plan seed from the counterexample trace."""
    digest = hashlib.sha256(
        "\n".join(violation.trace).encode()).hexdigest()
    return int(digest[:8], 16)


def _election_rules(trace: tuple) -> List[FaultRule]:
    wins = [m.group(1) for a in trace
            for m in [re.match(r"claim_win\(s(\d+)\)", a)] if m]
    first = wins[0] if wins else "0"
    late = next((w for w in wins[1:] if w != first), None)
    if late is None:
        # no second claimant in the trace: stall every OTHER detector
        late = "1" if first != "1" else "0"
    return [
        FaultRule("proc", "delay", point="election.promote",
                  owner=f"standby-{first}", count=1,
                  delay_ms=PROMOTE_HOLD_MS,
                  note=f"hold s{first}'s promotion window open "
                       f"(trace: claim_win(s{first}) first)"),
        FaultRule("proc", "delay", point="election.detect",
                  owner=f"standby-{late}", count=1,
                  delay_ms=DETECT_STALL_MS,
                  note=f"make s{late} the late detector (trace: its "
                       "claim lands after the first winner)"),
    ]


def _journal_rules(trace: tuple) -> List[FaultRule]:
    crashes = [a for a in trace if a.startswith("crash(")]
    return [
        FaultRule("proc", "kill", point="journal.before_marker",
                  count=1, after=n,
                  note=f"trace {crash}: crash in the batch-durable/"
                       "marker-absent window")
        for n, crash in enumerate(crashes or ("crash(#1)",))
    ]


def _genline_rules(trace: tuple) -> List[FaultRule]:
    stale = next((a for a in trace
                  if a.startswith(("deliver_report(", "heartbeat("))),
                 "deliver_report(w0,gen=0)")
    return [FaultRule("proc", "delay", point="controller.heartbeat",
                      count=1, delay_ms=DETECT_STALL_MS,
                      note=f"trace {stale}: a stale heartbeat is a "
                           "delayed heartbeat")]


def _publish_rules(trace: tuple) -> List[FaultRule]:
    return [FaultRule("proc", "kill", point="controller.heartbeat",
                      count=1,
                      note="trace crash(c0): incumbent dies "
                           "mid-republish, stale prepare/commit RPCs "
                           "left in flight")]


def export_faultplan(result: CheckResult) -> FaultPlan:
    """The FaultPlan whose schedule replays ``result``'s
    counterexample against the real implementation.  Raises
    ``ValueError`` for a clean result — there is nothing to export."""
    v = result.violation
    if v is None:
        raise ValueError(
            f"{result.protocol}: clean check has no counterexample "
            "to export")
    rules = {
        "election": _election_rules,
        "journal": _journal_rules,
        "genline": _genline_rules,
        "publish": _publish_rules,
    }.get(result.protocol)
    if rules is None:
        raise ValueError(
            f"no FaultPlan mapping for protocol {result.protocol!r}")
    return FaultPlan(
        rules(v.trace), seed=trace_seed(v),
        name=f"luxproto-{result.protocol}-counterexample")


def export_json(result: CheckResult) -> str:
    return export_faultplan(result).to_json()
