"""The explicit-state model checker: exhaustive BFS over hashable
protocol states.

Pure stdlib, deliberately tiny: a :class:`Model` enumerates initial
states and per-state actions (crash/restart transitions are ordinary
actions, so every model interleaves them at every step), declares a
safety ``invariant`` and which action-less states are acceptable
(``accepting``).  :func:`check` explores the FULL reachable state space
breadth-first — BFS, not DFS, so the first violation found is a
SHORTEST counterexample trace — and reports exact state/transition
counts (the numbers in docs/ANALYSIS.md's state-space table).

States must be hashable values built from primitives (nested tuples;
sort anything set-like so equal states hash equal).  Determinism is
part of the contract: two runs over the same model visit states in the
same order and return the same counterexample, which is what lets a
counterexample export as a seeded, reproducible FaultPlan
(``proto/export.py``).

A model stays *small-but-covering* (2–3 workers, 2 standbys, 1–2
in-flight writes): the protocols' guards are all pairwise (one fence,
one token comparison, one generation compare), so the classic
small-scope regime applies — every violation these protocols can
exhibit already shows up at these sizes.  ``max_states`` is a tripwire
against accidental state-space blowup, not a sampling knob: hitting it
FAILS the check (an unexplored space must never report clean).
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import (
    Dict,
    Hashable,
    Iterable,
    List,
    Optional,
    Tuple,
)

State = Hashable
Action = Tuple[str, State]  # (label, successor)


class Model:
    """One protocol as a transition system.  Subclasses implement the
    four hooks; everything else (search, traces, counts) is generic."""

    #: registry/reporting name ("election", "publish", ...)
    name = "unset"

    def initial(self) -> Iterable[State]:
        raise NotImplementedError

    def actions(self, state: State) -> Iterable[Action]:
        """Enabled transitions, in a DETERMINISTIC order."""
        raise NotImplementedError

    def invariant(self, state: State) -> Optional[str]:
        """None when ``state`` is safe, else the violation message."""
        raise NotImplementedError

    def accepting(self, state: State) -> bool:
        """True when a state with NO enabled actions is an acceptable
        terminal (protocol ran to completion); False makes it a
        deadlock finding."""
        raise NotImplementedError

    def config(self) -> Dict[str, object]:
        """The small-scope configuration, for the report."""
        return {}


@dataclasses.dataclass(frozen=True)
class Violation:
    """A counterexample: what broke, and the shortest action trace
    from an initial state to the breaking state."""

    kind: str  # "invariant" | "deadlock" | "state_space"
    message: str
    trace: Tuple[str, ...]
    state: State

    def format(self) -> str:
        steps = "\n".join(
            f"    {i + 1}. {a}" for i, a in enumerate(self.trace))
        return (f"{self.kind}: {self.message}\n  trace "
                f"({len(self.trace)} steps):\n{steps or '    (initial)'}")


@dataclasses.dataclass(frozen=True)
class CheckResult:
    """One exhaustive run: clean iff ``violation is None``."""

    protocol: str
    states: int
    transitions: int
    depth: int
    config: Dict[str, object]
    violation: Optional[Violation] = None

    @property
    def ok(self) -> bool:
        return self.violation is None

    def summary(self) -> str:
        tail = ("clean" if self.ok
                else f"VIOLATION ({self.violation.kind})")
        return (f"{self.protocol}: {self.states} states / "
                f"{self.transitions} transitions / depth {self.depth} "
                f"— {tail}")


def _trace(parents: Dict[State, Optional[Tuple[State, str]]],
           state: State) -> Tuple[str, ...]:
    out: List[str] = []
    cur: Optional[State] = state
    while cur is not None:
        link = parents[cur]
        if link is None:
            break
        cur, label = link
        out.append(label)
    return tuple(reversed(out))


def check(model: Model, max_states: int = 1_000_000) -> CheckResult:
    """Exhaustive BFS.  Returns on the FIRST violation (shortest trace
    by BFS construction) or after the whole reachable space is clean."""
    parents: Dict[State, Optional[Tuple[State, str]]] = {}
    frontier: deque = deque()
    depth_of: Dict[State, int] = {}
    transitions = 0
    max_depth = 0

    def fail(kind: str, message: str, state: State) -> CheckResult:
        return CheckResult(
            protocol=model.name, states=len(parents),
            transitions=transitions, depth=max_depth,
            config=dict(model.config()),
            violation=Violation(kind=kind, message=message,
                                trace=_trace(parents, state),
                                state=state))

    for s0 in model.initial():
        if s0 not in parents:
            parents[s0] = None
            depth_of[s0] = 0
            frontier.append(s0)
    while frontier:
        state = frontier.popleft()
        d = depth_of[state]
        max_depth = max(max_depth, d)
        bad = model.invariant(state)
        if bad is not None:
            return fail("invariant", bad, state)
        succ = list(model.actions(state))
        if not succ and not model.accepting(state):
            return fail(
                "deadlock",
                "no enabled action in a non-accepting state "
                "(the protocol wedged short of completion)", state)
        for label, nxt in succ:
            transitions += 1
            if nxt not in parents:
                if len(parents) >= max_states:
                    return fail(
                        "state_space",
                        f"state space exceeds max_states={max_states} "
                        "— an unexplored space must never report "
                        "clean; shrink the model config or raise the "
                        "bound", state)
                parents[nxt] = (state, label)
                depth_of[nxt] = d + 1
                frontier.append(nxt)
    return CheckResult(protocol=model.name, states=len(parents),
                       transitions=transitions, depth=max_depth,
                       config=dict(model.config()))
