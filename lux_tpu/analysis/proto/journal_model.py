"""Protocol model 4: journal crash-atomicity
(``mutate/deltalog.py`` — durable batch npz, then a separate fsync'd
``.ok`` marker; replay keeps the longest marker-committed prefix).

Conformance bridge: the model imports the real
``JOURNAL_FORMAT``/``OP_INSERT``/``OP_DELETE`` constants (reported in
``config()`` so a format bump is visible to the checker) and its
replay transition mirrors ``DeltaLog._journal_replay`` case for case:
stop at the first missing marker (removing the orphan batch so the seq
is reusable), tolerate a marker-without-batch torn directory, and —
the property under test — NEVER be asked to load a torn batch that
hides behind a marker, because the batch is fsync'd before the marker
(``fault.ppoint("journal.before_marker")`` sits exactly in that
window).

The writer appends batches seq 0..N-1; a crash can land anywhere —
including mid-append (a torn npz: bytes on disk, fsync never
finished).  After each crash the model replays and the writer resumes.

Safety invariants:

1. **acked durability** — every batch acked to the client survives
   every subsequent crash+replay;
2. **no torn batch behind a marker** — replay never admits a marker
   whose batch npz is torn (the real replay would ``np.load`` corrupt
   bytes); batch-before-marker ordering is exactly what forbids it.

The broken twin (``marker_first=True``) writes the marker BEFORE the
batch npz — the checker finds the crash point where replay admits a
torn (or absent-then-torn) batch.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from lux_tpu.analysis.proto.mc import Action, Model, State
from lux_tpu.mutate.deltalog import JOURNAL_FORMAT, OP_DELETE, OP_INSERT

# per-seq batch npz durability
B_NONE = 0   # nothing on disk
B_TORN = 2   # bytes on disk, fsync never completed (crash mid-append)
B_DURABLE = 1

RUNNING = "running"
CRASHED = "crashed"


class JournalModel(Model):
    """State: ``(phase, cells, acked, crashes, bad)`` where ``cells``
    holds one ``(batch, marker)`` per seq and ``acked`` is the set of
    seqs acked to the client.  The writer is deterministic (the real
    DeltaLog appends sequentially); the nondeterminism is WHERE the
    crash lands."""

    name = "journal"

    def __init__(self, n_batches: int = 2, max_crashes: int = 2,
                 marker_first: bool = False):
        self.n = int(n_batches)
        self.max_crashes = int(max_crashes)
        self.marker_first = bool(marker_first)

    def config(self) -> Dict[str, object]:
        return {"batches": self.n, "max_crashes": self.max_crashes,
                "marker_first": self.marker_first,
                "journal_format": JOURNAL_FORMAT,
                "ops": {"insert": OP_INSERT, "delete": OP_DELETE}}

    def initial(self) -> Iterable[State]:
        yield (RUNNING, ((B_NONE, False),) * self.n, frozenset(), 0,
               None)

    @staticmethod
    def _cell(cells: tuple, i: int, batch=None, marker=None) -> tuple:
        b, m = cells[i]
        nb = b if batch is None else batch
        nm = m if marker is None else marker
        return cells[:i] + ((nb, nm),) + cells[i + 1:]

    def _writer_step(self, cells: tuple, acked: frozenset,
                     seq: int) -> Optional[Tuple[str, tuple, frozenset]]:
        """The single enabled writer action for ``seq`` (the real
        append path is sequential): returns (label, cells', acked')."""
        batch, marker = cells[seq]
        if self.marker_first:
            # the broken twin commits before the bytes are durable
            if not marker:
                return (f"mark(seq={seq})",
                        self._cell(cells, seq, marker=True), acked)
            if batch == B_NONE:
                return (f"append_start(seq={seq})",
                        self._cell(cells, seq, batch=B_TORN), acked)
            if batch == B_TORN:
                return (f"append_fsync(seq={seq})",
                        self._cell(cells, seq, batch=B_DURABLE), acked)
        else:
            if batch == B_NONE:
                return (f"append_start(seq={seq})",
                        self._cell(cells, seq, batch=B_TORN), acked)
            if batch == B_TORN:
                return (f"append_fsync(seq={seq})",
                        self._cell(cells, seq, batch=B_DURABLE), acked)
            if not marker:
                # fault.ppoint("journal.before_marker") fires here in
                # the real writer — the canonical crash window
                return (f"mark(seq={seq})",
                        self._cell(cells, seq, marker=True), acked)
        if seq not in acked:
            return (f"ack(seq={seq})", cells, acked | {seq})
        return None

    def _replay(self, cells: tuple) -> Tuple[tuple, int, Optional[str]]:
        """Mirror of ``DeltaLog._journal_replay``: returns the
        post-replay cells, the recovered-prefix length, and a
        violation message if replay would load a torn batch."""
        out = list(cells)
        recovered = 0
        for seq in range(self.n):
            batch, marker = out[seq]
            if not marker:
                if batch != B_NONE:
                    out[seq] = (B_NONE, False)  # orphan npz removed
                break
            if batch == B_NONE:
                # marker without batch: torn directory state —
                # treated as uncommitted, marker removed
                out[seq] = (B_NONE, False)
                break
            if batch == B_TORN:
                return (tuple(out), recovered,
                        f"replay admitted seq {seq}: marker present "
                        "but the batch npz is torn — np.load reads "
                        "corrupt bytes (batch-before-marker ordering "
                        "violated)")
            recovered += 1
        return (tuple(out), recovered, None)

    def actions(self, state: State) -> Iterable[Action]:
        phase, cells, acked, crashes, bad = state
        out: List[Action] = []
        if bad is not None:
            return out  # freeze on first violation: shortest trace
        if phase == CRASHED:
            ncells, recovered, nbad = self._replay(cells)
            if nbad is None:
                lost = sorted(s for s in acked if s >= recovered
                              or ncells[s] != (B_DURABLE, True))
                if lost:
                    nbad = (f"acked batch(es) {lost} lost in replay "
                            f"(recovered prefix: {recovered})")
            out.append((f"replay(recovered={recovered})",
                        (RUNNING, ncells, acked, crashes, nbad)))
            return out
        step = None
        for seq in range(self.n):
            step = self._writer_step(cells, acked, seq)
            if step is not None:
                break
        if step is not None:
            label, ncells, nacked = step
            out.append((label, (RUNNING, ncells, nacked, crashes, bad)))
        if crashes < self.max_crashes:
            out.append((f"crash(#{crashes + 1})",
                        (CRASHED, cells, acked, crashes + 1, bad)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        _phase, cells, acked, _crashes, bad = state
        if bad is not None:
            return bad
        if not self.marker_first:
            # writer-order sanity on the clean model: a marker must
            # never exist over a non-durable batch at ANY instant —
            # this is the window a crash exploits
            for seq, (batch, marker) in enumerate(cells):
                if marker and batch != B_DURABLE:
                    return (f"seq {seq} has a marker over a "
                            f"non-durable batch (batch={batch})")
        return None

    def accepting(self, state: State) -> bool:
        # action-less ⇔ all batches acked + crash budget exhausted
        phase, cells, acked, crashes, _bad = state
        return (phase == RUNNING and len(acked) == self.n
                and crashes >= self.max_crashes
                and all(c == (B_DURABLE, True) for c in cells))
