"""Protocol model 3: the generation line with read-your-writes and
stale tags (``serve/live/journal.py`` generations ↔
``serve/live/controller.py`` delta-gen tracking and stale-bound
routing).

Conformance bridge: delta delivery raises the REAL
:class:`~lux_tpu.serve.live.errors.GenerationGap` (its ``have``/
``want`` fields drive the model's resync transition and appear in the
trace labels), so the catch-up contract being explored is the class
production code raises and handlers catch.

What the model explores (2 workers, 2 writes, 1 worker kill+rejoin):

* the journal generation ``G`` advances per acked write;
* each live worker applies deltas IN ORDER; an out-of-order delivery
  raises ``GenerationGap(have, want)`` and the worker resyncs from the
  journal (the real catch-up path);
* workers report their applied generation via acks and heartbeats —
  messages that can be DELAYED and arrive after newer reports: the
  controller's per-worker ``view`` must fold them in with a locked
  monotonic ``max`` (``LiveFleetController._raise_delta_gen``);
* reads carry a read-your-writes bound (the client's last acked gen):
  the controller serves FRESH from a worker whose view ≥ bound, else
  serves with a ``stale`` tag.

Safety invariants:

1. **view never leads reality** — ``view[w] <= applied[w]`` for every
   live worker, so a FRESH read is actually fresh;
2. **fresh means applied** — a read served fresh at bound ``b`` hits a
   worker with ``applied >= b``;
3. **the line never regresses** — a worker's view is nondecreasing
   while it is alive (the monotonic-max contract; regression breaks
   the read-your-writes session guarantee).

Broken twins:

* ``mode="stale_heartbeat"`` — ``view = report`` raw assignment: a
  delayed heartbeat drags the view backwards (invariant 3);
* ``mode="optimistic_send"`` — the view is bumped at delta SEND time
  instead of at the ack: a fresh read lands on a worker that has not
  applied the write yet (invariants 1/2).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from lux_tpu.analysis.proto.mc import Action, Model, State
from lux_tpu.serve.live.errors import GenerationGap

#: controller view folding modes; "monotonic_max" is the real
#: _raise_delta_gen contract, the others are the broken twins
MODES = ("monotonic_max", "stale_heartbeat", "optimistic_send")


class GenLineModel(Model):
    """State: ``(G, acked, workers, bad)`` with per-worker
    ``(alive, applied, view, deltas, reports)``:

    * ``G`` — journal generation (writes so far);
    * ``acked`` — highest write gen acked to the client (its
      read-your-writes bound);
    * ``deltas`` — in-flight delta gens (deliverable in any order);
    * ``reports`` — in-flight ack/heartbeat payloads (applied gen at
      send time — the delayed-message hazard);
    * ``bad`` — first observed safety violation, if any (reads are
      side-effect-free, so their violations are recorded in-state).
    """

    name = "genline"

    def __init__(self, n_workers: int = 2, max_writes: int = 2,
                 max_kills: int = 1, mode: str = "monotonic_max"):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}: {mode!r}")
        self.n = int(n_workers)
        self.max_writes = int(max_writes)
        self.max_kills = int(max_kills)
        self.mode = mode

    def config(self) -> Dict[str, object]:
        return {"workers": self.n, "max_writes": self.max_writes,
                "max_kills": self.max_kills, "mode": self.mode}

    def initial(self) -> Iterable[State]:
        w0 = (True, 0, 0, frozenset(), frozenset())
        yield (0, 0, (w0,) * self.n, 0, None)

    @staticmethod
    def _w(workers: tuple, i: int, **kw) -> tuple:
        alive, applied, view, deltas, reports = workers[i]
        cur = {"alive": alive, "applied": applied, "view": view,
               "deltas": deltas, "reports": reports}
        cur.update(kw)
        nw = (cur["alive"], cur["applied"], cur["view"], cur["deltas"],
              cur["reports"])
        return workers[:i] + (nw,) + workers[i + 1:]

    def _fold(self, view: int, report: int) -> int:
        if self.mode == "stale_heartbeat":
            return report  # the broken raw assignment
        return max(view, report)  # the real locked monotonic max

    def actions(self, state: State) -> Iterable[Action]:
        G, acked, workers, kills, bad = state
        out: List[Action] = []
        if bad is not None:
            return out  # freeze on first violation: shortest trace
        if G < self.max_writes:
            # journal commit: gen G+1; delta fans out to live workers,
            # the write is acked to the client at commit
            g = G + 1
            ws = workers
            for i, w in enumerate(workers):
                if not w[0]:
                    continue
                view = g if self.mode == "optimistic_send" else w[2]
                ws = self._w(ws, i, deltas=w[3] | {g}, view=view)
            out.append((f"write(gen={g})", (g, g, ws, kills, bad)))
        for i, (alive, applied, view, deltas, reports) in \
                enumerate(workers):
            if alive:
                for g in sorted(deltas):
                    if g == applied + 1:
                        ws = self._w(workers, i, applied=g,
                                     deltas=deltas - {g},
                                     reports=reports | {g})
                        out.append((f"apply(w{i},gen={g})",
                                    (G, acked, ws, kills, bad)))
                    else:
                        # out-of-order: the worker raises the real
                        # GenerationGap and resyncs from the journal
                        gap = GenerationGap(applied, g)
                        ws = self._w(workers, i, applied=G,
                                     deltas=frozenset(),
                                     reports=reports | {G})
                        out.append((
                            f"gap_resync(w{i},have={gap.have},"
                            f"want={gap.want})",
                            (G, acked, ws, kills, bad)))
                # heartbeat: report the CURRENT applied gen (acks above
                # already queued per-delta reports)
                if applied not in reports:
                    ws = self._w(workers, i, reports=reports | {applied})
                    out.append((f"heartbeat(w{i},gen={applied})",
                                (G, acked, ws, kills, bad)))
                if kills < self.max_kills:
                    ws = self._w(workers, i, alive=False,
                                 deltas=frozenset())
                    out.append((f"kill(w{i})",
                                (G, acked, ws, kills + 1, bad)))
                # reads: serve at the client's read-your-writes bound
                if view >= acked:
                    nbad = bad
                    if applied < acked:
                        nbad = (f"fresh read at bound {acked} served "
                                f"by w{i} with applied={applied} — an "
                                "unapplied write was read as fresh")
                    out.append((f"read_fresh(w{i},bound={acked})",
                                (G, acked, workers, kills, nbad)))
                elif acked > 0:
                    out.append((
                        f"read_stale(w{i},bound={acked},view={view})",
                        (G, acked, workers, kills, bad)))
            else:
                # rejoin: replica resyncs from the journal (applied=G);
                # the controller seeds the view from the resync gen
                ws = self._w(workers, i, alive=True, applied=G, view=G)
                out.append((f"rejoin(w{i},gen={G})",
                            (G, acked, ws, kills, bad)))
            # delayed report delivery (possible even after a kill: the
            # message was already in flight)
            for r in sorted(reports):
                nview = self._fold(view, r)
                nbad = bad
                if alive and nview < view:
                    nbad = (f"generation line regressed on w{i}: view "
                            f"{view} -> {nview} after a stale "
                            "heartbeat — read-your-writes session "
                            "guarantee broken")
                ws = self._w(workers, i, view=nview,
                             reports=reports - {r})
                out.append((f"deliver_report(w{i},gen={r})",
                            (G, acked, ws, kills, nbad)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        G, acked, workers, _kills, bad = state
        if bad is not None:
            return bad
        for i, (alive, applied, view, _d, _r) in enumerate(workers):
            if alive and view > applied:
                return (f"controller view of w{i} ({view}) leads its "
                        f"applied gen ({applied}) — a fresh read "
                        "routed there would serve an unapplied write")
            if applied > G:
                return (f"w{i} applied gen {applied} beyond the "
                        f"journal generation {G}")
        if acked > G:
            return f"acked gen {acked} beyond journal generation {G}"
        return None

    def accepting(self, state: State) -> bool:
        # reads/heartbeats keep at least one action enabled while any
        # worker lives, so action-less means every worker is dead with
        # kills exhausted and reports drained: an acceptable terminal
        # (no liveness promise with zero live replicas)
        _G, _acked, workers, kills, _bad = state
        return (all(not w[0] for w in workers)
                and kills >= self.max_kills
                and all(not w[4] for w in workers))
