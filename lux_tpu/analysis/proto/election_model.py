"""Protocol model 1: controller election fencing
(``serve/autopilot/election.py``).

The strongest possible conformance bridge: the model's transition
function IS the real code.  Every ``claim``/``release``/
``set_promoted``/``register``/``deregister`` transition rebuilds a real
:class:`~lux_tpu.serve.autopilot.election.StandbyGroup` from the model
state and invokes the real method, then reads the resulting state back
— so the checker exhaustively explores every interleaving of the
actual election logic rather than a hand-copied approximation that
could drift.

Small-but-covering configuration: 2–3 standbys, one dead incumbent
incarnation, at most one standby restart.  Coverage deliberately
includes the two nastiest schedules:

* **detached promotion** — ``stop()`` on a standby whose ``promote()``
  is still running deregisters it but cannot un-run the promotion; the
  in-flight call still reaches ``set_promoted``.  Deregistration shifts
  ``min(live ids)``, so WITHOUT the fence the next standby would win a
  rival claim while the detached promotion completes → two promotions.
* **check-then-claim TOCTOU** — ``_elect`` reads ``group.promoted``
  (None) and only then claims; a winner can finish in the gap.  The
  fence (claims keyed by the dead incarnation, never released on
  success) is what makes the late claim lose.

The safety invariant is the split-brain guard: **at most one promotion
per incumbent incarnation** (``group.elections <= 1``); the fenced
model additionally asserts claim integrity (a promoting standby holds
the claim; at most one promotion in flight).

The broken twin (:class:`UnfencedStandbyGroup`, ``fenced=False``) drops
the incarnation fence from ``claim`` and the checker finds the
shortest schedule to a second completed promotion;
``proto/export.py`` turns that trace into a seeded FaultPlan that
``fault.chaos.election_drill`` replays against real Standby threads.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from lux_tpu.analysis.proto.mc import Action, Model, State
from lux_tpu.serve.autopilot.election import StandbyGroup

#: the one dead-incumbent incarnation the model elects over
INCARNATION = "inc-0"

# standby phases
IDLE = "idle"          # probing; death not yet declared
DETECTED = "detected"  # in the _elect loop
PROMOTING = "promoting"  # claim held, promote() running
ZOMBIE = "zombie"      # stop()ed (deregistered) mid-promote: the
#                        in-flight promote still completes or fails
WON = "won"            # set_promoted ran
ADOPTED = "adopted"    # observed a winner, outcome "adopted"
STOPPED = "stopped"    # stop() ran (deregistered) / standby crashed


class UnfencedStandbyGroup(StandbyGroup):
    """The deliberately broken twin: ``claim`` keeps the lowest-live-id
    rule but DROPS the incarnation fence — a late detector can start a
    rival election for an already-handled death.  Exists only for the
    checker's broken-twin run and the chaos replay drill."""

    def claim(self, standby_id: int, incarnation: str) -> bool:
        standby_id = int(standby_id)
        with self._lock:
            if not self._ids or standby_id != min(self._ids):
                return False
            self._claimed[incarnation] = standby_id
            return True


class ElectionModel(Model):
    """State: (phases, registered, claimed, elections, restarts_used).

    ``phases[i]``/``registered[i]`` per standby; ``claimed`` is the
    incarnation fence holder (or None); ``elections`` counts
    ``set_promoted`` calls — the real split-brain counter.
    """

    name = "election"

    def __init__(self, n_standbys: int = 2, fenced: bool = True,
                 max_restarts: int = 1):
        self.n = int(n_standbys)
        self.fenced = bool(fenced)
        self.max_restarts = int(max_restarts)
        self.group_cls = StandbyGroup if fenced else UnfencedStandbyGroup

    def config(self) -> Dict[str, object]:
        return {"standbys": self.n, "fenced": self.fenced,
                "max_restarts": self.max_restarts,
                "incarnation": INCARNATION}

    # -- the real-code bridge -------------------------------------------

    def _group(self, registered: Tuple[bool, ...],
               claimed: Optional[int]) -> StandbyGroup:
        """A real StandbyGroup rebuilt from model state (the `_claimed`
        seed reaches into the class on purpose: there is no public
        'resume mid-election' API, and the model must explore exactly
        those mid-election states)."""
        g = self.group_cls()
        for i, reg in enumerate(registered):
            if reg:
                g.register(i)
        if claimed is not None:
            g._claimed[INCARNATION] = claimed
        return g

    # -- transition system ----------------------------------------------

    def initial(self) -> Iterable[State]:
        yield ((IDLE,) * self.n, (True,) * self.n, None, 0, 0)

    def actions(self, state: State) -> Iterable[Action]:
        phases, registered, claimed, elections, restarts = state
        out = []
        for i in range(self.n):
            ph = phases[i]
            if ph == IDLE:
                out.append((f"detect(s{i})", (
                    _set(phases, i, DETECTED), registered, claimed,
                    elections, restarts)))
            if ph == DETECTED:
                if elections >= 1:
                    # the _elect loop head saw group.promoted set
                    out.append((f"adopt(s{i})", (
                        _set(phases, i, ADOPTED), registered, claimed,
                        elections, restarts)))
                # ... and the TOCTOU schedule: the promoted check read
                # None BEFORE a winner landed, so the claim still runs
                # — the REAL claim decides (the fence is what makes a
                # late claim lose here)
                g = self._group(registered, claimed)
                if g.claim(i, INCARNATION):
                    out.append((f"claim_win(s{i})", (
                        _set(phases, i, PROMOTING), registered,
                        g.claimed_by(INCARNATION), elections,
                        restarts)))
                # a refused claim is wait_promoted + retry: no state
                # change, so no transition emitted
            if ph in (PROMOTING, ZOMBIE):
                nxt_done = WON if ph == PROMOTING else STOPPED
                nxt_fail = DETECTED if ph == PROMOTING else STOPPED
                # promotion completes: the real set_promoted (a ZOMBIE's
                # in-flight promote completes the same way)
                g = self._group(registered, claimed)
                g.set_promoted(i, None, None)
                out.append((f"promote_ok(s{i})", (
                    _set(phases, i, nxt_done), registered, claimed,
                    elections + g.elections, restarts)))
                # ... or raises: the real release lifts the fence
                g2 = self._group(registered, claimed)
                g2.release(i, INCARNATION)
                out.append((f"promote_fail(s{i})", (
                    _set(phases, i, nxt_fail), registered,
                    g2.claimed_by(INCARNATION), elections, restarts)))
            if ph == PROMOTING:
                # stop() mid-promote: the real deregister shifts
                # min(live ids) while the promote call keeps running
                g = self._group(registered, claimed)
                g.deregister(i)
                out.append((f"stop_mid_promote(s{i})", (
                    _set(phases, i, ZOMBIE),
                    _set(registered, i, False), claimed, elections,
                    restarts)))
            if ph in (IDLE, DETECTED, WON, ADOPTED):
                # clean shutdown or crash-before-claim
                g = self._group(registered, claimed)
                g.deregister(i)
                out.append((f"stop(s{i})", (
                    _set(phases, i, STOPPED),
                    _set(registered, i, False), claimed, elections,
                    restarts)))
            if ph == STOPPED and restarts < self.max_restarts:
                # a replacement standby under the same id re-registers
                # mid-incident; the fence must force it to adopt (or
                # lose), never re-elect
                g = self._group(registered, claimed)
                g.register(i)
                out.append((f"restart(s{i})", (
                    _set(phases, i, IDLE), _set(registered, i, True),
                    claimed, elections, restarts + 1)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        phases, registered, claimed, elections, _restarts = state
        if elections > 1:
            return (f"split brain: {elections} promotions for dead "
                    f"incarnation {INCARNATION!r} — the incarnation "
                    "fence admitted a second election")
        if not self.fenced:
            # the twin asks ONE question — can a second promotion
            # complete? — so claim-integrity (the fence's own
            # guarantee) is not asserted on it
            return None
        promoting = [i for i, p in enumerate(phases)
                     if p in (PROMOTING, ZOMBIE)]
        if len(promoting) > 1:
            return (f"standbys {promoting} promoting concurrently — "
                    "claim() returned True twice for one incarnation")
        for i in promoting:
            if claimed != i:
                return (f"standby s{i} promoting without holding the "
                        f"claim (fence holder: {claimed})")
        return None

    def accepting(self, state: State) -> bool:
        # action-less states are all-stopped with restarts exhausted:
        # nobody left to elect — acceptable (no liveness promise with
        # zero live standbys); any OTHER wedged state is a deadlock
        phases, _registered, _claimed, _elections, restarts = state
        return (all(p == STOPPED for p in phases)
                and restarts >= self.max_restarts)


def _set(tup: tuple, i: int, val) -> tuple:
    return tup[:i] + (val,) + tup[i + 1:]
