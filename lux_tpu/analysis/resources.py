"""LUX-R: resource-lifecycle analysis (jax-free, AST only).

The fleet's four leak-prone resource kinds, each with an acquire that
the code must pair with a release ON EVERY EXIT — a release that only
runs on the happy path is a finding, because the exception path is
exactly where a pod turns flaky:

* R001 — threads.  A ``threading.Thread`` stored on ``self`` and
  ``start()``-ed must be ``join()``-ed somewhere in the class, and a
  join on a stop/close path must carry ``timeout=`` (an unbounded join
  turns one wedged worker into a wedged fleet).  A LOCAL thread that is
  started, never stored, never joined, and not ``daemon=True`` outlives
  its function with nothing holding a handle to it.  Deliberate
  fire-and-forget daemon threads (the worker's per-connection loops)
  are exempt BY the ``daemon=True`` in their constructor — the
  constructor states the contract.
* R002 — sockets.  ``shutdown(SHUT_RDWR)`` must precede ``close()`` on
  any socket another thread may be parked in ``accept``/``recv`` on:
  on Linux ``close()`` alone does NOT wake the blocked thread, so every
  stop eats the full join timeout — the PR 16 bug, now a checker.  The
  park is recognized lexically: the same socket identity is accepted/
  received on in a DIFFERENT function than the one closing it.
* R003 — tmpdirs.  Every ``tempfile.mkdtemp`` needs a matching
  ``shutil.rmtree`` on the same identity somewhere in the module, and
  a local-scope reclaim must be exception-safe (``finally``/handler),
  not tail-of-function.
* R004 — file handles.  ``open()`` outside a ``with`` leaks its fd on
  any exception between open and close.  Exempt shapes: the handle is
  immediately the subject of ``with f:``, closed inside a ``finally``/
  handler, returned to a caller that owns it, or stored on ``self``
  with a ``close()`` elsewhere in the class (a lifecycle-managed
  member, e.g. the flight recorder's event log).

Identities are lexical base names (``self._srv`` and a local ``srv``
swapped out of it unify through simple-assignment aliasing, including
tuple swaps); see docs/ANALYSIS.md for the stated limits.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, Module, call_name

#: methods whose name marks a stop/close path — joins here must bound
#: their wait, or one wedged thread wedges every caller up the stack
_STOP_NAMES = {"stop", "close", "kill", "shutdown", "terminate",
               "__exit__", "__del__"}

#: receiver method names that park the calling thread on a socket
_PARK_ATTRS = {"accept", "recv", "recv_into", "recv_exact"}


def _base_name(expr: ast.AST) -> Optional[str]:
    """Lexical identity: 'x' for ``x``, '_f' for ``self._f`` (or any
    single-attribute access), None for anything deeper."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name):
        return expr.attr
    return None


def _unwrap(expr: ast.AST) -> ast.AST:
    """Peel ``list(x)`` / ``x[:]`` wrappers so ``for t in list(self._ts)``
    still aliases the container."""
    while True:
        if (isinstance(expr, ast.Call) and call_name(expr) in
                ("list", "tuple", "sorted", "reversed")
                and len(expr.args) == 1):
            expr = expr.args[0]
        elif isinstance(expr, ast.Subscript) and isinstance(
                expr.slice, ast.Slice):
            expr = expr.value
        else:
            return expr


class _Aliases:
    """Module-wide union of lexical identities through simple
    assignments (``a = b``, tuple swaps, for-loop iteration)."""

    def __init__(self, tree: ast.AST):
        self._parent: Dict[str, str] = {}
        for node in ast.walk(tree):
            pairs: List[Tuple[ast.AST, ast.AST]] = []
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, v = node.targets[0], node.value
                if isinstance(t, ast.Tuple) and isinstance(v, ast.Tuple) \
                        and len(t.elts) == len(v.elts):
                    pairs = list(zip(t.elts, v.elts))
                else:
                    pairs = [(t, v)]
            elif isinstance(node, ast.For):
                pairs = [(node.target, _unwrap(node.iter))]
            for t, v in pairs:
                a, b = _base_name(t), _base_name(_unwrap(v))
                if a and b and a != b:
                    self.union(a, b)

    def find(self, n: str) -> str:
        while n in self._parent:
            n = self._parent[n]
        return n

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self._parent[ra] = rb


def _in_cleanup(mod: Module, node: ast.AST) -> bool:
    """True when ``node`` sits in a ``finally`` block or an ``except``
    handler — i.e. it runs on the exception path."""
    for anc in mod.ancestors(node):
        if not isinstance(anc, ast.Try):
            continue
        stmts = list(anc.finalbody)
        for h in anc.handlers:
            stmts.extend(h.body)
        for stmt in stmts:
            if node is stmt or any(node is d for d in ast.walk(stmt)):
                return True
    return False


def _receiver_calls(tree: ast.AST) -> Iterable[Tuple[ast.Call, str,
                                                     str]]:
    """(call node, receiver base name, method attr) for every
    ``<recv>.<attr>(...)`` call in ``tree``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            base = _base_name(node.func.value)
            if base:
                yield node, base, node.func.attr


class ResourceLifecycleChecker(Checker):
    family = "resource-lifecycle"
    name = "resources"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        aliases = _Aliases(mod.tree)
        out.extend(self._sockets(mod, aliases))
        out.extend(self._tmpdirs(mod, aliases))
        out.extend(self._files(mod))
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._class_threads(mod, cls))
        out.extend(self._local_threads(mod))
        return out

    # -- R001: threads --------------------------------------------------

    def _class_threads(self, mod: Module, cls: ast.ClassDef
                       ) -> Iterable[Finding]:
        methods = [s for s in cls.body
                   if isinstance(s, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        #: field -> the Thread() ctor (or start) node to report at
        fields: Dict[str, ast.AST] = {}
        for meth in methods:
            # locals holding a Thread in this method
            local_threads: Set[str] = set()
            for node in ast.walk(meth):
                if isinstance(node, ast.Call) and \
                        call_name(node).split(".")[-1] == "Thread":
                    p = mod.parent(node)
                    if isinstance(p, ast.Assign):
                        for t in p.targets:
                            b = _base_name(t)
                            if b is None:
                                continue
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                fields.setdefault(b, node)
                            elif isinstance(t, ast.Name):
                                local_threads.add(b)
                    elif isinstance(p, ast.Call) and isinstance(
                            p.func, ast.Attribute) and \
                            p.func.attr == "append":
                        b = _base_name(p.func.value)
                        if b:
                            fields.setdefault(b, node)
            for node, base, attr in _receiver_calls(meth):
                if attr == "append" and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in local_threads:
                    tgt = _base_name(node.func.value)
                    if tgt:
                        fields.setdefault(tgt, node)

        if not fields:
            return []

        # join evidence, with intra-class aliasing (t = self._thread,
        # tuple swaps, for t in self._threads)
        aliases = _Aliases(cls)
        joined: Set[str] = set()
        out: List[Finding] = []
        for meth in methods:
            for node, base, attr in _receiver_calls(meth):
                if attr != "join":
                    continue
                root = aliases.find(base)
                for f in fields:
                    if aliases.find(f) == root:
                        joined.add(f)
                        if meth.name in _STOP_NAMES and not (
                                node.args or node.keywords):
                            out.append(self.finding(
                                mod, node, "LUX-R001",
                                f"unbounded join of '{cls.name}.{f}' "
                                f"on the stop path '{meth.name}' — "
                                "pass timeout=... so one wedged "
                                "thread cannot wedge every caller"))
        for f, site in sorted(fields.items()):
            if f not in joined:
                out.append(self.finding(
                    mod, site, "LUX-R001",
                    f"thread stored on '{cls.name}.{f}' is started "
                    "but never joined on any stop/close path — a "
                    "stop() that does not join leaks the thread (or "
                    "races its last writes)"))
        return out

    def _local_threads(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            ctors: Dict[str, ast.Call] = {}
            daemon: Set[str] = set()
            consumed: Set[str] = set()
            started: Set[str] = set()
            joined: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        call_name(node).split(".")[-1] == "Thread":
                    is_daemon = any(
                        kw.arg == "daemon" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is True
                        for kw in node.keywords)
                    p = mod.parent(node)
                    if isinstance(p, ast.Assign) and len(p.targets) == 1 \
                            and isinstance(p.targets[0], ast.Name):
                        n = p.targets[0].id
                        ctors[n] = node
                        if is_daemon:
                            daemon.add(n)
                    elif isinstance(p, ast.Attribute) and \
                            p.attr == "start" and not is_daemon:
                        # chained Thread(...).start(): nothing can ever
                        # join it — fine only when declared daemon
                        out.append(self.finding(
                            mod, node, "LUX-R001",
                            "Thread(...).start() drops the only "
                            "handle — join it, store it, or "
                            "declare daemon=True"))
            for node, base, attr in _receiver_calls(fn):
                if base in ctors:
                    if attr == "start":
                        started.add(base)
                    elif attr == "join":
                        joined.add(base)
            for node in ast.walk(fn):
                # any OTHER use of the name (argument, append, return,
                # attribute store) transfers ownership out of this rule
                if isinstance(node, ast.Name) and node.id in ctors:
                    p = mod.parent(node)
                    if isinstance(p, (ast.Call, ast.Return, ast.Tuple,
                                      ast.List, ast.Dict)) or (
                            isinstance(p, ast.Assign)
                            and node is p.value):
                        if not (isinstance(p, ast.Call)
                                and p.func is node):
                            consumed.add(node.id)
                if isinstance(node, ast.Attribute) and isinstance(
                        node.value, ast.Name) and \
                        node.value.id in ctors and \
                        node.attr not in ("start", "join", "daemon",
                                          "name", "is_alive", "ident"):
                    consumed.add(node.value.id)
            for n in sorted(started - joined - consumed - daemon):
                out.append(self.finding(
                    mod, ctors[n], "LUX-R001",
                    f"local thread '{n}' is started but neither "
                    "joined, stored, nor daemon=True — it outlives "
                    f"'{fn.name}' with no handle left to stop it"))
        return out

    # -- R002: sockets --------------------------------------------------

    def _sockets(self, mod: Module, aliases: _Aliases
                 ) -> Iterable[Finding]:
        socket_roots: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                last = call_name(node).split(".")[-1]
                if last in ("socket", "create_connection",
                            "create_server"):
                    p = mod.parent(node)
                    if isinstance(p, ast.Assign):
                        for t in p.targets:
                            b = _base_name(t)
                            if b:
                                socket_roots.add(aliases.find(b))

        parked: Dict[str, Set[str]] = {}    # root -> fn names parking
        shut: Set[str] = set()
        closes: List[Tuple[ast.Call, str, str]] = []
        for node, base, attr in _receiver_calls(mod.tree):
            root = aliases.find(base)
            if root not in socket_roots:
                continue
            fn = mod.enclosing_function(node)
            fname = fn.name if fn else "<module>"
            if attr in _PARK_ATTRS:
                parked.setdefault(root, set()).add(fname)
            elif attr == "shutdown":
                shut.add(root)
            elif attr == "close":
                closes.append((node, root, fname))

        out: List[Finding] = []
        for node, root, fname in closes:
            park_fns = parked.get(root, set()) - {fname}
            if park_fns and root not in shut:
                out.append(self.finding(
                    mod, node, "LUX-R002",
                    f"socket '{root}' is closed here while "
                    f"'{sorted(park_fns)[0]}' may be blocked in "
                    "accept/recv on it — call "
                    "shutdown(socket.SHUT_RDWR) first; close() alone "
                    "does not wake a parked thread on Linux (the "
                    "PR 16 stall)"))
        return out

    # -- R003: tmpdirs --------------------------------------------------

    def _tmpdirs(self, mod: Module, aliases: _Aliases
                 ) -> Iterable[Finding]:
        reclaimed: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node).split(".")[-1] == "rmtree" and \
                    node.args:
                b = _base_name(_unwrap(node.args[0]))
                if b:
                    reclaimed.add(aliases.find(b))

        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and
                    call_name(node).split(".")[-1] == "mkdtemp"):
                continue
            p = mod.parent(node)
            target: Optional[str] = None
            local = False
            if isinstance(p, ast.Assign) and len(p.targets) == 1:
                target = _base_name(p.targets[0])
                local = isinstance(p.targets[0], ast.Name)
            if target is None:
                out.append(self.finding(
                    mod, node, "LUX-R003",
                    "mkdtemp result is not bound to a reclaimable "
                    "name — nothing can ever rmtree it"))
                continue
            root = aliases.find(target)
            if local and self._transfers_ownership(mod, node, target):
                # returned / stored on self / handed to a constructor:
                # the new owner owes the rmtree, not this function
                continue
            if root not in reclaimed:
                out.append(self.finding(
                    mod, node, "LUX-R003",
                    f"tmpdir '{target}' from mkdtemp has no rmtree "
                    "reclamation anywhere in this module — every "
                    "call leaks a directory"))
                continue
            if local:
                out.extend(self._tmpdir_exception_path(
                    mod, node, target))
        return out

    @staticmethod
    def _transfers_ownership(mod: Module, site: ast.AST,
                             name: str) -> bool:
        """True when the local tmpdir name escapes its function with an
        owner attached: returned to the caller, stored on an attribute,
        or passed to a constructor (Uppercase-initial callee — the
        launcher's ProcHandle shape).  A plain lowercase call merely
        USES the dir; the opener still owes the reclaim."""
        fn = mod.enclosing_function(site)
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None \
                    and name in {n.id for n in ast.walk(node.value)
                                 if isinstance(n, ast.Name)}:
                return True
            if isinstance(node, ast.Assign):
                if any(isinstance(t, ast.Attribute)
                       for t in node.targets) and isinstance(
                           node.value, ast.Name) and \
                        node.value.id == name:
                    return True
            if isinstance(node, ast.Call) and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in node.args):
                last = call_name(node).split(".")[-1]
                if last[:1].isupper():
                    return True
        return False

    def _tmpdir_exception_path(self, mod: Module, site: ast.AST,
                               name: str) -> Iterable[Finding]:
        """A local-scope reclaim must survive an exception between
        mkdtemp and rmtree (ownership transfers were already excused
        by ``_transfers_ownership`` before this runs)."""
        fn = mod.enclosing_function(site)
        if fn is None:
            return []
        rmtree_sites: List[ast.AST] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    call_name(node).split(".")[-1] == "rmtree" and \
                    node.args and _base_name(
                        _unwrap(node.args[0])) == name:
                rmtree_sites.append(node)
        if not rmtree_sites:
            return []
        if any(_in_cleanup(mod, r) for r in rmtree_sites):
            return []
        return [self.finding(
            mod, rmtree_sites[0], "LUX-R003",
            f"tmpdir '{name}' is reclaimed only on the happy path — "
            "an exception between mkdtemp and this rmtree leaks the "
            "directory; move the rmtree into try/finally")]

    # -- R004: file handles ---------------------------------------------

    def _files(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and
                    call_name(node) in ("open", "io.open")):
                continue
            p = mod.parent(node)
            if isinstance(p, ast.withitem):
                continue
            if isinstance(p, ast.Return):
                continue  # caller owns the handle
            if isinstance(p, ast.Assign) and len(p.targets) == 1:
                t = p.targets[0]
                if isinstance(t, ast.Name) and self._name_is_managed(
                        mod, node, t.id):
                    continue
                if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name) and t.value.id == "self" \
                        and self._field_is_closed(mod, node, t.attr):
                    continue
            out.append(self.finding(
                mod, node, "LUX-R004",
                "open() outside a with block leaks the handle on any "
                "exception before close — use 'with open(...)', close "
                "in try/finally, or return the handle to a caller "
                "that does"))
        return out

    @staticmethod
    def _name_is_managed(mod: Module, site: ast.AST,
                         name: str) -> bool:
        fn = mod.enclosing_function(site) or mod.tree
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Name) and \
                            item.context_expr.id == name:
                        return True
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr == "close" and isinstance(
                        node.func.value, ast.Name) and \
                    node.func.value.id == name:
                if _in_cleanup(mod, node):
                    return True
        return False

    @staticmethod
    def _field_is_closed(mod: Module, site: ast.AST,
                         field: str) -> bool:
        cls = None
        for anc in mod.ancestors(site):
            if isinstance(anc, ast.ClassDef):
                cls = anc
                break
        if cls is None:
            return False
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr == "close":
                b = _base_name(node.func.value)
                if b == field:
                    return True
        return False


#: synthetic positives — each MUST fire (tools/luxcheck.py --twins and
#: tests/test_luxguard.py; a silently-pacified rule fails the suite)
TWINS = (
    ("r001_never_joined", """
import threading

class Pump:
    def __init__(self):
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        pass

    def stop(self):
        pass
""", ("LUX-R001",)),
    ("r001_unbounded_stop_join", """
import threading

class Pump:
    def __init__(self):
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        pass

    def stop(self):
        self._thread.join()
""", ("LUX-R001",)),
    ("r002_close_without_shutdown", """
import socket
import threading

class Server:
    def __init__(self):
        self._srv = socket.socket()
        self._thread = threading.Thread(target=self._accept_loop)

    def _accept_loop(self):
        while True:
            sock, _ = self._srv.accept()

    def stop(self):
        self._srv.close()
        self._thread.join(timeout=5.0)
""", ("LUX-R002",)),
    ("r003_no_reclaim", """
import tempfile

def scratch():
    d = tempfile.mkdtemp(prefix="twin_")
    return None
""", ("LUX-R003",)),
    ("r003_happy_path_only", """
import shutil
import tempfile

def scratch(work):
    d = tempfile.mkdtemp(prefix="twin_")
    work(d)
    shutil.rmtree(d)
""", ("LUX-R003",)),
    ("r004_bare_open", """
def head(path):
    f = open(path)
    line = f.readline()
    f.close()
    return line
""", ("LUX-R004",)),
)
