"""Lock-order checkers (LUX-L*): the serving/mutation fleet's lock
discipline as AST lints.

PR 12 split the replica worker's locking into ``_live_lock`` (admission
order) and ``_lock`` (engine/staged state) and wrote the ordering down
as COMMENTS ("Lock order _live_lock -> _lock matches _op_delta").  A
comment can't fail CI; these checkers can.  They build a per-module
lock-acquisition graph from the AST — lexically nested ``with`` blocks
plus one level of same-class/same-module call propagation — and flag
the four shapes that turn a two-lock design into a deadlock or a
stall:

* LUX-L001 — a CYCLE in the acquisition graph (including a self-cycle
  on a known non-reentrant ``threading.Lock`` reached through a helper
  call: the classic re-entrant deadlock).
* LUX-L002 — the same two locks acquired in BOTH orders by direct
  lexical nesting (the textbook AB/BA deadlock pair).
* LUX-L003 — a blocking call (thread ``join``, future ``result``,
  socket send/recv/accept/connect, ``time.sleep``, engine
  compile/prewarm) made while LEXICALLY holding a lock: the fleet's
  hot locks bound every RPC's tail latency, so blocking under one
  stalls the whole replica.  ``Condition.wait`` is deliberately NOT in
  the set — ``Condition(self._lock).wait()`` RELEASES the lock while
  waiting and is this repo's standard wake idiom.
* LUX-L004 — a raw ``.acquire()``/``.release()`` UNBALANCED within one
  function (acquired in one helper, released in another): invisible to
  both this graph and human readers; use ``with`` or pair them in one
  frame.  ``__enter__``/``__exit__`` pairs are exempt — a lock-shaped
  context manager is the FIX for this finding, not an instance of it.

Scope and honesty: the graph is PER MODULE and identities are lexical
(``ClassName._attr`` for ``self`` attributes, the bare name for
module-level locks, the unparsed expression otherwise).  Cross-module
cycles and aliased locks (``Condition(self._lock)`` shares its
underlying lock) are out of reach — the protocol tier
(``lux_tpu.analysis.proto``) covers the cross-component orderings;
docs/ANALYSIS.md states the boundary.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from lux_tpu.analysis.core import (
    Checker,
    Finding,
    Module,
    call_name,
    dotted_name,
)
from lux_tpu.analysis.threads import _walk_shallow

#: threading constructors that create a lock-like object; the bool says
#: whether re-acquisition on the same thread self-deadlocks
_LOCK_CTORS = {
    "Lock": True,          # non-reentrant
    "Semaphore": True,
    "BoundedSemaphore": True,
    "RLock": False,
    "Condition": False,    # re-entrant w.r.t. its (R)Lock by idiom here
}

#: keywords marking a with-expression as a lock (same list as
#: Module.under_lock, so LUX-L and LUX-C agree on what a lock is)
_LOCKISH = ("lock", "mutex", "cond", "flock", "wake")

#: method/attribute names whose call blocks the calling thread
_BLOCKING_ATTRS = {
    "join", "result", "sendall", "recv", "recv_exact", "recv_into",
    "accept", "connect", "wait_promoted", "prewarm", "compile",
}

#: dotted call names that block regardless of receiver
_BLOCKING_CALLS = {"time.sleep"}


def _is_lockish(src: str) -> bool:
    low = src.lower()
    return any(k in low for k in _LOCKISH)


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/... when ``value`` is a threading-style lock
    constructor call, else None."""
    if not isinstance(value, ast.Call):
        return None
    last = call_name(value).split(".")[-1]
    return last if last in _LOCK_CTORS else None


class _ModuleLocks:
    """The module's lock identities + per-function acquisition sets."""

    def __init__(self, mod: Module):
        self.mod = mod
        # identity -> ctor kind (None = lexically lock-ish, ctor unseen)
        self.kinds: Dict[str, Optional[str]] = {}
        self._collect_identities()
        # "C.m" / "f" -> locks acquired lexically anywhere in the body
        self.fn_locks: Dict[str, Set[str]] = {}
        self._collect_fn_locks()

    # -- identities -----------------------------------------------------

    def _collect_identities(self) -> None:
        for node in self.mod.tree.body:
            if isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.kinds[t.id] = kind
            elif isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    kind = _ctor_kind(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            self.kinds[f"{node.name}.{t.attr}"] = kind

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        for anc in self.mod.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
        return None

    def lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Resolve a with-context expression to a lock identity, or
        None when it isn't a lock."""
        src = ast.unparse(expr)
        if isinstance(expr, ast.Name):
            if expr.id in self.kinds:
                return expr.id
            return expr.id if _is_lockish(src) else None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None):
            ident = f"{cls}.{expr.attr}"
            if ident in self.kinds or _is_lockish(src):
                return ident
            return None
        return src if _is_lockish(src) else None

    def kind_of(self, ident: str) -> Optional[str]:
        return self.kinds.get(ident)

    # -- per-function lock sets ----------------------------------------

    def _fn_key(self, fn: ast.AST) -> str:
        cls = self.enclosing_class(fn)
        return f"{cls}.{fn.name}" if cls else fn.name

    def _collect_fn_locks(self) -> None:
        for fn in ast.walk(self.mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            cls = self.enclosing_class(fn)
            acquired: Set[str] = set()
            for node in _walk_shallow(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        ident = self.lock_id(item.context_expr, cls)
                        if ident:
                            acquired.add(ident)
            self.fn_locks[self._fn_key(fn)] = acquired

    def callee_locks(self, call: ast.Call,
                     cls: Optional[str]) -> Tuple[str, Set[str]]:
        """(callee display name, locks that callee acquires) for
        same-class ``self.m(...)`` and same-module ``f(...)`` calls;
        empty set for anything unresolvable."""
        f = call.func
        if (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls is not None):
            key = f"{cls}.{f.attr}"
            return key, self.fn_locks.get(key, set())
        if isinstance(f, ast.Name):
            return f.id, self.fn_locks.get(f.id, set())
        return dotted_name(f), set()


#: one acquisition-order edge: (held, then, site node, how, via)
_Edge = Tuple[str, str, ast.AST, str, str]


def _with_body_edges(locks: _ModuleLocks, fn: ast.AST,
                     cls: Optional[str]) -> List[_Edge]:
    """Edges contributed by one function: for every lock-holding
    ``with``, the locks acquired inside its body — directly (nested
    with) or one call level down (same class / same module)."""
    edges: List[_Edge] = []
    for node in _walk_shallow(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = [locks.lock_id(item.context_expr, cls)
                for item in node.items]
        held = [h for h in held if h]
        if not held:
            continue
        for inner in ast.walk(node):
            if inner is node:
                continue
            if isinstance(inner, (ast.With, ast.AsyncWith)):
                for item in inner.items:
                    ident = locks.lock_id(item.context_expr, cls)
                    if not ident:
                        continue
                    for h in held:
                        if ident != h:
                            edges.append((h, ident, inner, "direct",
                                          fn.name))
            elif isinstance(inner, ast.Call):
                callee, acq = locks.callee_locks(inner, cls)
                for ident in sorted(acq):
                    for h in held:
                        # self-edges via a call are kept: they are the
                        # re-entrant deadlock candidates for plain Lock
                        edges.append((h, ident, inner, "call",
                                      f"{fn.name} -> {callee}"))
    return edges


def _find_cycle(adj: Dict[str, Set[str]]) -> Optional[List[str]]:
    """Shortest-ish cycle via DFS; returns the node sequence (first ==
    last) or None."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if m not in color:
                continue
            if color[m] == GRAY:
                i = stack.index(m)
                return stack[i:] + [m]
            if color[m] == WHITE:
                got = dfs(m)
                if got:
                    return got
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(adj):
        if color[n] == WHITE:
            got = dfs(n)
            if got:
                return got
    return None


class LockOrderChecker(Checker):
    family = "lock-order"
    name = "locks"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        locks = _ModuleLocks(mod)
        edges: List[_Edge] = []
        in_pkg = mod.relpath.startswith("lux_tpu/")
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            cls = locks.enclosing_class(fn)
            edges.extend(_with_body_edges(locks, fn, cls))
            if in_pkg:
                out.extend(self._blocking(mod, locks, fn, cls))
            out.extend(self._unbalanced(mod, locks, fn, cls))
        out.extend(self._order_findings(mod, locks, edges))
        return out

    # -- L001 / L002: the acquisition graph -----------------------------

    def _order_findings(self, mod: Module, locks: _ModuleLocks,
                        edges: List[_Edge]) -> List[Finding]:
        out: List[Finding] = []
        direct: Dict[Tuple[str, str], _Edge] = {}
        adj: Dict[str, Set[str]] = {}
        first: Dict[Tuple[str, str], _Edge] = {}
        for e in edges:
            a, b, node, how, via = e
            if a == b:
                # self-cycle: only a deadlock for a known non-reentrant
                # ctor reached through a call (with A: helper() where
                # helper re-acquires A)
                kind = locks.kind_of(a)
                if how == "call" and kind and _LOCK_CTORS[kind]:
                    out.append(self.finding(
                        mod, node, "LUX-L001",
                        f"re-entrant self-deadlock: `{a}` is a "
                        f"non-reentrant threading.{kind} already held "
                        f"here and re-acquired via `{via}`"))
                continue
            key = (a, b)
            first.setdefault(key, e)
            if how == "direct":
                direct.setdefault(key, e)
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        reported_pairs: Set[Tuple[str, str]] = set()
        for (a, b), e in sorted(direct.items()):
            if (b, a) in direct and (b, a) not in reported_pairs:
                reported_pairs.add((a, b))
                ea, eb = direct[(a, b)], direct[(b, a)]
                out.append(self.finding(
                    mod, eb[2], "LUX-L002",
                    f"inconsistent lock order: `{a}` -> `{b}` in "
                    f"`{ea[4]}` (line {ea[2].lineno}) but `{b}` -> "
                    f"`{a}` here in `{eb[4]}` — two threads taking "
                    "opposite orders deadlock"))
                # drop the pair from the graph so L001 doesn't re-report
                adj[a].discard(b)
                adj[b].discard(a)
        cycle = _find_cycle(adj)
        if cycle:
            steps = []
            for x, y in zip(cycle, cycle[1:]):
                e = first[(x, y)]
                steps.append(f"`{x}` -> `{y}` ({e[3]} in {e[4]}, line "
                             f"{e[2].lineno})")
            anchor = first[(cycle[0], cycle[1])][2]
            out.append(self.finding(
                mod, anchor, "LUX-L001",
                "lock-order cycle: " + "; ".join(steps) +
                " — some interleaving of these paths deadlocks"))
        return out

    # -- L003: blocking call while holding a lock ------------------------

    def _blocking(self, mod: Module, locks: _ModuleLocks, fn: ast.AST,
                  cls: Optional[str]) -> List[Finding]:
        out: List[Finding] = []
        for node in _walk_shallow(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [locks.lock_id(item.context_expr, cls)
                    for item in node.items]
            held = [h for h in held if h]
            if not held:
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, ast.Call):
                    continue
                cn = call_name(inner)
                attr = (inner.func.attr
                        if isinstance(inner.func, ast.Attribute)
                        else "")
                if cn in _BLOCKING_CALLS or attr in _BLOCKING_ATTRS:
                    what = cn or attr
                    out.append(self.finding(
                        mod, inner, "LUX-L003",
                        f"blocking call `{what}` while holding "
                        f"`{', '.join(held)}` in `{fn.name}` — every "
                        "path contending this lock stalls behind it; "
                        "move the blocking call outside the critical "
                        "section"))
        return out

    # -- L004: acquire/release split across helpers ----------------------

    def _unbalanced(self, mod: Module, locks: _ModuleLocks,
                    fn: ast.AST, cls: Optional[str]) -> List[Finding]:
        if fn.name in ("__enter__", "__exit__"):
            return []  # a lock-shaped context manager is the fix
        acq: Dict[str, List[ast.AST]] = {}
        rel: Dict[str, List[ast.AST]] = {}
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("acquire", "release")):
                continue
            ident = locks.lock_id(f.value, cls)
            if not ident:
                continue
            (acq if f.attr == "acquire" else rel).setdefault(
                ident, []).append(node)
        out: List[Finding] = []
        for ident in sorted(set(acq) | set(rel)):
            na, nr = len(acq.get(ident, ())), len(rel.get(ident, ()))
            if na == nr:
                continue
            node = (acq.get(ident) or rel.get(ident))[0]
            out.append(self.finding(
                mod, node, "LUX-L004",
                f"`{ident}` {na} acquire / {nr} release in "
                f"`{fn.name}` — the other half lives in a different "
                "helper, invisible to readers and to the order graph; "
                "use `with` or pair them in one frame"))
        return out
