"""Thread-safety checkers (LUX-C*): shared mutable state touched by the
planner / scheduler worker threads without a lock.

PR 2 made the host planning layer genuinely concurrent
(``ops/expand._map_parts`` daemon fan-out, ``plan_async``, the native
colorer's thread pool) and PR 1 added the serving scheduler thread — so
module-level mutable state is now shared state.  CPython's GIL makes the
races "benign" only until a mutation compounds (read-modify-write,
check-then-act like lazy init running a 120 s ``make`` twice) — and the
reference's whole pitch is race-freedom checked by construction, so we
lint the shapes instead of trusting the GIL:

* LUX-C001 — write to a ``global`` inside a function, outside any
  ``with <...lock...>:`` block (lazy-init caches, counters).
* LUX-C002 — mutation of a module-level mutable container (dict/list/set
  assigned at module scope) inside a function, outside a lock.
* LUX-C003 — ``os.environ`` read inside a function used as a thread
  target (``threading.Thread(target=f)`` / ``executor.submit(f, ...)``):
  env mutations from the main thread race it, and per-thread env reads
  make behavior depend on scheduling.
* LUX-C004 — ``os.environ`` WRITE in lux_tpu package code: the process
  environment is global state shared with every reader thread; only
  tools/ entry points (which set env before spawning work) may write it.

Lock detection is lexical: a ``with`` whose context expression source
contains lock/mutex/cond/flock/wake.  That matches this repo's idiom
(``_PLAN_STATS_LOCK``, ``self._wake``); a cleverly-named lock needs an
inline suppression with a justification, which is the point.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set

from lux_tpu.analysis.core import Checker, Finding, Module, call_name

_MUTATORS = {"append", "extend", "add", "update", "setdefault", "pop",
             "popitem", "remove", "discard", "clear", "insert",
             "__setitem__"}

_ENV_WRITERS = {"setdefault", "update", "pop", "clear"}


def _module_mutable_names(mod: Module) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call) and call_name(value) in (
                "dict", "list", "set", "defaultdict",
                "collections.defaultdict", "OrderedDict",
                "collections.OrderedDict", "deque", "collections.deque"):
            mutable = True
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _thread_target_names(mod: Module) -> Set[str]:
    """Function names handed to Thread(target=...)/executor.submit/
    thread-pool map helpers in this module.  ONLY the callable position
    counts — a data argument that happens to share a function's name
    (``ex.submit(work, parse)``) must not mark that function a thread
    target, or LUX-C003 false-positives abort the chip_day gate."""
    targets: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        last = cn.split(".")[-1]
        pos = None
        if last == "Thread":
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    targets.add(kw.value.id)
        elif last == "submit":
            pos = 0
        elif last == "_parallel_map":  # ops/expand signature: (count, fn, w)
            pos = 1
        elif last == "map" and "executor" in cn.lower():
            pos = 0
        if pos is not None and pos < len(node.args) and isinstance(
                node.args[pos], ast.Name):
            targets.add(node.args[pos].id)
    return targets


def _walk_shallow(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested defs/lambdas
    (each nested def is visited as its own function by the caller)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


class ThreadSafetyChecker(Checker):
    family = "thread-safety"
    name = "threads"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        mutable = _module_mutable_names(mod)
        thread_targets = _thread_target_names(mod)
        in_pkg = mod.relpath.startswith("lux_tpu/")
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            globals_declared: Set[str] = set()
            for node in _walk_shallow(fn):
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
            is_thread_target = fn.name in thread_targets
            for node in _walk_shallow(fn):
                # --- C001: global write outside a lock ---
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    tgts = (node.targets if isinstance(node, ast.Assign)
                            else [node.target])
                    for t in tgts:
                        if (isinstance(t, ast.Name)
                                and t.id in globals_declared
                                and not mod.under_lock(node)):
                            out.append(self.finding(
                                mod, node, "LUX-C001",
                                f"write to global `{t.id}` in "
                                f"`{fn.name}` without a lock — planner/"
                                "scheduler threads share module state; "
                                "guard the write or make init eager"))
                        # --- C002: module container mutated in place ---
                        elif (isinstance(t, ast.Subscript)
                              and isinstance(t.value, ast.Name)
                              and t.value.id in mutable
                              and not mod.under_lock(node)):
                            out.append(self.finding(
                                mod, node, "LUX-C002",
                                f"unlocked mutation of module-level "
                                f"container `{t.value.id}` in "
                                f"`{fn.name}` — guard with a lock"))
                elif isinstance(node, ast.Call):
                    f = node.func
                    # --- C002: mutator method on module container ---
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _MUTATORS
                            and isinstance(f.value, ast.Name)
                            and f.value.id in mutable
                            and not mod.under_lock(node)):
                        out.append(self.finding(
                            mod, node, "LUX-C002",
                            f"unlocked `{f.value.id}.{f.attr}()` on "
                            f"module-level container in `{fn.name}` — "
                            "guard with a lock"))
                    # --- C004: env write in package code ---
                    elif (in_pkg and isinstance(f, ast.Attribute)
                          and f.attr in _ENV_WRITERS
                          and ast.unparse(f.value) == "os.environ"):
                        out.append(self.finding(
                            mod, node, "LUX-C004",
                            "os.environ mutation in package code — the "
                            "process env is global state shared with "
                            "every thread; only tools/ entry points may "
                            "set it"))
                    elif in_pkg and call_name(node) in ("os.putenv",
                                                        "os.unsetenv"):
                        out.append(self.finding(
                            mod, node, "LUX-C004",
                            "os.putenv in package code — env is "
                            "thread-shared global state"))
                # --- C004: os.environ[...] = in package code ---
                if (in_pkg and isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Subscript)
                                and ast.unparse(t.value) == "os.environ"
                                for t in node.targets)):
                    out.append(self.finding(
                        mod, node, "LUX-C004",
                        "os.environ write in package code — env is "
                        "thread-shared global state; only tools/ entry "
                        "points may set it"))
                # --- C003: env read inside a thread-target function ---
                if (is_thread_target and isinstance(node, ast.Attribute)
                        and ast.unparse(node) == "os.environ"):
                    parent = mod.parent(node)
                    is_write = (isinstance(parent, ast.Subscript)
                                and isinstance(mod.parent(parent),
                                               ast.Assign))
                    if not is_write:
                        out.append(self.finding(
                            mod, node, "LUX-C003",
                            f"os.environ read inside thread target "
                            f"`{fn.name}` — resolve env once on the "
                            "main thread and pass the value in"))
        return out
