"""Observability-safety checkers (LUX-O*): host syncs and flight-recorder
API misuse in the hot loops.

The luxtrace design contract (docs/OBSERVABILITY.md) is that telemetry is
ALWAYS on because it never touches the hot path: per-iteration counters
ride the compiled loop carry as a static-shape ring (lux_tpu.obs.ring)
and reach the host exactly once, after the loop.  The reference instead
fences every iteration on the host (-verbose, sssp_gpu.cu:513-518) —
that pattern serializes dispatch and is the single cheapest way to ruin
a chip window.  These lints reject it statically:

* LUX-O001 — a host-sync primitive (``block_until_ready`` /
  ``device_get`` / ``copy_to_host_async``) inside a TRACED body (jit /
  shard_map / scan / while_loop / fori_loop / cond / pallas_call).  At
  best a no-op at trace time, at worst an io_callback-shaped stall baked
  into every iteration.
* LUX-O002 — the flight recorder's HOST half (``obs.span`` /
  ``obs.point`` / ``recorder()`` / ``ring_rows`` / ``emit_ring``) inside
  a traced body.  Spans run at trace time there — the event log would
  record compile-time, not run-time, and a retrace would duplicate it.
  Inside compiled code the only legal telemetry API is ``ring_push`` on
  a carried ring.
* LUX-O003 — per-iteration telemetry fetch: ``ring_rows``/``emit_ring``
  lexically inside a Python loop that also drives a compiled runner
  (``run_pull_fixed``/``run_pull_until``/``run_push``/a compiled
  ``loop(...)``).  The ring contract is ONE fetch at run end; fetching
  per chunk re-introduces the reference's per-iteration fence.
* LUX-O004 — host-callback primitives (``jax.debug.print`` /
  ``jax.debug.callback`` / ``io_callback``) inside a traced body in the
  shipped tree.  Debug-only affordances; each one is a device->host
  round trip per execution.
* LUX-O005 — distributed trace-context API (``obs/dtrace.py``: mint /
  child / child_of / wire_ctx / tspan / emit_span / to_wire /
  from_wire) inside a traced body.  A context is host metadata: minted
  inside a jit body it runs at TRACE time, baking one span id into the
  compiled program — every execution would then "belong" to the trace
  that happened to be live at compile time, which is precisely the
  lie a tracing system must never tell.  Contexts are minted and
  propagated strictly outside compiled code.

Pure stdlib AST like the rest of the suite — the traced-context
detection is shared with the tracing-safety family (tracing.py).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from lux_tpu.analysis.core import Checker, Finding, Module, call_name
from lux_tpu.analysis.tracing import traced_functions

#: dotted call names that force a device->host sync wherever they run
_SYNC_CALLS = {"jax.block_until_ready", "block_until_ready",
               "jax.device_get", "device_get"}
#: method names that sync when called on any array
_SYNC_METHODS = {"block_until_ready", "copy_to_host_async"}

#: host-callback primitives (LUX-O004)
_CALLBACK_CALLS = {"jax.debug.print", "debug.print", "jax.debug.callback",
                   "debug.callback", "io_callback",
                   "jax.experimental.io_callback", "host_callback.call",
                   "jax.experimental.host_callback.call"}

#: recorder-API member names, resolved against the obs-package aliases
_RECORDER_MEMBERS = {"span", "point", "recorder"}
#: ring HOST-fetch members (ring_push is the traced-side API and legal)
_RING_FETCH_MEMBERS = {"ring_rows", "emit_ring"}

#: distributed trace-context API (LUX-O005): mutating/minting a trace
#: context inside a traced body runs at trace time and bakes one id
#: into the compiled program
_DTRACE_MEMBERS = {"mint", "child", "child_of", "wire_ctx", "tspan",
                   "emit_span", "to_wire", "from_wire", "wire_point"}

#: compiled-runner call names for LUX-O003 (suffix match: methods and
#: module-qualified forms both count)
_RUNNER_SUFFIXES = ("run_pull_fixed", "run_pull_until", "run_push",
                    "run_pull_fixed_overlapped")


def _obs_aliases(mod: Module) -> Tuple[Set[str], Set[str], Set[str],
                                       Set[str], Set[str], Set[str]]:
    """(obs_module_aliases, ring_module_aliases, direct_recorder_names,
    direct_ringfetch_names, dtrace_module_aliases, direct_dtrace_names):
    names this module binds to lux_tpu.obs / lux_tpu.obs.ring /
    lux_tpu.obs.dtrace / individual recorder+ring+dtrace functions.
    Import-resolution keeps the checker precise: a stray local
    ``span()`` helper is not a finding."""
    obs_mods: Set[str] = set()
    ring_mods: Set[str] = set()
    rec_names: Set[str] = set()
    fetch_names: Set[str] = set()
    dtrace_mods: Set[str] = set()
    dtrace_names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("lux_tpu.obs", "lux_tpu.obs.recorder"):
                    obs_mods.add(a.asname or a.name)
                elif a.name == "lux_tpu.obs.ring":
                    ring_mods.add(a.asname or a.name)
                elif a.name == "lux_tpu.obs.dtrace":
                    dtrace_mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom):
            m = node.module or ""
            for a in node.names:
                bound = a.asname or a.name
                if m == "lux_tpu" and a.name == "obs":
                    obs_mods.add(bound)
                elif m == "lux_tpu.obs" and a.name == "ring":
                    ring_mods.add(bound)
                elif m == "lux_tpu.obs" and a.name == "dtrace":
                    dtrace_mods.add(bound)
                elif m == "lux_tpu.obs" and a.name == "recorder":
                    obs_mods.add(bound)
                elif m in ("lux_tpu.obs", "lux_tpu.obs.recorder") and (
                        a.name in _RECORDER_MEMBERS):
                    rec_names.add(bound)
                elif m in ("lux_tpu.obs", "lux_tpu.obs.ring") and (
                        a.name in _RING_FETCH_MEMBERS):
                    fetch_names.add(bound)
                elif m == "lux_tpu.obs.dtrace" and (
                        a.name in _DTRACE_MEMBERS):
                    dtrace_names.add(bound)
    return (obs_mods, ring_mods, rec_names, fetch_names, dtrace_mods,
            dtrace_names)


def _is_dtrace_call(cn: str, dtrace_mods: Set[str],
                    dtrace_names: Set[str]) -> bool:
    if cn in dtrace_names:
        return True
    head, _, member = cn.rpartition(".")
    return member in _DTRACE_MEMBERS and (
        head in dtrace_mods or head == "lux_tpu.obs.dtrace")


def _is_recorder_call(cn: str, obs_mods: Set[str], ring_mods: Set[str],
                      rec_names: Set[str], fetch_names: Set[str]) -> bool:
    if cn in rec_names or cn in fetch_names:
        return True
    head, _, member = cn.rpartition(".")
    if member in _RECORDER_MEMBERS and (
            head in obs_mods
            or head in ("lux_tpu.obs", "lux_tpu.obs.recorder")):
        return True
    return member in _RING_FETCH_MEMBERS and (
        head in ring_mods or head == "lux_tpu.obs.ring")


def _is_ring_fetch(cn: str, ring_mods: Set[str],
                   fetch_names: Set[str]) -> bool:
    head, _, member = cn.rpartition(".")
    if head:
        return member in _RING_FETCH_MEMBERS and (
            head in ring_mods or head == "lux_tpu.obs.ring")
    return cn in fetch_names


def _compiled_loop_names(mod: Module) -> Set[str]:
    """Names bound from a ``compile_*`` factory call anywhere in the
    module (``loop = compile_push_chunk(...)``) — calling such a name is
    driving a compiled runner, the repo's dominant push idiom, and
    LUX-O003 must see it the same as a ``run_*`` entry point."""
    names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        last = call_name(node.value).rpartition(".")[2]
        if not last.startswith(("compile_", "_compile_")):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
    return names


def _is_runner_call(cn: str, loop_names: Set[str] = frozenset()) -> bool:
    last = cn.rpartition(".")[2]
    return last in _RUNNER_SUFFIXES or cn in loop_names


class ObsChecker(Checker):
    family = "observability"
    name = "obs"

    def run(self, mod: Module) -> Iterable[Finding]:
        out: List[Finding] = []
        (obs_mods, ring_mods, rec_names, fetch_names, dtrace_mods,
         dtrace_names) = _obs_aliases(mod)
        traced = set(traced_functions(mod))

        for fn in traced:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn in _SYNC_CALLS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHODS):
                    out.append(self.finding(
                        mod, node, "LUX-O001",
                        f"host sync `{cn or node.func.attr}` inside traced "
                        f"body `{fn.name}` — syncs belong outside the "
                        "compiled loop (fetch once at run end)"))
                elif _is_recorder_call(cn, obs_mods, ring_mods,
                                       rec_names, fetch_names):
                    out.append(self.finding(
                        mod, node, "LUX-O002",
                        f"flight-recorder host API `{cn}` inside traced "
                        f"body `{fn.name}` — spans/points run at trace "
                        "time here; carry a telemetry ring (ring_push) "
                        "instead"))
                elif cn in _CALLBACK_CALLS:
                    out.append(self.finding(
                        mod, node, "LUX-O004",
                        f"host callback `{cn}` inside traced body "
                        f"`{fn.name}` — a device->host round trip per "
                        "execution; remove before shipping"))
                elif _is_dtrace_call(cn, dtrace_mods, dtrace_names):
                    out.append(self.finding(
                        mod, node, "LUX-O005",
                        f"trace-context API `{cn}` inside traced body "
                        f"`{fn.name}` — contexts are host metadata; "
                        "minted here it runs at TRACE time and bakes "
                        "one span id into the compiled program (mint/"
                        "propagate outside jit, docs/OBSERVABILITY.md)"))

        # LUX-O003: ring fetch in a Python loop that drives a compiled
        # runner — the per-iteration-fence anti-pattern, host side
        loop_names = _compiled_loop_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
            if not any(_is_runner_call(call_name(c), loop_names)
                       for c in calls):
                continue
            for c in calls:
                cn = call_name(c)
                if _is_ring_fetch(cn, ring_mods, fetch_names):
                    out.append(self.finding(
                        mod, c, "LUX-O003",
                        f"per-iteration telemetry fetch `{cn}` inside a "
                        "driving loop — the ring contract is ONE host "
                        "fetch after the run (docs/OBSERVABILITY.md)"))
        return out
