"""lux_tpu.analysis — luxcheck, the repo-native static-analysis suite.

Eight checker families encode the invariants that have actually bitten
this codebase (see each module's docstring for the incident history):

* tracing-safety (LUX-T*) — Python control flow / host concretization on
  traced values inside jit/shard_map/Pallas bodies (retraces, host
  syncs in the hot loop);
* determinism   (LUX-D*) — set-iteration order, wall clock, global RNG
  feeding result bytes (the bitwise-rerun contract, statically);
* thread-safety (LUX-C*) — unlocked module state under the PR-2 planner
  fan-out and the serving scheduler thread;
* policy        (LUX-P*) — no pickle in cache paths, env knobs through
  utils.config.env_int, u8 index narrowing through _narrow_idx only;
* observability (LUX-O*) — no host syncs / flight-recorder host API in
  traced bodies, no per-iteration telemetry fetch in driving loops
  (the luxtrace ring contract, docs/OBSERVABILITY.md);
* lock-order    (LUX-L*) — the fleet's lock discipline: acquisition-
  graph cycles, AB/BA order inversions, blocking calls under a held
  lock, acquire/release split across helpers (docs/ANALYSIS.md's
  protocol tier; the dynamic side is ``lux_tpu.analysis.proto``);
* guarded-by    (LUX-G*) — inferred field→lock maps: guarded fields
  accessed outside their guard from second-thread-reachable methods,
  mixed-guard fields, check-then-act across separate acquisitions
  (the lock-*discipline* bugs LUX-L's order graph cannot see);
* resource-lifecycle (LUX-R*) — acquire/release pairing for the four
  leak-prone kinds: un-joined threads, close()-without-shutdown() on
  parked sockets (the PR 16 stall), unreclaimed or happy-path-only
  tmpdirs, file handles opened outside ``with``.

Meta findings (LUX-X*) keep the suppression machinery itself honest:
X000 unparsable file, X001 inline suppression without a justification,
X002 malformed baseline entry, X003 stale baseline entry.

Run it: ``python tools/luxcheck.py --all`` (chip_day step -3, a tier-1
test, and tools/ci_check.sh all gate on exit 0).  Pure stdlib — never
imports jax/numpy, so the gate costs milliseconds.

The jaxpr/HLO-level sibling gate lives in the ``lux_tpu.analysis.ir``
SUBPACKAGE (luxaudit, chip_day step -3b): it shares this package's
Finding/fingerprint/baseline machinery but DOES import jax (it traces
the real engines), so it is deliberately NOT imported here — importing
``lux_tpu.analysis`` must stay jax-free for the millisecond preflight.
"""
from lux_tpu.analysis.core import (  # noqa: F401
    DEFAULT_TARGETS,
    Checker,
    Finding,
    Module,
    check_module,
    check_paths,
    iter_py_files,
    load_baseline,
    repo_root,
)
from lux_tpu.analysis.determinism import DeterminismChecker
from lux_tpu.analysis.guards import GuardedByChecker
from lux_tpu.analysis.locks import LockOrderChecker
from lux_tpu.analysis.obs import ObsChecker
from lux_tpu.analysis.policy import PolicyChecker
from lux_tpu.analysis.resources import ResourceLifecycleChecker
from lux_tpu.analysis.threads import ThreadSafetyChecker
from lux_tpu.analysis.tracing import TracingSafetyChecker

#: the shipped checker set, in report order
ALL_CHECKERS = (
    TracingSafetyChecker(),
    DeterminismChecker(),
    ThreadSafetyChecker(),
    PolicyChecker(),
    ObsChecker(),
    LockOrderChecker(),
    GuardedByChecker(),
    ResourceLifecycleChecker(),
)

FAMILIES = tuple(c.family for c in ALL_CHECKERS)
