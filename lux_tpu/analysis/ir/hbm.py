"""LUX-J5: HBM-pass accounting must match the kernels actually traced.

``roofline.routed_hbm_passes`` is PR 4's headline metric — every routed
bench row carries it, and the pass-fusion bet is scored by it (expand
17.0 -> 9.0 sweeps at rmat20-class k=4).  The number is DERIVED from the
plan static, not measured; if the replay grows an extra kernel (a pf
group that silently fails to fuse, a new out-of-band XLA pass, an ff
level that falls off the Pallas path) the published metric drifts from
the machine's real traffic with no test noticing.

Two cross-checks pin it:

* LUX-J501 — the ``pallas_call`` equation count of the traced replay
  must equal the static-derived kernel count
  (r1 + fill-forward levels + r2 [+ vr] via route_num_hbm_passes);
* LUX-J502 — the roofline dict's per-stage fields must agree with those
  same kernel counts after un-scaling the space factors it applies
  (fused r2 is scaled by n2/n, vr by nv_route/n; ff is a fractional
  BYTES model, not a kernel count, and is excluded);
* LUX-J503 — a pure-telemetry twin (the luxtrace ring in the loop carry,
  docs/OBSERVABILITY.md) must launch EXACTLY the kernels of its base
  config: zero added accounted HBM passes is a shipped claim, and a ring
  that grows a kernel silently skews every hbm_passes bench row.
"""
from __future__ import annotations

from typing import List, Optional

from lux_tpu.analysis.core import Finding
from lux_tpu.analysis.ir import aot


def expected_kernels(static) -> int:
    """Kernel launches of one replay of ``static`` (expand or fused):
    one per unfused route pass / fused route group, one per
    fill-forward level."""
    from lux_tpu.ops import expand as E
    from lux_tpu.ops import pallas_shuffle as shuf

    if isinstance(static, E.CFRouteStatic):
        return expected_kernels(static.src) + expected_kernels(static.dst)
    n = (shuf.route_num_hbm_passes(static.r1) + len(static.ff.levels)
         + shuf.route_num_hbm_passes(static.r2))
    if getattr(static, "mx", None) is not None:
        n += 1  # the MXREDUCE final group: suffix gathers + reduction
    if hasattr(static, "vr"):
        n += shuf.route_num_hbm_passes(static.vr)
    return n


def claimed_kernels(static, claimed: dict) -> Optional[float]:
    """Reconstruct the kernel count a routed_hbm_passes dict CLAIMS, by
    un-scaling the space factors the model applies (fused r2 runs over
    n2, vr over nv_route; unfused fields are already kernel counts).
    None when the dict is missing stage fields (malformed claim)."""
    try:
        r1 = float(claimed["r1"])
        r2 = float(claimed["r2"])
    except (KeyError, TypeError):
        return None
    if hasattr(static, "n2"):  # FusedStatic: un-scale the space factors
        r2 = r2 * static.n / static.n2
        mx = 0.0
        if getattr(static, "mx", None) is not None:
            # the mx kernel is claimed at HALF a sweep over n2 (one
            # read, no full write) — un-scale back to one kernel
            try:
                mx = float(claimed["mx"]) * static.n / static.n2 / 0.5
            except (KeyError, TypeError):
                return None
        try:
            vr = float(claimed["vr"]) * static.n / static.nv_route
        except (KeyError, TypeError):
            return None
        return r1 + r2 + mx + vr
    return r1 + r2


def check_kernel_parity(traced_base, traced_twin, path: str, label: str,
                        line: int = 1) -> List[Finding]:
    """Audit a telemetry (or other pure-observer) twin against its base
    config: the twin's ``pallas_call`` count must equal the base's."""
    n_base = aot.count_primitive(aot.traced_jaxpr(traced_base),
                                 "pallas_call")
    n_twin = aot.count_primitive(aot.traced_jaxpr(traced_twin),
                                 "pallas_call")
    if n_twin != n_base:
        return [Finding(
            path=path, line=line, col=0, code="LUX-J503",
            message=f"telemetry twin launches {n_twin} pallas_call "
                    f"kernel(s) vs {n_base} in the base config — the "
                    "flight-recorder ring is adding HBM passes the "
                    "roofline accounting (and every bench row's "
                    "hbm_passes) does not see",
            text=label)]
    return []


def check_kernel_count(traced, expected: int, path: str, label: str,
                       line: int = 1) -> List[Finding]:
    """LUX-J501 for standalone kernels (ISSUE 11's mxscan leg): the
    traced program must launch EXACTLY ``expected`` pallas_call kernels.
    mxscan's whole accounting claim (REDUCE_HBM_PASSES["mxscan"] == 2 is
    EXACT, not a ladder floor) rests on the segmented scan being ONE
    kernel — a fallback to the VPU ladder or a split kernel silently
    falsifies every hbm_passes row that cites it."""
    observed = aot.count_primitive(aot.traced_jaxpr(traced), "pallas_call")
    if observed != expected:
        return [Finding(
            path=path, line=line, col=0, code="LUX-J501",
            message=f"traced program launches {observed} pallas_call "
                    f"kernel(s) but the accounting derives {expected} — "
                    "the published hbm_passes no longer describes the "
                    "kernels actually launched",
            text=label)]
    return []


def check_hbm(traced, static, path: str, label: str, line: int = 1,
              claimed: Optional[dict] = None,
              method: str = "scan") -> List[Finding]:
    """Audit one routed replay: ``traced`` is the jit-traced replay of
    ``static`` (apply_expand / apply_fused / a routed engine iteration);
    ``claimed`` defaults to the live roofline model's output for it."""
    from lux_tpu.utils import roofline

    findings: List[Finding] = []
    observed = aot.count_primitive(aot.traced_jaxpr(traced), "pallas_call")
    expect = expected_kernels(static)
    if observed != expect:
        findings.append(Finding(
            path=path, line=line, col=0, code="LUX-J501",
            message=f"traced replay launches {observed} pallas_call "
                    f"kernel(s) but the plan static derives {expect} "
                    "(route passes/groups + ff levels) — a pass fell off "
                    "the Pallas path or a group failed to fuse; the "
                    "hbm_passes metric no longer describes the kernels",
            text=label))
    if not hasattr(static, "r1"):
        # CFRouteStatic: no single roofline claim to cross-check — the
        # src/dst halves are audited as their own expand replays
        return findings
    if claimed is None:
        claimed = roofline.routed_hbm_passes(static, method=method)
    want = claimed_kernels(static, claimed)
    route_expect = expect - len(static.ff.levels)
    if want is None or abs(want - route_expect) > 0.51:
        findings.append(Finding(
            path=path, line=line, col=0, code="LUX-J502",
            message=f"roofline hbm_passes claims {want} route kernels "
                    f"(un-scaled r1/r2[/vr]) but the plan static carries "
                    f"{route_expect} — the published headline metric has "
                    "drifted from the real kernels",
            text=label))
    return findings
