"""LUX-J2: donated buffers must actually alias in the lowered module.

``donate_argnums`` is a REQUEST: XLA matches each donated input against
an output of identical shape/dtype/layout and silently drops the ones it
cannot place (jax raises a warning at execution, not an error — and a
warning scrolled past in a chip-day log is how "single state copy in the
hot loop" becomes two copies for a whole window).  PR 4's pull-side
donation and this PR's push/serve twins are CLAIMS about HBM residency;
this checker turns them into a lowered-module property: every leaf of a
``donate``d argument must carry ``tf.aliasing_output`` in the StableHLO
@main signature (the MLIR spelling of input_output_aliases).

One documented exemption: a donated leaf the lowering PRUNED as unused
(jax DCE — e.g. the single push step never reads ``carry.active``, the
while-loop twin's cond does) holds no runtime buffer, so there is
nothing to alias and nothing resident to free; the kept-vs-pruned split
is read from the lowering's ``kept_var_idx``.  On a jax that stops
exposing it, attribution degrades to a total-count comparison (AOT
caveat in docs/ANALYSIS.md).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from lux_tpu.analysis.core import Finding
from lux_tpu.analysis.ir import aot


def _kept_var_idx(lowered) -> Optional[Sequence[int]]:
    """Flat input-leaf indices that survived DCE into the lowered main,
    in argument order — private jax API, guarded (see module docstring)."""
    try:
        kept = lowered._lowering.compile_args["kept_var_idx"]
    except (AttributeError, KeyError, TypeError):  # pragma: no cover
        return None
    return sorted(kept)


def check_donation(traced, args: Sequence, donate_argnums: Sequence[int],
                   path: str, label: str, line: int = 1) -> List[Finding]:
    """Lower ``traced`` and assert every kept leaf of each donated
    argument is aliased to an output.

    ``args``: the dynamic (non-static) positional arguments, in call
    order — their tree_flatten spans map donated pytree leaves onto
    @main argument positions.  ``donate_argnums`` indexes into ``args``.
    """
    findings: List[Finding] = []
    lowered = traced.lower()
    text = lowered.as_text()
    aliased, total = aot.aliased_arg_indices(text)
    spans = aot.leaf_spans(args)
    n_leaves = spans[-1][1] if spans else 0
    donated = []
    for i in donate_argnums:
        lo, hi = spans[i]
        donated.extend(range(lo, hi))
    kept = _kept_var_idx(lowered)
    if kept is None and total == n_leaves:
        kept = list(range(n_leaves))  # nothing pruned: identity map
    if kept is None or len(kept) != total:
        # attribution unavailable (jax internals drifted): a dropped
        # donation must still fail, just without naming the leaf
        if len(aliased) < len(donated):
            findings.append(Finding(
                path=path, line=line, col=0, code="LUX-J201",
                message=f"only {len(aliased)} of {len(donated)} donated "
                        "leaves carry an input_output_alias in the "
                        "lowered module (XLA dropped donations; leaf "
                        "attribution unavailable on this jax — "
                        f"@main has {total} args vs {n_leaves} leaves)",
                text=label))
        return findings
    pos_of = {leaf: arg_pos for arg_pos, leaf in enumerate(kept)}
    missing = [leaf for leaf in donated
               if leaf in pos_of and pos_of[leaf] not in aliased]
    if missing:
        findings.append(Finding(
            path=path, line=line, col=0, code="LUX-J201",
            message=f"donated flat leaves {missing} carry no "
                    "tf.aliasing_output in the lowered module — XLA "
                    "dropped the donation (no matching output "
                    "shape/dtype), so the hot loop holds an extra full "
                    "state copy",
            text=label))
    return findings
