"""lux_tpu.analysis.ir — luxaudit, the jaxpr/HLO-level static auditor.

luxcheck (lux_tpu.analysis, PR 3) lints the Python AST; this subpackage
audits the layer below: the jaxpr and StableHLO the engines actually
ship.  Five checker families turn five rounds of "single-trace,
donated, bitwise, under-budget" prose into machine-checked invariants:

* LUX-J1 retrace stability (retrace.py) — J101 structural drift across
  a family's configs, J102 unhashable jit statics, J103 dynamic-knob
  recompiles;
* LUX-J2 donation (donation.py) — J201: every ``donate``d leaf must
  carry an input_output_alias in the lowered module (XLA drops
  mismatched donations silently);
* LUX-J3 collective order (collectives.py) — J301/J302: collectives
  inside ``lax.cond`` arms / ``lax.while_loop`` bodies require a
  provably mesh-agreed predicate (the push direction switch can never
  deadlock a mesh);
* LUX-J4 VMEM budget (vmem.py) — J401: pass-fused group residency
  recomputed from the frozen plan's tile geometry + real index dtypes
  against the budget the knobs promise;
* LUX-J5 HBM-pass accounting (hbm.py) — J501/J502: the roofline
  ``routed_hbm_passes`` headline metric cross-checked against the
  pallas_call kernels actually traced.

Everything runs on CPU (tools/luxaudit.py, chip-day step -3b, a
ci_check stage) against the REAL engine entry points over a small
fixture graph (targets.py).  Findings reuse luxcheck's machinery —
same Finding/fingerprint dataclass, same baseline format
(tools/luxaudit_baseline.txt, shipped empty, stale entries are
LUX-X003 findings) — so one suppression policy covers both gates.

Unlike the parent package this subpackage DOES import jax (that is the
point); ``lux_tpu.analysis`` itself must stay jax-free for the
millisecond luxcheck preflight, which is why nothing here is imported
from the parent ``__init__``.
"""
from __future__ import annotations

import time
from typing import List, Optional, Tuple

from lux_tpu.analysis.core import Finding, _apply_baseline

#: checker families in report order
FAMILIES = ("retrace", "donation", "collective", "vmem", "hbm")


def run_audit(fast: bool = False,
              baseline_path: Optional[str] = None,
              families: Optional[Tuple[str, ...]] = None):
    """Run the audit units and return ``(findings, report)``.

    ``findings`` is the baseline-filtered, sorted list (empty == exit
    0); ``report`` is the JSON-ready audit record (per-unit status and
    timings) the CLI writes as AUDIT_r0X.json.
    """
    from lux_tpu.analysis.ir.targets import audit_units

    units = audit_units(fast=fast)
    findings: List[Finding] = []
    if families:
        bad = sorted(set(families) - set(FAMILIES))
        if bad:
            findings.append(Finding(
                path="lux_tpu/analysis/ir", line=1, col=0,
                code="LUX-J000",
                message=f"unknown audit family {', '.join(bad)!s} — "
                        f"valid families: {', '.join(FAMILIES)}",
                text="families"))
        units = [u for u in units if u.family in families]
    if not units:
        # zero selected units must FAIL, never pass as clean — a typo'd
        # filter (or a tier with no matching units) silently auditing
        # nothing is how a preflight stops preflighting (the luxcheck
        # LUX-X000 missing-target policy, one layer down)
        findings.append(Finding(
            path="lux_tpu/analysis/ir", line=1, col=0, code="LUX-J000",
            message="the family/tier filter selected ZERO audit units — "
                    "an empty audit must never report clean; fix the "
                    "--families value or drop --fast",
            text="no-units"))
    unit_rows = []
    for u in units:
        t0 = time.perf_counter()
        try:
            got = list(u.run())
        except Exception as e:  # an audit crash must FAIL the gate,
            # never pass as clean — same policy as luxcheck LUX-X000
            got = [Finding(
                path=u.path, line=1, col=0, code="LUX-J000",
                message=f"audit unit crashed: {type(e).__name__}: {e}",
                text=u.label)]
        findings.extend(got)
        unit_rows.append({
            "family": u.family,
            "label": u.label,
            "path": u.path,
            "findings": len(got),
            "seconds": round(time.perf_counter() - t0, 3),
        })
    findings = _apply_baseline(findings, baseline_path)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    import jax

    report = {
        "tool": "luxaudit",
        "jax": jax.__version__,
        "tier": "fast" if fast else "all",
        "units": unit_rows,
        "findings": [
            {"path": f.path, "code": f.code, "message": f.message,
             "target": f.text, "fingerprint": f.fingerprint()}
            for f in findings
        ],
        "clean": not findings,
    }
    return findings, report
