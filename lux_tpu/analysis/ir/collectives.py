"""LUX-J3: collective-order safety of shard_map bodies, statically.

The push engine's direction switch places collectives (the dense
branch's all_gather, the ring engine's ppermute sweep) inside
``lax.cond`` arms and runs the whole thing under ``lax.while_loop``.
On a mesh that is only safe when every participant takes the SAME
branch every iteration — i.e. when each branch/loop predicate is a
mesh-agreed value.  The engines guarantee this by deriving every such
predicate from a psum; this checker PROVES it from the jaxpr instead of
trusting the comment (the static-uniformity discipline Tascade argues
for deterministic reduction trees, arXiv:2311.15810 — reduction and
collective order must be provably identical on every participant).

Analysis: abstract interpretation over the shard_map body jaxpr with a
two-point lattice per value — "agreed" (provably identical on every
mesh participant) or not:

* literals / jaxpr consts: agreed (host constants are broadcast);
* shard_map inputs: agreed iff their in_names entry is empty
  (replicated P() operands), per the shard_map equation params;
* psum / pmin / pmax / all_gather outputs: agreed REGARDLESS of input
  agreement (an all-reduce of divergent values is still identical
  everywhere);
* ppermute / psum_scatter(reduce_scatter) / all_to_all / pgather /
  axis_index outputs: never agreed;
* everything else: agreed iff every operand is agreed;
* while carries: greatest fixpoint (start from the init values'
  agreement, demote until stable);
* cond outputs: agreed iff the predicate AND every branch's outputs
  are agreed.

Findings:

* LUX-J301 — a ``cond`` with collectives in any arm whose predicate is
  not provably mesh-agreed (participants could take different arms:
  mismatched collective sequences deadlock the mesh);
* LUX-J302 — a ``while_loop`` whose body contains collectives and whose
  stop predicate is not provably mesh-agreed (participants could
  disagree on the trip count: one device exits, the rest block in the
  next iteration's collective).

A cond whose arms have DIFFERENT collective sequences is legal exactly
when the predicate is agreed — the direction switch's design — so
sequence asymmetry alone is not a finding; the predicate proof is.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from lux_tpu.analysis.core import Finding
from lux_tpu.analysis.ir import aot

#: collective primitives whose OUTPUT is identical on every participant
REPLICATING = frozenset({"psum", "pmin", "pmax", "all_gather"})
#: mesh-synchronizing primitives whose output differs per participant
DIVERGENT = frozenset(
    {"ppermute", "reduce_scatter", "all_to_all", "pgather"}
)
#: every primitive that synchronizes the mesh (deadlocks when sequences
#: diverge); axis_index communicates nothing so it is only non-agreed
COLLECTIVES = REPLICATING | DIVERGENT


def _collective_seq(jaxpr) -> Tuple[str, ...]:
    return tuple(
        str(e.primitive)
        for e in aot.iter_eqns(jaxpr)
        if str(e.primitive) in COLLECTIVES
    )


class _BodyAnalysis:
    """One shard_map body walk: agreement propagation + findings."""

    def __init__(self, path: str, line: int, label: str):
        self.path = path
        self.line = line
        self.label = label
        self.findings: List[Finding] = []

    def _finding(self, code: str, message: str) -> None:
        self.findings.append(Finding(
            path=self.path, line=self.line, col=0, code=code,
            message=message, text=self.label))

    def _read(self, env: Dict[int, bool], v) -> bool:
        if aot.is_literal(v):
            return True
        return env.get(id(v), False)

    def eval_jaxpr(self, jaxpr, in_agreed: List[bool],
                   consts_agreed: bool = True) -> List[bool]:
        env: Dict[int, bool] = {}
        for var, ag in zip(jaxpr.invars, in_agreed):
            env[id(var)] = ag
        for var in jaxpr.constvars:
            env[id(var)] = consts_agreed
        for eqn in jaxpr.eqns:
            self._eval_eqn(env, eqn)
        return [self._read(env, v) for v in jaxpr.outvars]

    # -- equation dispatch ---------------------------------------------------

    def _eval_eqn(self, env: Dict[int, bool], eqn) -> None:
        prim = str(eqn.primitive)
        ins = [self._read(env, v) for v in eqn.invars]
        if prim in REPLICATING:
            outs = [True] * len(eqn.outvars)
        elif prim in DIVERGENT or prim == "axis_index":
            outs = [False] * len(eqn.outvars)
        elif prim == "cond":
            outs = self._eval_cond(eqn, ins)
        elif prim == "while":
            outs = self._eval_while(eqn, ins)
        elif prim == "scan":
            outs = self._eval_scan(eqn, ins)
        else:
            body = list(aot.sub_jaxprs(eqn))
            if body:
                # pjit / remat / custom_* / closed_call: evaluate the
                # (single) body with operand agreement; fall back to
                # all-operands-agreed when the body shape is unexpected
                sub = body[0]
                if len(sub.invars) == len(ins):
                    outs_sub = self.eval_jaxpr(sub, ins)
                    outs = (outs_sub if len(outs_sub) == len(eqn.outvars)
                            else [all(ins)] * len(eqn.outvars))
                else:
                    outs = [all(ins)] * len(eqn.outvars)
            else:
                outs = [all(ins)] * len(eqn.outvars)
        for var, ag in zip(eqn.outvars, outs):
            env[id(var)] = ag

    def _eval_cond(self, eqn, ins: List[bool]) -> List[bool]:
        branches = eqn.params["branches"]
        pred_agreed = ins[0]
        op_agreed = ins[1:]
        seqs = []
        branch_outs = []
        for br in branches:
            sub = br.jaxpr if hasattr(br, "jaxpr") else br
            seqs.append(_collective_seq(sub))
            branch_outs.append(self.eval_jaxpr(sub, list(op_agreed)))
        if any(seqs) and not pred_agreed:
            uniq = sorted(set(seqs))
            self._finding(
                "LUX-J301",
                "lax.cond arms contain collectives "
                f"({' / '.join(','.join(s) or '-' for s in uniq)}) but the "
                "predicate is not provably mesh-agreed (derive it from a "
                "psum/pmin/pmax so every participant takes the same arm)")
        n_out = len(eqn.outvars)
        outs = []
        for i in range(n_out):
            outs.append(pred_agreed and all(
                bo[i] if i < len(bo) else False for bo in branch_outs))
        return outs

    def _eval_while(self, eqn, ins: List[bool]) -> List[bool]:
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        cond_sub = cond_j.jaxpr if hasattr(cond_j, "jaxpr") else cond_j
        body_sub = body_j.jaxpr if hasattr(body_j, "jaxpr") else body_j
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        init = ins[cn + bn:]
        # greatest fixpoint over the carry: a slot is agreed only when
        # its init AND every body output for it stay agreed
        carry = list(init)
        for _ in range(len(carry) + 1):
            body_out = self.eval_jaxpr(body_sub, body_consts + carry)
            new = [c and o for c, o in zip(carry, body_out)]
            if new == carry:
                break
            carry = new
        # collectives in the COND jaxpr count too: a device that exits
        # while stragglers re-enter the cond's psum deadlocks the same
        # way a body collective does
        seq = _collective_seq(body_sub) + _collective_seq(cond_sub)
        if seq:
            pred = self.eval_jaxpr(cond_sub, cond_consts + carry)
            if not all(pred):
                self._finding(
                    "LUX-J302",
                    "lax.while_loop contains collectives "
                    f"({','.join(seq)}) but the stop predicate is not "
                    "provably mesh-agreed (psum the active count so "
                    "every participant agrees on the trip count)")
        return carry

    def _eval_scan(self, eqn, ins: List[bool]) -> List[bool]:
        sub_j = eqn.params["jaxpr"]
        sub = sub_j.jaxpr if hasattr(sub_j, "jaxpr") else sub_j
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        consts = ins[:nc]
        carry = list(ins[nc:nc + ncar])
        xs = ins[nc + ncar:]
        for _ in range(ncar + 1):
            out = self.eval_jaxpr(sub, consts + carry + xs)
            new = [c and o for c, o in zip(carry, out[:ncar])]
            if new == carry:
                break
            carry = new
        out = self.eval_jaxpr(sub, consts + carry + xs)
        ys = out[ncar:]
        n_out = len(eqn.outvars)
        outs = (carry + ys)[:n_out]
        return outs + [False] * (n_out - len(outs))


def check_shard_map_bodies(jaxpr, path: str, label: str,
                           line: int = 1) -> List[Finding]:
    """Walk ``jaxpr`` (a traced entry point), analyze every shard_map
    body found, and return the LUX-J3 findings.  Also usable on jaxprs
    with no shard_map at all (single-device entry points audit clean by
    construction — there is no mesh to deadlock)."""
    findings: List[Finding] = []
    for eqn in aot.iter_eqns(jaxpr):
        if str(eqn.primitive) != "shard_map":
            continue
        in_names = eqn.params.get("in_names", ())
        body = eqn.params["jaxpr"]
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        agreed = [not names for names in in_names]
        if len(agreed) != len(body.invars):
            # unexpected param shape (jax version drift): treat every
            # input as non-agreed — conservative, never hides a finding
            agreed = [False] * len(body.invars)
        ba = _BodyAnalysis(path, line, label)
        ba.eval_jaxpr(body, agreed)
        # the while/scan carry fixpoint re-evaluates bodies, so a broken
        # nested cond is re-found once per fixpoint round — report each
        # distinct finding once
        seen = set()
        for f in ba.findings:
            key = (f.code, f.message, f.text)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return findings


def collective_sequence(jaxpr) -> Tuple[str, ...]:
    """The linearized mesh-collective sequence of a traced entry point
    (shard_map bodies included) — the audit report records it so a
    reordering between rounds is visible in the AUDIT json diff."""
    return _collective_seq(jaxpr)
