"""The audited entry points: what ``luxaudit --all`` actually traces.

One small fixture graph (rmat scale 8, 2 parts — the shapes are
irrelevant to every property audited: donation aliasing, program
structure, collective agreement, kernel counts, and pf tile geometry are
all SIZE-INDEPENDENT claims about the engine code) is pushed through the
REAL engine entry points — the same jit-wrapped functions the drivers
call, not reimplementations — and each checker family runs on the
resulting jaxpr / StableHLO.

``--fast`` covers pull + push + one pass-fused config + the luxtrace
telemetry-ring twins + the mutation-overlay twins (ISSUE 10: LUX-J1
occupancy-invariant traces, LUX-J2 delta-carry donation, LUX-J503
overlay-on/off kernel parity; the ci_check tier); ``--all`` adds the serve
batched steps, the distributed push engines (allgather + ring, on a
host-device mesh), the fused-pf and fused-mx plans (the MXREDUCE
in-kernel reduction: its retrace stability, VMEM ledger incl. the
one-hot/accumulator tiles, kernel-count parity against the 0.5-sweep
roofline claim, and ring neutrality), the mxscan entry points (ISSUE
11 — the blocked MXU segmented scan: LUX-J1 trace stability, LUX-J4
tile residency, LUX-J501 one-kernel accounting, LUX-J503 ring
neutrality), the dynamic-knob recompile probes (chip-day step
-3b), and the luxmerge units (ISSUE 17): the fused-family overlay's
LUX-J1 occupancy invariance, its LUX-J503 overlay-on/off kernel parity
on fused-pf, and the tree merge's LUX-J3 static collective schedule
(the tree's LUX-J1 compile-cache contract rides the fast tier).

The telemetry units ("+ring"/"ring-donate"/"ring-neutral") audit the
flight-recorder contract (docs/OBSERVABILITY.md): the ring must trace
like any other config of its family (LUX-J1), donate with the state
(LUX-J2), and launch zero additional kernels (LUX-J503).
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Callable, List

from lux_tpu.analysis.core import Finding
from lux_tpu.analysis.ir import donation, hbm, retrace, vmem
from lux_tpu.analysis.ir.collectives import check_shard_map_bodies


@dataclasses.dataclass
class AuditUnit:
    """One audited (entry point, checker family) pair."""

    family: str  # "retrace" | "donation" | "collective" | "vmem" | "hbm"
    label: str   # stable config descriptor (the finding fingerprint text)
    path: str    # repo-relative module the finding points at
    fast: bool   # included in the --fast tier
    run: Callable[[], List[Finding]]


def _active_fn(old, new):
    """Top-level (hashable) convergence probe for the pull-until audit
    — the shape run_pull_until's contract requires of callers."""
    import jax.numpy as jnp

    return jnp.sum(
        jnp.abs(new - old) > 1e-7,
        axis=tuple(range(1, old.ndim)),
    ).astype(jnp.int32)


@lru_cache(maxsize=1)
def fixture():
    """The shared audit fixture: graph, shard layouts, programs, plans,
    device-placed trees.  Built once per process (plan construction and
    device placement dominate the audit's cost)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.models.sssp import SSSPProgram
    from lux_tpu.ops import expand

    g = generate.rmat(8, 8, seed=7)
    shards = build_pull_shards(g, 2)
    pshards = build_push_shards(g, 2)
    prank = PageRankProgram(nv=shards.spec.nv)
    psssp = SSSPProgram(nv=g.nv, start=0)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    state0 = pull.init_state(prank, arrays)
    plan = expand.plan_expand_shards(shards)
    plan_pf = expand.to_pf(plan)
    return {
        "graph": g,
        "shards": shards,
        "pshards": pshards,
        "prank": prank,
        "psssp": psssp,
        "arrays": arrays,
        "state0": state0,
        "plan": plan,
        "plan_pf": plan_pf,
    }


@lru_cache(maxsize=1)
def _fused_pf_plan():
    from lux_tpu.ops import expand

    return expand.plan_fused_shards(fixture()["shards"], reduce="sum",
                                    pf=True)


@lru_cache(maxsize=1)
def _fused_mx_plan():
    from lux_tpu.ops import expand

    return expand.plan_fused_shards(fixture()["shards"], reduce="sum",
                                    mx=True)


def _dev_route(plan):
    import jax
    import jax.numpy as jnp

    rs, ra = plan
    return rs, jax.tree.map(jnp.asarray, ra)


@lru_cache(maxsize=1)
def _overlay_fixture():
    """Mutation overlays at three delta-buffer occupancies — EMPTY,
    HALF, FULL — against the shared fixture graph (cap pinned small so
    FULL is cheap).  The LUX-J1 unit's whole point: occupancy is DATA,
    so all three must produce byte-identical traces (ISSUE 10)."""
    import numpy as np

    from lux_tpu.mutate import MutableGraph

    fx = fixture()
    g = fx["graph"]
    cap = 128
    rng = np.random.default_rng(0)
    out = {}
    for name, n_ins in (("empty", 0), ("half", cap // 2), ("full", cap)):
        mg = MutableGraph(g, num_parts=2, cap=cap)
        mg._pull = fx["shards"]  # share the fixture layout
        if n_ins:
            # inserts confined to part 0's dst range so ONE part's
            # buffer actually reaches the occupancy under test, plus a
            # few tombstones so the deleted-mask path is live
            hi = int(fx["shards"].cuts[1])
            mg.apply(rng.integers(0, g.nv, n_ins),
                     rng.integers(0, hi, n_ins),
                     np.ones(n_ins, np.int8))
            dele = rng.choice(g.ne, 8, replace=False)
            mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
                     np.zeros(8, np.int8))
        out[name] = mg.pull_overlay()
    return out


# a (static, arrays) overlay pair device-places exactly like a route
# plan pair — one helper, two names for call-site clarity
_dev_overlay = _dev_route


# ---------------------------------------------------------------------------
# retrace (LUX-J1)
# ---------------------------------------------------------------------------


def _pull_fixed_traced(num_iters: int, route=None, ring=None,
                       overlay=None, method: str = "scan"):
    from lux_tpu.engine import pull

    fx = fixture()
    rs, ra = _dev_route(route) if route is not None else (None, None)
    os_, oa = _dev_overlay(overlay) if overlay is not None else (None,
                                                                 None)
    return pull._pull_fixed_jit.trace(
        fx["prank"], fx["shards"].spec, num_iters, method, fx["arrays"],
        fx["state0"], ring, route_static=rs, route_arrays=ra,
        interpret=True, ostatic=os_, oarrays=oa)


def _retrace_pull_fixed(routed: bool) -> List[Finding]:
    fx = fixture()
    route = fx["plan_pf"] if routed else None
    label = "pull-fixed/" + ("routed-pf" if routed else "direct")
    path = "lux_tpu/engine/pull.py"
    statics = (fx["prank"], fx["shards"].spec, "scan",
               route[0] if routed else None)
    out = retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, route), path, label, statics=statics)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, route), _pull_fixed_traced(3, route)],
        path, label)
    return out


def _retrace_pull_until() -> List[Finding]:
    from lux_tpu.engine import pull

    fx = fixture()
    path = "lux_tpu/engine/pull.py"

    def tr(max_iters):
        return pull._pull_until_jit.trace(
            fx["prank"], fx["shards"].spec, max_iters, _active_fn, "scan",
            fx["arrays"], fx["state0"], route_static=None,
            route_arrays=None, interpret=True)

    out = retrace.trace_twice_stable(
        lambda: tr(2), path, "pull-until/direct",
        statics=(fx["prank"], fx["shards"].spec, _active_fn, "scan"))
    out += retrace.check_variants([tr(2), tr(3)], path,
                                  "pull-until/direct")
    return out


def _retrace_pull_fixed_ring() -> List[Finding]:
    """The luxtrace ring's LUX-J1 leg (docs/OBSERVABILITY.md): the
    telemetry ring is static-shape loop carry, so telemetry-on must
    trace exactly like any other config of the family — stable across
    re-traces of one config and structurally identical across iteration
    counts (one compile still serves every run length)."""
    from lux_tpu.obs import ring as obs_ring

    fx = fixture()
    route = fx["plan_pf"]
    ring = obs_ring.new_ring("pull_fixed")
    path = "lux_tpu/engine/pull.py"
    label = "pull-fixed/routed-pf+ring"
    out = retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, route, ring), path, label)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, route, ring),
         _pull_fixed_traced(3, route, ring)], path, label)
    return out


def _retrace_pull_fixed_overlay() -> List[Finding]:
    """ISSUE 10's LUX-J1 guardrail: the mutation overlay's delta-buffer
    occupancy (empty / half / full at one capacity) is pure DATA — all
    three configs must produce the SAME trace (strict: identical avals,
    identical primitive sequence), and one config must re-trace
    stably.  A shape- or occupancy-dependent overlay would recompile
    the serving hot loop on every churn batch."""
    ovs = _overlay_fixture()
    path = "lux_tpu/engine/pull.py"
    label = "pull-fixed/overlay"
    fx = fixture()
    out = retrace.check_statics(
        (fx["prank"], fx["shards"].spec, "scan", ovs["half"][0]),
        path, label)
    out += retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, overlay=ovs["half"]), path, label)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, overlay=ovs[k])
         for k in ("empty", "half", "full")], path, label)
    return out


def _retrace_push_chunk_overlay() -> List[Finding]:
    """The push side of the churn-never-recompiles contract: the
    overlay chunk loop is ONE compile across delta occupancies — a
    re-call with different overlay arrays (and a different it_stop)
    must hit the jit cache."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    ovs = _overlay_fixture()
    os_, _ = ovs["half"]
    loop = push.compile_push_chunk(fx["psssp"], sh.pspec, sh.spec,
                                   "scan", overlay_static=os_)
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)

    def call(key, stop):
        oa = _dev_overlay(ovs[key])[1]

        def go():
            out = loop(arrays, parrays, carry0, jnp.int32(stop),
                       oarrays=oa)
            jax.block_until_ready(out.state)
            return out

        return go

    out = retrace.check_statics(
        (fx["psssp"], sh.pspec, sh.spec, "scan", os_),
        "lux_tpu/engine/push.py", "push-chunk/overlay")
    out += retrace.check_dynamic_recall(
        loop, call("empty", 2), call("full", 3),
        "lux_tpu/engine/push.py", "push-chunk/overlay")
    return out


def _retrace_push_chunk() -> List[Finding]:
    """The push loop's 'one compile serves every run length' contract:
    it_stop is DYNAMIC — a re-call with a different stop must hit the
    compile cache, not re-specialize."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    loop = push.compile_push_chunk(fx["psssp"], sh.pspec, sh.spec, "scan")
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)

    def call(stop):
        def go():
            out = loop(arrays, parrays, carry0, jnp.int32(stop))
            jax.block_until_ready(out.state)
            return out

        return go

    out = retrace.check_statics(
        (fx["psssp"], sh.pspec, sh.spec, "scan"),
        "lux_tpu/engine/push.py", "push-chunk")
    out += retrace.check_dynamic_recall(
        loop, call(2), call(3), "lux_tpu/engine/push.py",
        "push-chunk/it_stop")
    return out


def _retrace_push_chunk_tree() -> List[Finding]:
    """ISSUE 17's LUX-J1 leg for the TREE cross-part merge: the
    asynchronous reduction tree is a STATIC schedule (ops/merge_tree.py
    — plan_tree is a pure function of the part count), so the tree-merge
    chunk loop must hold the same contracts as the bulk one: hashable
    statics and one compile across run lengths (it_stop re-calls hit
    the cache)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    loop = push.compile_push_chunk(fx["psssp"], sh.pspec, sh.spec, "scan",
                                   merge="tree")
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)

    def call(stop):
        def go():
            out = loop(arrays, parrays, carry0, jnp.int32(stop))
            jax.block_until_ready(out.state)
            return out

        return go

    out = retrace.check_statics(
        (fx["psssp"], sh.pspec, sh.spec, "scan", "tree"),
        "lux_tpu/engine/push.py", "push-chunk/tree-merge")
    out += retrace.check_dynamic_recall(
        loop, call(2), call(3), "lux_tpu/engine/push.py",
        "push-chunk/tree-merge/it_stop")
    return out


def _retrace_pull_fixed_fused_overlay() -> List[Finding]:
    """ISSUE 17's LUX-J1 leg for overlays on the FUSED families: the
    group-space tombstone (the plan's gslot route) is scattered from
    overlay DATA, so delta occupancy must stay trace-invariant on the
    fused-pf hot loop exactly as it is on expand — empty / half / full
    produce one trace, and the config re-traces stably (a churn batch
    never recompiles the fastest serving kernels)."""
    ovs = _overlay_fixture()
    fx = fixture()
    route = _fused_pf_plan()
    path = "lux_tpu/engine/pull.py"
    label = "pull-fixed/fused-pf+overlay"
    out = retrace.check_statics(
        (fx["prank"], fx["shards"].spec, "scan", route[0],
         ovs["half"][0]), path, label)
    out += retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, route, overlay=ovs["half"]),
        path, label)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, route, overlay=ovs[k])
         for k in ("empty", "half", "full")], path, label)
    return out


def _serve_traced(app: str, q: int):
    import jax.numpy as jnp

    from lux_tpu.serve import batched

    fx = fixture()
    spec = fx["shards"].spec
    prog = batched.make_program(app, spec.nv)
    if prog.fixpoint:
        run = batched._compile_batched_fixpoint(prog, spec, "scan")
    else:
        run = batched._compile_batched_fixed(prog, spec, "scan")
    init = batched._compile_batched_init(prog)
    queries = jnp.zeros((q,), jnp.int32)
    s0 = init(fx["arrays"], queries)
    return run, (fx["arrays"], queries, s0, jnp.int32(4))


def _retrace_serve(app: str) -> List[Finding]:
    """Q-bucket structural identity: the batched loop's program may
    differ across buckets ONLY in the Q axis — a Q-dependent op set or
    unroll would multiply the warm cache's compile bill."""
    path = "lux_tpu/serve/batched.py"
    run1, args1 = _serve_traced(app, 1)
    run4, args4 = _serve_traced(app, 4)
    # Q changes the trailing-axis SHAPES, so the comparison is the
    # coarse structural one: broadcasting idioms may differ at Q=1,
    # loops/gathers/kernels may not
    out = retrace.check_variants(
        [run1.trace(*args1), run4.trace(*args4)], path,
        f"serve-{app}/Q-buckets", strict=False)
    return out


def _retrace_serve_overlay() -> List[Finding]:
    """ISSUE 12's LUX-J1 guardrail on the SERVING loop: the overlay-twin
    batched fixpoint (the live fleet's query path) across delta-buffer
    occupancies — empty / half / full are pure data, so all three must
    trace byte-identically; a churn batch must never recompile a warm
    Q-bucket engine."""
    import jax.numpy as jnp

    from lux_tpu.serve import batched

    ovs = _overlay_fixture()
    fx = fixture()
    spec = fx["shards"].spec
    prog = batched.make_program("sssp", spec.nv)
    path = "lux_tpu/serve/batched.py"
    label = "serve-sssp/overlay"

    def traced(key):
        os_, oa = _dev_overlay(ovs[key])
        run = batched._compile_batched_fixpoint(prog, spec, "scan", os_)
        queries = jnp.zeros((4,), jnp.int32)
        s0 = batched._compile_batched_init(prog)(fx["arrays"], queries)
        return run.trace(fx["arrays"], queries, s0, jnp.int32(4), oa)

    out = retrace.trace_twice_stable(lambda: traced("half"), path,
                                     label)
    out += retrace.check_variants(
        [traced(k) for k in ("empty", "half", "full")], path, label)
    return out


def _retrace_serve_dynamic() -> List[Finding]:
    """max_iters is a dynamic operand of the serve loops: re-calls with
    a different stop must not recompile (the scheduler varies it)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.serve import batched

    fx = fixture()
    run, args = _serve_traced("sssp", 1)
    arrays, queries, _, _ = args
    prog = batched.make_program("sssp", fx["shards"].spec.nv)
    ini = batched._compile_batched_init(prog)

    def call(stop):
        # the state is donated per call: rebuild it for each probe
        def go():
            out = run(arrays, queries, ini(arrays, queries),
                      jnp.int32(stop))
            jax.block_until_ready(out[0])
            return out

        return go

    return retrace.check_dynamic_recall(
        run, call(2), call(3), "lux_tpu/serve/batched.py",
        "serve-sssp/max_iters")


# ---------------------------------------------------------------------------
# donation (LUX-J2)
# ---------------------------------------------------------------------------


def _donation_pull_fixed() -> List[Finding]:
    from lux_tpu.engine import pull

    fx = fixture()
    args = (fx["arrays"], fx["state0"])
    traced = pull._pull_fixed_jit_donate.trace(
        fx["prank"], fx["shards"].spec, 3, "scan", *args,
        route_static=None, route_arrays=None, interpret=True)
    return donation.check_donation(
        traced, args, donate_argnums=(1,), path="lux_tpu/engine/pull.py",
        label="pull-fixed/donate")


def _donation_pull_until() -> List[Finding]:
    from lux_tpu.engine import pull

    fx = fixture()
    args = (fx["arrays"], fx["state0"])
    traced = pull._pull_until_jit_donate.trace(
        fx["prank"], fx["shards"].spec, 4, _active_fn, "scan", *args,
        route_static=None, route_arrays=None, interpret=True)
    return donation.check_donation(
        traced, args, donate_argnums=(1,), path="lux_tpu/engine/pull.py",
        label="pull-until/donate")


def _donation_push_chunk() -> List[Finding]:
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    loop = push.compile_push_chunk(fx["psssp"], sh.pspec, sh.spec, "scan",
                                   donate=True)
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)
    args = (arrays, parrays, carry0, jnp.int32(4))
    traced = loop.trace(*args)
    return donation.check_donation(
        traced, args, donate_argnums=(2,), path="lux_tpu/engine/push.py",
        label="push-chunk/donate")


def _donation_push_step() -> List[Finding]:
    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    step = push.compile_push_step(fx["psssp"], sh.pspec, sh.spec, "scan")
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)
    args = (arrays, parrays, carry0)
    traced = step.trace(*args)
    return donation.check_donation(
        traced, args, donate_argnums=(2,), path="lux_tpu/engine/push.py",
        label="push-step/donate")


def _donation_pull_fixed_ring() -> List[Finding]:
    """The luxtrace ring's LUX-J2 leg: a donating telemetry run must
    consume the ring's input buffer WITH the state (the ring is pure
    loop carry — one ring copy in HBM, not two)."""
    from lux_tpu.engine import pull
    from lux_tpu.obs import ring as obs_ring

    fx = fixture()
    ring = obs_ring.new_ring("pull_fixed", cap=64)
    args = (fx["arrays"], fx["state0"], ring)
    traced = pull._pull_fixed_jit_donate.trace(
        fx["prank"], fx["shards"].spec, 3, "scan", *args,
        route_static=None, route_arrays=None, interpret=True)
    return donation.check_donation(
        traced, args, donate_argnums=(1, 2), path="lux_tpu/engine/pull.py",
        label="pull-fixed/ring-donate")


def _donation_push_chunk_ring() -> List[Finding]:
    import jax.numpy as jnp

    from lux_tpu.engine import push
    from lux_tpu.obs import ring as obs_ring

    fx = fixture()
    sh = fx["pshards"]
    loop = push.compile_push_chunk(fx["psssp"], sh.pspec, sh.spec, "scan",
                                   donate=True, telemetry=True)
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)
    ring = obs_ring.new_ring("push", cap=64)
    args = (arrays, parrays, carry0, jnp.int32(4), ring)
    traced = loop.trace(*args)
    return donation.check_donation(
        traced, args, donate_argnums=(2, 4), path="lux_tpu/engine/push.py",
        label="push-chunk/ring-donate")


def _donation_pull_fixed_overlay() -> List[Finding]:
    """ISSUE 10's LUX-J2 leg: a donating refresh run must still consume
    the state carry with the overlay present — the delta buffers ride
    as read-only arguments (reused across iterations AND refreshes, so
    they must NOT be donated), while the warm state's input buffer
    frees for the loop's ping-pong exactly as without the overlay."""
    from lux_tpu.engine import pull

    fx = fixture()
    ovs = _overlay_fixture()
    os_, oa = _dev_overlay(ovs["half"])
    args = (fx["arrays"], fx["state0"])
    traced = pull._pull_fixed_jit_donate.trace(
        fx["prank"], fx["shards"].spec, 3, "scan", *args,
        route_static=None, route_arrays=None, interpret=True,
        ostatic=os_, oarrays=oa)
    return donation.check_donation(
        traced, args, donate_argnums=(1,), path="lux_tpu/engine/pull.py",
        label="pull-fixed/overlay-donate")


def _donation_serve(app: str) -> List[Finding]:
    run, args = _serve_traced(app, 4)
    traced = run.trace(*args)
    return donation.check_donation(
        traced, args, donate_argnums=(2,),
        path="lux_tpu/serve/batched.py", label=f"serve-{app}/donate")


# ---------------------------------------------------------------------------
# collective order (LUX-J3)
# ---------------------------------------------------------------------------


def _mesh(n: int):
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    return make_mesh_for_parts(n)


def _collective_push_dist() -> List[Finding]:
    import jax.numpy as jnp

    from lux_tpu.analysis.ir import aot
    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    mesh = _mesh(2)
    run = push._compile_push_dist(fx["psssp"], mesh, sh.pspec, sh.spec,
                                  "scan")
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)
    traced = run.trace(arrays, parrays, carry0, jnp.int32(4))
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/engine/push.py",
        "push-dist/allgather")


def _collective_push_ring() -> List[Finding]:
    import jax.numpy as jnp

    from lux_tpu.analysis.ir import aot
    from lux_tpu.engine import push
    from lux_tpu.parallel.ring import build_push_ring_shards

    fx = fixture()
    mesh = _mesh(2)
    rsh = build_push_ring_shards(fx["graph"], 2)
    run = push._compile_push_ring(fx["psssp"], mesh, rsh.pspec, rsh.spec,
                                  rsh.e_bucket_pad, "scan")
    rarrays, parrays, view, carry0 = push.ring_init_dist(
        fx["psssp"], rsh, mesh)
    traced = run.trace(rarrays, parrays, view, carry0, jnp.int32(4))
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/engine/push.py",
        "push-ring/ppermute")


def _collective_push_dist_tree() -> List[Finding]:
    """ISSUE 17's LUX-J3 leg: the tree merge's staged ppermute
    concatenation (merge_tree.staged_concat_gather) replaces the bulk
    all_gather barrier — every stage's permutation is derived from the
    mesh-agreed device count alone (bruck_schedule), never from data,
    so the checker must find an identical collective sequence in every
    shard_map body (the deadlock-freedom proof obligation)."""
    import jax.numpy as jnp

    from lux_tpu.analysis.ir import aot
    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    mesh = _mesh(2)
    run = push._compile_push_dist(fx["psssp"], mesh, sh.pspec, sh.spec,
                                  "scan", merge="tree")
    arrays, parrays, carry0 = push.push_init(fx["psssp"], sh)
    traced = run.trace(arrays, parrays, carry0, jnp.int32(4))
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/engine/push.py",
        "push-dist/tree-merge")


def _collective_pull_dist() -> List[Finding]:
    from lux_tpu.analysis.ir import aot
    from lux_tpu.parallel import dist
    from lux_tpu.parallel.mesh import shard_stacked

    fx = fixture()
    mesh = _mesh(2)
    run = dist._compile_fixed(fx["prank"], mesh, 3, "scan")
    arrays = shard_stacked(mesh, fx["arrays"])
    state0 = shard_stacked(mesh, fx["state0"])
    traced = run.trace(arrays, state0)
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/parallel/dist.py",
        "pull-dist/allgather")


def _collective_halo_roundtrip() -> List[Finding]:
    """ISSUE 19's LUX-J3 leg, minimal form: the placement tree's two
    halo primitives back to back — halo_all_gather's tiled all_gather
    and halo_reduce_scatter's tiled psum_scatter.  Both permutation-free
    by construction (the schedule is the mesh axis itself), so the
    checker must see the identical two-collective sequence in the one
    shard_map body."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from lux_tpu.analysis.ir import aot
    from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked
    from lux_tpu.parallel.placement import (
        halo_all_gather,
        halo_reduce_scatter,
    )

    mesh = _mesh(2)

    def body(blk):  # blk: (k=1, V, F) per device
        full = halo_all_gather(blk)          # (P*V, F)
        partials = full.reshape((2,) + blk.shape[1:])
        return halo_reduce_scatter(partials, 1)

    roundtrip = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(PARTS_AXIS),),
        out_specs=P(PARTS_AXIS)))
    x = shard_stacked(mesh, jnp.zeros((2, 8, 4), jnp.float32))
    traced = roundtrip.trace(x)
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/parallel/placement.py",
        "placement/halo-roundtrip")


def _collective_pull_scatter() -> List[Finding]:
    """The scatter engine's exchange (ISSUE 19): per-destination
    partials pre-summed on the source chip, then ONE halo_reduce_scatter
    hands each chip its own destination block."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.analysis.ir import aot
    from lux_tpu.parallel import scatter
    from lux_tpu.parallel.mesh import shard_stacked

    fx = fixture()
    mesh = _mesh(2)
    ssh = scatter.build_scatter_shards(fx["graph"], 2, pull=fx["shards"])
    run = scatter._compile_scatter_fixed(fx["prank"], mesh, 2, 3, "scan")
    sarrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, ssh.sarrays))
    vtx_mask = shard_stacked(mesh, jnp.asarray(ssh.arrays.vtx_mask))
    degree = shard_stacked(mesh, jnp.asarray(ssh.arrays.degree))
    state0 = shard_stacked(mesh, fx["state0"])
    traced = run.trace(sarrays, vtx_mask, degree, state0)
    return check_shard_map_bodies(
        aot.traced_jaxpr(traced), "lux_tpu/parallel/scatter.py",
        "pull-scatter/psum-scatter")


# ---------------------------------------------------------------------------
# VMEM budget (LUX-J4) + HBM passes (LUX-J5)
# ---------------------------------------------------------------------------


def _vmem_expand_pf() -> List[Finding]:
    fx = fixture()
    rs, ra = fx["plan_pf"]
    return vmem.check_vmem(rs, ra, "lux_tpu/ops/pallas_shuffle.py",
                           "expand-pf")


def _vmem_fused_pf() -> List[Finding]:
    rs, ra = _fused_pf_plan()
    return vmem.check_vmem(rs, ra, "lux_tpu/ops/pallas_shuffle.py",
                           "fused-pf")


def _vmem_fused_mx() -> List[Finding]:
    """LUX-J4's mxreduce leg (ISSUE 7): the MXREDUCE final group's
    one-hot / accumulator / rank tiles join the residency ledger."""
    rs, ra = _fused_mx_plan()
    return vmem.check_vmem(rs, ra, "lux_tpu/ops/pallas_shuffle.py",
                           "fused-mx")


def _expand_traced(plan):
    import jax

    from lux_tpu.ops import expand

    fx = fixture()
    rs, ra = _dev_route(plan)
    part = jax.tree.map(lambda a: a[0], ra)
    full = fx["state0"].reshape(-1)

    def replay(x, arrs):
        return expand.apply_expand(x, rs, arrs, interpret=True)

    return jax.jit(replay).trace(full, part), rs


def _hbm_expand(routed_pf: bool) -> List[Finding]:
    fx = fixture()
    plan = fx["plan_pf"] if routed_pf else fx["plan"]
    traced, rs = _expand_traced(plan)
    label = "expand-pf" if routed_pf else "expand"
    return hbm.check_hbm(traced, rs, "lux_tpu/ops/expand.py", label)


def _hbm_ring_neutral() -> List[Finding]:
    """The luxtrace ring's LUX-J5 leg: telemetry-on launches EXACTLY the
    kernels of telemetry-off on the routed-pf hot loop — zero added
    accounted HBM passes (the shipped claim in docs/OBSERVABILITY.md)."""
    from lux_tpu.obs import ring as obs_ring

    fx = fixture()
    route = fx["plan_pf"]
    base = _pull_fixed_traced(2, route)
    twin = _pull_fixed_traced(2, route, obs_ring.new_ring("pull_fixed"))
    return hbm.check_kernel_parity(base, twin, "lux_tpu/engine/pull.py",
                                   "pull-fixed/ring-neutral")


def _hbm_overlay_neutral() -> List[Finding]:
    """ISSUE 10's LUX-J503 leg: overlay-on vs overlay-off kernel parity
    on the routed-pf hot loop — the tombstone mask is an elementwise
    select and the delta fold an XLA gather+scatter, so the overlay
    must launch EXACTLY the base config's custom kernels (zero extra
    pallas_calls; the O(cap) delta pass rides the fused XLA graph)."""
    fx = fixture()
    route = fx["plan_pf"]
    ovs = _overlay_fixture()
    base = _pull_fixed_traced(2, route)
    twin = _pull_fixed_traced(2, route, overlay=ovs["half"])
    return hbm.check_kernel_parity(base, twin, "lux_tpu/engine/pull.py",
                                   "pull-fixed/overlay-neutral")


def _hbm_fused_overlay_neutral() -> List[Finding]:
    """ISSUE 17's LUX-J503 leg: overlay-on vs overlay-off kernel parity
    on the FUSED-PF hot loop — the group-space tombstone is a scatter +
    select in plain XLA and the insert fold rides the existing
    delta_scatter graph, so mutation on the fastest plan family must
    launch EXACTLY the base config's pallas kernels (the accounted
    hbm_passes win is real, not paid back in hidden launches)."""
    route = _fused_pf_plan()
    ovs = _overlay_fixture()
    base = _pull_fixed_traced(2, route)
    twin = _pull_fixed_traced(2, route, overlay=ovs["half"])
    return hbm.check_kernel_parity(base, twin, "lux_tpu/engine/pull.py",
                                   "pull-fixed/fused-pf/overlay-neutral")


def _hbm_fused_pf() -> List[Finding]:
    import jax

    from lux_tpu.ops import expand

    fx = fixture()
    rs, ra = _dev_route(_fused_pf_plan())
    part = jax.tree.map(lambda a: a[0], ra)
    full = fx["state0"].reshape(-1)

    def replay(x, arrs):
        return expand.apply_fused(x, rs, arrs, interpret=True)

    traced = jax.jit(replay).trace(full, part)
    return hbm.check_hbm(traced, rs, "lux_tpu/ops/expand.py", "fused-pf")


def _hbm_fused_mx() -> List[Finding]:
    """LUX-J5's mxreduce leg: the fused-mx replay's pallas_call count
    must match the static's derivation (prefix groups + ONE combined
    gather+reduce kernel), and the roofline claim — which charges that
    kernel 0.5 sweeps and drops the separate reduce sweep — must
    un-scale back to the same kernel count."""
    import jax

    from lux_tpu.ops import expand

    fx = fixture()
    rs, ra = _dev_route(_fused_mx_plan())
    part = jax.tree.map(lambda a: a[0], ra)
    full = fx["state0"].reshape(-1)

    def replay(x, arrs):
        return expand.apply_fused(x, rs, arrs, interpret=True)

    traced = jax.jit(replay).trace(full, part)
    return hbm.check_hbm(traced, rs, "lux_tpu/ops/expand.py", "fused-mx")


def _retrace_pull_fixed_mx() -> List[Finding]:
    """LUX-J1 for the mxreduce engine entry point: the fused-mx routed
    pull must trace stably and keep one compile across run lengths,
    exactly like every other config of the pull-fixed family."""
    fx = fixture()
    route = _fused_mx_plan()
    path = "lux_tpu/engine/pull.py"
    label = "pull-fixed/fused-mx"
    statics = (fx["prank"], fx["shards"].spec, "scan", route[0])
    out = retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, route), path, label, statics=statics)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, route), _pull_fixed_traced(3, route)],
        path, label)
    return out


def _hbm_mx_ring_neutral() -> List[Finding]:
    """LUX-J503 for the mxreduce entry point: the telemetry ring on the
    fused-mx hot loop must launch EXACTLY the base config's kernels —
    the in-kernel reduction must stay one kernel with the ring riding
    the carry."""
    from lux_tpu.obs import ring as obs_ring

    route = _fused_mx_plan()
    base = _pull_fixed_traced(2, route)
    twin = _pull_fixed_traced(2, route, obs_ring.new_ring("pull_fixed"))
    return hbm.check_kernel_parity(base, twin, "lux_tpu/engine/pull.py",
                                   "pull-fixed/fused-mx/ring-neutral")


def _retrace_pull_fixed_mxscan() -> List[Finding]:
    """LUX-J1 for the mxscan engine entry point (ISSUE 11): the
    mxscan-reduced pull must trace stably and keep one compile across
    run lengths — segment geometry (row_ptr/head_flag VALUES) is data,
    so different censuses share the compile; only the tile-rows knob,
    read at trace time, may change the program."""
    fx = fixture()
    path = "lux_tpu/engine/pull.py"
    label = "pull-fixed/mxscan"
    statics = (fx["prank"], fx["shards"].spec, "mxscan", None)
    out = retrace.trace_twice_stable(
        lambda: _pull_fixed_traced(2, method="mxscan"), path, label,
        statics=statics)
    out += retrace.check_variants(
        [_pull_fixed_traced(2, method="mxscan"),
         _pull_fixed_traced(3, method="mxscan")], path, label)
    return out


def _vmem_mxscan() -> List[Finding]:
    """LUX-J4's mxscan leg: the scan tile + head-count tiles + masked
    triangular operand + carry against LUX_PF_VMEM_MB."""
    return vmem.check_vmem_mxscan("lux_tpu/ops/pallas_scan.py", "mxscan")


def _hbm_mxscan() -> List[Finding]:
    """LUX-J5's mxscan leg: the traced csc segment sum on
    method='mxscan' must launch EXACTLY ONE pallas_call — the kernel
    count behind REDUCE_HBM_PASSES['mxscan'] == 2 being exact."""
    import jax

    from lux_tpu.ops import segment

    fx = fixture()
    arr = fx["arrays"]
    e_pad = fx["shards"].arrays.src_pos.shape[1]
    import jax.numpy as jnp

    vals = jnp.ones((e_pad,), jnp.float32)

    def reduce_part(v, rp, hf, dl):
        return segment.segment_sum_csc(v, rp, hf, dl, method="mxscan")

    traced = jax.jit(reduce_part).trace(
        vals, arr.row_ptr[0], arr.head_flag[0], arr.dst_local[0])
    return hbm.check_kernel_count(traced, 1, "lux_tpu/ops/pallas_scan.py",
                                  "segment/mxscan")


def _hbm_mxscan_ring_neutral() -> List[Finding]:
    """LUX-J503 for the mxscan entry point: the telemetry ring on the
    mxscan-reduced hot loop must launch EXACTLY the base config's
    kernels — the scan stays one kernel per part-iteration with the
    ring riding the carry."""
    from lux_tpu.obs import ring as obs_ring

    base = _pull_fixed_traced(2, method="mxscan")
    twin = _pull_fixed_traced(2, method="mxscan",
                              ring=obs_ring.new_ring("pull_fixed"))
    return hbm.check_kernel_parity(base, twin, "lux_tpu/engine/pull.py",
                                   "pull-fixed/mxscan/ring-neutral")


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


def _spec_labelprop_prog():
    """A spec-compiled WIDE pull program (ISSUE 13) — the audit gates
    must cover compiled programs exactly like hand-wired dataclasses."""
    from lux_tpu.program import workloads

    return workloads.labelprop_program(labels=4, stride=8)


def _spec_labelprop_traced(num_iters: int):
    from lux_tpu.engine import pull

    fx = fixture()
    prog = _spec_labelprop_prog()
    state0 = pull.init_state(prog, fx["arrays"])
    return pull._pull_fixed_jit.trace(
        prog, fx["shards"].spec, num_iters, "scan", fx["arrays"], state0,
        None, route_static=None, route_arrays=None, interpret=True,
        ostatic=None, oarrays=None), state0


def _retrace_spec_labelprop() -> List[Finding]:
    """LUX-J1 for a spec-compiled program (labelprop, dense pull, wide
    state): the compiled program must be a stable jit static — stable
    across re-traces AND across reconstruction of an equal program
    (two equal specs ARE one program), structurally identical across
    iteration counts."""
    fx = fixture()
    path = "lux_tpu/program/spec.py"
    label = "pull-fixed/spec-labelprop"
    out = retrace.check_statics(
        (_spec_labelprop_prog(), fx["shards"].spec, "scan"), path, label)
    out += retrace.trace_twice_stable(
        lambda: _spec_labelprop_traced(2)[0], path, label)
    out += retrace.check_variants(
        [_spec_labelprop_traced(2)[0], _spec_labelprop_traced(3)[0]],
        path, label)
    return out


def _donation_spec_labelprop() -> List[Finding]:
    """LUX-J2 for the spec-compiled pull program: the donating twin
    must consume the wide state buffer exactly like a hand-wired
    program's."""
    from lux_tpu.engine import pull

    fx = fixture()
    prog = _spec_labelprop_prog()
    state0 = pull.init_state(prog, fx["arrays"])
    args = (fx["arrays"], state0)
    traced = pull._pull_fixed_jit_donate.trace(
        prog, fx["shards"].spec, 3, "scan", *args,
        route_static=None, route_arrays=None, interpret=True)
    return donation.check_donation(
        traced, args, donate_argnums=(1,),
        path="lux_tpu/program/spec.py",
        label="pull-fixed/spec-labelprop-donate")


def _spec_bfs_prog():
    from lux_tpu.program import workloads

    return workloads.bfs_program(fixture()["graph"].nv, (0, 5))


def _retrace_spec_bfs_push() -> List[Finding]:
    """LUX-J1 for a spec-compiled frontier program (bfs) on the push
    chunk loop: statics hashable, it_stop re-calls hit the compile
    cache (the same one-compile-serves-every-run-length contract the
    hand-wired sssp unit pins)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    prog = _spec_bfs_prog()
    loop = push.compile_push_chunk(prog, sh.pspec, sh.spec, "scan")
    arrays, parrays, carry0 = push.push_init(prog, sh)

    def call(stop):
        def go():
            out = loop(arrays, parrays, carry0, jnp.int32(stop))
            jax.block_until_ready(out.state)
            return out

        return go

    path = "lux_tpu/program/spec.py"
    out = retrace.check_statics((prog, sh.pspec, sh.spec, "scan"),
                                path, "push-chunk/spec-bfs")
    out += retrace.check_dynamic_recall(
        loop, call(2), call(3), path, "push-chunk/spec-bfs/it_stop")
    return out


def _donation_spec_bfs_push() -> List[Finding]:
    """LUX-J2 for the spec-compiled push program: the donating chunk
    loop consumes the carry."""
    import jax.numpy as jnp

    from lux_tpu.engine import push

    fx = fixture()
    sh = fx["pshards"]
    prog = _spec_bfs_prog()
    loop = push.compile_push_chunk(prog, sh.pspec, sh.spec, "scan",
                                   donate=True)
    arrays, parrays, carry0 = push.push_init(prog, sh)
    args = (arrays, parrays, carry0, jnp.int32(4))
    traced = loop.trace(*args)
    return donation.check_donation(
        traced, args, donate_argnums=(2,),
        path="lux_tpu/program/spec.py",
        label="push-chunk/spec-bfs-donate")


def audit_units(fast: bool = False) -> List[AuditUnit]:
    units = [
        AuditUnit("retrace", "pull-fixed/direct",
                  "lux_tpu/engine/pull.py", True,
                  lambda: _retrace_pull_fixed(False)),
        AuditUnit("retrace", "pull-fixed/routed-pf",
                  "lux_tpu/engine/pull.py", True,
                  lambda: _retrace_pull_fixed(True)),
        AuditUnit("retrace", "pull-fixed/routed-pf+ring",
                  "lux_tpu/engine/pull.py", True,
                  _retrace_pull_fixed_ring),
        AuditUnit("retrace", "pull-fixed/fused-mx",
                  "lux_tpu/engine/pull.py", False, _retrace_pull_fixed_mx),
        AuditUnit("retrace", "pull-fixed/mxscan",
                  "lux_tpu/engine/pull.py", False,
                  _retrace_pull_fixed_mxscan),
        AuditUnit("retrace", "pull-until/direct",
                  "lux_tpu/engine/pull.py", False, _retrace_pull_until),
        AuditUnit("retrace", "pull-fixed/overlay",
                  "lux_tpu/engine/pull.py", True,
                  _retrace_pull_fixed_overlay),
        AuditUnit("retrace", "push-chunk/overlay",
                  "lux_tpu/engine/push.py", False,
                  _retrace_push_chunk_overlay),
        AuditUnit("retrace", "push-chunk/it_stop",
                  "lux_tpu/engine/push.py", True, _retrace_push_chunk),
        AuditUnit("retrace", "push-chunk/tree-merge",
                  "lux_tpu/engine/push.py", True,
                  _retrace_push_chunk_tree),
        AuditUnit("retrace", "pull-fixed/fused-pf+overlay",
                  "lux_tpu/engine/pull.py", False,
                  _retrace_pull_fixed_fused_overlay),
        AuditUnit("retrace", "serve-sssp/Q-buckets",
                  "lux_tpu/serve/batched.py", False,
                  lambda: _retrace_serve("sssp")),
        AuditUnit("retrace", "serve-ppr/Q-buckets",
                  "lux_tpu/serve/batched.py", False,
                  lambda: _retrace_serve("ppr")),
        AuditUnit("retrace", "serve-sssp/max_iters",
                  "lux_tpu/serve/batched.py", False,
                  _retrace_serve_dynamic),
        AuditUnit("retrace", "serve-sssp/overlay",
                  "lux_tpu/serve/batched.py", False,
                  _retrace_serve_overlay),
        AuditUnit("retrace", "pull-fixed/spec-labelprop",
                  "lux_tpu/program/spec.py", False,
                  _retrace_spec_labelprop),
        AuditUnit("retrace", "push-chunk/spec-bfs",
                  "lux_tpu/program/spec.py", False,
                  _retrace_spec_bfs_push),
        AuditUnit("donation", "pull-fixed/donate",
                  "lux_tpu/engine/pull.py", True, _donation_pull_fixed),
        AuditUnit("donation", "pull-until/donate",
                  "lux_tpu/engine/pull.py", False, _donation_pull_until),
        AuditUnit("donation", "push-chunk/donate",
                  "lux_tpu/engine/push.py", True, _donation_push_chunk),
        AuditUnit("donation", "push-step/donate",
                  "lux_tpu/engine/push.py", False, _donation_push_step),
        AuditUnit("donation", "pull-fixed/overlay-donate",
                  "lux_tpu/engine/pull.py", True,
                  _donation_pull_fixed_overlay),
        AuditUnit("donation", "pull-fixed/ring-donate",
                  "lux_tpu/engine/pull.py", True,
                  _donation_pull_fixed_ring),
        AuditUnit("donation", "push-chunk/ring-donate",
                  "lux_tpu/engine/push.py", False,
                  _donation_push_chunk_ring),
        AuditUnit("donation", "serve-sssp/donate",
                  "lux_tpu/serve/batched.py", False,
                  lambda: _donation_serve("sssp")),
        AuditUnit("donation", "serve-ppr/donate",
                  "lux_tpu/serve/batched.py", False,
                  lambda: _donation_serve("ppr")),
        AuditUnit("donation", "pull-fixed/spec-labelprop-donate",
                  "lux_tpu/program/spec.py", False,
                  _donation_spec_labelprop),
        AuditUnit("donation", "push-chunk/spec-bfs-donate",
                  "lux_tpu/program/spec.py", False,
                  _donation_spec_bfs_push),
        AuditUnit("collective", "push-dist/allgather",
                  "lux_tpu/engine/push.py", False, _collective_push_dist),
        AuditUnit("collective", "push-ring/ppermute",
                  "lux_tpu/engine/push.py", False, _collective_push_ring),
        AuditUnit("collective", "push-dist/tree-merge",
                  "lux_tpu/engine/push.py", False,
                  _collective_push_dist_tree),
        AuditUnit("collective", "pull-dist/allgather",
                  "lux_tpu/parallel/dist.py", False, _collective_pull_dist),
        AuditUnit("collective", "placement/halo-roundtrip",
                  "lux_tpu/parallel/placement.py", False,
                  _collective_halo_roundtrip),
        AuditUnit("collective", "pull-scatter/psum-scatter",
                  "lux_tpu/parallel/scatter.py", False,
                  _collective_pull_scatter),
        AuditUnit("vmem", "expand-pf", "lux_tpu/ops/pallas_shuffle.py",
                  True, _vmem_expand_pf),
        AuditUnit("vmem", "fused-pf", "lux_tpu/ops/pallas_shuffle.py",
                  False, _vmem_fused_pf),
        AuditUnit("vmem", "fused-mx", "lux_tpu/ops/pallas_shuffle.py",
                  False, _vmem_fused_mx),
        AuditUnit("vmem", "mxscan", "lux_tpu/ops/pallas_scan.py",
                  False, _vmem_mxscan),
        AuditUnit("hbm", "expand", "lux_tpu/ops/expand.py", False,
                  lambda: _hbm_expand(False)),
        AuditUnit("hbm", "expand-pf", "lux_tpu/ops/expand.py", True,
                  lambda: _hbm_expand(True)),
        AuditUnit("hbm", "pull-fixed/ring-neutral",
                  "lux_tpu/engine/pull.py", True, _hbm_ring_neutral),
        AuditUnit("hbm", "pull-fixed/overlay-neutral",
                  "lux_tpu/engine/pull.py", True, _hbm_overlay_neutral),
        AuditUnit("hbm", "pull-fixed/fused-pf/overlay-neutral",
                  "lux_tpu/engine/pull.py", False,
                  _hbm_fused_overlay_neutral),
        AuditUnit("hbm", "fused-pf", "lux_tpu/ops/expand.py", False,
                  _hbm_fused_pf),
        AuditUnit("hbm", "fused-mx", "lux_tpu/ops/expand.py", False,
                  _hbm_fused_mx),
        AuditUnit("hbm", "pull-fixed/fused-mx/ring-neutral",
                  "lux_tpu/engine/pull.py", False, _hbm_mx_ring_neutral),
        AuditUnit("hbm", "segment/mxscan", "lux_tpu/ops/pallas_scan.py",
                  False, _hbm_mxscan),
        AuditUnit("hbm", "pull-fixed/mxscan/ring-neutral",
                  "lux_tpu/engine/pull.py", False,
                  _hbm_mxscan_ring_neutral),
    ]
    if fast:
        units = [u for u in units if u.fast]
    return units
