"""LUX-J1: retrace stability — one trace per engine family config.

"Single trace, no recompiles in the window" is load-bearing perf prose
in five rounds of PERF.md; what actually enforces it is (a) every jit
static being hashable with a stable hash, (b) program STRUCTURE not
depending on the family's config axis (iteration count, Q bucket) —
a Python-level unroll over the config turns one compile into one per
value — and (c) genuinely-dynamic knobs (the push engine's ``it_stop``,
the serve loops' ``max_iters``) actually hitting the compile cache
instead of re-specializing.  Each sub-check maps to a finding code:

* LUX-J101 — structural drift: two configs of one family trace to
  different primitive sequences (config-dependent unrolling, an op set
  that changes with Q, a shape leak into control flow);
* LUX-J102 — a jit static that is unhashable or hash-unstable (the
  compile cache can never hit; every call retraces);
* LUX-J103 — a dynamic-argument re-call grew the jit compile cache
  (the "one compile serves every run length" contract broken).

The cache-size probe uses the private ``_cache_size`` accessor; on a
jax without it the J103 check degrades to skipped (documented AOT
caveat) rather than guessing.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from lux_tpu.analysis.core import Finding
from lux_tpu.analysis.ir import aot


def check_statics(statics: Sequence, path: str, label: str,
                  line: int = 1) -> List[Finding]:
    findings: List[Finding] = []
    for s in statics:
        err = aot.hashable(s)
        if err is not None:
            findings.append(Finding(
                path=path, line=line, col=0, code="LUX-J102",
                message=f"jit static {type(s).__name__!r} is not usable as "
                        f"a compile-cache key: {err}",
                text=label))
    return findings


def check_variants(traced_variants: Sequence, path: str, label: str,
                   line: int = 1, strict: bool = True) -> List[Finding]:
    """All configs of one family must share one program structure.

    ``strict=True`` (configs with IDENTICAL avals, e.g. iteration
    counts): the full primitive sequence must match.  ``strict=False``
    (configs that change shapes, e.g. Q buckets): only the structural
    multiset (aot.STRUCTURAL_PRIMS — control flow, kernels, gathers,
    collectives) must match; degenerate-broadcast idiom differences at
    Q=1 are not drift, an extra loop or kernel per config is."""
    findings: List[Finding] = []
    sig = (aot.primitive_sequence if strict
           else aot.structural_signature)
    seqs = [sig(aot.traced_jaxpr(t)) for t in traced_variants]
    base = seqs[0]
    for i, s in enumerate(seqs[1:], start=1):
        if s != base:
            if strict:
                # name the first structural divergence, not 500 prims
                k = next((j for j in range(min(len(base), len(s)))
                          if base[j] != s[j]), min(len(base), len(s)))
                a = base[k] if k < len(base) else "<end>"
                b = s[k] if k < len(s) else "<end>"
                detail = (f"{len(base)} vs {len(s)} equations; first "
                          f"divergence at eqn {k}: {a} vs {b}")
            else:
                da = dict(base)
                db = dict(s)
                diff = {k for k in set(da) | set(db)
                        if da.get(k, 0) != db.get(k, 0)}
                detail = "structural counts differ: " + ", ".join(
                    f"{k} {da.get(k, 0)}->{db.get(k, 0)}"
                    for k in sorted(diff))
            findings.append(Finding(
                path=path, line=line, col=0, code="LUX-J101",
                message=f"config variant {i} traces to a different program "
                        f"structure ({detail}) — the family would retrace "
                        "per config value in-window",
                text=label))
    return findings


def check_dynamic_recall(fn, call_a: Callable[[], object],
                         call_b: Callable[[], object], path: str,
                         label: str, line: int = 1) -> List[Finding]:
    """Execute ``call_a`` then ``call_b`` (same shapes, different values
    of a dynamic knob) and assert the jit cache did not grow on the
    second call.  ``fn`` is the jitted callable owning the cache."""
    size = getattr(fn, "_cache_size", None)
    if size is None:  # pragma: no cover - jax version drift
        return []
    call_a()
    n1 = size()
    call_b()
    n2 = size()
    if n2 > n1:
        return [Finding(
            path=path, line=line, col=0, code="LUX-J103",
            message=f"a dynamic-argument re-call recompiled (jit cache "
                    f"{n1} -> {n2} entries) — the knob is specializing "
                    "the trace; one compile must serve every value",
            text=label)]
    return []


def trace_twice_stable(make_traced: Callable[[], object], path: str,
                       label: str, line: int = 1,
                       statics: Optional[Sequence] = None) -> List[Finding]:
    """Convenience: hash-check statics and assert two traces of the SAME
    config agree structurally (an unstable trace — e.g. an RNG or a set
    iteration inside the traced function — shows up here)."""
    findings = list(check_statics(statics or (), path, label, line))
    t1, t2 = make_traced(), make_traced()
    s1 = aot.primitive_sequence(aot.traced_jaxpr(t1))
    s2 = aot.primitive_sequence(aot.traced_jaxpr(t2))
    if s1 != s2:
        findings.append(Finding(
            path=path, line=line, col=0, code="LUX-J101",
            message="two traces of the SAME config disagree structurally "
                    "— the trace is nondeterministic (host RNG / set "
                    "iteration inside the traced function)",
            text=label))
    return findings
