"""AOT tracing/lowering plumbing shared by the luxaudit checker families.

luxcheck (PR 3) guards the Python AST; this layer guards the IR we
actually ship: every checker here works on the jaxpr produced by
``jit(...).trace(...)`` and/or the StableHLO produced by ``.lower()``.
Both are available on CPU with no accelerator attached — the whole audit
runs in a chip-day preflight before any tunnel is needed.

Version caveats (jax 0.4.37, the pinned toolchain):

* ``Traced.lower(platforms=('tpu',))`` does not exist yet — cross-
  platform lowering landed in the 0.5 era.  We lower for the DEFAULT
  (CPU) backend; donation aliasing, jaxpr structure, and pallas_call
  kernel counts are platform-independent at this level, which is exactly
  the property the checkers need.  When the pin moves to >= 0.5, switch
  ``lower_traced`` to ``platforms=('tpu',)`` so the audited module is
  byte-for-byte the chip one.
* Donation shows up in the lowered module as per-argument
  ``tf.aliasing_output`` attributes (the MLIR spelling of XLA's
  input_output_aliases).  XLA drops a donation SILENTLY (a warning, not
  an error) when no output matches the donated buffer — the exact
  failure mode LUX-J2 exists to catch.
* Pallas kernels survive as ``pallas_call`` jaxpr equations even when
  traced with ``interpret=True`` (the CPU test mode), so HBM-sweep
  kernel counting (LUX-J5) does not need a TPU lowering either.
"""
from __future__ import annotations

import re
from typing import Iterator, List, Optional, Tuple


def is_literal(v) -> bool:
    """Literal-vs-Var by shape, not class identity: the Literal class
    moved between jax.core and jax.extend.core across 0.4/0.5, and the
    duck test (Literals carry ``val``, Vars carry ``count``) survives
    both."""
    return hasattr(v, "val") and not hasattr(v, "count")


def sub_jaxprs(eqn) -> Iterator:
    """Every Jaxpr/ClosedJaxpr reachable from one equation's params —
    cond branches, while cond/body, scan/pjit/remat/custom_* bodies —
    yielded as plain ``Jaxpr``s (ClosedJaxprs unwrapped)."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for sub in vals:
            if hasattr(sub, "jaxpr") and hasattr(sub, "consts"):
                yield sub.jaxpr  # ClosedJaxpr
            elif hasattr(sub, "eqns"):
                yield sub  # bare Jaxpr


def iter_eqns(jaxpr) -> Iterator:
    """All equations of ``jaxpr`` and every nested sub-jaxpr, pre-order."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def count_primitive(jaxpr, name: str) -> int:
    return sum(1 for e in iter_eqns(jaxpr) if str(e.primitive) == name)


def primitive_sequence(jaxpr) -> Tuple[str, ...]:
    """The flattened pre-order primitive names — the structural signature
    LUX-J1 compares across a family's configs.  Avals are deliberately
    excluded: a Q-bucket family's shapes differ BY DESIGN; what must not
    differ is the program structure (an iteration-count-dependent unroll
    or a config-dependent op set is exactly the drift being hunted)."""
    return tuple(str(e.primitive) for e in iter_eqns(jaxpr))


#: primitives that define a program's retrace-relevant STRUCTURE:
#: control flow, kernels, memory movement, collectives.  Elementwise /
#: broadcasting idioms are excluded on purpose — jnp legitimately traces
#: a degenerate Q=1 broadcast differently from Q=4 (slice vs
#: broadcast_in_dim), and that difference costs nothing; an extra while
#: loop, gather, or pallas kernel per config value costs a compile and
#: an HBM sweep.
STRUCTURAL_PRIMS = frozenset({
    "while", "cond", "scan", "pallas_call", "custom_call",
    "gather", "scatter", "scatter-add", "scatter-min", "scatter-max",
    "dynamic_slice", "dynamic_update_slice", "sort", "dot_general",
    "psum", "pmin", "pmax", "all_gather", "ppermute", "reduce_scatter",
    "all_to_all", "shard_map",
})


def structural_signature(jaxpr) -> Tuple[Tuple[str, int], ...]:
    """Sorted (primitive, count) multiset over STRUCTURAL_PRIMS — the
    coarse cross-config signature for families whose configs change
    SHAPES (Q buckets): shapes may differ, structure may not."""
    counts: dict = {}
    for e in iter_eqns(jaxpr):
        name = str(e.primitive)
        if name in STRUCTURAL_PRIMS:
            counts[name] = counts.get(name, 0) + 1
    return tuple(sorted(counts.items()))


def traced_jaxpr(traced):
    """The Jaxpr of a ``jit(...).trace(...)`` result (ClosedJaxpr
    unwrapped)."""
    j = traced.jaxpr
    return j.jaxpr if hasattr(j, "jaxpr") else j


def lower_traced(traced):
    """Lower a Traced to StableHLO text (see module docstring for the
    cross-platform caveat)."""
    return traced.lower().as_text()


# ---------------------------------------------------------------------------
# donation-aliasing extraction from the lowered module
# ---------------------------------------------------------------------------

_MAIN_RE = re.compile(r"func\.func public @main\((.*?)\)\s*->", re.DOTALL)
_ARG_RE = re.compile(r"%arg(\d+):")


def main_signature(stablehlo_text: str) -> str:
    m = _MAIN_RE.search(stablehlo_text)
    return m.group(1) if m else ""


def aliased_arg_indices(stablehlo_text: str) -> Tuple[set, int]:
    """(indices of @main arguments carrying ``tf.aliasing_output``,
    total argument count).  Argument order is jax's flatten order of the
    dynamic (non-static) call arguments, so callers can map donated
    pytree leaves onto these positions with tree_flatten spans."""
    sig = main_signature(stablehlo_text)
    aliased: set = set()
    total = 0
    # split the signature at each %argN marker; the chunk following a
    # marker holds that argument's type + attribute dict
    parts = _ARG_RE.split(sig)
    # parts = [prefix, idx0, chunk0, idx1, chunk1, ...]
    for i in range(1, len(parts) - 1, 2):
        idx = int(parts[i])
        total = max(total, idx + 1)
        if "tf.aliasing_output" in parts[i + 1]:
            aliased.add(idx)
    return aliased, total


def leaf_spans(args) -> List[Tuple[int, int]]:
    """Flattened-leaf [start, stop) span of each top-level argument, in
    jax's flatten order (None leaves vanish, matching jax)."""
    import jax

    spans = []
    off = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        spans.append((off, off + n))
        off += n
    return spans


def hashable(x) -> Optional[str]:
    """None when ``hash(x)`` works and is stable; otherwise the error
    string (the LUX-J102 payload)."""
    try:
        h1 = hash(x)
        h2 = hash(x)
    except TypeError as e:
        return str(e)
    if h1 != h2:
        return "hash() is not stable across calls"
    if x != x:
        return "static compares unequal to itself (breaks cache keying)"
    return None
