"""LUX-J4: pass-fused group VMEM residency, recomputed at audit time.

``_pf_block_rows`` (ops/pallas_shuffle) sizes each fused pass group's
tile under LUX_PF_VMEM_MB at PLAN time.  A frozen plan then outlives the
knobs: it replays from cache in later processes, after planner edits,
under different env settings.  A planner bug (or a hand-built
StaticRoutePF) whose tiles exceed the budget fails as a Mosaic VMEM
blow-up ON CHIP — interpret-mode CPU tests can never catch it, which is
why this is an audit property, not a unit test.

The recomputation mirrors the Pallas pipeline's actual residency: the
grid double-buffers every BlockSpec'd operand, so one group holds

    2 * block_rows * 128 * (data_in + data_out + sum(idx itemsize))

bytes of VMEM, with the index itemsize read from the REAL plan arrays
(u8 after _narrow_idx, i32 otherwise) — tighter than the planner's
conservative int32 estimate, so a plan the planner accepted always
passes, and an over-budget group is a genuine LUX-J401 finding.
"""
from __future__ import annotations

from typing import List

from lux_tpu.analysis.core import Finding

#: lane width (ops/pallas_shuffle.LANE) — kept as a literal so this
#: module stays importable without the kernel stack
LANE = 128
#: f32 data tile, in + out
_DATA_BYTES = 8


def _budget_bytes() -> int:
    from lux_tpu.ops.pallas_shuffle import _pf_defaults

    _, _, vmem_mb = _pf_defaults()
    return vmem_mb << 20


def group_residency_bytes(group, idx_arrays) -> int:
    """Double-buffered VMEM residency of ONE fused pass group given its
    per-step index arrays (dtype read from the arrays themselves)."""
    idx_bytes = sum(int(a.dtype.itemsize) for a in idx_arrays)
    return 2 * group.block_rows * LANE * (_DATA_BYTES + idx_bytes)


def mx_residency_bytes(mxg, mx_arrays, weighted: bool) -> int:
    """VMEM residency of an MXREDUCE final group (the LUX-J4 satellite:
    the one-hot and accumulator tiles join the ledger).  Streamed
    operands double-buffer like any BlockSpec'd input: the data tile
    (f32 in, NO full out tile — the kernel's output is the totals
    column), the per-step index tiles, the rank tile, and the optional
    weight tile.  On top: the materialized (v_blk, 128) one-hot operand
    (f32-width bound — bf16 plans use half), the f32 per-tile
    accumulator, and the revisited (v_blk, 1) output block (also
    double-buffered by the pipeline)."""
    step_arrays = mx_arrays[:len(mxg.steps)]
    dst_rel = mx_arrays[len(mxg.steps)]
    idx_bytes = sum(int(a.dtype.itemsize) for a in step_arrays)
    idx_bytes += int(dst_rel.dtype.itemsize)
    if weighted:
        idx_bytes += 4
    tile = 2 * mxg.block_rows * LANE * (4 + idx_bytes)
    onehot = mxg.v_blk * LANE * 4
    acc = mxg.v_blk * 4
    out_blk = 2 * mxg.v_blk * 4
    return tile + onehot + acc + out_blk


def check_vmem_mxscan(path: str, label: str, line: int = 1,
                      budget_bytes: int | None = None,
                      tile_rows: int | None = None,
                      val_bytes: int = 4) -> List[Finding]:
    """LUX-J4 for the mxscan kernel (ISSUE 11): the scan tile's
    residency — streamed value/byte tiles double-buffered + the head
    count and its transpose + the per-row (128, 128) masked triangular
    operand + the carry scratch — against the same LUX_PF_VMEM_MB
    budget the pf groups answer to.  The tile geometry is env-shaped
    (LUX_MXSCAN_TILE_ROWS) at TRACE time, so like the pf plans a bad
    knob combination must fail in this audit, not as a Mosaic VMEM
    blow-up on chip."""
    from lux_tpu.ops.pallas_scan import (_mxscan_defaults,
                                         mxscan_residency_bytes)

    if budget_bytes is None:
        budget_bytes = _budget_bytes()
    tb = _mxscan_defaults(tile_rows)
    need = mxscan_residency_bytes(tb, val_bytes)
    if need > budget_bytes:
        return [Finding(
            path=path, line=line, col=0, code="LUX-J401",
            message=f"mxscan tile (LUX_MXSCAN_TILE_ROWS={tb}, "
                    f"{val_bytes}B values) needs {need} B of VMEM "
                    f"(streamed tiles double-buffered + head-count "
                    f"tiles + the masked triangular operand + carry), "
                    f"over the {budget_bytes} B budget the knobs "
                    "promise (LUX_PF_VMEM_MB) — this blows up in "
                    "Mosaic on chip, not in interpret-mode tests",
            text=f"{label}:mxscan")]
    return []


def _iter_pf_routes(static):
    """(name, StaticRoutePF) for every pass-fused route inside a plan
    static (ExpandStatic r1/r2, FusedStatic r1/r2/vr, CFRouteStatic
    src/dst recursion); unfused routes are skipped — their kernels hold
    one (rb, 128) block pair, far under any budget."""
    from lux_tpu.ops import expand as E
    from lux_tpu.ops.pallas_shuffle import StaticRoutePF

    if isinstance(static, E.CFRouteStatic):
        for half, sub in (("src", static.src), ("dst", static.dst)):
            for name, r in _iter_pf_routes(sub):
                yield f"{half}.{name}", r
        return
    names = ("r1", "r2", "vr") if hasattr(static, "vr") else ("r1", "r2")
    for name in names:
        r = getattr(static, name)
        if isinstance(r, StaticRoutePF):
            yield name, r


def _route_arrays_of(static, arrays):
    """Map each pf route of ``static`` to its slice of the flat plan
    arrays, using the same split helpers the replay uses."""
    from lux_tpu.ops import expand as E

    if isinstance(static, E.CFRouteStatic):
        n_src = E._num_expand_arrays(static.src)
        out = {}
        for k, v in _route_arrays_of(static.src, arrays[:n_src]).items():
            out[f"src.{k}"] = v
        for k, v in _route_arrays_of(static.dst, arrays[n_src:]).items():
            out[f"dst.{k}"] = v
        return out
    if isinstance(static, E.FusedStatic):
        r1a, _, r2a, _, _, _, vra, mxa = E.split_fused_arrays(
            static, arrays, static.weighted)
        return {"r1": r1a, "r2": r2a, "vr": vra, "mx": mxa}
    r1a, _, r2a = E.split_arrays(static, arrays)
    return {"r1": r1a, "r2": r2a}


def check_vmem(static, arrays, path: str, label: str, line: int = 1,
               budget_bytes: int | None = None) -> List[Finding]:
    """Audit every pass-fused group of one frozen plan against the VMEM
    budget the knobs promise (LUX_PF_VMEM_MB at audit time unless
    ``budget_bytes`` overrides).  ``arrays`` is the plan's flat array
    tuple (single-part 2-D or stacked (P, ...) — dtypes are identical
    across parts, which is all the residency model reads)."""
    findings: List[Finding] = []
    if budget_bytes is None:
        budget_bytes = _budget_bytes()
    by_route = _route_arrays_of(static, tuple(arrays))
    for name, route in _iter_pf_routes(static):
        route_arrays = by_route.get(name, ())
        i = 0
        for gi, g in enumerate(route.groups):
            steps = route_arrays[i:i + len(g.steps)]
            i += len(g.steps)
            need = group_residency_bytes(g, steps)
            if need > budget_bytes:
                findings.append(Finding(
                    path=path, line=line, col=0, code="LUX-J401",
                    message=f"pass-fused group {name}[{gi}] "
                            f"(block_rows={g.block_rows}, "
                            f"{len(g.steps)} steps) needs {need} B of "
                            f"VMEM double-buffered, over the "
                            f"{budget_bytes} B budget the knobs promise "
                            "(LUX_PF_VMEM_MB) — this blows up in Mosaic "
                            "on chip, not in interpret-mode tests",
                    text=f"{label}:{name}[{gi}]"))
    mxg = getattr(static, "mx", None)
    if mxg is not None:
        mxa = by_route.get("mx", ())
        need = mx_residency_bytes(mxg, mxa, bool(static.weighted))
        if need > budget_bytes:
            findings.append(Finding(
                path=path, line=line, col=0, code="LUX-J401",
                message=f"MXREDUCE final group (block_rows="
                        f"{mxg.block_rows}, {len(mxg.steps)} steps, "
                        f"v_blk={mxg.v_blk}) needs {need} B of VMEM "
                        f"(streamed tiles double-buffered + the one-hot "
                        f"and accumulator tiles), over the "
                        f"{budget_bytes} B budget the knobs promise "
                        "(LUX_PF_VMEM_MB) — this blows up in Mosaic on "
                        "chip, not in interpret-mode tests",
                text=f"{label}:mx"))
    return findings
