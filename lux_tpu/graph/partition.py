"""Edge-balanced contiguous vertex partitioning.

Pure-function equivalent of the reference's bounds sweep
(core/pull_model.inl:105-131, push variant core/push_model.inl:378-423):
vertices are split into ``num_parts`` contiguous ranges so each range holds at
most ``edge_cap = ceil(ne / num_parts)`` in-edges (a range may exceed the cap
only when a single vertex's in-degree does).
"""
from __future__ import annotations

import numpy as np


def edge_balanced_cuts(row_ptr: np.ndarray, num_parts: int) -> np.ndarray:
    """Compute vertex cut points for edge-balanced contiguous partitioning.

    Args:
      row_ptr: (nv+1,) int64 CSC offsets with leading 0.
      num_parts: number of parts P.

    Returns:
      cuts: (P+1,) int64; part p owns vertices [cuts[p], cuts[p+1]).
      cuts[0] == 0, cuts[P] == nv, monotone non-decreasing.
    """
    nv = row_ptr.shape[0] - 1
    ne = int(row_ptr[-1])
    edge_cap = -(-ne // num_parts) if ne else 0  # ceil div
    cuts = np.empty(num_parts + 1, dtype=np.int64)
    cuts[0] = 0
    if ne == 0:
        # Degenerate: spread vertices evenly.
        step = -(-nv // num_parts)
        for p in range(1, num_parts):
            cuts[p] = min(nv, p * step)
        cuts[num_parts] = nv
        return cuts
    # Greedy sweep, same contract as the reference: extend each part's right
    # bound until it holds >= its share of edges.  searchsorted finds the
    # first vertex boundary at/past the cumulative target.
    for p in range(1, num_parts):
        target = min(ne, p * edge_cap)
        v = int(np.searchsorted(row_ptr, target, side="left"))
        # row_ptr[v] >= target; ensure we advance past the previous cut.
        cuts[p] = max(v, cuts[p - 1])
    cuts[num_parts] = nv
    return np.minimum(cuts, nv)


def part_of_vertex(cuts: np.ndarray, vids: np.ndarray) -> np.ndarray:
    """Map vertex ids to owning part index under ``cuts``."""
    return (np.searchsorted(cuts, vids, side="right") - 1).astype(np.int32)


def weighted_cuts(weights: np.ndarray, num_parts: int) -> np.ndarray:
    """Contiguous cuts balancing an arbitrary per-vertex work weight.

    Generalizes ``edge_balanced_cuts`` (whose weight is the in-degree —
    the reference's static policy) to runtime-measured weights: the Lux
    paper describes repartitioning from per-part runtimes, a feature the
    reference code never shipped; here the driver feeds per-vertex work
    estimates (e.g. degree masked by the live frontier) and gets cuts of
    the same contiguous-range form, so the shard layout machinery is
    unchanged.

    weights: (nv,) non-negative float/int per-vertex work estimates.
    Returns (P+1,) int64 cuts, cuts[0]==0, cuts[P]==nv, monotone.
    """
    nv = weights.shape[0]
    cum = np.zeros(nv + 1, dtype=np.float64)
    np.cumsum(weights, out=cum[1:])
    total = cum[-1]
    if total <= 0:
        return edge_balanced_cuts(
            np.arange(nv + 1, dtype=np.int64), num_parts
        )
    cap = total / num_parts
    cuts = np.empty(num_parts + 1, dtype=np.int64)
    cuts[0] = 0
    for p in range(1, num_parts):
        target = min(total, p * cap)
        v = int(np.searchsorted(cum, target, side="left"))
        cuts[p] = max(v, cuts[p - 1])
    cuts[num_parts] = nv
    return np.minimum(cuts, nv)
