"""Streaming sharded graph loading: build device shards straight from
partial `.lux` file reads, never materializing the whole edge array.

This is the full pull_load_task pipeline (core/pull_model.inl:253-320 —
every node reads only its partitions' byte ranges) composed with the shard
builder: a multi-host launch gives each host `parts_subset =
multihost.local_part_range(P)` and holds only O(its edges) in memory.

The only whole-file pass is the out-degree scan (the reference's serial
`pull_scan_task_impl`, core/pull_model.inl:322-345), done here as a
streaming chunked histogram over the memory-mapped column array.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from lux_tpu.graph import format as fmt
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import (
    LANE,
    PullShards,
    ShardSpec,
    _round_up,
    alloc_arrays,
    build_compact_mirror,
    fill_part,
    shard_geometry,
    sort_segments_inplace,
)


def out_degrees_from_file(
    path: str,
    chunk_edges: int = 1 << 24,
    header: Optional[HostGraph] = None,
) -> np.ndarray:
    """Streaming out-degree histogram over the mmap-backed column array."""
    if header is None:
        header = fmt.read_lux(path, mmap=True)
    deg = np.zeros(header.nv, np.int64)
    col = header.col_idx  # memory-mapped view, never fully materialized
    for lo in range(0, header.ne, chunk_edges):
        hi = min(lo + chunk_edges, header.ne)
        deg += np.bincount(col[lo:hi], minlength=header.nv)
    return deg.astype(np.int32)


def compact_width_from_file(path: str, num_parts: int,
                            header: Optional[HostGraph] = None) -> int:
    """GLOBAL compact-mirror width U_pad for a file-loaded graph: max
    unique in-source count over ALL parts, LANE-padded.  One streaming
    pass of per-part range reads; deterministic, so every multi-host
    process computes the same width and subset loads keep identical
    block shapes (the same contract shard_geometry provides for
    nv_pad/e_pad)."""
    if header is None:
        header = fmt.read_lux(path, mmap=True)
    cuts, _, _ = shard_geometry(
        np.asarray(header.row_ptr), num_parts, header.nv
    )
    u_max = 1
    for p in range(num_parts):
        _, srcs, _ = fmt.read_lux_range(
            path, int(cuts[p]), int(cuts[p + 1]), header=header
        )
        u_max = max(u_max, int(np.unique(srcs).size) if len(srcs) else 1)
    return max(LANE, _round_up(u_max, LANE))


def load_pull_shards(
    path: str,
    num_parts: int,
    parts_subset: Optional[Sequence[int]] = None,
    degrees: Optional[np.ndarray] = None,
    sort_segments: bool = False,
    compact_gather: bool = False,
    compact_u_pad: Optional[int] = None,
) -> PullShards:
    """Build pull shards from a `.lux` file with per-part partial reads.

    parts_subset: the part indices to materialize (default: all).  The
    returned stacked arrays have leading dimension len(parts_subset), in
    subset order — feed them to multihost.assemble_global on multi-host.
    Padded geometry (nv_pad/e_pad) is computed GLOBALLY so every host
    produces identically-shaped blocks.  The header/offsets are read once
    and reused for every per-part range read; only the selected parts'
    edges ever enter host memory.

    ``sort_segments`` / ``compact_gather``: the gather relayouts of
    build_pull_shards, applied to the loaded rows.  A SUBSET load with
    compact_gather needs the GLOBAL mirror width for cross-host shape
    consistency: pass ``compact_u_pad`` (every host calling
    compact_width_from_file(path, num_parts) gets the same value), or
    leave it None to pay one extra streaming pass here.
    """
    header = fmt.read_lux(path, mmap=True)
    nv, ne = header.nv, header.ne
    cuts, nv_pad, e_pad = shard_geometry(np.asarray(header.row_ptr), num_parts, nv)
    if parts_subset is None:
        parts_subset = range(num_parts)
    parts_subset = list(parts_subset)
    if degrees is None:
        degrees = out_degrees_from_file(path, header=header)

    arrays = alloc_arrays(len(parts_subset), nv_pad, e_pad)
    for i, p in enumerate(parts_subset):
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        rp_local, srcs, w = fmt.read_lux_range(path, vlo, vhi, header=header)
        fill_part(
            arrays, i, vlo, vhi, rp_local, srcs, w, cuts, nv_pad, nv,
            degrees[vlo:vhi],
        )

    if sort_segments:
        sort_segments_inplace(arrays)
    if compact_gather:
        if compact_u_pad is None and len(parts_subset) < num_parts:
            compact_u_pad = compact_width_from_file(
                path, num_parts, header=header
            )
        arrays = build_compact_mirror(arrays, u_pad=compact_u_pad)
    spec = ShardSpec(
        num_parts=num_parts, nv=nv, ne=ne, nv_pad=nv_pad, e_pad=e_pad,
        weighted=header.weighted,
    )
    return PullShards(spec=spec, arrays=arrays, cuts=cuts)
