"""Host-side graph container in CSC (compressed sparse column) form.

The layout mirrors the reference `.lux` on-disk CSC model
(reference: README.md:56-75, core/graph.h:53-87): edges are grouped by
*destination* vertex; `col_idx[row_ptr[v] : row_ptr[v+1]]` are the in-neighbor
sources of vertex ``v``.  Unlike the reference (which keeps raw arrays inside
Legion regions), this container is plain NumPy — device-ready shard building
lives in :mod:`lux_tpu.graph.shards`.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class HostGraph:
    """A directed graph in CSC form on the host.

    Attributes:
      nv: number of vertices (reference V_ID is uint32; we require nv < 2**31
        so device indices fit int32).
      ne: number of directed edges.
      row_ptr: (nv + 1,) int64, ``row_ptr[0] == 0``, monotone non-decreasing;
        in-edges of vertex v occupy ``col_idx[row_ptr[v]:row_ptr[v+1]]``.
        (The on-disk format stores nv offsets without the leading 0 —
        reference core/pull_model.inl:97-103; we normalize to nv+1.)
      col_idx: (ne,) int32 source vertex ids, grouped by destination.
      weights: optional (ne,) edge weights (reference WeightType is int,
        col_filter/app.h:24; any numeric dtype accepted here).
    """

    nv: int
    ne: int
    row_ptr: np.ndarray
    col_idx: np.ndarray
    weights: Optional[np.ndarray] = None

    def __post_init__(self):
        assert self.nv >= 1 and self.nv < 2**31, self.nv
        assert self.row_ptr.shape == (self.nv + 1,)
        assert self.col_idx.shape == (self.ne,)
        assert self.row_ptr[0] == 0 and self.row_ptr[-1] == self.ne
        if self.weights is not None:
            assert self.weights.shape == (self.ne,)

    @property
    def weighted(self) -> bool:
        return self.weights is not None

    def validate(self) -> None:
        """Full O(ne) validation (monotone row_ptr, src ids in range).

        Mirrors the reference's load-time asserts (core/pull_model.inl:99-102).
        """
        assert np.all(np.diff(self.row_ptr) >= 0), "row_ptr not monotone"
        if self.ne:
            assert self.col_idx.min() >= 0 and self.col_idx.max() < self.nv

    def out_degrees(self) -> np.ndarray:
        """Out-degree per vertex, counted from the in-edge lists.

        Equivalent of `pull_scan_task_impl` (core/pull_model.inl:322-345),
        which walks every partition's raw cols and increments degrees[src].
        """
        return np.bincount(self.col_idx, minlength=self.nv).astype(np.int32)

    def in_degrees(self) -> np.ndarray:
        return np.diff(self.row_ptr).astype(np.int32)

    def to_csr(self):
        """Build the out-edge (CSR) view: (csr_row_ptr, csr_dst, csr_perm).

        Equivalent of the push engine's CSR-from-CSC build
        (components_gpu.cu:550-607: out-degree histogram -> prefix sum ->
        scatter), done with a stable sort on the host.  ``csr_perm`` maps each
        CSR slot back to its CSC edge index (for weights).
        """
        dst_of_edge = self.dst_of_edges()
        perm = np.argsort(self.col_idx, kind="stable")
        csr_dst = dst_of_edge[perm]
        csr_row_ptr = np.zeros(self.nv + 1, dtype=np.int64)
        np.cumsum(np.bincount(self.col_idx, minlength=self.nv), out=csr_row_ptr[1:])
        return csr_row_ptr, csr_dst, perm

    def dst_of_edges(self) -> np.ndarray:
        """(ne,) int32 destination id of each CSC edge slot."""
        return np.repeat(
            np.arange(self.nv, dtype=np.int64), np.diff(self.row_ptr)
        ).astype(np.int32)


def from_edge_list(
    src: np.ndarray,
    dst: np.ndarray,
    nv: int,
    weights: Optional[np.ndarray] = None,
) -> HostGraph:
    """Build a CSC HostGraph from a raw edge list (sorted by dst, stable).

    Host equivalent of the reference converter (tools/converter.cc:92-124):
    sort edges by destination, emit per-destination offsets then sources.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    ne = src.shape[0]
    assert dst.shape[0] == ne
    order = np.argsort(dst, kind="stable")
    col_idx = src[order].astype(np.int32)
    row_ptr = np.zeros(nv + 1, dtype=np.int64)
    np.cumsum(np.bincount(dst, minlength=nv), out=row_ptr[1:])
    w = None if weights is None else np.asarray(weights)[order]
    return HostGraph(nv=nv, ne=ne, row_ptr=row_ptr, col_idx=col_idx, weights=w)
