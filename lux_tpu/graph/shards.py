"""Device-ready graph shards: static-shape padded CSC partitions.

This is the TPU-native replacement for the reference's Legion logical
regions/partitions + per-GPU `GraphPiece` (core/graph.h:53-98).  Where the
reference carves one region into disjoint 1-D subregions and lets Legion
materialize per-GPU instances, we build *stacked* `(P, ...)` NumPy arrays with
every part padded to identical static shapes, ready to be:

  * consumed whole on one chip (vmap over the leading axis), or
  * dropped onto a 1-D `jax.sharding.Mesh` with the leading axis sharded and
    used inside `shard_map` (lux_tpu.parallel).

Key encodings:
  * Vertices are split into contiguous edge-balanced ranges (partition.py).
  * Per part, vertex count is padded to ``nv_pad`` and edge count to
    ``e_pad`` (multiples of 128 — TPU lane width).
  * ``src_pos`` pre-encodes each edge's source position in the *padded
    all-gathered* state vector of shape (P * nv_pad,): for source s owned by
    part q, ``src_pos = q * nv_pad + (s - cuts[q])``.  This makes the
    per-iteration whole-state exchange (the analog of the reference's
    whole-region zero-copy read, core/pull_model.inl:454-461) a plain
    `all_gather` + vectorized gather with no runtime id remapping.
  * CSC edges arrive sorted by destination, so per-part destination segment
    boundaries are encoded once as ``row_ptr``/``head_flag`` and all
    per-destination reductions run as segmented scans (lux_tpu.ops.segment)
    instead of the reference's atomicAdd/Min/Max
    (pagerank_gpu.cu:90, sssp_gpu.cu:59-77).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.partition import edge_balanced_cuts

LANE = 128  # TPU vector lane width; pad 1-D extents to multiples of this.


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Static (hashable, jit-safe) shard geometry."""

    num_parts: int
    nv: int
    ne: int
    nv_pad: int  # per-part padded vertex count
    e_pad: int  # per-part padded edge count
    weighted: bool

    @property
    def gathered_size(self) -> int:
        """Length of the padded all-gathered state vector."""
        return self.num_parts * self.nv_pad


class ShardArrays(NamedTuple):
    """Stacked per-part arrays (leading axis = part).  A jax pytree.

    Shapes (P = num_parts, V = nv_pad, E = e_pad):
      row_ptr:   (P, V+1) int32  local CSC offsets into the part's edge slice;
                 padded vertices get empty ranges.
      src_pos:   (P, E)   int32  source position in the (P*V,) gathered state.
      dst_local: (P, E)   int32  local destination index in [0, V); padding
                 slots hold the out-of-range sentinel V (keeps the array
                 sorted and makes XLA segment_* drop padding contributions).
      head_flag: (P, E)   bool   True at the first edge of each destination's
                 block (segment starts for segmented scans).
      edge_mask: (P, E)   bool   True for real (non-padding) edges.
      vtx_mask:  (P, V)   bool   True for real (non-padding) vertices.
      degree:    (P, V)   int32  out-degree of each local vertex (equivalent
                 of pull_scan_task_impl, core/pull_model.inl:322-345).
      global_vid:(P, V)   int32  global vertex id of each local slot (clamped
                 to nv-1 on padding slots; check vtx_mask).
      weights:   (P, E)   float32 edge weights (zeros when unweighted).
      mirror_pos:(P, U)   int32  compact-gather mirror: the part's UNIQUE
                 in-source positions in the gathered state, sorted
                 ascending (U = 0 when the layout is disabled — the
                 zero width is static, so jitted engines pick the gather
                 path at trace time with no extra plumbing).
      mirror_rel:(P, E)   int32  per-edge index into the part's mirror
                 (mirror_pos[mirror_rel] == src_pos on real edges).

    The mirror pair is the TPU answer to the reference's per-GPU unique
    in-vertex list + load_kernel FB staging (pagerank_gpu.cu:229-240,
    34-47): the per-edge gather's working set drops from O(P*V) to
    O(unique in-sources), and the O(U) mirror fill reads ASCENDING
    positions — sequential-friendly HBM traffic where src_pos gathers
    are random.
    """

    row_ptr: np.ndarray
    src_pos: np.ndarray
    dst_local: np.ndarray
    head_flag: np.ndarray
    edge_mask: np.ndarray
    vtx_mask: np.ndarray
    degree: np.ndarray
    global_vid: np.ndarray
    weights: np.ndarray
    mirror_pos: np.ndarray
    mirror_rel: np.ndarray


@dataclasses.dataclass
class PullShards:
    """Host bundle: spec + arrays + partition bookkeeping."""

    spec: ShardSpec
    arrays: ShardArrays
    cuts: np.ndarray  # (P+1,) vertex cut points

    def scatter_to_global(self, stacked: np.ndarray) -> np.ndarray:
        """Collapse a (P, nv_pad, ...) stacked state back to (nv, ...) global
        order, dropping padding."""
        return stacked_to_global(self.cuts, stacked)

    def global_to_stacked(self, full: np.ndarray) -> np.ndarray:
        """Split a (nv, ...) global state into (P, nv_pad, ...) padded stacks.
        Padding slots are filled with zeros."""
        return global_to_stacked(self.cuts, self.spec.nv_pad, full)


def global_to_stacked(cuts: np.ndarray, nv_pad: int,
                      full: np.ndarray) -> np.ndarray:
    """Split a (nv, ...) global state into (P, nv_pad, ...) zero-padded
    stacks under ``cuts`` — the inverse of ``stacked_to_global``; any
    shard bundle (pull/push/ring/scatter/edge2d) restacks an elastic
    checkpoint with its own cuts through this."""
    P = cuts.shape[0] - 1
    out = np.zeros((P, nv_pad) + full.shape[1:], dtype=full.dtype)
    for p in range(P):
        lo, hi = int(cuts[p]), int(cuts[p + 1])
        out[p, : hi - lo] = full[lo:hi]
    return out


def stacked_to_global(cuts: np.ndarray, stacked: np.ndarray) -> np.ndarray:
    """De-pad a (P, nv_pad, ...) stacked state into (nv, ...) global order
    under ``cuts`` (shared by every engine's shard bundle)."""
    out = []
    for p in range(cuts.shape[0] - 1):
        n = int(cuts[p + 1] - cuts[p])
        out.append(np.asarray(stacked[p])[:n])
    return np.concatenate(out, axis=0)


def shard_geometry(row_ptr_global: np.ndarray, num_parts: int, nv: int,
                   cuts: Optional[np.ndarray] = None):
    """(cuts, nv_pad, e_pad) for edge-balanced padded shards, with the
    int32-range guards (global E_ID stays int64 on host, like the
    reference's uint64 E_ID / uint32 V_ID split, pagerank/app.h:21-22).

    ``cuts`` overrides the static edge-balanced sweep with caller-chosen
    contiguous bounds (the dynamic-repartitioning path feeds
    partition.weighted_cuts here)."""
    if cuts is None:
        cuts = edge_balanced_cuts(row_ptr_global, num_parts)
    nv_counts = np.diff(cuts)
    e_counts = row_ptr_global[cuts[1:]] - row_ptr_global[cuts[:-1]]
    nv_pad = max(LANE, _round_up(int(nv_counts.max()), LANE))
    e_pad = max(LANE, _round_up(int(e_counts.max()) or 1, LANE))
    if int(e_counts.max()) >= 2**31:
        raise ValueError(
            f"a part holds {int(e_counts.max())} edges >= 2^31; "
            f"increase num_parts (currently {num_parts})"
        )
    if num_parts * nv_pad >= 2**31:
        raise ValueError("num_parts * nv_pad exceeds int32 gather range")
    del nv
    return cuts, nv_pad, e_pad


def edge2d_chunk_pad(max_part_edges: int, num_edge_shards: int) -> int:
    """Padded per-chunk edge capacity E2 of the 2-D (parts x edge)
    layout — ONE formula shared by the builder
    (parallel/edge2d.build_edge2d_shards) and the preflight hint
    (utils/preflight.suggest_edge_shards) so they can never diverge."""
    chunk_max = -(-max(1, int(max_part_edges)) // max(1, num_edge_shards))
    return _round_up(max(1, chunk_max), LANE)


def alloc_arrays(num_rows: int, nv_pad: int, e_pad: int) -> ShardArrays:
    """Zeroed stacked arrays for ``num_rows`` parts."""
    return ShardArrays(
        row_ptr=np.zeros((num_rows, nv_pad + 1), np.int32),
        src_pos=np.zeros((num_rows, e_pad), np.int32),
        dst_local=np.full((num_rows, e_pad), nv_pad, np.int32),
        head_flag=np.zeros((num_rows, e_pad), bool),
        edge_mask=np.zeros((num_rows, e_pad), bool),
        vtx_mask=np.zeros((num_rows, nv_pad), bool),
        degree=np.zeros((num_rows, nv_pad), np.int32),
        global_vid=np.zeros((num_rows, nv_pad), np.int32),
        weights=np.zeros((num_rows, e_pad), np.float32),
        mirror_pos=np.zeros((num_rows, 0), np.int32),
        mirror_rel=np.zeros((num_rows, 0), np.int32),
    )


def fill_part(
    arrays: ShardArrays,
    i: int,
    vlo: int,
    vhi: int,
    rp_local: np.ndarray,
    srcs: np.ndarray,
    w: Optional[np.ndarray],
    cuts: np.ndarray,
    nv_pad: int,
    nv: int,
    degrees_slice: np.ndarray,
) -> None:
    """Fill stacked-row ``i`` with one part's data.

    rp_local: (n+1,) local offsets with leading 0; srcs: (m,) global source
    ids; degrees_slice: (n,) out-degrees of [vlo, vhi).  Shared by the
    in-memory builder and the streaming file loader so the encodings can
    never diverge.
    """
    n, m = vhi - vlo, len(srcs)
    rp = np.asarray(rp_local, np.int32)
    arrays.row_ptr[i, : n + 1] = rp
    arrays.row_ptr[i, n + 1 :] = m  # padded vertices: empty tail ranges
    from lux_tpu import native

    if native.fill_src_pos(srcs, cuts, nv_pad, arrays.src_pos[i, :m]) is None:
        srcs64 = np.asarray(srcs, np.int64)
        own = (np.searchsorted(cuts, srcs64, side="right") - 1).astype(np.int64)
        arrays.src_pos[i, :m] = (
            own * nv_pad + (srcs64 - cuts[own])
        ).astype(np.int32)
    arrays.dst_local[i, :m] = np.repeat(
        np.arange(n, dtype=np.int32), np.diff(rp[: n + 1])
    )
    starts = rp[:n][rp[:n] < rp[1 : n + 1]]
    arrays.head_flag[i, starts] = True
    arrays.edge_mask[i, :m] = True
    arrays.vtx_mask[i, :n] = True
    arrays.degree[i, :n] = degrees_slice
    arrays.global_vid[i, :n] = np.arange(vlo, vhi, dtype=np.int32)
    arrays.global_vid[i, n:] = nv - 1
    if w is not None:
        arrays.weights[i, :m] = np.asarray(w, np.float32)


def sort_segments_inplace(arrays: ShardArrays) -> None:
    """Reorder edges WITHIN each destination segment by ascending gather
    index (src_pos) — the gather-locality relayout.

    Every shipped combiner is commutative, so per-segment order is
    semantically free (float sums round differently than the unsorted
    layout, but the relayout is a fixed deterministic choice — reruns
    stay bitwise identical; tests/test_determinism.py).  The payoff is
    on TPU: `state[src_pos]` is the roofline's dominant unknown
    (docs/PERF.md gather-amplification band), and ascending in-segment
    gather indices cluster hub sources so consecutive reads hit the
    same HBM tiles.  The reference cannot reorder this way — its
    atomicAdd scatter order is already arbitrary (pr_kernel,
    pagerank_gpu.cu:86-95); here the relayout is explicit and testable.

    Only src_pos and weights move: the lexsort's primary key is
    dst_local, so the dst sequence (and with it row_ptr, head_flag,
    edge_mask, and the padding tail at dst_local == V) is unchanged.
    """
    for r in range(arrays.src_pos.shape[0]):
        order = np.lexsort((arrays.src_pos[r], arrays.dst_local[r]))
        arrays.src_pos[r] = arrays.src_pos[r][order]
        arrays.weights[r] = arrays.weights[r][order]


def build_compact_mirror(arrays: ShardArrays,
                         u_pad: Optional[int] = None) -> ShardArrays:
    """Attach the compact-gather mirror to filled pull-layout arrays.

    Per part: ``mirror_pos`` = sorted unique src_pos of the real edges
    (padded to a shared lane-aligned width U by repeating position 0 —
    harmless extra gathers of a valid slot), and ``mirror_rel`` remaps
    every edge's src_pos to its mirror index via binary search (padding
    edges map to 0; their contributions are already dropped by the
    dst_local sentinel).  The remap is exact, so engine results are
    bitwise identical to the direct layout — only the gather traffic
    shape changes.  Host-side one-time cost, like the reference's
    init-time in-vertex sort (pagerank_gpu.cu:229-240).

    Composes with sort_segments_inplace (call it first: the mirror is
    order-insensitive per segment, and src_pos->mirror_rel is a monotone
    remap, so the relayout's in-segment ascending order survives).

    ``u_pad`` overrides the width (multi-host subset loads pass the
    GLOBAL width from sharded_load.compact_width_from_file so every
    host's blocks keep identical shapes)."""
    P = arrays.src_pos.shape[0]
    uniqs = []
    for p in range(P):
        uniqs.append(np.unique(arrays.src_pos[p][arrays.edge_mask[p]]))
    need = max((len(u) for u in uniqs), default=1) or 1
    if u_pad is None:
        u_pad = max(LANE, _round_up(need, LANE))
    elif u_pad < need:
        raise ValueError(f"compact u_pad {u_pad} < required width {need}")
    mirror_pos = np.zeros((P, u_pad), np.int32)
    mirror_rel = np.zeros_like(arrays.src_pos)
    for p in range(P):
        u = uniqs[p]
        mirror_pos[p, : len(u)] = u
        rel = np.searchsorted(u, arrays.src_pos[p])
        # padding edges hold src_pos 0; searchsorted keeps them in range
        # unless the part is empty, where clip pins them to slot 0
        mirror_rel[p] = np.clip(rel, 0, u_pad - 1).astype(np.int32)
    return arrays._replace(mirror_pos=mirror_pos, mirror_rel=mirror_rel)


def build_pull_shards(
    g: HostGraph,
    num_parts: int,
    degrees: Optional[np.ndarray] = None,
    cuts: Optional[np.ndarray] = None,
    sort_segments: bool = False,
    compact_gather: bool = False,
) -> PullShards:
    """Partition + pad a HostGraph into device-ready pull-model shards.

    ``cuts`` (optional (P+1,) bounds) selects a custom contiguous
    partition — used by dynamic repartitioning to rebalance on measured
    work instead of static in-degree.  ``sort_segments`` applies the
    gather-locality relayout (sort_segments_inplace); ``compact_gather``
    attaches the unique-in-source mirror (build_compact_mirror)."""
    cuts, nv_pad, e_pad = shard_geometry(g.row_ptr, num_parts, g.nv, cuts)
    if degrees is None:
        degrees = g.out_degrees()
    arrays = alloc_arrays(num_parts, nv_pad, e_pad)
    for p in range(num_parts):
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        elo, ehi = int(g.row_ptr[vlo]), int(g.row_ptr[vhi])
        fill_part(
            arrays, p, vlo, vhi,
            g.row_ptr[vlo : vhi + 1] - elo,
            g.col_idx[elo:ehi],
            None if g.weights is None else g.weights[elo:ehi],
            cuts, nv_pad, g.nv, degrees[vlo:vhi],
        )
    if sort_segments:
        sort_segments_inplace(arrays)
    if compact_gather:
        arrays = build_compact_mirror(arrays)
    spec = ShardSpec(
        num_parts=num_parts,
        nv=g.nv,
        ne=g.ne,
        nv_pad=nv_pad,
        e_pad=e_pad,
        weighted=g.weights is not None,
    )
    return PullShards(spec=spec, arrays=arrays, cuts=cuts)
