"""Push-model shards: per-part CSR restricted to local destinations.

The reference push engine materializes, per GPU, out-edge (CSR) structure
over ALL nv sources but containing only the edges whose destination falls in
that GPU's range — the replicated `nv * numParts` push row-ptr region
(core/push_model.inl:321-324,449-465) built by init kernels
(components_gpu.cu:550-607).  This lets every GPU scatter frontier updates
exclusively into its OWN vertex slice: no cross-part writes, the frontier is
the only thing exchanged.

TPU-native twist: instead of replicating an nv-sized row array per part, we
store only the part's *unique sources* (sorted) + their edge offsets, and
resolve frontier vertex -> row by vectorized binary search.  This is the
moral equivalent of the reference's unique in-vertex gather list
(pagerank_gpu.cu:229-240) applied to the push direction, and keeps per-part
memory O(part edges), not O(nv).

Shapes (U = u_pad unique-source slots, E = e_pad edge slots):
  uniq_src:      (P, U)   int32 sorted global source ids; INT32_MAX padding.
  csr_row_ptr:   (P, U+1) int32 offsets into the CSR-ordered edge slots.
  csr_dst_local: (P, E)   int32 local dst of each CSR-ordered edge;
                          nv_pad sentinel on padding (drops scatters).
  csr_weight:    (P, E)   float32.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import (
    LANE,
    PullShards,
    _round_up,
    build_pull_shards,
)

SRC_SENTINEL = np.iinfo(np.int32).max


class PushArrays(NamedTuple):
    uniq_src: np.ndarray
    csr_row_ptr: np.ndarray
    csr_dst_local: np.ndarray
    csr_weight: np.ndarray


@dataclasses.dataclass(frozen=True)
class PushSpec:
    """Static geometry for the frontier path."""

    u_pad: int  # padded unique-source count per part
    f_cap: int  # sparse frontier queue capacity per part (vertices)
    e_sp: int  # compacted sparse edge-buffer capacity per part
    pull_threshold_den: int = 16  # frontier > nv/DEN => dense/pull mode
    # (SPARSE_THRESHOLD = 16: queue sizing at core/push_model.inl:393-397
    # and the pull/push switch at sssp_gpu.cu:414)
    #: second, smaller sparse tier: rounds whose frontier out-edges fit it
    #: run an O(e_sp_small) walk instead of O(e_sp) — the late-round tail
    #: of SSSP/CC is many tiny frontiers, and a 10-vertex frontier must
    #: not pay a full e_pad/4 scan (VERDICT r1 weak #3).  0 disables.
    e_sp_small: int = 0


@dataclasses.dataclass
class PushShards:
    """Pull shards (dense path) + CSR arrays (sparse frontier path)."""

    pull: PullShards
    pspec: PushSpec
    parrays: PushArrays

    @property
    def spec(self):
        return self.pull.spec

    @property
    def arrays(self):
        return self.pull.arrays

    @property
    def cuts(self):
        return self.pull.cuts

    def scatter_to_global(self, stacked):
        return self.pull.scatter_to_global(stacked)


def build_push_shards(
    g: HostGraph,
    num_parts: int,
    f_cap: Optional[int] = None,
    e_sp: Optional[int] = None,
    cuts: Optional[np.ndarray] = None,
    sort_segments: bool = False,
    compact_gather: bool = False,
) -> PushShards:
    # sort_segments: gather-locality relayout of the embedded pull
    # layout — the push engine's DENSE rounds gather full[src_pos]
    # exactly like the pull engine (min/max relaxation is order-free,
    # so this is bitwise-invariant for the frontier apps).
    # compact_gather: dense rounds gather through the unique-in-source
    # mirror instead (engine/push.dense_part_step)
    pull = build_pull_shards(
        g, num_parts, cuts=cuts, sort_segments=sort_segments,
        compact_gather=compact_gather,
    )
    spec = pull.spec
    P, e_pad, nv_pad = num_parts, spec.e_pad, spec.nv_pad
    cuts = pull.cuts

    csr_dst_local = np.full((P, e_pad), nv_pad, np.int32)
    csr_weight = np.zeros((P, e_pad), np.float32)
    # native hot path: per-part counting sort by source, O(E + U log U)
    # writing the dst/weight rows in place (lux_io.lux_push_part_build);
    # the NumPy argsort path below is the fallback and the oracle
    from lux_tpu import native

    use_native = native.get_lib() is not None and (
        g.weights is None or np.can_cast(g.weights.dtype, np.int32)
    )
    counts_scratch = np.zeros(g.nv, np.uint32) if use_native else None

    uniq_all, rp_all = [], []
    for p in range(P):
        vlo, vhi = int(cuts[p]), int(cuts[p + 1])
        elo, ehi = int(g.row_ptr[vlo]), int(g.row_ptr[vhi])
        srcs = g.col_idx[elo:ehi]
        if use_native:
            uniq, rp = native.push_part_build(
                srcs, g.row_ptr[vlo : vhi + 1],
                g.weights[elo:ehi] if g.weights is not None else None,
                g.nv, counts_scratch, csr_dst_local[p, : ehi - elo],
                csr_weight[p, : ehi - elo] if g.weights is not None
                else None,
            )
            uniq_all.append(uniq)
            rp_all.append(rp)
            continue
        order = np.argsort(srcs, kind="stable")
        s_sorted = srcs[order]
        uniq, counts = (
            np.unique(s_sorted, return_counts=True)
            if len(s_sorted)
            else (np.array([], np.int32), np.array([], np.int64))
        )
        rp = np.zeros(len(uniq) + 1, np.int64)
        np.cumsum(counts, out=rp[1:])
        uniq_all.append(uniq.astype(np.int32))
        rp_all.append(rp.astype(np.int32))
        # part-local dst per edge straight from the row_ptr slice — no
        # global O(ne) dst_of_edges materialization (mmap-friendly)
        dl_slice = np.repeat(
            np.arange(vhi - vlo, dtype=np.int32),
            np.diff(np.asarray(g.row_ptr[vlo : vhi + 1])).astype(np.int64),
        )
        csr_dst_local[p, : ehi - elo] = dl_slice[order]
        if g.weights is not None:
            csr_weight[p, : ehi - elo] = (
                g.weights[elo:ehi][order].astype(np.float32)
            )

    u_pad = max(LANE, _round_up(max(len(u) for u in uniq_all) or 1, LANE))
    uniq_src = np.full((P, u_pad), SRC_SENTINEL, np.int32)
    csr_row_ptr = np.zeros((P, u_pad + 1), np.int32)
    for p in range(P):
        u, rp = uniq_all[p], rp_all[p]
        uniq_src[p, : len(u)] = u
        csr_row_ptr[p, : len(rp)] = rp
        csr_row_ptr[p, len(rp) :] = rp[-1] if len(rp) else 0

    if f_cap is None:
        # queue sized like the reference: part vertices / SPARSE_THRESHOLD
        # + slack (core/push_model.inl:393-397)
        f_cap = _round_up(nv_pad // 16 + 128, LANE)
    if e_sp is None:
        e_sp = _round_up(max(e_pad // 4, LANE) + LANE, LANE)
    # small tier = e_sp/16 (same ratio as the frontier threshold); only
    # worth a second compiled branch when it actually shrinks the walk
    e_sp_small = _round_up(max(int(e_sp) // 16, LANE), LANE)
    if e_sp_small >= int(e_sp):
        e_sp_small = 0

    pspec = PushSpec(
        u_pad=u_pad, f_cap=int(f_cap), e_sp=int(e_sp),
        e_sp_small=e_sp_small,
    )
    parrays = PushArrays(
        uniq_src=uniq_src,
        csr_row_ptr=csr_row_ptr,
        csr_dst_local=csr_dst_local,
        csr_weight=csr_weight,
    )
    return PushShards(pull=pull, pspec=pspec, parrays=parrays)
