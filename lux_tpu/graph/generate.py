"""Synthetic graph generators (the reference ships none; its datasets are
external downloads, README.md:77-86).  Used for tests and benchmarks."""
from __future__ import annotations

import numpy as np

from lux_tpu.graph.csc import HostGraph, from_edge_list


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    weighted: bool = False,
    max_weight: int = 100,
) -> HostGraph:
    """Recursive-matrix (Graph500-style) power-law graph: nv = 2**scale,
    ne = nv * edge_factor.  Matches the scale recipe of the reference's RMAT27
    dataset (nv=2^27, ne=2^31 at edge_factor 16, README.md:83)."""
    from lux_tpu import obs

    with obs.span("graph.generate", kind="rmat", scale=scale,
                  ef=edge_factor, seed=seed):
        rng = np.random.default_rng(seed)
        nv = 1 << scale
        ne = nv * edge_factor
        src = np.zeros(ne, dtype=np.int64)
        dst = np.zeros(ne, dtype=np.int64)
        ab = a + b
        c_norm = c / (1.0 - ab)
        a_norm = a / ab
        for bit in range(scale):
            r1 = rng.random(ne)
            r2 = rng.random(ne)
            src_bit = r1 > ab
            dst_bit = np.where(src_bit, r2 > c_norm, r2 > a_norm)
            src |= src_bit.astype(np.int64) << bit
            dst |= dst_bit.astype(np.int64) << bit
        # Permute vertex labels to avoid degree locality artifacts.
        perm = rng.permutation(nv)
        src = perm[src]
        dst = perm[dst]
        w = (rng.integers(1, max_weight + 1, size=ne).astype(np.int32)
             if weighted else None)
        return from_edge_list(src, dst, nv, weights=w)


def uniform_random(
    nv: int, ne: int, seed: int = 0, weighted: bool = False, max_weight: int = 100
) -> HostGraph:
    """Erdos-Renyi-ish uniform random directed multigraph."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    w = rng.integers(1, max_weight + 1, size=ne).astype(np.int32) if weighted else None
    return from_edge_list(src, dst, nv, weights=w)


def path_graph(nv: int) -> HostGraph:
    """0 -> 1 -> ... -> nv-1 (handy for SSSP/CC correctness)."""
    src = np.arange(nv - 1, dtype=np.int64)
    dst = src + 1
    return from_edge_list(src, dst, nv)


def star_graph(nv: int, center: int = 0) -> HostGraph:
    """center -> every other vertex."""
    dst = np.array([v for v in range(nv) if v != center], dtype=np.int64)
    src = np.full(nv - 1, center, dtype=np.int64)
    return from_edge_list(src, dst, nv)


def barabasi_albert(n: int, m: int = 8, seed: int = 0,
                    directed: bool = True) -> HostGraph:
    """Preferential-attachment (Barabási–Albert) power-law graph: each
    new vertex attaches ``m`` out-edges to targets drawn proportionally
    to degree (the classic repeated-endpoint-list construction).  A
    SECOND heavy-tail family, independent of the RMAT generator — its
    early vertices become hubs with degree ~ sqrt(n*m), stressing the
    frontier-adaptivity thresholds with a different skew shape than
    RMAT's community structure.  ``directed`` keeps only the new->old
    citation orientation (hub OUT-degree <= m: traversals from hubs go
    nowhere); ``directed=False`` adds both directions, so a hub's
    in-mass becomes out-edges and frontier traversals genuinely fan
    out."""
    rng = np.random.default_rng(seed)
    if not 1 <= m < n:
        raise ValueError(f"need 1 <= m < n, got m={m} n={n}")
    src = np.empty((n - m) * m, np.int64)
    dst = np.empty((n - m) * m, np.int64)
    # repeated list: each endpoint appended once per incident edge, so a
    # uniform draw over it IS degree-proportional attachment
    repeated = np.empty(2 * (n - m) * m, np.int64)
    rlen = 0
    e = 0
    for v in range(m, n):
        if rlen == 0:
            targets = np.arange(m, dtype=np.int64)  # seed clique targets
        else:
            # sample WITH replacement then dedupe — cheaper than
            # rejection at m << degree-mass and keeps out-degree <= m
            targets = np.unique(
                repeated[rng.integers(0, rlen, size=m)]
            )
        k = len(targets)
        src[e : e + k] = v
        dst[e : e + k] = targets
        repeated[rlen : rlen + k] = targets
        repeated[rlen + k : rlen + 2 * k] = v
        rlen += 2 * k
        e += k
    if directed:
        return from_edge_list(src[:e], dst[:e], n)
    return from_edge_list(
        np.concatenate([src[:e], dst[:e]]),
        np.concatenate([dst[:e], src[:e]]), n,
    )


def bipartite_ratings(
    n_users: int, n_items: int, n_ratings: int, seed: int = 0, max_rating: int = 5
) -> HostGraph:
    """Weighted bipartite rating graph with edges in BOTH directions (the CF
    app updates destination vertices only, colfilter_gpu.cu:85-104, so both
    user->item and item->user edges are needed for both sides to train)."""
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, size=n_ratings)
    items = rng.integers(0, n_items, size=n_ratings) + n_users
    ratings = rng.integers(1, max_rating + 1, size=n_ratings).astype(np.int32)
    src = np.concatenate([users, items])
    dst = np.concatenate([items, users])
    w = np.concatenate([ratings, ratings])
    return from_edge_list(src, dst, n_users + n_items, weights=w)
