"""Reader/writer for the `.lux` binary graph format.

On-disk layout (reference README.md:56-75; header read at
core/pull_model.inl:36-39, body read at core/pull_model.inl:295-319):

    uint32  nv
    uint64  ne
    uint64  row_ptr[nv]      # CSC offsets; row_ptr[i] is the END of vertex
                             # i's in-edge block (no leading zero on disk)
    uint32  col_idx[ne]      # in-edge sources grouped by destination
    int32   weights[ne]      # only for weighted graphs (WeightType = int,
                             # col_filter/app.h:24)

If the native loader library has been built (lux_tpu/native), it is used for
parallel partial-range reads; otherwise NumPy memory-mapping is used.  Both
produce identical HostGraph objects.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from lux_tpu.graph.csc import HostGraph

LUX_HEADER_BYTES = 12  # sizeof(uint32) + sizeof(uint64)


def read_lux(path: str, weighted: Optional[bool] = None, mmap: bool = True) -> HostGraph:
    """Read a `.lux` file into a HostGraph.

    Args:
      path: file path.
      weighted: if None, inferred from the exact file size.  Recognized
        layouts: base (unweighted), base + 4*nv (unweighted with the trailing
        degree array the reference converter appends but never reads,
        tools/converter.cc:124), base + 4*ne (weighted), and
        base + 4*ne + 4*nv (weighted + degrees).  Ambiguous sizes (nv == ne)
        resolve to unweighted; unrecognized sizes raise — pass ``weighted``
        explicitly in those cases.
      mmap: memory-map the arrays instead of copying (read-only views).
    """
    from lux_tpu import obs

    with obs.span("graph.load", file=os.path.basename(path),
                  mmap=mmap) as sp:
        g = _read_lux_impl(path, weighted, mmap)
        sp.set(nv=g.nv, ne=g.ne, weighted=g.weights is not None)
        return g


def _read_lux_impl(path: str, weighted: Optional[bool], mmap: bool) -> HostGraph:
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        header = f.read(LUX_HEADER_BYTES)
    nv = int(np.frombuffer(header, dtype="<u4", count=1)[0])
    ne = int(np.frombuffer(header[4:], dtype="<u8", count=1)[0])

    rows_off = LUX_HEADER_BYTES
    cols_off = rows_off + 8 * nv
    w_off = cols_off + 4 * ne
    base_size = w_off
    if weighted is None:
        if ne == nv and ne > 0 and size == base_size + 4 * ne:
            # weighted (base + 4*ne) and unweighted-with-degree-array
            # (base + 4*nv) are byte-identical sizes when nv == ne; a wrong
            # guess silently drops real weights (ADVICE r1)
            import warnings

            warnings.warn(
                f"{path}: nv == ne makes the weighted and unweighted+degrees "
                "layouts the same size; assuming unweighted — pass weighted= "
                "explicitly to silence or override",
                stacklevel=2,
            )
        if ne == 0 or size in (base_size, base_size + 4 * nv):
            weighted = False
        elif size in (base_size + 4 * ne, base_size + 4 * ne + 4 * nv):
            weighted = True
        else:
            raise ValueError(
                f"{path}: cannot infer weights from size {size} "
                f"(nv={nv}, ne={ne}); pass weighted= explicitly"
            )

    def _arr(dtype, count, offset):
        if mmap:
            return np.memmap(path, dtype=dtype, mode="r", offset=offset, shape=(count,))
        with open(path, "rb") as f:
            f.seek(offset)
            return np.fromfile(f, dtype=dtype, count=count)

    raw_rows = _arr("<u8", nv, rows_off)
    col_idx = _arr("<u4", ne, cols_off)
    row_ptr = np.zeros(nv + 1, dtype=np.int64)
    row_ptr[1:] = raw_rows
    weights = _arr("<i4", ne, w_off) if weighted else None
    return HostGraph(
        nv=nv,
        ne=ne,
        row_ptr=row_ptr,
        # zero-copy reinterpret (u4 -> i4, same itemsize): with mmap=True
        # the O(ne) arrays stay file-backed — the streaming loaders depend
        # on this never materializing
        col_idx=np.asarray(col_idx).view(np.int32),
        weights=None if weights is None else np.asarray(weights),
    )


def write_lux(path: str, g: HostGraph) -> None:
    """Write a HostGraph as a `.lux` file (converter output format,
    tools/converter.cc:108-124, minus the trailing degree array the reference
    appends but never reads back — see SURVEY.md §2.3)."""
    with open(path, "wb") as f:
        f.write(np.uint32(g.nv).tobytes())
        f.write(np.uint64(g.ne).tobytes())
        f.write(g.row_ptr[1:].astype("<u8").tobytes())
        f.write(g.col_idx.astype("<u4").tobytes())
        if g.weights is not None:
            f.write(g.weights.astype("<i4").tobytes())


def read_lux_range(path: str, row_lo: int, row_hi: int,
                   weighted: Optional[bool] = None,
                   header: Optional[HostGraph] = None):
    """Read one partition's slice of a `.lux` file: the per-host sharded
    load (equivalent of pull_load_task_impl's partial fseeko/fread,
    core/pull_model.inl:253-320 — every host reads only its vertex range).

    Returns (row_ptr_local (n+1,) int64 rebased to 0, col_idx (m,) int32,
    weights (m,) int32 | None) for vertices [row_lo, row_hi).

    Pass ``header`` (a prior mmap read_lux result) to avoid re-reading the
    header/offsets per call.  Uses the native pread loader (lux_tpu.native)
    when built, else mmap.
    """
    g_header = header if header is not None else read_lux(
        path, weighted=weighted, mmap=True
    )
    nv, ne = g_header.nv, g_header.ne
    assert 0 <= row_lo <= row_hi <= nv
    col_lo = int(g_header.row_ptr[row_lo])
    col_hi = int(g_header.row_ptr[row_hi])
    if weighted is None:
        weighted = g_header.weighted

    try:
        from lux_tpu import native

        rng = native.read_range(
            path, nv, ne, row_lo, row_hi, col_lo, col_hi, weighted
        )
    except OSError:
        raise
    except Exception:
        rng = None
    if rng is not None:
        rows_end, cols, w = rng
        row_ptr = np.empty(row_hi - row_lo + 1, np.int64)
        row_ptr[0] = 0
        row_ptr[1:] = rows_end.astype(np.int64) - col_lo
        return row_ptr, cols.astype(np.int32), w
    row_ptr = (g_header.row_ptr[row_lo : row_hi + 1] - col_lo).astype(np.int64)
    cols = np.asarray(g_header.col_idx[col_lo:col_hi])
    w = (
        np.asarray(g_header.weights[col_lo:col_hi])
        if weighted and g_header.weights is not None
        else None
    )
    return row_ptr, cols, w


def read_edge_list_text(path: str, weighted: bool = False):
    """Parse a whitespace text edge list ("src dst [weight]" per line) —
    converter input format (tools/converter.cc:80-97)."""
    data = np.loadtxt(path, dtype=np.int64, ndmin=2)
    src = data[:, 0]
    dst = data[:, 1]
    w = data[:, 2].astype(np.int32) if weighted else None
    return src, dst, w
