"""Profiling integration.

TPU-native replacement for the reference's observability hooks: Legion
Prof/Spy exist behind -lg:* flags but are unused in-repo (SURVEY.md §5);
the in-tree story is Realm::Clock timers.  Here: `jax.profiler` traces
(viewable in XProf/Perfetto/TensorBoard) wrapping any run, plus
`block_until_ready` fencing so phases attribute correctly.

Round 6 (luxtrace): a captured trace no longer just sits in the profile
dir — ``trace()`` parses it on exit (lux_tpu.obs.xprof, stdlib gzip+json)
and writes the per-kernel device-time table into the run's event log, so
``tools/luxview.py`` can answer "how much of the window ran inside the
routed-pf ``fused_pass_gather`` kernels vs gathers/scatters/collectives"
from the flight-recorder artifact alone.
"""
from __future__ import annotations

import contextlib
import logging

import jax

log = logging.getLogger("lux_tpu")


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """Context manager: capture a jax.profiler trace when dir is given.
    On exit the trace is parsed and the kernel-attribution table lands
    in the event log (best-effort: attribution can never fail a run)."""
    if not trace_dir:
        yield
        return
    from lux_tpu import obs

    jax.profiler.start_trace(trace_dir)
    try:
        with obs.span("xprof.trace", dir=trace_dir):
            yield
    finally:
        jax.profiler.stop_trace()
        rows = attribute_trace(trace_dir)
        if rows:
            top = ", ".join(f"{r['name'][:40]}={r['total_ms']}ms"
                            for r in rows[:3])
            log.info("profiler trace written to %s; top kernels: %s",
                     trace_dir, top)
        else:
            log.info("profiler trace written to %s", trace_dir)
        print(f"profiler trace written to {trace_dir}")


def attribute_trace(trace_dir: str, top: int = 40):
    """Parse an already-captured XProf/Perfetto bundle and emit the
    per-kernel table into the event log; returns the rows (None when no
    trace file was found).  Safe on any dir."""
    from lux_tpu.obs import xprof

    return xprof.emit_kernel_table(trace_dir, top=top)


def annotate(name: str):
    """Named region for trace timelines (no-op outside tracing)."""
    return jax.profiler.TraceAnnotation(name)
