"""Profiling integration.

TPU-native replacement for the reference's observability hooks: Legion
Prof/Spy exist behind -lg:* flags but are unused in-repo (SURVEY.md §5);
the in-tree story is Realm::Clock timers.  Here: `jax.profiler` traces
(viewable in XProf/Perfetto/TensorBoard) wrapping any run, plus
`block_until_ready` fencing so phases attribute correctly.
"""
from __future__ import annotations

import contextlib
import logging

import jax

log = logging.getLogger("lux_tpu")


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """Context manager: capture a jax.profiler trace when dir is given."""
    if not trace_dir:
        yield
        return
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", trace_dir)
        print(f"profiler trace written to {trace_dir}")


def annotate(name: str):
    """Named region for trace timelines (no-op outside tracing)."""
    return jax.profiler.TraceAnnotation(name)
