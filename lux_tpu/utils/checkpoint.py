"""Checkpoint/resume of vertex state.

The reference has NO checkpointing (SURVEY.md §5: the USE_HDF knob exists
but is unused) — this is a capability extension: vertex-state arrays are
small relative to the graph, so saving (state, iteration, metadata) per
iteration range is cheap.  Format: NumPy .npz with atomic rename (no
extra dependencies).

Checkpoints are ELASTIC: the saved state is the GLOBAL (nv, ...) vertex
vector, de-padded from whatever shard layout produced it, so a resume may
use a different part count, exchange strategy, or device mesh than the
run that saved it (the app restacks onto its current layout).  bfloat16
state is stored widened to float32 (the .npy format has no bf16 descr;
the cast is value-exact) and narrowed back on resume.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np


def save(path: str, state, iteration: int, meta: Optional[Dict[str, Any]] = None):
    """Save stacked vertex state + iteration counter (atomic rename)."""
    state = np.asarray(state)
    tmp = path + ".tmp"
    np.savez(
        tmp, state=state, iteration=np.int64(iteration),
        meta=json.dumps(meta or {}),
    )
    os.replace(tmp + ".npz" if not tmp.endswith(".npz") else tmp, path)


def load(path: str) -> Tuple[np.ndarray, int, Dict[str, Any]]:
    with np.load(path, allow_pickle=False) as z:
        return (
            z["state"],
            int(z["iteration"]),
            json.loads(str(z["meta"])),
        )


def save_iteration(directory: str, iteration: int, state_global, app: str) -> str:
    """Save the GLOBAL (nv, ...) state under the canonical name
    ``ckpt_<iteration>.npz`` (the format ``latest`` scans for); creates
    the directory on first use."""
    os.makedirs(directory, exist_ok=True)
    state_global = np.asarray(state_global)
    meta = {
        "app": app,
        "layout": "global",
        "nv": int(state_global.shape[0]),
        "dtype": str(state_global.dtype),
    }
    if state_global.dtype.name == "bfloat16":
        state_global = state_global.astype(np.float32)
    path = os.path.join(directory, f"ckpt_{iteration}.npz")
    save(path, state_global, iteration, meta)
    return path


def load_resume(directory: str, app: str, nv: int):
    """Validated elastic resume: the latest checkpoint in ``directory``
    for this app/graph, as (state_global, start_iteration, path) — or
    (None, 0, None) when the directory has no checkpoint yet.  The state
    comes back in its original dtype (bf16 is narrowed back from the
    widened on-disk f32)."""
    prev = latest(directory)
    if prev is None:
        return None, 0, None
    state, it, meta = load(prev)
    if meta.get("layout") != "global":
        raise SystemExit(
            f"{prev}: layout-specific checkpoint from an older format; "
            "elastic resume needs global-layout checkpoints — delete the "
            "directory and re-run"
        )
    if meta.get("app") != app:
        raise SystemExit(
            f"{prev}: checkpoint is from app {meta.get('app')!r}, "
            f"refusing to resume {app!r}"
        )
    if int(meta.get("nv", -1)) != nv:
        raise SystemExit(
            f"{prev}: checkpoint is for nv={meta.get('nv')}, "
            f"this graph has nv={nv}"
        )
    if meta.get("dtype") == "bfloat16":
        import ml_dtypes

        state = state.astype(ml_dtypes.bfloat16)
    return state, it, prev


def _save_global_ckpt(directory: str, iteration: int, state_global,
                      changed_global, edges, app: str, layout: str,
                      extra: Dict[str, Any]) -> str:
    """Shared body of the mask-carrying checkpoint savers (frontier +
    delta): GLOBAL state + GLOBAL bool mask + exact edge counter +
    layout-tagged meta, written atomically (tmp + rename).  ONE
    implementation so the two formats can never drift."""
    os.makedirs(directory, exist_ok=True)
    state_global = np.asarray(state_global)
    meta = {
        "app": app,
        "layout": layout,
        "nv": int(state_global.shape[0]),
        "dtype": str(state_global.dtype),
    }
    path = os.path.join(directory, f"ckpt_{iteration}.npz")
    tmp = path + ".tmp"
    np.savez(
        tmp, state=state_global,
        changed=np.asarray(changed_global, bool),
        edges=np.asarray(edges, np.uint32), iteration=np.int64(iteration),
        meta=json.dumps(meta), **extra,
    )
    os.replace(tmp + ".npz", path)
    return path


def _load_global_ckpt(prev: str, app: str, nv: int, layout: str,
                      wrong_layout_hint: str):
    """Shared validation + field extraction of _save_global_ckpt files.
    Returns the open npz dict as plain arrays plus the iteration."""
    with np.load(prev, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        if meta.get("layout") != layout:
            raise SystemExit(
                f"{prev}: layout {meta.get('layout')!r} is not {layout!r}"
                f"; {wrong_layout_hint}"
            )
        if meta.get("app") != app:
            raise SystemExit(
                f"{prev}: checkpoint is from app {meta.get('app')!r}, "
                f"refusing to resume {app!r}"
            )
        if int(meta.get("nv", -1)) != nv:
            raise SystemExit(
                f"{prev}: checkpoint is for nv={meta.get('nv')}, "
                f"this graph has nv={nv}"
            )
        return {k: z[k] for k in z.files if k != "meta"}


def save_frontier(directory: str, iteration: int, state_global,
                  changed_global, edges, app: str) -> str:
    """Frontier-app (push engine) checkpoint: the GLOBAL (nv,) state, the
    GLOBAL changed-vertex mask (the frontier, layout-free), and the exact
    traversed-edge accumulator ((2,) uint32 [hi, lo]).  Elastic like
    save_iteration: any later part count / exchange / mesh rebuilds its
    queues from the mask (engine.repartition._rebuild_carry machinery)."""
    return _save_global_ckpt(directory, iteration, state_global,
                             changed_global, edges, app,
                             "global-frontier", {})


def load_resume_frontier(directory: str, app: str, nv: int):
    """Latest frontier checkpoint as (state_global, changed_global,
    edges, start_iteration, path); (None, None, None, 0, None) when the
    directory holds none."""
    prev = latest(directory)
    if prev is None:
        return None, None, None, 0, None
    z = _load_global_ckpt(
        prev, app, nv, "global-frontier",
        "fixed-iteration, frontier, and delta drivers use separate "
        "directories",
    )
    return z["state"], z["changed"], z["edges"], int(z["iteration"]), prev


def save_delta(directory: str, iteration: int, state_global,
               pending_global, edges, thr: int, app: str) -> str:
    """Delta-stepping checkpoint: the frontier format (GLOBAL state +
    GLOBAL pending mask + exact edge counter) plus the bucket threshold
    — everything DeltaCarry needs (engine/delta.py).  Elastic like
    save_frontier: any later part count restacks the global arrays."""
    return _save_global_ckpt(directory, iteration, state_global,
                             pending_global, edges, app, "global-delta",
                             {"thr": np.int32(thr)})


def load_resume_delta(directory: str, app: str, nv: int):
    """Latest delta checkpoint as (state_global, pending_global, edges,
    thr, start_iteration, path); (None, None, None, 0, 0, None) when the
    directory holds none."""
    prev = latest(directory)
    if prev is None:
        return None, None, None, 0, 0, None
    z = _load_global_ckpt(
        prev, app, nv, "global-delta",
        "use a separate --ckpt-dir per driver kind",
    )
    return (z["state"], z["changed"], z["edges"], int(z["thr"]),
            int(z["iteration"]), prev)


def latest(directory: str, prefix: str = "ckpt_") -> Optional[str]:
    """Most recent checkpoint file in a directory (by iteration suffix)."""
    if not os.path.isdir(directory):
        return None
    best, best_it = None, -1
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(".npz"):
            try:
                it = int(name[len(prefix) : -4])
            except ValueError:
                continue
            if it > best_it:
                best, best_it = os.path.join(directory, name), it
    return best
