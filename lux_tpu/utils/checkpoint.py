"""Checkpoint/resume of vertex state.

The reference has NO checkpointing (SURVEY.md §5: the USE_HDF knob exists
but is unused) — this is a capability extension: vertex-state arrays are
small relative to the graph, so saving (state, iteration, metadata) per
iteration range is cheap.  Format: NumPy .npz with atomic rename (no extra
dependencies; multi-host runs save per-host part slices via the same API).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np


def save(path: str, state, iteration: int, meta: Optional[Dict[str, Any]] = None):
    """Save stacked vertex state + iteration counter (atomic rename)."""
    state = np.asarray(state)
    tmp = path + ".tmp"
    np.savez(
        tmp, state=state, iteration=np.int64(iteration),
        meta=json.dumps(meta or {}),
    )
    os.replace(tmp + ".npz" if not tmp.endswith(".npz") else tmp, path)


def load(path: str) -> Tuple[np.ndarray, int, Dict[str, Any]]:
    with np.load(path, allow_pickle=False) as z:
        return (
            z["state"],
            int(z["iteration"]),
            json.loads(str(z["meta"])),
        )


def save_iteration(directory: str, iteration: int, state, app: str) -> str:
    """Save under the canonical name ``ckpt_<iteration>.npz`` (the format
    ``latest`` scans for); creates the directory on first use."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{iteration}.npz")
    save(path, state, iteration, {"app": app})
    return path


def latest(directory: str, prefix: str = "ckpt_") -> Optional[str]:
    """Most recent checkpoint file in a directory (by iteration suffix)."""
    if not os.path.isdir(directory):
        return None
    best, best_it = None, -1
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(".npz"):
            try:
                it = int(name[len(prefix) : -4])
            except ValueError:
                continue
            if it > best_it:
                best, best_it = os.path.join(directory, name), it
    return best
