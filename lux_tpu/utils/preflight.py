"""Memory preflight: per-chip HBM requirement estimate before running.

Equivalent of the reference's framebuffer/zero-copy minimum calculator
printed by each driver (pagerank.cc:60-85, sssp.cc:59-90): the reference
tells the user what -ll:fsize/-ll:zsize to pass; we report the expected
per-chip HBM footprint of the shard arrays + state + the all-gathered
exchange buffer, and warn if it exceeds the device.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from lux_tpu.graph.push_shards import PushSpec
from lux_tpu.graph.shards import ShardSpec


@dataclasses.dataclass
class MemoryEstimate:
    shard_bytes: int  # static graph arrays per chip
    state_bytes: int  # vertex state (old + new) per chip
    gathered_bytes: int  # the all-gathered whole-state buffer
    total_bytes: int

    def __str__(self):
        gib = 1 << 30
        return (
            f"per-chip HBM estimate: graph {self.shard_bytes/gib:.3f} GiB + "
            f"state {self.state_bytes/gib:.3f} GiB + "
            f"gathered exchange {self.gathered_bytes/gib:.3f} GiB = "
            f"{self.total_bytes/gib:.3f} GiB"
        )


def scale_residency(est: MemoryEstimate, k: int) -> MemoryEstimate:
    """Per-chip estimate with k parts RESIDENT per device (mapper-slicing
    layouts): the per-part graph arrays and state scale by k; the
    gathered/exchange buffer is global-sized and does not.  The ring
    estimates (estimate_ring / estimate_push_ring) keep every streamed
    (k, V)-block term in state_bytes with gathered_bytes == 0, so the
    streamed blocks scale with k here too —
    tests/test_utils.py::test_preflight_ring_k_resident_exact pins the
    scaled estimate against the exact k-resident array bytes."""
    if k <= 1:
        return est
    shard, state = est.shard_bytes * k, est.state_bytes * k
    return MemoryEstimate(
        shard, state, est.gathered_bytes,
        shard + state + est.gathered_bytes,
    )


def estimate_pull(spec: ShardSpec, state_width: int = 1,
                  state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the pull engine with one part per chip."""
    V, E = spec.nv_pad, spec.e_pad
    # row_ptr, src_pos, dst_local int32; head/edge/vtx masks byte; degree,
    # global_vid int32; weights f32
    shard = 4 * (V + 1) + 4 * E * 2 + E * 2 + V + 4 * V * 2 + 4 * E
    state = 2 * V * state_width * state_dtype_bytes
    gathered = spec.gathered_size * state_width * state_dtype_bytes
    return MemoryEstimate(shard, state, gathered, shard + state + gathered)


def routed_plan_bytes(static) -> int:
    """Device-resident bytes of a routed plan's pass arrays
    (ops/expand.py; uint8 indices by default — LUX_ROUTE_IDX8).  Add to
    a MemoryEstimate's shard term when `route=` is in play: at rmat20
    the expand plan is ~270 MB and the fused plan ~630 MB per part, a
    real slice of one chip's HBM."""
    from lux_tpu.ops.expand import (CFRouteStatic, FusedStatic,
                                    _idx8_enabled)

    idx = 1 if _idx8_enabled() else 4
    if isinstance(static, CFRouteStatic):
        return routed_plan_bytes(static.src) + routed_plan_bytes(static.dst)

    def route_cost(r, space):
        # pass-fused routes (StaticRoutePF) carry one index array per
        # in-group gather STEP — same total as the unfused pass count,
        # so plan residency is unchanged by fusion (counted by the one
        # layout-arithmetic home, pallas_shuffle.route_num_arrays)
        from lux_tpu.ops import pallas_shuffle as shuf

        return shuf.route_num_arrays(r) * space * idx

    def ff_cost(ff):
        return sum(lv.rows * 128 * (idx + (0 if lv.base else 1))
                   for lv in ff.levels)

    n = static.n
    b = route_cost(static.r1, n) + ff_cost(static.ff)
    if isinstance(static, FusedStatic):
        b += route_cost(static.r2, static.n2)
        mxg = getattr(static, "mx", None)
        if mxg is not None:
            # MXREDUCE final group: its in-group gather step tiles +
            # the dst_rel rank tile (all idx-width over n2) replace the
            # group mask; tile_block/tile_first are O(tiles) int32
            n_tiles = static.n2 // (mxg.block_rows * 128)
            b += (len(mxg.steps) + 1) * static.n2 * idx + 2 * n_tiles * 4
        else:
            b += static.n2  # group mask byte
        if static.weighted:
            b += static.n2 * 4  # pre-routed f32 weights
        # runtime gslot tombstone route (int32 over the base edge slots,
        # FUSED_FORMAT 1 — what lets overlays ride the fused families)
        b += static.e_pad * 4
        b += route_cost(static.vr, static.nv_route)
    else:
        b += route_cost(static.r2, n)
    return b


def add_routed_bytes(est: MemoryEstimate, extra: int) -> MemoryEstimate:
    """MemoryEstimate with ``extra`` routed-plan bytes counted as shard
    (static per-graph) bytes — the ONE place the arithmetic lives."""
    return MemoryEstimate(
        est.shard_bytes + extra, est.state_bytes, est.gathered_bytes,
        est.total_bytes + extra,
    )


def add_routed(est: MemoryEstimate, static) -> MemoryEstimate:
    """MemoryEstimate with a routed plan's arrays counted in."""
    return add_routed_bytes(est, routed_plan_bytes(static))


def routed_bucket_plan_bytes_analytic(num_parts: int, e_bucket_pad: int,
                                      nv_pad: int) -> int:
    """Per-RESIDENT-PART plan bytes for the bucketed (ring /
    reduce_scatter) routed exchanges: P plans, one per peer bucket,
    each over n_b = pow2(max(e_bucket_pad, nv_pad)) — NOT the allgather
    geometry (a skewed graph's padded bucket can make P * n_b far
    exceed e_pad)."""
    from lux_tpu.ops.expand import _idx8_enabled, _next_pow2
    from lux_tpu.ops.route import factor_digits

    idx = 1 if _idx8_enabled() else 4
    n_b = max(_next_pow2(e_bucket_pad), _next_pow2(nv_pad), 128)
    k = len(factor_digits(n_b))
    per_plan = 2 * (2 * k - 1) * n_b * idx + int(1.02 * n_b) * (idx + 1)
    return num_parts * per_plan


def routed_plan_bytes_analytic(spec: ShardSpec, mode: str = "expand",
                               wide: bool = False) -> int:
    """Routed-plan bytes from the shard GEOMETRY alone (no plan built):
    the pass structure depends only on the padded sizes, so preflight
    can charge the plan before the (minutes-long) construction runs.
    ``wide`` doubles the expand term (colfilter routes src AND dst)."""
    from lux_tpu.ops.expand import _idx8_enabled, _next_pow2
    from lux_tpu.ops.route import factor_digits

    idx = 1 if _idx8_enabled() else 4

    def expand_cost(n):
        k = len(factor_digits(n))
        passes = 2 * (2 * k - 1)  # r1 + r2
        ff = int(1.02 * n) * (idx + 1)  # lane idx + ext-mask byte
        return passes * n * idx + ff

    # pass-fused modes ('expand-pf'/'fused-pf'/'fused-mx') carry the
    # SAME index bytes as their base (one index tile per gather step
    # either way — fusion collapses data sweeps, not plan residency);
    # fused-mx swaps the group mask (1 B/elem) for the rank tile
    # (idx B/elem) — same order, charged identically here
    mx = mode == "fused-mx"
    if mode.endswith(("-pf", "-mx")):
        mode = mode[:-3]
    n = max(_next_pow2(spec.e_pad), _next_pow2(spec.gathered_size), 128)
    b = expand_cost(n)
    if wide:
        b += expand_cost(max(_next_pow2(spec.e_pad),
                             _next_pow2(spec.nv_pad), 128))
    if mode == "fused":
        # r2 moves to the ~2x group space and gains mask+weights (or,
        # mx: the rank tile + weights); the accumulator route is small;
        # the gslot tombstone route adds 4 B per base edge slot
        n2 = 2 * n
        k2 = len(factor_digits(n2))
        b += (2 * k2 - 1) * n2 * idx + n2 * (idx + 4 if mx else 5)
        b += 4 * spec.e_pad
    return b


def estimate_push(spec: ShardSpec, pspec: PushSpec,
                  state_dtype_bytes: int = 4) -> MemoryEstimate:
    base = estimate_pull(spec, 1, state_dtype_bytes)
    U, E, F = pspec.u_pad, spec.e_pad, pspec.f_cap
    extra = 4 * U + 4 * (U + 1) + 4 * E + 4 * E  # uniq, rp, dst, weight
    queues = 2 * 4 * F * 2 + 2 * 4 * spec.num_parts * F  # local + gathered
    sparse_buf = 4 * pspec.e_sp * 3
    return MemoryEstimate(
        base.shard_bytes + extra,
        base.state_bytes + queues + sparse_buf,
        base.gathered_bytes,
        base.total_bytes + extra + queues + sparse_buf,
    )


def estimate_edge2d(spec: ShardSpec, e2_pad: int, state_width: int = 1,
                    state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint on the 2-D (parts x edge) mesh: one edge chunk
    (13 B/slot) + the part's vertex view + state, plus the all-gathered
    whole state (the 2-D driver still replicates state across parts; its
    win is splitting the EDGE arrays)."""
    V = spec.nv_pad
    shard = e2_pad * 13 + V * 9
    blk = V * state_width * state_dtype_bytes
    state = 3 * blk  # local + new + combined accumulator
    gathered = spec.gathered_size * state_width * state_dtype_bytes
    return MemoryEstimate(shard, state, gathered, shard + state + gathered)


def estimate_push_ring(spec: ShardSpec, pspec: PushSpec, e_bucket_pad: int,
                       state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the push engine with the RING dense exchange:
    frontier CSR + queues + sparse buffer (like estimate_push) plus the P
    ring buckets, but NO O(E) pull arrays and NO gathered state buffer —
    dense rounds stream O(nv/P) blocks."""
    U, E, F = pspec.u_pad, spec.e_pad, pspec.f_cap
    Pn, V = spec.num_parts, spec.nv_pad
    csr = 4 * U + 4 * (U + 1) + 4 * E + 4 * E  # uniq, rp, dst, weight
    buckets = Pn * e_bucket_pad * 13
    view = V * (4 + 4 + 1)  # global_vid, degree, vtx_mask
    shard = csr + buckets + view
    queues = 2 * 4 * F * 2 + 2 * 4 * Pn * F
    sparse_buf = 4 * pspec.e_sp * 3
    blk = V * state_dtype_bytes
    state = 4 * blk + queues + sparse_buf  # local + in-flight + acc + new
    return MemoryEstimate(shard, state, 0, shard + state)


def estimate_ring(spec: ShardSpec, e_bucket_pad: int, state_width: int = 1,
                  state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the ring-streamed exchange driver: P buckets of
    edge-aligned arrays (src/dst int32, head bool, weight f32 = 13 B/slot —
    no (V+1) row_ptr per bucket by design), plus the resident state block,
    the in-flight ppermute block, and the fold accumulator.  The whole point
    of the ring is gathered_bytes == 0 (no nv-sized exchange buffer)."""
    Pn, V = spec.num_parts, spec.nv_pad
    shard = Pn * e_bucket_pad * 13 + V * 5  # buckets + vtx_mask/degree
    blk = V * state_width * state_dtype_bytes
    state = 4 * blk  # local + in-flight block + accumulator + new state
    return MemoryEstimate(shard, state, 0, shard + state)


def estimate_scatter(spec: ShardSpec, e_bucket_pad: int, state_width: int = 1,
                     state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the reduce_scatter exchange driver.  Same
    bucket layout as the ring; the transient is the (P, V, ...) partials
    stack consumed by psum_scatter (reported as gathered_bytes — it is the
    O(nv) term this strategy still pays, unlike the ring)."""
    Pn, V = spec.num_parts, spec.nv_pad
    shard = Pn * e_bucket_pad * 13 + V * 5
    blk = V * state_width * state_dtype_bytes
    state = 2 * blk
    partials = Pn * blk
    return MemoryEstimate(shard, state, partials, shard + state + partials)


def estimate_push_pallas(spec: ShardSpec, pspec: PushSpec, num_chunks: int,
                         t_chunk: int,
                         state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the push engine with Pallas dense rounds:
    block-CSR chunk arrays + frontier CSR/queues/sparse buffer; no O(E)
    pull-layout arrays on device (the dense reduce reads the chunks)."""
    U, E, F = pspec.u_pad, spec.e_pad, pspec.f_cap
    Pn, V = spec.num_parts, spec.nv_pad
    ct = num_chunks * t_chunk
    blockcsr = 4 * ct * 2 + (4 * ct if spec.weighted else 0) + 4 * num_chunks * 2
    csr = 4 * U + 4 * (U + 1) + 4 * E + 4 * E  # uniq, rp, dst, weight
    view = V * 9  # global_vid, degree, vtx_mask
    shard = blockcsr + csr + view
    queues = 2 * 4 * F * 2 + 2 * 4 * Pn * F
    sparse_buf = 4 * pspec.e_sp * 3
    state = 2 * V * state_dtype_bytes + queues + sparse_buf
    gathered = spec.gathered_size * state_dtype_bytes + 4 * ct  # + edge vals
    return MemoryEstimate(shard, state, gathered, shard + state + gathered)


def suggest_edge_shards(spec: ShardSpec, hbm_bytes: int,
                        state_width: int = 1, state_dtype_bytes: int = 4,
                        max_shards: int = 64) -> Optional[int]:
    """Smallest edge-shard count EP >= 2 whose 2-D per-chip footprint
    fits ``hbm_bytes`` — the auto-selection hint for a part whose edge
    slice exceeds one device (the layout's reason to exist; the
    reference simply cannot run this case, core/graph.h:31 one part ==
    one GPU).  None if no EP <= max_shards fits (the gathered-state
    replica is the irreducible floor: edge sharding divides only the
    EDGE arrays).  Pass the RUN's state width/dtype (a bf16 estimate
    judged with f32 candidates would over-reject).  ``max_shards``
    should be capped by the caller at devices // num_parts — edge2d
    keeps one part-column slot per device, no k-residency."""
    from lux_tpu.graph.shards import edge2d_chunk_pad

    for ep in range(2, max_shards + 1):
        # conservative: e_pad >= the raw per-part max the builder uses,
        # so a suggested EP always fits (formula shared with the builder)
        e2 = edge2d_chunk_pad(spec.e_pad, ep)
        est = estimate_edge2d(spec, e2, state_width, state_dtype_bytes)
        if est.total_bytes <= hbm_bytes:
            return ep
    return None


def check_fits(est: MemoryEstimate, hbm_bytes: Optional[int] = None,
               spec: Optional[ShardSpec] = None, state_width: int = 1,
               state_dtype_bytes: int = 4,
               max_edge_shards: int = 64,
               stream_hint: bool = False) -> bool:
    """Warn (returns False) if the estimate exceeds the device HBM.
    With ``spec`` (1-D pull layouts), the warning also names the
    smallest --edge-shards that WOULD fit (suggest_edge_shards), sized
    with the run's state width/dtype and capped at ``max_edge_shards``
    (pass devices // num_parts; apps/common.report_preflight does)."""
    if hbm_bytes is None:
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            hbm_bytes = stats.get("bytes_limit") if stats else None
        except Exception:
            hbm_bytes = None
    if hbm_bytes is None:
        return True
    if est.total_bytes > hbm_bytes:
        hint = "increase num_parts"
        if stream_hint and spec is not None and max_edge_shards < 2:
            # one device: more parts can't help either — stream instead
            # (only when the calling app actually exposes the flag)
            hint = ("stream the edges from host RAM "
                    "(--stream-hbm-gib; engine/stream.py)")
        if spec is not None and max_edge_shards >= 2:
            ep = suggest_edge_shards(
                spec, hbm_bytes, state_width, state_dtype_bytes,
                max_shards=max_edge_shards,
            )
            if ep is not None:
                # name the FULL runnable combination (edge2d always
                # needs --distributed; redundant-but-correct when the
                # run already passed it)
                hint = (f"increase num_parts, or split the edge arrays "
                        f"with --distributed --edge-shards {ep}")
        print(
            f"WARNING: estimated {est.total_bytes/(1<<30):.2f} GiB exceeds "
            f"device HBM {hbm_bytes/(1<<30):.2f} GiB — {hint}"
        )
        return False
    return True


def estimate_pallas_pull(num_chunks: int, t_chunk: int, nv_pad: int,
                         gathered_size: int, weighted: bool = False,
                         state_dtype_bytes: int = 4) -> MemoryEstimate:
    """Per-chip footprint of the distributed Pallas pull (block-CSR chunk
    arrays instead of the CSC shard layout)."""
    ct = num_chunks * t_chunk
    shard = 4 * ct * 2 + (4 * ct if weighted else 0) + 4 * num_chunks * 2
    shard += 4 * nv_pad * 2 + nv_pad  # degree, global_vid, vtx_mask
    state = 2 * nv_pad * state_dtype_bytes
    gathered = gathered_size * state_dtype_bytes + 4 * ct  # + edge values
    return MemoryEstimate(shard, state, gathered, shard + state + gathered)
