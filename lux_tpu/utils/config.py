"""Run configuration + CLI flag parsing.

Replaces the reference's three config layers (SURVEY.md §5): per-app CLI
flags (parse_input_args, pagerank.cc:121-148), Legion machine flags
(-ll:gpu/-ll:fsize/-ll:zsize), and compile-time app.h constants — collapsed
into one dataclass resolved before jit.  Flag names keep reference parity
where they exist (-ng, -ni, -file, -start, -verbose/-v, -check/-c); memory
sizing flags are obsolete (XLA owns HBM) and are replaced by the preflight
report (lux_tpu.utils.preflight).
"""
from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Optional


def env_int(name: str, default: Optional[int] = None, *,
            minimum: Optional[int] = None,
            maximum: Optional[int] = None) -> Optional[int]:
    """Parse an integer env knob at the BOUNDARY, with an error that
    names the variable.  ``LUX_PLAN_THREADS=garbage`` used to surface as
    a bare ``ValueError: invalid literal`` deep inside the planner
    fan-out (or worse, be silently swallowed into a fallback, hiding the
    typo'd knob); every ``int(os.environ...)`` cast now routes through
    here (enforced by luxcheck LUX-P002).

    Unset or empty reads as ``default``.  A set-but-garbage or
    out-of-bounds value raises ValueError immediately — a mistyped
    thread count must fail the launch, not quietly run single-threaded
    through a chip window."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}") from None
    if minimum is not None and val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    if maximum is not None and val > maximum:
        raise ValueError(f"{name} must be <= {maximum}, got {val}")
    return val


def env_float(name: str, default: Optional[float] = None, *,
              minimum: Optional[float] = None,
              maximum: Optional[float] = None) -> Optional[float]:
    """``env_int``'s float twin — same boundary contract: unset/empty
    reads as ``default``, garbage or out-of-bounds raises a ValueError
    that NAMES the knob (LUX-P002 routes every ``float(os.environ...)``
    cast through here, like the int casts through env_int)."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = float(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r}") from None
    if val != val:  # NaN would defeat every min/max comparison below
        raise ValueError(f"{name} must be a number, got NaN")
    if minimum is not None and val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    if maximum is not None and val > maximum:
        raise ValueError(f"{name} must be <= {maximum}, got {val}")
    return val


@dataclasses.dataclass
class RunConfig:
    file: Optional[str] = None  # .lux path; None => synthetic RMAT
    num_parts: int = 1  # -ng: parts == chips used
    num_iters: int = 10  # -ni (fixed-iteration apps)
    start: int = 0  # -start (SSSP source)
    verbose: bool = False  # -verbose/-v: per-iteration stats
    check: bool = False  # -check/-c: run the invariant validator
    max_iters: int = 10_000  # convergence-app safety bound
    #: segment-reduction strategy; "auto" resolves to the platform's
    #: measured winner at driver entry (lux_tpu.engine.methods)
    method: str = "auto"
    distributed: bool = False  # place parts on a device mesh
    rmat_scale: int = 16  # synthetic graph size when file is None
    rmat_ef: int = 8
    seed: int = 0
    ckpt_dir: Optional[str] = None  # checkpoint/resume directory
    ckpt_every: int = 0  # save every N iterations (0 = off)
    profile_dir: Optional[str] = None  # jax.profiler trace output
    #: distributed state-exchange strategy (SURVEY.md §2.5): allgather
    #: (replicated state, the reference's model), ring (ppermute-streamed
    #: O(nv/P) blocks), scatter (reduce_scatter pre-combined partials;
    #: sum programs only)
    exchange: str = "allgather"
    weighted: bool = False  # SSSP: relax with edge weights (Dijkstra-style)
    #: >0 = delta-stepping bucket width for weighted SSSP (engine/delta.py)
    delta: int = 0
    #: >0 = host-offload streaming under this device-byte budget in GiB
    #: (engine/stream.py; pagerank/colfilter fixed + components until —
    #: the -ll:zsize analog)
    stream_hbm_gib: float = 0.0
    dtype: str = "float32"  # state storage dtype (pagerank/CF)
    #: >1 = 2-D (parts x edge) mesh: each part's edges split over this many
    #: chips, partial reductions psum'd (for parts too big for one chip)
    edge_shards: int = 1
    feat_shards: int = 1
    #: gather-locality relayout: sort edges within each destination
    #: segment by src_pos (graph/shards.sort_segments_inplace)
    sort_segments: bool = False
    #: compact-gather layout: per-part unique-in-source mirror, the
    #: reference's load_kernel FB staging (graph/shards.build_compact_mirror)
    compact_gather: bool = False
    #: routed gather: "expand" replaces the pull LOAD phase with Benes
    #: lane shuffles (bitwise-identical); "fused" also replaces the
    #: segmented reduce (group-layout sum association).  ops/expand.py.
    route_gather: str = ""
    #: >0 = adaptive dynamic repartitioning (push apps): every N iterations
    #: rebalance the vertex cuts from the measured per-part load (the Lux
    #: paper's runtime repartitioning, absent from the reference code)
    repartition_every: int = 0
    #: recut when the window's max/mean per-part load exceeds this
    repartition_threshold: float = 1.25
    #: --serve: run the app as a batched query service (lux_tpu.serve):
    #: warm Q-bucket engines + micro-batching scheduler instead of one
    #: whole-graph run
    serve: bool = False
    serve_queries: int = 64  # random query count when no explicit list
    serve_sources: str = ""  # comma-separated query vertices (overrides)
    serve_buckets: str = "1,8,64"  # warm Q buckets, pre-traced at start
    serve_wait_ms: float = 5.0  # micro-batch coalescing window
    serve_timeout_ms: float = 0.0  # per-request deadline (0 = none)
    serve_max_queue: int = 256  # admission bound (backpressure past it)
    # --- generic program driver (python -m lux_tpu.apps.run) ---------------
    sources: str = "0"  # bfs: comma-separated seed vertices
    labels: int = 8  # labelprop: number of classes
    seed_stride: int = 16  # labelprop: every Nth vertex is a seed
    kmax: int = 0  # kcore: peel ceiling (0 = until the core empties)
    prog_engine: str = "auto"  # workload surface override (push/pull)
    directed: bool = False  # kcore/triangles: skip the symmetrized view


def parse_args(argv=None, description: str = "", sssp: bool = False,
               pull: bool = False, push: bool = False,
               stream: bool = False, serve: bool = False,
               program: bool = False, prog: str = "") -> RunConfig:
    """``sssp`` adds -start/--weighted; ``pull`` adds --exchange
    {allgather,ring,scatter}/--dtype; ``push`` adds --exchange
    {allgather,ring} (frontier apps: dense rounds can ring-stream, but
    reduce_scatter can't pre-combine min/max); ``program`` adds the
    generic program driver's workload knobs (apps/run.py — ``prog``
    names the workload in the usage line).  Flags appear only on apps
    that consume them — a silently-ignored flag would misreport what was
    benchmarked."""
    ap = argparse.ArgumentParser(
        description=description,
        prog=f"python -m lux_tpu.apps.run {prog}" if prog else None)
    ap.add_argument("-file", help=".lux graph file (default: synthetic RMAT)")
    ap.add_argument("-ng", "--num-parts", type=int, default=1,
                    help="number of graph parts (one per chip)")
    ap.add_argument("-ni", "--num-iters", type=int, default=10)
    if sssp:
        ap.add_argument("-start", type=int, default=0, help="source vertex")
    ap.add_argument("-verbose", "-v", action="store_true")
    ap.add_argument("-check", "-c", action="store_true")
    ap.add_argument("--max-iters", type=int, default=10_000)
    ap.add_argument("--method", default="auto",
                    choices=["auto", "scan", "cumsum", "mxsum", "mxscan",
                             "scatter", "pallas"],
                    help="segment-reduction strategy; auto = the measured "
                         "per-platform winner (engine.methods; float sums "
                         "additionally refine through the banked tpu:sum "
                         "scan-family winner, engine/methods.sum_mode / "
                         "LUX_SUM_MODE)")
    ap.add_argument("--distributed", action="store_true",
                    help="shard parts over the device mesh")
    ap.add_argument("--rmat-scale", type=int, default=16)
    ap.add_argument("--rmat-ef", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-dir", help="checkpoint directory (resume if present)")
    ap.add_argument("--ckpt-every", type=int, default=0,
                    help="save state every N iterations")
    ap.add_argument("--profile-dir",
                    help="write a jax.profiler trace (XProf/Perfetto) here")
    if pull:
        ap.add_argument("--exchange", default="allgather",
                        choices=["allgather", "ring", "scatter"],
                        help="distributed state-exchange strategy")
        ap.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"],
                        help="state storage dtype")
        ap.add_argument("--edge-shards", type=int, default=1,
                        help="split each part's edges over N chips "
                             "(2-D parts x edge mesh; total chips = "
                             "num_parts * N). Capacity feature for parts "
                             "bigger than one chip: state is replicated "
                             "per edge shard, so exchange wire volume "
                             "scales xN")
        ap.add_argument("--feat-shards", type=int, default=1,
                        help="split the latent feature dim over N chips "
                             "(2-D parts x feat mesh, CF only; total "
                             "chips = num_parts * N)")
        ap.add_argument("--sort-segments", action="store_true",
                        help="reorder edges within each destination "
                             "segment by gather index (HBM gather "
                             "locality; commutative reduces only — "
                             "semantically free, float sums round "
                             "differently than the unsorted layout)")
        ap.add_argument("--compact-gather", action="store_true",
                        help="two-stage gather through a per-part "
                             "unique-in-source mirror (working set "
                             "O(unique srcs) instead of O(nv); bitwise-"
                             "identical results)")
        ap.add_argument("--route-gather", nargs="?", const="auto",
                        default="",
                        choices=["auto", "expand", "expand-pf", "fused",
                                 "fused-pf", "fused-mx"],
                        help="Benes-routed pull hot loop (ops/expand.py): "
                             "'expand' replaces the per-edge state gather "
                             "with lane shuffles (bitwise-identical); "
                             "'fused' also replaces the segmented reduce "
                             "(deterministic group association; single "
                             "device).  The '-pf' variants run the "
                             "PASS-FUSED kernels (2-3 Benes passes per "
                             "kernel, VMEM-resident intermediates — same "
                             "bits, ~40% fewer HBM sweeps).  'fused-mx' "
                             "additionally computes the segmented "
                             "reduction INSIDE the final routed kernel "
                             "as an MXU one-hot contraction (own "
                             "deterministic float-sum association; "
                             "min/max + integer ops bitwise); 'fused-pf' "
                             "follows the measured tpu:reduce_mode "
                             "winner between the two.  The bare "
                             "flag means 'auto': expand-pf or expand per "
                             "the chip-measured tpu:route_mode overlay "
                             "(engine/methods.route_mode).  'expand' runs "
                             "--distributed on the allgather, ring, and "
                             "scatter exchanges (per-bucket plans for "
                             "the bucketed two); the -pf variants are "
                             "allgather-layout modes")
    elif push:
        ap.add_argument("--exchange", default="allgather",
                        choices=["allgather", "ring"],
                        help="dense-round state-exchange strategy")
        ap.add_argument("--repartition-every", type=int, default=0,
                        help="rebalance vertex cuts from measured per-part "
                             "load every N iterations (0 = static cuts)")
        ap.add_argument("--repartition-threshold", type=float, default=1.25,
                        help="recut when the window's max/mean per-part "
                             "load exceeds this ratio")
        ap.add_argument("--sort-segments", action="store_true",
                        help="reorder the dense-round pull layout's edges "
                             "within each destination segment by gather "
                             "index (HBM gather locality; bitwise-free "
                             "for min/max relaxation)")
        ap.add_argument("--compact-gather", action="store_true",
                        help="dense rounds gather through a per-part "
                             "unique-in-source mirror (working set "
                             "O(unique srcs); bitwise-identical)")
        ap.add_argument("--route-gather", nargs="?", const="auto",
                        default="",
                        choices=["auto", "expand", "expand-pf"],
                        help="dense rounds' per-edge gather as Benes "
                             "lane shuffles (ops/expand.py; bitwise-"
                             "identical; 'expand-pf' = pass-fused "
                             "kernels; bare flag = 'auto', following "
                             "the tpu:route_mode overlay winner).  "
                             "Single-device allgather only for push apps")
    if sssp:
        ap.add_argument("--weighted", action="store_true",
                        help="relax with edge weights (Dijkstra-style)")
        ap.add_argument("--delta", type=int, default=0,
                        help="delta-stepping bucket width (weighted, "
                             "allgather exchange; single-device or "
                             "--distributed): expand only pending "
                             "vertices with dist < current bucket — "
                             "near-Dijkstra edge counts (0 = chaotic "
                             "relaxation)")
    if serve:
        sg = ap.add_argument_group(
            "serving (lux_tpu.serve: batched multi-source query service)")
        sg.add_argument("--serve", action="store_true",
                        help="serve a burst of queries through warm "
                             "batched engines + the micro-batching "
                             "scheduler instead of one whole-graph run")
        sg.add_argument("--serve-queries", type=int, default=64,
                        help="number of random query vertices to serve")
        sg.add_argument("--serve-sources", default="",
                        help="comma-separated query vertices (overrides "
                             "--serve-queries)")
        sg.add_argument("--serve-buckets", default="1,8,64",
                        help="warm Q buckets pre-traced at service start")
        sg.add_argument("--serve-wait-ms", type=float, default=5.0,
                        help="micro-batch coalescing window")
        sg.add_argument("--serve-timeout-ms", type=float, default=0.0,
                        help="per-request deadline (0 = none)")
        sg.add_argument("--serve-max-queue", type=int, default=256,
                        help="admission-queue bound (rejects past it)")
    if program:
        pg = ap.add_argument_group(
            "program (generic spec-workload driver, lux_tpu.apps.run)")
        pg.add_argument("--sources", default="0",
                        help="bfs: comma-separated seed vertices "
                             "(distance = hops to the nearest)")
        pg.add_argument("--labels", type=int, default=8,
                        help="labelprop: number of label classes (the "
                             "wide-state trailing dim)")
        pg.add_argument("--seed-stride", type=int, default=16,
                        help="labelprop: every Nth vertex is a pinned "
                             "seed of class vid %% labels")
        pg.add_argument("--kmax", type=int, default=0,
                        help="kcore: peel ceiling (0 = peel until the "
                             "core empties)")
        pg.add_argument("--engine", dest="prog_engine", default="auto",
                        choices=["auto", "push", "pull"],
                        help="execution surface override for workloads "
                             "that lower onto both (bfs)")
        pg.add_argument("--directed", action="store_true",
                        help="kcore/triangles: run on the directed "
                             "in-neighborhoods as-is instead of the "
                             "symmetrized simple view")
    if stream:
        # apps with a streamed driver (pagerank/colfilter pull-fixed,
        # components pull-until): host-offload edge streaming
        ap.add_argument("--stream-hbm-gib", type=float, default=0.0,
                        help="host-offload streaming: keep the edge "
                             "arrays in host RAM and stream double-"
                             "buffered chunks through this device-byte "
                             "budget per iteration — runs graphs whose "
                             "edges exceed one chip's HBM (the "
                             "zero-copy-memory analog)")
    ns = ap.parse_args(argv)
    if ns.ckpt_every and not ns.ckpt_dir:
        ap.error("--ckpt-every requires --ckpt-dir")
    return RunConfig(
        file=ns.file,
        num_parts=ns.num_parts,
        num_iters=ns.num_iters,
        start=getattr(ns, "start", 0),
        verbose=ns.verbose,
        check=ns.check,
        max_iters=ns.max_iters,
        method=ns.method,
        distributed=ns.distributed,
        rmat_scale=ns.rmat_scale,
        rmat_ef=ns.rmat_ef,
        seed=ns.seed,
        ckpt_dir=ns.ckpt_dir,
        ckpt_every=ns.ckpt_every,
        profile_dir=ns.profile_dir,
        exchange=getattr(ns, "exchange", "allgather"),
        weighted=getattr(ns, "weighted", False),
        delta=getattr(ns, "delta", 0),
        stream_hbm_gib=getattr(ns, "stream_hbm_gib", 0.0),
        dtype=getattr(ns, "dtype", "float32"),
        edge_shards=getattr(ns, "edge_shards", 1),
        feat_shards=getattr(ns, "feat_shards", 1),
        sort_segments=getattr(ns, "sort_segments", False),
        compact_gather=getattr(ns, "compact_gather", False),
        route_gather=getattr(ns, "route_gather", ""),
        repartition_every=getattr(ns, "repartition_every", 0),
        repartition_threshold=getattr(ns, "repartition_threshold", 1.25),
        serve=getattr(ns, "serve", False),
        serve_queries=getattr(ns, "serve_queries", 64),
        serve_sources=getattr(ns, "serve_sources", ""),
        serve_buckets=getattr(ns, "serve_buckets", "1,8,64"),
        serve_wait_ms=getattr(ns, "serve_wait_ms", 5.0),
        serve_timeout_ms=getattr(ns, "serve_timeout_ms", 0.0),
        serve_max_queue=getattr(ns, "serve_max_queue", 256),
        sources=getattr(ns, "sources", "0"),
        labels=getattr(ns, "labels", 8),
        seed_stride=getattr(ns, "seed_stride", 16),
        kmax=getattr(ns, "kmax", 0),
        prog_engine=getattr(ns, "prog_engine", "auto"),
        directed=getattr(ns, "directed", False),
    )
