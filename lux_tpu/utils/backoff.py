"""Jittered exponential backoff — ONE retry-pacing policy for the fleet.

Before this module every retry loop in the serving stack paced itself
ad hoc: fixed ``time.sleep`` polls in the worker's drain loop, a bare
reconnect-and-hope in the bench clients, and no reconnect story at all
for controller failover.  Fixed sleeps synchronize: when a controller
dies, every client and every rejoining worker that sleeps exactly
``0.1 * attempt`` retries in lockstep and thunders the promoted
controller.  The standard fix (AWS architecture blog's "full jitter")
is to draw each delay uniformly from ``[0, min(cap, base * factor^n)]``
— decorrelated retries, same expected wait.

Everything is explicitly seeded (``random.Random(seed)`` per instance —
LUX-D003: no process-global RNG), so a fault drill that logs its seed
replays the exact same pacing.

Knobs (read at construction, named in errors):

* ``LUX_BACKOFF_BASE_MS`` — first-retry ceiling (default 25 ms)
* ``LUX_BACKOFF_CAP_MS``  — per-retry ceiling (default 2000 ms)
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from lux_tpu.utils.config import env_float


class Backoff:
    """Full-jitter exponential backoff schedule.

    ``next_s()`` returns the next delay (seconds) and advances the
    attempt counter; ``sleep()`` draws and sleeps it.  ``reset()``
    returns to attempt 0 (call after a success so the NEXT failure
    starts cheap again).  Instances are not thread-safe — each retry
    loop owns its own (sharing one would couple unrelated schedules).
    """

    def __init__(self, base_ms: Optional[float] = None,
                 cap_ms: Optional[float] = None,
                 factor: float = 2.0, seed: int = 0):
        self.base_ms = float(
            env_float("LUX_BACKOFF_BASE_MS", 25.0, minimum=0.0)
            if base_ms is None else base_ms)
        self.cap_ms = float(
            env_float("LUX_BACKOFF_CAP_MS", 2000.0, minimum=0.0)
            if cap_ms is None else cap_ms)
        self.factor = float(factor)
        self._rng = random.Random(seed)
        self.attempt = 0

    def next_s(self) -> float:
        # exponent clamped: factor ** attempt overflows float past
        # ~1024 attempts (a long poll_until easily gets there), and by
        # 64 doublings the cap has won for ANY sane base/cap pair
        ceil_ms = min(self.cap_ms,
                      self.base_ms * (self.factor ** min(self.attempt, 64)))
        self.attempt += 1
        return self._rng.uniform(0.0, ceil_ms) / 1e3

    def sleep(self, floor_s: float = 0.0) -> float:
        """Sleep the next jittered delay (at least ``floor_s`` — pass a
        server's ``retry_after_ms`` hint here so the hint is honored and
        the jitter only ever ADDS decorrelation).  Returns the slept
        seconds."""
        d = max(self.next_s(), float(floor_s))
        if d > 0:
            time.sleep(d)
        return d

    def reset(self) -> None:
        self.attempt = 0


def retry_call(fn: Callable, *, retry_on: Tuple[Type[BaseException], ...],
               deadline_s: float, backoff: Optional[Backoff] = None,
               on_retry: Optional[Callable] = None):
    """Call ``fn()`` until it succeeds, an exception outside
    ``retry_on`` escapes, or ``deadline_s`` of wall time elapses (the
    LAST error re-raises at the deadline — never a synthetic one).
    A ``retry_after_ms`` attribute on the caught error (the fleet's
    shed hint) floors the jittered delay.  ``on_retry(exc, attempt)``
    observes each retry (counters)."""
    bo = backoff if backoff is not None else Backoff()
    deadline = time.monotonic() + float(deadline_s)
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if on_retry is not None:
                on_retry(e, attempt)
            floor_s = float(getattr(e, "retry_after_ms", 0.0) or 0.0) / 1e3
            if time.monotonic() + floor_s >= deadline:
                raise
            bo.sleep(floor_s=floor_s)


def poll_until(pred: Callable[[], bool], timeout_s: float,
               base_ms: float = 2.0, cap_ms: float = 50.0,
               seed: int = 0) -> bool:
    """Poll ``pred`` with jittered growing intervals until it returns
    True or ``timeout_s`` elapses — the replacement for the fixed
    ``while: sleep(0.01)`` drain loops (fast first checks, backed-off
    tail).  Returns the final predicate value."""
    bo = Backoff(base_ms=base_ms, cap_ms=cap_ms, seed=seed)
    deadline = time.monotonic() + float(timeout_s)
    while True:
        if pred():
            return True
        if time.monotonic() >= deadline:
            return bool(pred())
        bo.sleep()
