"""Roofline accounting: per-iteration HBM-traffic and FLOP models.

GTEPS alone cannot answer "is this number good?" — that needs the
achieved fraction of what the memory system / MXU could possibly
sustain.  This module models, per engine iteration and reduce method,

  * ``bytes_moved`` — the MINIMAL coalesced HBM traffic of the useful
    data (each operand counted once at its natural width; VMEM-resident
    intermediates free).  Real traffic is >= this: TPU gathers at
    fine granularity read whole tiles, so the random ``state[src]``
    gather can be amplified by 8-128x depending on locality.  The model
    is the denominator for an honest "fraction of roofline" — a measured
    run at 30% of the coalesced-min roofline is GOOD; 0.3% says the
    gather amplification or dispatch overhead dominates.
  * ``flops`` — algorithmically useful FLOPs (the reference's work:
    pr_kernel does E adds + V fmas, pagerank_gpu.cu:86-100).
  * ``device_flops`` — FLOPs actually issued including method
    redundancy: the one-hot MXU contraction spends V_BLK MACs to sum one
    edge value (ops/pallas_spmv.py), mxsum spends T MACs per value — the
    price those methods pay to ride the 100x-denser MXU instead of the
    VPU (docs/PERF.md strategy matrix).

All models count REAL edges/vertices (ne, nv), not padded — padding
overhead is a layout cost, not useful work.  The graph workloads are
heavily memory-bound (intensity << 1 FLOP/byte everywhere except the
MXU methods' device_flops), so the binding roof is HBM bandwidth:

    GTEPS_roof = peak_GBps / bytes_per_edge

bench.py emits these fields next to every GTEPS line; docs/PERF.md
carries the expected-GTEPS table for candidate chip specs.

Reference framing: the reference never models traffic — its perf story
is one ELAPSED TIME print (pagerank/pagerank.cc:115-118).  SURVEY.md §6
derives GTEPS; this closes the "vs what roof?" gap (VERDICT r3 weak #5).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class TrafficModel:
    bytes_moved: int
    flops: int
    device_flops: int

    def __add__(self, other: "TrafficModel") -> "TrafficModel":
        return TrafficModel(
            self.bytes_moved + other.bytes_moved,
            self.flops + other.flops,
            self.device_flops + other.device_flops,
        )

    def scale(self, n: int) -> "TrafficModel":
        return TrafficModel(
            self.bytes_moved * n, self.flops * n, self.device_flops * n
        )


#: default Pallas one-hot tile (ops/pallas_spmv.py V_BLK) — the MAC
#: redundancy factor of the one-hot contraction
PALLAS_V_BLK = 512
#: default mxsum block size (ops/segment.py MX_BLOCK) — MACs per value
MXSUM_T = 512
#: mxscan triangular tile (ops/pallas_scan: the 128-lane row) — MACs
#: per scanned value in EACH of its two per-row contractions (the
#: head-count matmul + the masked value contraction)
MXSCAN_T = 128


def _reduce_bytes_per_edge(method: str, sb: int, w: int) -> float:
    """COMP-phase HBM bytes per edge value of width ``w`` (state dtype
    ``sb`` bytes), by reduce strategy.  VMEM-resident accumulation is
    free; every HBM-resident intermediate pass costs a read+write."""
    v = sb * w
    if method == "scan":
        # associative_scan over (value, head_flag): ~2 HBM passes over
        # the value array (log-depth ladder touches tiles repeatedly;
        # 2 passes is the optimistic floor) + the flag byte
        return 2 * v + 1
    if method == "mxscan":
        # blocked MXU segmented scan (ops/pallas_scan): ONE kernel —
        # value read + scanned write (the floor "scan" only aspires to:
        # the ladder's 2 is unattainable, the kernel's 2 is exact) +
        # the flag byte read + the packed head/pad byte (write + read)
        return 2 * v + 3
    if method == "scatter":
        # sorted segment_* scatter: value read + accumulator read/write
        # per edge (random by dst) + dst ids
        return 3 * v + 4
    if method == "cumsum":
        # global prefix (1 pass r+w) + boundary gather-diff (per edge:
        # read; per segment cost folded into the vertex term elsewhere)
        return 2 * v + 1
    if method == "mxsum":
        # blocked triangular matmuls: values stream through the MXU once
        # (read + block-prefix write)
        return 2 * v
    if method == "pallas":
        # one-hot contraction, VMEM accumulators: one value read
        return v
    raise ValueError(f"unknown method {method!r}")


def _reduce_device_flops_per_edge(method: str, w: int) -> int:
    """FLOPs ISSUED per edge value by the reduce (useful = 1 add/cmp)."""
    if method == "pallas":
        return 2 * PALLAS_V_BLK * w  # V_BLK MACs to sum one value
    if method == "mxsum":
        return 2 * MXSUM_T * w  # T MACs per prefix value
    if method == "mxscan":
        # two per-row contractions (head count + masked values), T MACs
        # per scanned value each
        return 2 * 2 * MXSCAN_T * w
    return w  # element-wise reduce: 1 op per value lane


def pull_iter_model(
    ne: int,
    nv: int,
    method: str = "scan",
    state_bytes: int = 4,
    width: int = 1,
    weighted: bool = False,
    needs_dst: bool = False,
    apply_flops_per_vertex: int = 3,
    compact_unique: int = 0,
) -> TrafficModel:
    """One pull-engine iteration over the whole graph (engine/pull.py
    gather -> reduce -> apply; the pr_kernel envelope,
    pagerank_gpu.cu:49-102).

    ``needs_dst``: the program's edge_value reads the destination state
    (CF's error term) — pagerank's dst gather is DCE'd by XLA.
    ``apply_flops_per_vertex``: per-vertex update cost in FLOP-lanes
    (pagerank: mul+add+div = 3; CF: ~3 per lane).
    ``compact_unique``: total unique in-sources over all parts when the
    compact-gather mirror is on (graph/shards.build_compact_mirror; the
    reference's load_kernel staging, pagerank_gpu.cu:34-47).  In this
    COALESCED-MIN model the mirror costs extra: per unique source one
    mirror_pos read + state read + mirror write on top of the per-edge
    read — the win it buys is off-model (it shrinks the per-edge
    gather's working set from P*nv_pad*v to U*v bytes, attacking the
    8-128x random-gather amplification this model excludes by
    construction).  The A/B on hardware decides."""
    v = state_bytes * width
    gather = 4 + v + (4 if weighted else 0) + ((4 + v) if needs_dst else 0)
    if compact_unique:
        gather_extra = compact_unique * (4 + 2 * v)
    else:
        gather_extra = 0
    reduce_b = _reduce_bytes_per_edge(method, state_bytes, width)
    # apply: read old state + write new (+ degree int32 when the program
    # uses it — folded in as 4B: every shipped pull program reads it)
    vertex = 2 * v + 4
    bytes_moved = ne * int(gather + reduce_b) + nv * vertex + gather_extra
    # useful: 1 combine per edge lane (+ edge_value arithmetic for
    # weighted/dst programs: err = w - <u,v> is 2w FLOPs, err*vec is w)
    edge_flops = width + (3 * width if needs_dst else 0)
    flops = ne * edge_flops + nv * apply_flops_per_vertex * width
    dev = ne * (
        _reduce_device_flops_per_edge(method, width)
        + (edge_flops - width)
    ) + nv * apply_flops_per_vertex * width
    return TrafficModel(bytes_moved, flops, dev)


def _route_counts(r) -> tuple[int, int]:
    """(HBM data sweeps, index-array reads) of one frozen route's
    replay.  Unfused StaticRoute: one kernel — and one full read+write
    of the data — per pass.  Pass-fused StaticRoutePF: one kernel per
    GROUP (the 2-3 chained passes keep their intermediate in VMEM), but
    every in-group gather step still streams its own index tile.
    Delegates to the ONE layout-arithmetic home (ops/pallas_shuffle);
    lazy import keeps this module importable without the kernel stack."""
    from lux_tpu.ops import pallas_shuffle as shuf

    return shuf.route_num_hbm_passes(r), shuf.route_num_arrays(r)


#: COMP-phase full-array HBM sweeps by reduce strategy (the v-coefficient
#: of _reduce_bytes_per_edge: value-array read/write passes).  mxscan's
#: 2 is EXACT — one Pallas kernel, one value read + one scanned write,
#: enforced by luxaudit LUX-J501 kernel counting — where scan's 2 is the
#: optimistic floor of a log-depth ladder (measured materializations:
#: docs/PERF.md "MXU scan" accounting table).
REDUCE_HBM_PASSES = {"scan": 2, "cumsum": 2, "mxsum": 2, "mxscan": 2,
                     "scatter": 3, "pallas": 1}


def routed_hbm_passes(static, method: str = "scan") -> dict:
    """Equivalent FULL-STATE HBM read+write sweeps of one routed pull
    iteration, per pipeline stage — the accounting behind the
    pass-fusion bet (ISSUE 4): fusing 2-3 Benes passes per kernel cuts
    the dominant r1/r2 terms from len(passes) to len(groups).  Stages
    over spaces other than the expand space n are scaled by their
    space (vr moves nv_route/n of a sweep per kernel; the fused r2/group
    reduce run over n2).  ``reduce`` is the chosen segment method's
    sweep count for expand-shaped plans, or the single masked
    group-reduce read for fused plans.  Emitted into every routed bench
    row next to the byte model (bench.py)."""
    r1, _ = _route_counts(static.r1)
    r2, _ = _route_counts(static.r2)
    n = static.n
    ff = sum(lv.rows * 128 for lv in static.ff.levels) / n
    out = {"r1": float(r1), "ff": round(ff, 2)}
    if hasattr(static, "n2"):  # FusedStatic
        out["r2"] = round(r2 * static.n2 / n, 2)
        if getattr(static, "mx", None) is not None:
            # MXREDUCE (ISSUE 7): the final pass group and the segmented
            # reduction share ONE kernel that reads the group space once
            # and writes only the tiny totals column — half a read+write
            # sweep, and the separate masked group-reduce sweep is GONE
            # (r2 above already counts only the prefix groups)
            out["mx"] = round(0.5 * static.n2 / n, 2)
            out["reduce"] = 0.0
        else:
            out["reduce"] = round(static.n2 / n, 2)  # masked group-reduce read
        vr, _ = _route_counts(static.vr)
        out["vr"] = round(vr * static.nv_route / n, 2)
    else:
        out["r2"] = float(r2)
        out["reduce"] = float(REDUCE_HBM_PASSES[method])
    out["total"] = round(sum(out.values()), 2)
    return out


def pull_hbm_passes(method: str = "scan") -> dict:
    """Full-array HBM sweep accounting for the DIRECT (unrouted) pull
    iteration, so every bench row reports the same field family: one
    per-edge gather sweep + the reduce method's sweeps."""
    r = REDUCE_HBM_PASSES[method]
    return {"gather": 1.0, "reduce": float(r), "total": round(1.0 + r, 2)}


def routed_pull_iter_model(static, ne: int, nv: int,
                            state_bytes: int = 4,
                            method: str = "scan") -> TrafficModel:
    """One ROUTED pull iteration (ops/expand.py) from its plan static.

    Every routed pass streams the value array (read+write) plus its
    int32 index array over the pass's space; fill-forward is a
    geometric ~1.01 lane passes; the fused variant adds the group-
    layout edge_value/mask pass, the reduce pass, and the small
    accumulator route.  A PASS-FUSED route (StaticRoutePF) pays the
    data read+write once per fusion GROUP — the in-group intermediates
    live in VMEM — while every gather step still reads its index tile.
    Useful FLOPs are the per-edge combines + apply, as in
    pull_iter_model — routing moves bits, it does not compute."""
    v = state_bytes

    def route_bytes(r, space):
        data_passes, idx_reads = _route_counts(r)
        return space * (data_passes * 2 * v + idx_reads * 4)

    b = route_bytes(static.r1, static.n)
    ff_elems = sum(lv.rows * 128 for lv in static.ff.levels)
    b += ff_elems * (2 * v + 4 + 1)  # lane gather + idx + ext-mask byte
    if hasattr(static, "n2"):  # FusedStatic: fused reduce half
        b += route_bytes(static.r2, static.n2)
        mxg = getattr(static, "mx", None)
        if mxg is not None:
            # MXREDUCE final group: one read of the group space + its
            # gather-step index tiles + the rank tile (+ f32 weights),
            # totals column write is negligible; no separate mask /
            # reduce sweep.  int32 tile widths, like route_bytes.
            b += static.n2 * (v + len(mxg.steps) * 4 + 4
                              + (4 if static.weighted else 0))
        else:
            # edge_value + mask + group reshape-reduce: one streaming
            # pass over the group space (weights f32 + mask byte reads)
            b += static.n2 * (2 * v + 4 + 1)
        b += route_bytes(static.vr, static.nv_route)
        dev_reduce = ne  # element-wise group adds
    else:  # ExpandStatic: values land in CSC order, the chosen
        # segmented reducer still runs — charge its method terms
        b += route_bytes(static.r2, static.n)
        b += ne * int(_reduce_bytes_per_edge(method, state_bytes, 1))
        dev_reduce = ne * _reduce_device_flops_per_edge(method, 1)
    b += nv * (2 * v + 4)  # apply: old + new state + degree
    flops = ne + nv * 3
    return TrafficModel(b, flops, dev_reduce + nv * 3)


def edge2d_iter_model(
    ne: int,
    nv: int,
    num_parts: int,
    edge_shards: int,
    method: str = "scan",
    state_bytes: int = 4,
    weighted: bool = False,
    apply_flops_per_vertex: int = 3,
) -> dict:
    """One 2-D (parts x edge) iteration, WHOLE-JOB accounting summed
    over all P*EP devices (parallel/edge2d.py) — closes VERDICT r4 weak
    #4 (the layout's per-iteration cost was unmodeled).

    Components:
      * ``hbm``: per-edge gather+reduce (each real edge processed once,
        identical to the 1-D model) + the vertex apply, which runs
        REPLICATED on every edge shard — its traffic scales by EP (the
        useful-FLOPs figure does not: replication is redundancy).
      * ``ici_bytes``: the two exchanges per iteration —
          - all_gather of the part-sharded state into EVERY edge-column
            replica: each of the P*EP devices receives the (P-1)/P
            remote share of the nv-state => P*EP * (P-1)/P * nv * sb;
          - psum of the (V,) partial accumulators over the edge axis
            (ring all-reduce): per part column 2*(EP-1) * (nv/P) * 4
            accumulator bytes (f32), summed over P columns.
        EP == 1 degenerates to the 1-D allgather exchange term.
    The model makes the tradeoff inspectable: edge sharding divides the
    per-device EDGE arrays by EP (capacity win) while multiplying the
    state exchange by EP (ICI cost) — exactly why it is a capacity
    feature, not a speed feature."""
    base = pull_iter_model(
        ne, nv, method, state_bytes, 1, weighted, False,
        apply_flops_per_vertex,
    )
    # replicate the vertex apply term (2v + 4 bytes, apply flops) EP-1
    # extra times as ISSUED work
    v = state_bytes
    extra_apply_bytes = (edge_shards - 1) * nv * (2 * v + 4)
    extra_apply_flops = (edge_shards - 1) * nv * apply_flops_per_vertex
    hbm = TrafficModel(
        base.bytes_moved + extra_apply_bytes,
        base.flops,
        base.device_flops + extra_apply_flops,
    )
    gather_ici = num_parts * edge_shards * (
        (num_parts - 1) * nv * state_bytes // max(num_parts, 1)
    )
    psum_ici = num_parts * 2 * (edge_shards - 1) * (nv // max(num_parts, 1)) * 4
    return {"hbm": hbm, "ici_bytes": int(gather_ici + psum_ici),
            "replication_factor": edge_shards}


def push_sparse_edge_model(
    state_bytes: int = 4, weighted: bool = False
) -> TrafficModel:
    """Per TRAVERSED frontier out-edge in a sparse push round
    (engine/push.py sparse_part_step: compact the frontier's out-edges,
    scatter-combine by destination — the sssp_push_kernel envelope,
    sssp_gpu.cu:198-244).  Bytes: dst id + value scatter read/write
    (+ weight); the queue/binary-search costs are per-frontier-vertex,
    amortized below an edge each on power-law graphs."""
    b = 4 + 2 * state_bytes + (4 if weighted else 0)
    return TrafficModel(b, 1, 1)


def push_run_model(
    ne: int,
    nv: int,
    traversed: int,
    dense_rounds: int,
    method: str = "scan",
    state_bytes: int = 4,
    weighted: bool = False,
    compact_unique: int = 0,
) -> TrafficModel:
    """A whole frontier-app run: ``dense_rounds`` full pull-style sweeps
    (direction-optimized dense mode walks every in-edge) + the remaining
    ``traversed - dense_rounds*ne`` sparse frontier edges.  Matches the
    engine's exact accounting (PushCarry.edges / dense_rounds).
    ``compact_unique``: see pull_iter_model (dense rounds only)."""
    dense = pull_iter_model(
        ne, nv, method, state_bytes, 1, weighted, False, 1,
        compact_unique=compact_unique,
    ).scale(dense_rounds)
    sparse_edges = max(0, traversed - dense_rounds * ne)
    sparse = push_sparse_edge_model(state_bytes, weighted).scale(sparse_edges)
    # queue rebuild: every round scans the changed mask + rewrites queues
    rounds = dense_rounds + (1 if sparse_edges else 0)
    return dense + sparse + TrafficModel(rounds * nv * (1 + 4), 0, 0)


def serve_summarize(num_queries: int, elapsed_s: float,
                    traversed_edges: int, latencies_s=None) -> dict:
    """JSON-ready serving fields (the summarize() analog where the unit
    of work is a REQUEST): queries/sec, aggregate traversed-edge GTEPS,
    and latency percentiles (ms).  Batch occupancy lives with the batch
    records (serve/metrics.ServeMetrics.summary) — one implementation."""
    from lux_tpu.utils.timing import percentiles

    out = {
        "qps": round(num_queries / elapsed_s, 3) if elapsed_s > 0 else 0.0,
        "queries": int(num_queries),
        "gteps_aggregate": round(traversed_edges / elapsed_s / 1e9, 4)
        if elapsed_s > 0 else 0.0,
        "traversed_edges": int(traversed_edges),
    }
    if latencies_s:
        out["latency_ms"] = {
            k: round(v * 1e3, 3)
            for k, v in percentiles(latencies_s).items()
        }
    return out


def summarize(model: TrafficModel, elapsed_s: float, edges_done: int) -> dict:
    """JSON-ready roofline fields for a measured run."""
    out = {
        "bytes_moved": int(model.bytes_moved),
        "flops": int(model.flops),
        "device_flops": int(model.device_flops),
        "bytes_per_edge": round(model.bytes_moved / max(edges_done, 1), 2),
        "achieved_GBps": round(model.bytes_moved / elapsed_s / 1e9, 3),
        "achieved_GFLOPs": round(model.flops / elapsed_s / 1e9, 3),
    }
    import os

    peak = os.environ.get("LUX_PEAK_GBPS")
    if peak:
        out["frac_bw_roof"] = round(
            (model.bytes_moved / elapsed_s / 1e9) / float(peak), 4
        )
    return out
