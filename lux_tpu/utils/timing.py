"""Timers + per-iteration stats.

Equivalent of the reference's Realm::Clock wall timers and ELAPSED TIME
print (pagerank/pagerank.cc:108-118) and the -verbose per-iteration
activeNodes/loadTime/compTime/updateTime breakdown (sssp_gpu.cu:513-518).
On TPU, `block_until_ready` is the quiescing fence (the analog of the
execution fence + TimingLauncher at sssp/sssp.cc:132-135).
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import List, Optional

import jax

log = logging.getLogger("lux_tpu")


class Timer:
    """Wall-clock timer with a device fence on stop."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.elapsed = 0.0

    def stop(self, *fence_on) -> float:
        for x in fence_on:
            jax.block_until_ready(x)
        self.elapsed = time.perf_counter() - self.t0
        return self.elapsed


@dataclasses.dataclass
class IterStat:
    it: int
    active: int
    seconds: float
    #: per-phase wall times (s), when the driver steps the iteration as
    #: fenced load/comp/update sub-steps (the reference's verbose kernel
    #: timer split, sssp_gpu.cu:513-518); None on whole-iteration records
    load_s: Optional[float] = None
    comp_s: Optional[float] = None
    update_s: Optional[float] = None


class IterStats:
    """Collects and prints per-iteration stats in verbose mode."""

    def __init__(self, verbose: bool = False):
        self.verbose = verbose
        self.stats: List[IterStat] = []

    def record(self, it: int, active: int, seconds: float):
        self.stats.append(IterStat(it, active, seconds))
        if self.verbose:
            print(f"iter {it:4d}: activeNodes({active}) time({seconds*1e3:.3f} ms)")

    def record_phases(self, it: int, active: int, load_s: float,
                      comp_s: float, update_s: float):
        total = load_s + comp_s + update_s
        self.stats.append(IterStat(it, active, total, load_s, comp_s, update_s))
        if self.verbose:
            print(
                f"iter {it:4d}: activeNodes({active}) "
                f"loadTime({load_s*1e3:.3f} ms) "
                f"compTime({comp_s*1e3:.3f} ms) "
                f"updateTime({update_s*1e3:.3f} ms)"
            )

    @property
    def total_active(self) -> int:
        return sum(s.active for s in self.stats)

    def phase_totals(self):
        """(load, comp, update) sums in seconds over recorded iterations."""
        return (
            sum(s.load_s or 0.0 for s in self.stats),
            sum(s.comp_s or 0.0 for s in self.stats),
            sum(s.update_s or 0.0 for s in self.stats),
        )


def percentiles(values, ps=(50, 95, 99)) -> dict:
    """{"p50": ..., ...} over ``values`` (nearest-rank on the sorted
    sample — the convention serving dashboards expect: p99 of 100 samples
    is the 99th largest, never an interpolated value that no request
    actually experienced).  Empty input yields an empty dict."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {}
    out = {}
    for p in ps:
        rank = max(int((p / 100.0) * len(vals) + 0.999999) - 1, 0)
        out[f"p{p}"] = vals[min(rank, len(vals) - 1)]
    return out


class LatencyHistogram:
    """Per-request latency recorder for the serving path: record seconds,
    summarize as millisecond percentiles (the structured-stats sibling of
    IterStats — requests instead of iterations).

    Bounded: past ``max_samples`` the recorder switches to reservoir
    sampling (uniform over the full stream, deterministic seed), so a
    long-lived service keeps O(max_samples) memory and statistically
    valid percentiles instead of one float per request forever."""

    def __init__(self, max_samples: int = 65_536):
        import random

        self.samples: List[float] = []
        self.count = 0
        self.max_samples = max_samples
        self._rng = random.Random(0x1c3)

    def record(self, seconds: float):
        self.count += 1
        if len(self.samples) < self.max_samples:
            self.samples.append(float(seconds))
        else:
            j = self._rng.randrange(self.count)
            if j < self.max_samples:
                self.samples[j] = float(seconds)

    def __len__(self) -> int:
        return self.count

    def summary_ms(self, ps=(50, 95, 99)) -> dict:
        return {
            k: round(v * 1e3, 3) for k, v in percentiles(self.samples, ps).items()
        }


def report_elapsed(seconds: float, ne: int, iters: int,
                   traversed: Optional[int] = None) -> float:
    """Print the end-of-run summary; returns GTEPS (BASELINE.md metric:
    fixed-iteration apps use iters*ne, frontier apps use actually-traversed
    edge counts)."""
    edges = traversed if traversed is not None else iters * ne
    gteps = edges / seconds / 1e9 if seconds > 0 else float("nan")
    print(f"ELAPSED TIME = {seconds:.7f} s")
    print(f"ITERATIONS   = {iters}")
    print(f"GTEPS        = {gteps:.4f}")
    return gteps
