"""The restricted expression language vertex-program specs are written in.

A spec field (``init`` / ``edge`` / ``apply`` / ``frontier``) is a short
straight-line program in Python SYNTAX but not Python semantics: a
sequence of ``name = expression`` bindings followed by one final
expression, compiled through :mod:`ast` against a CLOSED vocabulary —
names resolve to the lowering environment (engine-supplied arrays plus
program parameters), calls resolve to the builtin table below, and every
other construct (attribute access, subscripts, comprehensions, lambdas,
imports, statements beyond assignment) is rejected at definition time.
There is no ``eval``/``exec`` of user text (luxcheck policy family): the
AST is walked into nested closures once per distinct source string
(cached), so evaluating a spec during tracing costs dict lookups.

Why a DSL instead of Python callables: specs must be DATA — hashable,
comparable, printable — so compiled programs participate in the engines'
jit-static and lru compile caches exactly like the hand-wired program
dataclasses they replaced (two equal specs ARE one program: zero
retrace, LUX-J1), and so a new scenario is a config edit reviewable as
config (arXiv:2210.06438's fine-grained-task aggregation argument).

Vocabulary (beyond ``+ - * / // % ** << >> & | ^ ~ -x`` and single
comparisons):

  where(c, a, b)        jnp.where
  maximum / minimum     elementwise (the monoid ops)
  abs(x), sqrt(x)       sqrt keeps Python/NumPy scalars scalar (trace-
                        time constants fold in float64, like hand code)
  f32/i32/u32(x)        dtype cast: scalars via the NumPy scalar type
                        (== jnp.float32(v) in the hand-wired bodies),
                        arrays via .astype
  cast(x, dt)           astype to a dtype NAME (a param or a literal)
  lane(x)               x[..., None] — broadcast a per-vertex/edge
                        column against a trailing feature/query axis
  row(x)                x[None, :]
  arange(n)             int32 iota (n is a trace-time int param)
  onehot(x, n)          (len(x), n) float32 one-hot of an int vector
  fullk(ref, n, v)      (len(ref), n) float32 filled with v
  rowsum(x)             jnp.sum(x, axis=-1, keepdims=True)
  sum_lanes(x)          jnp.sum(x, axis=-1) — collapse a feature axis
  popcount(x)           jax.lax.population_count
  isin(x, vals)         membership of x in a (small) tuple param
  dot_lanes(a, b, mode) the CF error-dot K-contraction
                        (models.colfilter.err_dot: "vpu" | "mxu")
"""
from __future__ import annotations

import ast
import functools
import operator
from typing import Any, Callable, Dict

import numpy as np


class SpecSyntaxError(ValueError):
    """A spec expression used a construct outside the language."""


def _is_scalar(x) -> bool:
    return isinstance(x, (bool, int, float, np.bool_, np.number))


def _cast(x, dt):
    """Dtype cast matching the hand-wired idioms bitwise: Python/NumPy
    scalars through the NumPy scalar type (``jnp.float32(v)``), arrays
    through ``.astype``.  Same-dtype astype is a no-op."""
    if _is_scalar(x):
        return np.dtype(dt).type(x)
    return x.astype(dt)


def _sqrt(x):
    # trace-time constants stay float64 Python-side (np.sqrt(1.0/k) in
    # the hand-wired CF init); arrays go through jnp
    if _is_scalar(x):
        return float(np.sqrt(x))
    import jax.numpy as jnp

    return jnp.sqrt(x)


def _isin(x, vals):
    if not isinstance(vals, (tuple, list)):
        raise SpecSyntaxError(
            f"isin() needs a tuple parameter, got {type(vals).__name__}")
    import jax.numpy as jnp

    out = x == vals[0]
    for v in vals[1:]:
        out = jnp.logical_or(out, x == v)
    return out


def _builtins() -> Dict[str, Callable]:
    """The call vocabulary.  Built lazily (jax import) and returned as a
    fresh dict so a caller can never mutate the shared table."""
    import jax
    import jax.numpy as jnp

    def dot_lanes(a, b, mode):
        from lux_tpu.models.colfilter import err_dot

        return err_dot(a, b, mode)

    return {
        "where": jnp.where,
        "maximum": jnp.maximum,
        "minimum": jnp.minimum,
        "abs": jnp.abs,
        "sqrt": _sqrt,
        "f32": functools.partial(_cast, dt="float32"),
        "i32": functools.partial(_cast, dt="int32"),
        "u32": functools.partial(_cast, dt="uint32"),
        "cast": _cast,
        "lane": lambda x: x[..., None],
        "row": lambda x: x[None, :],
        "arange": lambda n: jnp.arange(n, dtype=jnp.int32),
        "onehot": lambda x, n: (
            jnp.arange(n, dtype=jnp.int32)[None, :] == x[..., None]
        ).astype(jnp.float32),
        "fullk": lambda ref, n, v: jnp.full(
            (ref.shape[0], int(n)), v, jnp.float32),
        "rowsum": lambda x: jnp.sum(x, axis=-1, keepdims=True),
        "sum_lanes": lambda x: jnp.sum(x, axis=-1),
        "popcount": jax.lax.population_count,
        "isin": _isin,
        "dot_lanes": dot_lanes,
    }


def _lnot(x):
    import jax.numpy as jnp

    return ~x if not _is_scalar(x) else jnp.logical_not(x)


_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
    ast.LShift: operator.lshift,
    ast.RShift: operator.rshift,
    ast.BitAnd: operator.and_,
    ast.BitOr: operator.or_,
    ast.BitXor: operator.xor,
}

_CMPOPS = {
    ast.Eq: operator.eq,
    ast.NotEq: operator.ne,
    ast.Lt: operator.lt,
    ast.LtE: operator.le,
    ast.Gt: operator.gt,
    ast.GtE: operator.ge,
}

_UNOPS = {
    ast.USub: operator.neg,
    ast.Invert: _lnot,
}


def _err(src: str, node: ast.AST, msg: str) -> SpecSyntaxError:
    line = src.splitlines()[node.lineno - 1] if hasattr(node, "lineno") else src
    return SpecSyntaxError(f"{msg} (in spec expression: {line.strip()!r})")


def _compile_expr(node: ast.expr, src: str) -> Callable[[dict], Any]:
    """Recursively lower one expression node to an env -> value closure."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, (bool, int, float, str)):
            v = node.value
            return lambda env: v
        raise _err(src, node, f"constant {node.value!r} is not allowed")
    if isinstance(node, ast.Name):
        name = node.id
        marker = object()

        def load(env, name=name, marker=marker):
            v = env.get(name, marker)
            if v is marker:
                raise SpecSyntaxError(
                    f"unknown name {name!r}; available here: "
                    + ", ".join(sorted(k for k in env if not k.startswith("_"))))
            return v

        return load
    if isinstance(node, ast.BinOp):
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise _err(src, node, f"operator {type(node.op).__name__} "
                                  "is not in the language")
        lf = _compile_expr(node.left, src)
        rf = _compile_expr(node.right, src)
        return lambda env: op(lf(env), rf(env))
    if isinstance(node, ast.UnaryOp):
        op = _UNOPS.get(type(node.op))
        if op is None:
            raise _err(src, node, f"unary {type(node.op).__name__} "
                                  "is not in the language")
        vf = _compile_expr(node.operand, src)
        return lambda env: op(vf(env))
    if isinstance(node, ast.Compare):
        if len(node.ops) != 1:
            raise _err(src, node, "chained comparisons are not allowed")
        op = _CMPOPS.get(type(node.ops[0]))
        if op is None:
            raise _err(src, node, f"comparison {type(node.ops[0]).__name__} "
                                  "is not in the language")
        lf = _compile_expr(node.left, src)
        rf = _compile_expr(node.comparators[0], src)
        return lambda env: op(lf(env), rf(env))
    if isinstance(node, ast.Call):
        if node.keywords:
            raise _err(src, node, "keyword arguments are not allowed")
        if not isinstance(node.func, ast.Name):
            raise _err(src, node, "only builtin-name calls are allowed")
        fname = node.func.id
        argfs = [_compile_expr(a, src) for a in node.args]

        def call(env, fname=fname, argfs=argfs):
            fn = env["_builtins"].get(fname)
            if fn is None:
                raise SpecSyntaxError(
                    f"unknown function {fname!r}; builtins: "
                    + ", ".join(sorted(env["_builtins"])))
            return fn(*[f(env) for f in argfs])

        return call
    if isinstance(node, ast.Tuple):
        elfs = [_compile_expr(e, src) for e in node.elts]
        return lambda env: tuple(f(env) for f in elfs)
    raise _err(src, node, f"{type(node).__name__} is not in the language")


@functools.lru_cache(maxsize=1024)
def compile_source(src: str):
    """Compile a spec field to ``run(env) -> value``.  ``src`` is a
    sequence of single-name assignments ending in one expression;
    rebinding a name is allowed (straight-line SSA-ish style).  Raises
    :class:`SpecSyntaxError` for anything outside the language — at
    spec-definition time, not at trace time."""
    try:
        tree = ast.parse(src, mode="exec")
    except SyntaxError as e:
        raise SpecSyntaxError(f"spec expression does not parse: {e}") from None
    if not tree.body:
        raise SpecSyntaxError("empty spec expression")
    steps = []
    for stmt in tree.body[:-1]:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                or not isinstance(stmt.targets[0], ast.Name):
            raise _err(src, stmt,
                       "only 'name = expression' bindings may precede the "
                       "final expression")
        steps.append((stmt.targets[0].id,
                      _compile_expr(stmt.value, src)))
    last = tree.body[-1]
    if not isinstance(last, ast.Expr):
        raise _err(src, last, "a spec must END in a bare expression "
                              "(its value is the result)")
    final = _compile_expr(last.value, src)

    def run(env: dict):
        scope = dict(env)
        scope["_builtins"] = _builtins()
        for name, fn in steps:
            scope[name] = fn(scope)
        return final(scope)

    return run


def run(src: str, env: dict):
    """Evaluate a spec field against ``env`` (parameters + lowering
    arrays).  Parsing is cached per distinct source string."""
    return compile_source(src)(env)


def check(src: str) -> None:
    """Parse-validate a spec field (definition-time gate); no-op result."""
    compile_source(src)
