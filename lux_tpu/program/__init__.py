"""luxprog — the declarative vertex-program compiler (ISSUE 13).

Lux's whole design is one fixed vertex-program contract — the paper's
pull/push task bodies are exactly ``init / compute(gather) /
update(apply)`` — yet the apps used to hand-wire gather/apply/scatter
into the engines, so every new scenario cost a PR.  This package turns
that contract into DATA:

  * :mod:`lux_tpu.program.expr` — the restricted expression language
    spec fields are written in (Python syntax, closed vocabulary, no
    ``eval``; compiled once per distinct source through ``ast``).
  * :mod:`lux_tpu.program.spec` — :class:`VertexProgramSpec` (the
    declarative program: state init, edge message, a reduce from the
    ``ops/segment.py`` monoid set, apply/update, convergence rule,
    frontier rule) plus the compiled-program bases that implement BOTH
    engine protocols (pull's ``init_state/edge_value/apply`` and push's
    ``init_state/init_frontier/relax``) and the serve Q-axis lift.
  * :mod:`lux_tpu.program.library` — the named spec registry: the four
    reference apps re-expressed as specs (``models/*`` classes now
    evaluate these — the hand-wired bodies are DELETED, not shadowed)
    and the four payoff workloads (bfs, kcore, labelprop, triangles).
  * :mod:`lux_tpu.program.workloads` — runners + NumPy oracles for the
    new workloads, lowering through the EXISTING engine entry points
    (zero edits inside the engine hot-loop bodies).

Because a compiled program is a frozen dataclass over the spec and its
parameter bindings, two equal specs ARE the same program to every jit
static and lru compile cache: spec-compiled programs hit the exact
plan/trace caches the hand-wired dataclasses did (LUX-J1; pinned by
tests/test_program.py's ``_cache_size`` probes).  This is the
fine-grained-task-to-portable-kernel aggregation argument of
arXiv:2210.06438 applied to the repo: express the per-vertex task once,
declaratively, and lower it onto every execution surface.

See docs/PROGRAMS.md for the spec schema and the lowering matrix.
"""
from lux_tpu.program.spec import (  # noqa: F401
    BatchedSpecBacked,
    BatchedSpecProgram,
    SpecBacked,
    SpecProgram,
    VertexProgramSpec,
    active_changed,
)
