"""VertexProgramSpec — the declarative vertex program — and its compiled
forms.

A spec is the whole app contract as data (the paper's ``init / compute /
update`` task bodies, SURVEY.md §2): per-vertex state initialization,
the per-edge message, a combiner from the :mod:`lux_tpu.ops.segment`
monoid set, the apply/update rule, the convergence rule, and (for
frontier programs) the initial-frontier rule.  Every field is a string
in the :mod:`lux_tpu.program.expr` language, so a spec is hashable,
comparable, and printable — which is exactly what the engines need from
a program: their jit statics and lru compile caches key on the program
object, and two equal specs ARE one program (zero retrace across
reconstruction; tests/test_program.py pins the ``_cache_size`` probes).

The compiled forms implement the EXISTING engine protocols verbatim —
no engine edit was needed to consume them:

  * :class:`SpecBacked` / :class:`SpecProgram` — pull's
    ``init_state/edge_value/apply`` (engine/pull.PullProgram) AND push's
    ``init_state/init_frontier/relax`` (engine/push.PushProgram) from
    one spec, so a program runs on pull fixed/until (direct, routed,
    routed-pf), push (sparse/dense direction switch), the dist engines,
    and the mutation overlays of both engines unchanged.
  * :class:`BatchedSpecBacked` / :class:`BatchedSpecProgram` — the
    serve Q-axis lift (serve/batched.QueryProgram): the spec's declared
    ``query_param`` binds to the traced (Q,) query vector on a TRAILING
    axis and every per-vertex name broadcasts with ``[:, None]``, so
    column q of a batched run is bitwise the single-query program.

Environment names a spec may use (beyond its own parameters):

  init:      vid, degree, vtx_mask          -> per-vertex state
  edge:      src, weight, dst               -> per-edge message
             (``dst`` — the destination's CURRENT state — exists on the
             pull surfaces only; push relax sees src/weight)
  apply:     old, acc, vid, degree, vtx_mask -> new per-vertex state
  frontier:  vid, state, vtx_mask           -> initial active mask
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

from lux_tpu.program import expr

REDUCES = ("sum", "min", "max")
CONVERGENCES = ("fixed", "quiescent")


@dataclasses.dataclass(frozen=True)
class VertexProgramSpec:
    """One declarative vertex program.  ``edge`` doubles as pull's
    edge_value and push's relax (they are the same message along the
    edge); ``apply`` may be empty for reduce-only phases (triangle
    counting's phase 2) and ``frontier`` empty for pull-only programs.
    ``query_param`` names the parameter that becomes the serve Q axis
    ("" = not Q-liftable).  ``state_width`` documents the trailing
    feature width (1 = scalar state); width-parameterized specs (e.g.
    labelprop's ``labels``) carry the width on the compiled program
    instead."""

    name: str
    reduce: str
    init: str
    edge: str
    apply: str = ""
    frontier: str = ""
    convergence: str = "fixed"
    state_width: int = 1
    needs_dst_state: bool = False
    query_param: str = ""

    def __post_init__(self):
        if self.reduce not in REDUCES:
            raise ValueError(
                f"spec {self.name!r}: reduce must be one of {REDUCES} "
                f"(the ops/segment.py monoid set), got {self.reduce!r}")
        if self.convergence not in CONVERGENCES:
            raise ValueError(
                f"spec {self.name!r}: convergence must be one of "
                f"{CONVERGENCES}, got {self.convergence!r}")
        for field in ("init", "edge", "apply", "frontier"):
            src = getattr(self, field)
            if src:
                try:
                    expr.check(src)
                except expr.SpecSyntaxError as e:
                    raise expr.SpecSyntaxError(
                        f"spec {self.name!r}.{field}: {e}") from None


def active_changed(old, new):
    """Top-level (hashable) convergence probe shared by every quiescent
    spec program: per-part count of state entries that moved — the
    run_pull_until ``active_fn`` contract (models/components
    active_count_stacked generalized over trailing state axes)."""
    import jax.numpy as jnp

    return jnp.sum(old != new,
                   axis=tuple(range(1, old.ndim))).astype(jnp.int32)


class SpecBacked:
    """Engine-protocol methods evaluated from a declarative spec.

    Subclasses provide ``spec`` (a :class:`VertexProgramSpec`, as a
    property or dataclass field) and ``_env()`` (the parameter
    bindings).  The five protocol methods below ARE the former
    hand-wired gather/apply bodies of the model classes — there is no
    shadow implementation left."""

    def _env(self) -> dict:
        return {}

    def _eval(self, source: str, **env):
        return expr.run(source, {**self._env(), **env})

    # --- shared contract -------------------------------------------------
    @property
    def reduce(self) -> str:
        return self.spec.reduce

    @property
    def needs_dst_state(self) -> bool:
        return self.spec.needs_dst_state

    def init_state(self, global_vid, degree, vtx_mask):
        return self._eval(self.spec.init, vid=global_vid, degree=degree,
                          vtx_mask=vtx_mask)

    # --- pull engine contract -------------------------------------------
    def edge_value(self, src_state, weight, dst_state=None):
        return self._eval(self.spec.edge, src=src_state, weight=weight,
                          dst=dst_state)

    def apply(self, old_local, acc, arrays):
        if not self.spec.apply:
            raise ValueError(
                f"spec {self.spec.name!r} is a reduce-only phase (no "
                "apply rule); run it through the load/comp phase split "
                "(program.workloads.reduce_phase), not an update loop")
        env = {"old": old_local, "acc": acc}
        # the bucketed exchange drivers (ring/scatter/edge2d/feat) pass
        # duck-typed views carrying only the fields their applies need
        # (vtx_mask/degree); bind what exists — a spec referencing a
        # missing name fails with the evaluator's unknown-name error
        for name, attr in (("vid", "global_vid"), ("degree", "degree"),
                           ("vtx_mask", "vtx_mask")):
            if hasattr(arrays, attr):
                env[name] = getattr(arrays, attr)
        return self._eval(self.spec.apply, **env)

    # --- push engine contract -------------------------------------------
    def init_frontier(self, global_vid, state, vtx_mask):
        if not self.spec.frontier:
            raise ValueError(
                f"spec {self.spec.name!r} declares no frontier rule; "
                "it lowers onto the pull engines only")
        return self._eval(self.spec.frontier, vid=global_vid, state=state,
                          vtx_mask=vtx_mask)

    def relax(self, src_val, weight):
        if self.spec.needs_dst_state:
            raise ValueError(
                f"spec {self.spec.name!r} reads the destination state "
                "per edge; the push (scatter) lowering has no dst read "
                "— run it on a pull surface")
        return self._eval(self.spec.edge, src=src_val, weight=weight,
                          dst=None)


@dataclasses.dataclass(frozen=True)
class SpecProgram(SpecBacked):
    """A spec compiled against concrete parameter bindings — the generic
    form the registry workloads and the ``apps.run`` driver use (the
    model classes in ``models/*`` are named spec-backed dataclasses with
    the same machinery).  ``args`` is a sorted tuple of (name, value)
    pairs; values must be hashable (ints, floats, strings, tuples).
    ``width`` is the trailing state width this instance runs at (for
    width-parameterized specs)."""

    spec: VertexProgramSpec
    args: Tuple[Tuple[str, Any], ...] = ()
    width: int = 0

    def __post_init__(self):
        object.__setattr__(self, "args", tuple(sorted(self.args)))
        hash(self.args)  # fail at construction, not inside a jit cache

    def _env(self) -> dict:
        return dict(self.args)

    @property
    def k(self) -> int:
        return self.width or self.spec.state_width


def bind(spec: VertexProgramSpec, width: int = 0, **params) -> SpecProgram:
    """Sugar: ``bind(library.BFS, nv=..., sources=(0, 5))``."""
    return SpecProgram(spec, tuple(sorted(params.items())), width)


class BatchedSpecBacked:
    """The serve Q-axis lift of a spec (serve/batched.QueryProgram
    contract): state carries a TRAILING query axis, the spec's declared
    ``query_param`` binds to the traced (Q,) query vector as a leading
    broadcast row, and every per-vertex name binds with a trailing
    broadcast lane — so the SAME init/edge/apply text lowers to the
    (V, Q) batched step, bitwise equal per column to the single-query
    program (the hand-wired MultiSource* bodies this replaces)."""

    def _env(self) -> dict:
        return {}

    @property
    def reduce(self) -> str:
        return self.spec.reduce

    @property
    def fixpoint(self) -> bool:
        return self.spec.convergence == "quiescent"

    def _qenv(self, global_vid, degree, vtx_mask, queries) -> dict:
        qp = self.spec.query_param
        if not qp:
            raise ValueError(
                f"spec {self.spec.name!r} declares no query_param; it "
                "has no Q-axis serve lowering")
        return {**self._env(), "vid": global_vid[:, None],
                "degree": degree[:, None], "vtx_mask": vtx_mask[:, None],
                qp: queries[None, :]}

    def init_part(self, global_vid, degree, vtx_mask, queries):
        return expr.run(self.spec.init,
                        self._qenv(global_vid, degree, vtx_mask, queries))

    def edge_value(self, src_state, weights):
        return expr.run(self.spec.edge,
                        {**self._env(), "src": src_state,
                         "weight": weights[:, None], "dst": None})

    def apply(self, old_local, acc, arr, queries):
        env = self._qenv(arr.global_vid, arr.degree, arr.vtx_mask, queries)
        env.update(old=old_local, acc=acc)
        return expr.run(self.spec.apply, env)


@dataclasses.dataclass(frozen=True)
class BatchedSpecProgram(BatchedSpecBacked):
    """Generic Q-lifted program (the serve registry's named classes are
    spec-backed dataclasses over the same machinery)."""

    spec: VertexProgramSpec
    args: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "args", tuple(sorted(self.args)))
        hash(self.args)

    def _env(self) -> dict:
        return dict(self.args)
