"""Runners + NumPy oracles for the spec-only workloads (ISSUE 13).

The four payoff workloads — multi-source BFS, k-core decomposition,
seeded label propagation, and weighted triangle counting — exist ONLY as
declarative specs (:mod:`lux_tpu.program.library`) plus the thin host
drivers below, which lower through the EXISTING public engine entry
points (``run_push`` / ``run_pull_until`` / ``run_pull_fixed`` /
``compile_pull_phases`` and their dist twins).  Zero lines changed
inside the engine hot-loop bodies: the compiler, not the engines,
absorbs the new scenarios (the ISSUE 13 acceptance criterion).

Each workload ships a NetworkX-free NumPy oracle (the ``*_reference``
functions) and a ``check_*`` invariant for the CLI's ``-check`` verdict
(the reference apps' CHECK_TASK_ID discipline).

Stress corners, by design:
  * bfs        — frontier/push, sparse->dense direction switch, routed
                 dense rounds; a SEED-SET rule (distance to the nearest
                 of several sources) instead of sssp's single start.
  * kcore      — ITERATIVE PEEL: a host loop over k, each level one
                 spec program run to quiescence, warm-started from the
                 previous level's survivors (k-cores nest).
  * labelprop  — dense pull with a WIDE (V, L) probability state.
  * triangles  — a genuinely new INTERSECTION-HEAVY access pattern the
                 compiler expresses as a TWO-PHASE program: phase 1
                 builds per-vertex neighborhood bitsets (a sum-reduce
                 whose integer sum IS the set union), phase 2 is a
                 reduce-only pass intersecting the src/dst bitsets per
                 edge (the dst-dependent load only pull provides).
"""
from __future__ import annotations

from collections import deque
from typing import Sequence, Tuple

import numpy as np

from lux_tpu.graph.csc import HostGraph, from_edge_list
from lux_tpu.program import library
from lux_tpu.program.spec import SpecProgram, active_changed, bind

#: triangle counting builds (V, ceil(nv/32)) uint32 bitsets — quadratic
#: memory in nv.  Bound it loudly instead of OOMing quietly; the
#: workload is a small-scale bench row by design (LUX_BENCH_APPS opt-in).
TRIANGLES_MAX_NV = 1 << 15


def active_changed_scalar(old, new):
    """Per-part SCALAR active count (the run_pull_until_dist contract;
    top-level so compiled loops cache)."""
    import jax.numpy as jnp

    return jnp.sum(old != new)


def symmetrize(g: HostGraph, unit_weights: bool = False) -> HostGraph:
    """Undirected simple view of ``g``: dedupe unordered pairs, drop
    self-loops, emit BOTH orientations.  Weights: max over the parallel
    directed duplicates of a pair (1 everywhere when the input is
    unweighted or ``unit_weights``) — k-core and triangle counting are
    classically undirected, so their apps run on this view by default."""
    src = np.asarray(g.col_idx, np.int64)
    dst = np.asarray(g.dst_of_edges(), np.int64)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    key = lo * g.nv + hi
    if g.weights is None or unit_weights:
        pairs = np.unique(key)
        w_und = np.ones(pairs.shape[0], np.int32)
    else:
        order = np.argsort(key, kind="stable")
        key_s = key[order]
        w_s = np.asarray(g.weights)[keep][order]
        pairs, first = np.unique(key_s, return_index=True)
        w_und = np.maximum.reduceat(w_s, first).astype(np.int32)
    lo = (pairs // g.nv).astype(np.int64)
    hi = (pairs % g.nv).astype(np.int64)
    es = np.concatenate([lo, hi])
    ed = np.concatenate([hi, lo])
    return from_edge_list(es, ed, g.nv,
                          weights=np.concatenate([w_und, w_und]))


def _pull_setup(g, num_parts: int):
    import jax
    import jax.numpy as jnp

    from lux_tpu.graph.shards import PullShards, build_pull_shards

    shards = g if isinstance(g, PullShards) else build_pull_shards(
        g, num_parts)
    return shards, jax.tree.map(jnp.asarray, shards.arrays)


# ---------------------------------------------------------------------------
# BFS
# ---------------------------------------------------------------------------


def bfs_program(nv: int, sources: Sequence[int]) -> SpecProgram:
    srcs = tuple(sorted(set(int(s) for s in sources)))
    if not srcs:
        raise ValueError("bfs needs at least one source vertex")
    for s in srcs:
        if not 0 <= s < nv:
            raise ValueError(f"bfs source {s} out of range [0, {nv})")
    return bind(library.BFS, nv=nv, sources=srcs)


def bfs(g, sources: Sequence[int], num_parts: int = 1,
        max_iters: int = 10_000, method: str = "auto",
        engine: str = "push", mesh=None, route=None,
        exchange: str = "allgather") -> Tuple[np.ndarray, int]:
    """Multi-source BFS: hop distance to the NEAREST source, INF == nv.
    ``engine="push"`` runs the direction-optimizing frontier engine
    (the workload's home surface; ``route`` routes the dense rounds);
    ``engine="pull"`` runs the pull-until surface — bitwise-identical
    distances (unique min fixpoint).  Returns (dist (nv,), iters)."""
    from lux_tpu.graph.push_shards import PushShards, build_push_shards

    if engine == "push":
        from lux_tpu.engine import push

        if exchange == "ring":
            from lux_tpu.parallel.ring import (PushRingShards,
                                               build_push_ring_shards)

            if mesh is None:
                raise ValueError("bfs exchange='ring' needs a mesh")
            rsh = (g if isinstance(g, PushRingShards)
                   else build_push_ring_shards(g, num_parts))
            prog = bfs_program(rsh.spec.nv, sources)
            final, it, _ = push.run_push_ring(prog, rsh, mesh, max_iters,
                                              method)
            return rsh.scatter_to_global(np.asarray(final)), int(it)
        shards = g if isinstance(g, PushShards) else build_push_shards(
            g, num_parts)
        prog = bfs_program(shards.spec.nv, sources)
        if mesh is None:
            final, it, _ = push.run_push(prog, shards, max_iters, method,
                                         route=route)
        else:
            final, it, _ = push.run_push_dist(prog, shards, mesh,
                                              max_iters, method,
                                              route=route)
        return shards.scatter_to_global(np.asarray(final)), int(it)
    if engine != "pull":
        raise ValueError(f"bfs engine must be 'push' or 'pull', got {engine!r}")
    from lux_tpu.engine import pull

    shards, arrays = _pull_setup(g, num_parts)
    prog = bfs_program(shards.spec.nv, sources)
    state0 = pull.init_state(prog, arrays)
    if mesh is not None:
        if route is not None:
            # run_pull_until_dist has no routed form — dropping the
            # plan silently would misreport what was benchmarked
            raise ValueError(
                "bfs engine='pull' routes single-device runs only; "
                "the dist pull-until driver has no route= path (use "
                "engine='push' for routed distributed dense rounds)")
        from lux_tpu.parallel import dist

        final, it = dist.run_pull_until_dist(
            prog, shards.spec, shards.arrays, state0, max_iters,
            active_changed_scalar, mesh, method)
    else:
        final, it = pull.run_pull_until(
            prog, shards.spec, arrays, state0, max_iters, active_changed,
            method, route=route)
    return shards.scatter_to_global(np.asarray(final)), int(it)


def bfs_reference(g: HostGraph, sources: Sequence[int]) -> np.ndarray:
    """Host multi-source BFS oracle over the out-adjacency (CSR) view."""
    csr_row_ptr, csr_dst, _ = g.to_csr()
    dist = np.full(g.nv, g.nv, np.int32)
    dq = deque()
    for s in sorted(set(int(s) for s in sources)):
        dist[s] = 0
        dq.append(s)
    while dq:
        u = dq.popleft()
        for v in csr_dst[csr_row_ptr[u]: csr_row_ptr[u + 1]]:
            if dist[v] == g.nv:
                dist[v] = dist[u] + 1
                dq.append(v)
    return dist


def check_bfs(g: HostGraph, dist: np.ndarray,
              sources: Sequence[int]) -> int:
    """-check invariant — the full min fixpoint, so the gate bounds the
    distances from BOTH sides: every source at 0; every edge satisfies
    dist[dst] <= dist[src] + 1 (reached sources only — the upper
    bound); and every non-source vertex's distance EQUALS
    min over in-edges of dist[src] + 1, INF included (the lower bound:
    an all-zeros answer fails here, not just an over-estimate)."""
    dist = np.asarray(dist, np.int64)
    srcs = set(int(s) for s in sources)
    bad = sum(int(dist[s] != 0) for s in srcs)
    dst = g.dst_of_edges()
    reached = dist[g.col_idx] < g.nv
    bad += int(np.sum((dist[dst] > dist[g.col_idx] + 1) & reached))
    # lower bound via the fixpoint: relax every edge once into a fresh
    # accumulator; a non-source vertex must sit exactly at its best
    # in-edge relaxation (clipped at the INF sentinel nv)
    best = np.full(g.nv, g.nv, np.int64)
    np.minimum.at(best, dst, np.minimum(dist[g.col_idx] + 1, g.nv))
    non_src = np.ones(g.nv, bool)
    non_src[list(srcs)] = False
    bad += int(np.sum(non_src & (dist != best)))
    return bad


# ---------------------------------------------------------------------------
# k-core decomposition
# ---------------------------------------------------------------------------


def kcore(g, kmax: int = 0, num_parts: int = 1, max_iters: int = 10_000,
          method: str = "auto", mesh=None, route=None,
          ) -> Tuple[np.ndarray, int, int]:
    """Coreness per vertex by ITERATIVE PEEL over the in-neighborhood:
    for k = 1, 2, ... run the one-level spec (library.KCORE) to
    quiescence — a vertex survives level k iff it keeps >= k alive
    in-neighbors — warm-starting each level from the previous level's
    survivors (k-cores nest, so the monotone fixpoint carries over).
    Classic undirected coreness: pass a ``symmetrize(g)`` view (the
    app's default).  ``kmax=0`` peels until the core empties.  Each
    level is its own compiled program (k is a static parameter — the
    honest shape of a peel); levels reuse one layout and one ``route``
    plan.  Returns (coreness (nv,) int32, k_max, total_rounds)."""
    from lux_tpu.engine import pull

    shards, arrays = _pull_setup(g, num_parts)
    nv = shards.spec.nv
    coreness = np.zeros(nv, np.int32)
    state = None
    rounds = 0
    k = 1
    while kmax == 0 or k <= kmax:
        prog = bind(library.KCORE, kk=k)
        if state is None:
            state = pull.init_state(prog, arrays)
        if mesh is not None:
            from lux_tpu.parallel import dist

            state, it = dist.run_pull_until_dist(
                prog, shards.spec, shards.arrays, state, max_iters,
                active_changed_scalar, mesh, method)
        else:
            state, it = pull.run_pull_until(
                prog, shards.spec, arrays, state, max_iters,
                active_changed, method, route=route)
        rounds += int(it)
        alive = shards.scatter_to_global(np.asarray(state)) > 0
        if not alive.any():
            break
        coreness[alive] = k
        k += 1
    return coreness, int(coreness.max(initial=0)), rounds


def kcore_reference(g: HostGraph, kmax: int = 0) -> np.ndarray:
    """NumPy peel oracle (same in-neighborhood semantics)."""
    nv = g.nv
    dst = g.dst_of_edges()
    coreness = np.zeros(nv, np.int32)
    alive = np.ones(nv, bool)
    k = 1
    while kmax == 0 or k <= kmax:
        while True:
            cnt = np.zeros(nv, np.int64)
            live = alive[g.col_idx] & alive[dst]
            np.add.at(cnt, dst[live], 1)
            new = alive & (cnt >= k)
            if (new == alive).all():
                break
            alive = new
        if not alive.any():
            break
        coreness[alive] = k
        k += 1
    return coreness


def check_kcore(g: HostGraph, coreness: np.ndarray) -> int:
    """-check invariant: inside the level-c subgraph induced by
    {v: coreness[v] >= c}, every member keeps >= c in-neighbors — for
    c = each vertex's own coreness.  One vectorized pass: count
    in-neighbors u with coreness[u] >= coreness[v]."""
    coreness = np.asarray(coreness, np.int64)
    dst = g.dst_of_edges()
    cnt = np.zeros(g.nv, np.int64)
    np.add.at(cnt, dst, (coreness[g.col_idx] >= coreness[dst]).astype(
        np.int64))
    return int(np.sum((coreness > 0) & (cnt < coreness)))


# ---------------------------------------------------------------------------
# label propagation
# ---------------------------------------------------------------------------


def labelprop_program(labels: int, stride: int) -> SpecProgram:
    if labels < 2:
        raise ValueError(f"labelprop needs >= 2 labels, got {labels}")
    if stride < 1:
        raise ValueError(f"labelprop seed stride must be >= 1, got {stride}")
    return bind(library.LABELPROP, labels=int(labels), stride=int(stride),
                width=int(labels))


def labelprop(g, labels: int = 8, stride: int = 16, num_iters: int = 10,
              num_parts: int = 1, method: str = "auto", mesh=None,
              ) -> np.ndarray:
    """Seeded multi-class label propagation (dense pull, WIDE state):
    every ``stride``-th vertex is pinned to one-hot class
    ``vid % labels``; everyone else averages incoming class rows for
    ``num_iters`` fixed iterations.  Returns (nv, labels) float32
    class probabilities."""
    from lux_tpu.engine import pull

    shards, arrays = _pull_setup(g, num_parts)
    prog = labelprop_program(labels, stride)
    state0 = pull.init_state(prog, arrays)
    if mesh is not None:
        from lux_tpu.parallel import dist

        final = dist.run_pull_fixed_dist(
            prog, shards.spec, shards.arrays, state0, num_iters, mesh,
            method)
    else:
        final = pull.run_pull_fixed(prog, shards.spec, arrays, state0,
                                    num_iters, method)
    return shards.scatter_to_global(np.asarray(final))


def labelprop_reference(g: HostGraph, labels: int = 8, stride: int = 16,
                        num_iters: int = 10) -> np.ndarray:
    """Float64 oracle of the identical recurrence."""
    nv = g.nv
    vid = np.arange(nv)
    seeded = (vid % stride) == 0
    eye = np.eye(labels)
    p = np.full((nv, labels), 1.0 / labels)
    p[seeded] = eye[vid[seeded] % labels]
    dst = g.dst_of_edges()
    for _ in range(num_iters):
        acc = np.zeros_like(p)
        np.add.at(acc, dst, p[g.col_idx])
        tot = acc.sum(-1, keepdims=True)
        norm = np.where(tot > 0, acc / np.maximum(tot, 1e-30), p)
        p = np.where(seeded[:, None], eye[vid % labels], norm)
    return p


def check_labelprop(probs: np.ndarray, labels: int, stride: int) -> int:
    """-check invariant: finite rows; seed rows exactly one-hot; every
    row with in-edges sums to ~1 (rows that kept the uniform prior do
    too, so the check is unconditional)."""
    probs = np.asarray(probs, np.float64)
    nv = probs.shape[0]
    vid = np.arange(nv)
    seeded = (vid % stride) == 0
    bad = int((~np.isfinite(probs)).any(axis=-1).sum())
    eye = np.eye(labels)
    bad += int((probs[seeded] != eye[vid[seeded] % labels]).any(-1).sum())
    bad += int(np.sum(np.abs(probs.sum(-1) - 1.0) > 1e-3))
    return bad


# ---------------------------------------------------------------------------
# weighted triangle counting (two-phase)
# ---------------------------------------------------------------------------


def triangles(g, num_parts: int = 1, method: str = "auto",
              ) -> Tuple[np.ndarray, dict]:
    """Weighted triangle counting as the TWO-PHASE spec program:

      phase 1 (library.TRI_NEIGHBORS, one pull iteration): each vertex
        accumulates the uint32 bitset union of its in-neighbors' ids;
      phase 2 (library.TRI_COUNT, reduce-only through the pull engine's
        load/comp phase split): per edge (u, v), weight(u, v) *
        |bits(u) & bits(v)|, sum-reduced per destination.

    Returns (incidence (nv,) float32, stats).  ``incidence[v]`` is the
    weighted triangle incidence Σ_{u→v} w(u,v)·|N(u) ∩ N(v)|.  On a
    ``symmetrize(..., unit_weights=True)`` view the totals are exact
    counts: stats["triangles"] = Σ incidence / 6 (each triangle is seen
    once per directed edge).  Requires an edge-weighted graph (the
    symmetrize helper provides unit weights)."""
    shards, arrays = _pull_setup(g, num_parts)
    nv = shards.spec.nv
    if nv > TRIANGLES_MAX_NV:
        raise ValueError(
            f"triangles builds (V, ceil(nv/32)) uint32 bitsets — "
            f"quadratic memory; nv={nv} exceeds the supported "
            f"{TRIANGLES_MAX_NV} (run a smaller graph)")
    if not shards.spec.weighted:
        raise ValueError(
            "triangles weights each closing edge; pass a weighted graph "
            "(program.workloads.symmetrize assigns unit weights)")
    if isinstance(g, HostGraph):
        # phase 1's sum-as-union is exact only on a SIMPLE graph: a
        # duplicate (src, dst) edge adds the source's bit twice and the
        # binary carry corrupts the neighboring bitset lane.  symmetrize
        # dedupes; a raw --directed input must be checked here.
        key = g.col_idx.astype(np.int64) * g.nv + g.dst_of_edges()
        if np.unique(key).size != g.ne:
            raise ValueError(
                "triangles needs a SIMPLE graph (no parallel duplicate "
                "edges — a duplicate source bit would carry into the "
                "next bitset lane); dedupe first, e.g. via "
                "program.workloads.symmetrize")
    from lux_tpu.engine import pull

    words = (nv + 31) // 32
    phase1 = bind(library.TRI_NEIGHBORS, w=words, width=words)
    bits = pull.run_pull_fixed(phase1, shards.spec, arrays,
                               pull.init_state(phase1, arrays), 1, method)
    incidence = reduce_phase(bind(library.TRI_COUNT), shards, arrays,
                             bits, method)
    total = float(incidence.sum())
    return incidence, {
        "total_weighted_incidence": total,
        # exact only under unit weights (documented above)
        "triangles_if_unit": total / 6.0,
        "bitset_words": words,
    }


def reduce_phase(prog, shards, arrays, state, method: str = "auto",
                 ) -> np.ndarray:
    """Run a reduce-only spec phase: ONE gather + edge_value + segmented
    reduce over the supplied state, through the pull engine's public
    load/comp phase split (compile_pull_phases) — no update loop, so the
    phase needs no apply rule.  Returns the reduced (nv,) accumulator."""
    from lux_tpu.engine import pull

    load, comp, _ = pull.compile_pull_phases(prog, shards.spec, method)
    acc = comp(arrays, load(arrays, state))
    return shards.scatter_to_global(np.asarray(acc))


def triangles_reference(g: HostGraph) -> np.ndarray:
    """NumPy oracle: per-vertex weighted triangle incidence via
    adjacency sets (O(E·deg) — CLI/test scale)."""
    nv = g.nv
    dst = g.dst_of_edges()
    nbrs = [set() for _ in range(nv)]
    for u, v in zip(g.col_idx, dst):
        nbrs[int(v)].add(int(u))
    out = np.zeros(nv, np.float64)
    w = g.weights if g.weights is not None else np.ones(g.ne, np.int64)
    for u, v, ww in zip(g.col_idx, dst, w):
        out[int(v)] += float(ww) * len(nbrs[int(u)] & nbrs[int(v)])
    return out.astype(np.float32)


def check_triangles(g: HostGraph, incidence: np.ndarray) -> int:
    """-check: recompute the oracle and count mismatches (the workload
    is small-scale by construction, so the O(E·deg) oracle is the
    honest validator)."""
    ref = triangles_reference(g)
    got = np.asarray(incidence, np.float64)
    tol = 1e-5 * np.maximum(np.abs(ref), 1.0)
    return int(np.sum(~np.isfinite(got) | (np.abs(got - ref) > tol)))
