"""The named spec registry: every app the repo ships, as config.

The four reference apps (pagerank/ppr, sssp, components, colfilter) are
DEFINED here; the dataclasses in ``models/*`` and ``serve/batched`` are
named parameter bundles that evaluate these specs — their hand-wired
gather/apply bodies are deleted (ISSUE 13 acceptance), and the copy-
pasted PPR-vs-PageRank and weighted-vs-unweighted-SSSP bodies collapse
into the two template builders below (the dedupe satellite).

Expression text is written to mirror the former hand-wired op order
EXACTLY, so spec-compiled programs are bitwise-identical to the deleted
bodies (PageRank carries the usual ≤1-ulp cross-layout caveat the
hand-wired path already carried); tests/test_program.py pins each one
against an in-test copy of the old body on every surface.

The four payoff workloads (bfs, kcore, labelprop, triangles) land as
specs only — no model class, no engine edit; see
:mod:`lux_tpu.program.workloads` for their runners and oracles and
docs/PROGRAMS.md for the lowering matrix.
"""
from __future__ import annotations

from lux_tpu.program.spec import VertexProgramSpec

#: reference ALPHA (pagerank/app.h:24) — models/pagerank re-exports it.
ALPHA = 0.15


def _pr_spec(name: str, mass: str, teleport: str,
             query_param: str = "") -> VertexProgramSpec:
    """PageRank-family template: the pre-divided recurrence with the
    teleport MASS as the only degree of freedom — uniform ``1/nv`` for
    PageRank, a one-hot at ``seed`` for personalized PageRank.  One
    template, two specs: the former copy-pasted PPRProgram init/apply
    bodies are this substitution."""
    return VertexProgramSpec(
        name=name,
        reduce="sum",
        # state holds rank PRE-DIVIDED by out-degree (pagerank_gpu.cu:
        # 256-259) so the gather needs no degree lookup
        init=(
            f"mass = {mass}\n"
            "deg = maximum(f32(degree), 1.0)\n"
            "state = where(degree > 0, mass / deg, mass)\n"
            "cast(where(vtx_mask, state, 0.0), dtype)"
        ),
        # reduce in f32 regardless of the storage dtype
        edge="f32(src)",
        # (teleport + ALPHA * acc), re-divided (pr_kernel tail,
        # pagerank_gpu.cu:97-100)
        apply=(
            f"pr = {teleport} + f32(alpha) * acc\n"
            "deg = f32(degree)\n"
            "pr = where(degree > 0, pr / maximum(deg, 1.0), pr)\n"
            "cast(where(vtx_mask, pr, 0.0), dtype)"
        ),
        convergence="fixed",
        query_param=query_param,
    )


#: uniform teleport: initRank = (1-ALPHA)/nv computed as ONE f32 round
#: of the Python-float product (pagerank/pagerank.cc:141-144 parity —
#: f32(1-alpha)*f32(1/nv) would round twice and drift the last ulp)
PAGERANK = _pr_spec("pagerank", mass="f32(1.0 / nv)",
                    teleport="f32((1.0 - alpha) / nv)")

#: personalized: the teleport mass is a one-hot at ``seed``; the seed is
#: the serve Q axis (MultiSourcePPR is this spec with seed = queries)
PPR = _pr_spec("ppr", mass="f32(vid == seed)",
               teleport="f32(1.0 - alpha) * f32(vid == seed)",
               query_param="seed")


def _sssp_spec(name: str, relax: str) -> VertexProgramSpec:
    """SSSP-family template: min-relaxation from ``start`` with INF
    encoded as the ``inf`` parameter (nv for BFS-SSSP hop counts,
    reference parity sssp_gpu.cu:733-744; 1<<30 for weighted costs).
    The relax expression is the only degree of freedom — the former
    WeightedSSSPProgram duplication."""
    return VertexProgramSpec(
        name=name,
        reduce="min",
        init=(
            "far = i32(inf)\n"
            "d = where(vid == start, i32(0), far)\n"
            "where(vtx_mask, d, far)"
        ),
        edge=relax,
        # pull form of the same relaxation (serve's batched engines and
        # the pull-until surface; push's scatter-min needs no apply)
        apply=(
            "new = minimum(old, acc)\n"
            "where(vtx_mask, new, old)"
        ),
        frontier="(vid == start) & vtx_mask",
        convergence="quiescent",
        query_param="start",
    )


SSSP = _sssp_spec("sssp", relax="src + i32(1)")
SSSP_WEIGHTED = _sssp_spec("sssp_weighted", relax="src + i32(weight)")

#: max-label propagation (the CC kernel, components_gpu.cu:85-130):
#: labels init to the vertex id (-1 on padding so it never wins a max),
#: everyone starts active (dense all-ones bitmap, :733-737)
COMPONENTS = VertexProgramSpec(
    name="components",
    reduce="max",
    init="where(vtx_mask, vid, -1)",
    edge="src",
    apply=(
        "new = maximum(old, acc)\n"
        "where(vtx_mask, new, old)"
    ),
    frontier="vtx_mask",
    convergence="quiescent",
)

#: collaborative filtering (col_filter/): K-dim latents at sqrt(1/K),
#: per-edge err = rating - <v_src, v_dst> (the error-dot reads the
#: DESTINATION state per edge — the dst-dependent load only the pull
#: surfaces provide), update v += GAMMA*(accErr - LAMBDA*v).  The
#: error-dot lowering ("vpu" | "mxu") stays a program parameter so the
#: banked ``tpu:cf_err_dot`` winner keeps flowing through unchanged.
COLFILTER = VertexProgramSpec(
    name="colfilter",
    reduce="sum",
    init=(
        "v0 = fullk(vid, k, sqrt(1.0 / k))\n"
        "cast(where(lane(vtx_mask), v0, 0.0), dtype)"
    ),
    edge=(
        "src32 = f32(src)\n"
        "err = weight - dot_lanes(src32, f32(dst), err_dot)\n"
        "lane(err) * src32"
    ),
    apply=(
        "old32 = f32(old)\n"
        "new = old32 + f32(gamma) * (acc - f32(lam) * old32)\n"
        "cast(where(lane(vtx_mask), new, old32), dtype)"
    ),
    convergence="fixed",
    state_width=20,
    needs_dst_state=True,
)


# ---------------------------------------------------------------------------
# the four payoff workloads (ISSUE 13): new scenarios as config only
# ---------------------------------------------------------------------------

#: multi-source BFS (frontier/push): hop distance to the NEAREST of the
#: ``sources`` tuple, INF == nv.  Differs from sssp in the seed rule
#: only — which is the point: a new scenario is a spec edit.
BFS = VertexProgramSpec(
    name="bfs",
    reduce="min",
    init=(
        "far = i32(nv)\n"
        "d = where(isin(vid, sources), i32(0), far)\n"
        "where(vtx_mask, d, far)"
    ),
    edge="src + i32(1)",
    apply=(
        "new = minimum(old, acc)\n"
        "where(vtx_mask, new, old)"
    ),
    frontier="isin(vid, sources) & vtx_mask",
    convergence="quiescent",
)

#: one peel level of k-core decomposition (iterative peel): state is an
#: int32 alive flag; the sum reduce counts alive in-neighbors and a
#: vertex survives iff it keeps >= kk of them.  The decomposition
#: driver (workloads.kcore) runs this spec to quiescence per k with a
#: warm start from the previous level's survivors (k-cores nest).
KCORE = VertexProgramSpec(
    name="kcore",
    reduce="sum",
    init="where(vtx_mask, i32(1), i32(0))",
    edge="src",
    apply="where(vtx_mask, old * i32(acc >= kk), i32(0))",
    convergence="quiescent",
)

#: seeded multi-class label propagation (dense pull, WIDE state): every
#: stride-th vertex is a seed pinned to one-hot class ``vid % labels``;
#: everyone else averages the incoming class-probability rows each
#: fixed iteration (vertices with no in-edges keep their prior row).
LABELPROP = VertexProgramSpec(
    name="labelprop",
    reduce="sum",
    init=(
        "seeded = (vid % stride) == 0\n"
        "uni = fullk(vid, labels, 1.0 / labels)\n"
        "base = where(lane(seeded), onehot(vid % labels, labels), uni)\n"
        "where(lane(vtx_mask), base, 0.0)"
    ),
    edge="f32(src)",
    apply=(
        "seeded = (vid % stride) == 0\n"
        "tot = rowsum(acc)\n"
        "norm = where(tot > 0.0, acc / maximum(tot, 1e-30), old)\n"
        "out = where(lane(seeded), onehot(vid % labels, labels), norm)\n"
        "where(lane(vtx_mask), out, 0.0)"
    ),
    convergence="fixed",
)

#: triangle counting phase 1: each vertex's state is the uint32 BITSET
#: of its own id (w words); one sum-reduce pull iteration ORs the
#: in-neighbor bitsets (distinct sources contribute distinct bits, so
#: the integer sum IS the union) into each vertex — the neighborhood
#: sketch phase of the intersection-heavy access pattern.
TRI_NEIGHBORS = VertexProgramSpec(
    name="tri_neighbors",
    reduce="sum",
    init=(
        "bit = u32(1) << u32(vid % 32)\n"
        "bits = where(row(arange(w)) == lane(vid // 32), lane(bit), u32(0))\n"
        "where(lane(vtx_mask), bits, u32(0))"
    ),
    edge="src",
    apply="where(lane(vtx_mask), cast(acc, 'uint32'), old)",
    convergence="fixed",
)

#: triangle counting phase 2 (reduce-only): per edge (u, v), intersect
#: the two gathered bitsets and weight the common-neighbor count by the
#: edge weight; the segmented sum per destination is the weighted
#: triangle incidence.  No apply — this phase lowers through the pull
#: engine's load/comp split (workloads.reduce_phase), which is exactly
#: what "a two-phase program" means to the compiler.
TRI_COUNT = VertexProgramSpec(
    name="tri_count",
    reduce="sum",
    init="f32(0.0)",  # unused: phase 2 consumes phase 1's state
    edge="f32(sum_lanes(popcount(src & dst))) * f32(weight)",
    convergence="fixed",
    needs_dst_state=True,
)


#: name -> spec, for the generic driver and docs
REGISTRY = {
    s.name: s
    for s in (PAGERANK, PPR, SSSP, SSSP_WEIGHTED, COMPONENTS, COLFILTER,
              BFS, KCORE, LABELPROP, TRI_NEIGHBORS, TRI_COUNT)
}
