"""Generic spec-workload driver: ``python -m lux_tpu.apps.run <program>``.

ONE driver for every declarative workload (ISSUE 13): it owns the CLI
boilerplate the four original apps used to each re-wire — graph load,
flag validation, shard build, ``--route-gather``/``--method`` resolution
through :mod:`lux_tpu.apps.common`, preflight, timing, the reference
[PASS]/[FAIL] ``-check`` verdict — so a new workload is a spec in
:mod:`lux_tpu.program.library` plus a ~40-line runner entry here.

Shipped programs (the ISSUE 13 payoff set):

  bfs        multi-source BFS on the frontier/push engine (``--sources``;
             ``--engine pull`` runs the pull-until surface — bitwise-
             identical distances); the full push flag surface applies
             (--distributed, --exchange ring, --route-gather, ...)
  kcore      k-core decomposition by iterative peel (``--kmax``); runs on
             the symmetrized simple view unless ``--directed``
  labelprop  seeded multi-class label propagation (dense pull, wide
             (V, --labels) state; seeds every ``--seed-stride``)
  triangles  weighted triangle counting — the two-phase
             intersection-heavy program (symmetrized view; unit weights
             when the input graph is unweighted)

The four reference apps keep their dedicated CLIs
(``lux_tpu.apps.{pagerank,sssp,components,colfilter}``) for their deep
flag surfaces; they evaluate the same spec registry.
"""
from __future__ import annotations

import sys

import numpy as np

from lux_tpu.apps import common
from lux_tpu.program import workloads
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import Timer, report_elapsed


def _parse_sources(cfg, nv: int):
    try:
        srcs = [int(s) for s in cfg.sources.split(",") if s.strip()]
    except ValueError:
        raise SystemExit(f"--sources must be comma-separated vertex ids, "
                         f"got {cfg.sources!r}")
    if not srcs:
        raise SystemExit("--sources needs at least one vertex")
    for s in srcs:
        if not 0 <= s < nv:
            raise SystemExit(f"--sources vertex {s} out of range [0, {nv})")
    return srcs


def _require_allgather(cfg, what: str) -> None:
    if cfg.exchange != "allgather" or cfg.edge_shards > 1 \
            or cfg.feat_shards > 1:
        raise SystemExit(
            f"{what} runs on the allgather pull layout; --exchange "
            "ring/scatter, --edge-shards and --feat-shards are not "
            "wired to this workload")


def _check_verdict(cfg, name: str, violations: int) -> int:
    if not cfg.check:
        return 0
    return 0 if common.print_check(name, violations) else 1


def _run_bfs(cfg) -> int:
    g = common.load_graph(cfg)
    sources = _parse_sources(cfg, g.nv)
    if cfg.prog_engine == "pull":
        # the pull-until surface: bitwise the same min fixpoint
        _require_allgather(cfg, "bfs --engine pull")
        from lux_tpu.engine import methods

        cfg.method = methods.resolve_sum(cfg.method, "min")
        common.resolve_route_auto(cfg)
        if cfg.route_gather and (cfg.distributed or cfg.method == "pallas"):
            raise SystemExit("bfs --engine pull routes single-device "
                             "allgather runs only")
        from lux_tpu.graph.shards import build_pull_shards

        shards = build_pull_shards(g, cfg.num_parts)
        prog = workloads.bfs_program(g.nv, sources)
        route = common.build_pull_route(cfg, shards, prog)
        mesh = common.make_mesh_if(cfg)
        timer = Timer()
        dist, iters = workloads.bfs(
            shards, sources,
            num_parts=cfg.num_parts, max_iters=cfg.max_iters,
            method=cfg.method, engine="pull", mesh=mesh, route=route)
        elapsed = timer.stop(dist)
    else:
        # home surface: the direction-optimizing push engine, through
        # the SAME convergence driver the sssp/components CLIs use —
        # preflight, routing, ring exchange, repartition, GTEPS
        from lux_tpu.apps.sssp import build_push_app_shards, \
            run_convergence_app

        if cfg.method == "pallas":
            raise SystemExit("--method pallas is a sum-reduce kernel; "
                             "bfs reduces with min")
        shards = build_push_app_shards(g, cfg)
        prog = workloads.bfs_program(shards.spec.nv, sources)
        dist, _state, shards = run_convergence_app(
            prog, shards, cfg, "bfs", g=g)
        elapsed = None  # run_convergence_app already reported
        iters = None
    reached = int(np.sum(dist < g.nv))
    depth = int(dist[dist < g.nv].max(initial=0))
    if elapsed is not None:
        print(f"bfs converged in {iters} iterations")
        report_elapsed(elapsed, g.ne, max(iters, 1))
    print(f"reached {reached}/{g.nv} vertices from {len(sources)} "
          f"source(s); max level {depth}")
    return _check_verdict(cfg, "bfs",
                          workloads.check_bfs(g, dist, sources))


def _run_kcore(cfg) -> int:
    g0 = common.load_graph(cfg)
    g = g0 if cfg.directed else workloads.symmetrize(g0)
    view = "directed in-neighborhoods" if cfg.directed else \
        "symmetrized simple view"
    from lux_tpu.program import library
    from lux_tpu.program.spec import bind

    prog = bind(library.KCORE, kk=1)
    common.validate_exchange(cfg, prog)
    _require_allgather(cfg, "kcore")
    from lux_tpu.graph.shards import build_pull_shards

    shards = build_pull_shards(g, cfg.num_parts)
    est = common.estimate_exchange(shards, cfg)
    common.report_preflight(est, cfg, shards)
    mesh = common.make_mesh_if(cfg)
    route = common.build_pull_route(cfg, shards, prog) \
        if mesh is None else None
    timer = Timer()
    coreness, kmax, rounds = workloads.kcore(
        shards, kmax=cfg.kmax, num_parts=cfg.num_parts,
        max_iters=cfg.max_iters, method=cfg.method, mesh=mesh,
        route=route)
    elapsed = timer.stop(coreness)
    print(f"kcore ({view}): k_max={kmax} in {rounds} peel rounds")
    report_elapsed(elapsed, g.ne, max(rounds, 1))
    top = np.bincount(coreness, minlength=kmax + 1)
    print("core sizes (|coreness >= k|): "
          + ", ".join(f"k{k}={int(top[k:].sum())}"
                      for k in range(1, min(kmax, 8) + 1)))
    return _check_verdict(cfg, "kcore", workloads.check_kcore(g, coreness))


def _run_labelprop(cfg) -> int:
    g = common.load_graph(cfg)
    prog = workloads.labelprop_program(cfg.labels, cfg.seed_stride)
    common.validate_exchange(cfg, prog)
    _require_allgather(cfg, "labelprop")
    if cfg.route_gather:
        raise SystemExit(
            "labelprop's wide probability state is not wired to "
            "--route-gather (see docs/PROGRAMS.md lowering matrix)")
    from lux_tpu.graph.shards import build_pull_shards

    shards = build_pull_shards(g, cfg.num_parts)
    est = common.estimate_exchange(shards, cfg, state_width=cfg.labels)
    common.report_preflight(est, cfg, shards, state_width=cfg.labels)
    mesh = common.make_mesh_if(cfg)
    timer = Timer()
    probs = workloads.labelprop(
        shards, labels=cfg.labels,
        stride=cfg.seed_stride, num_iters=cfg.num_iters,
        num_parts=cfg.num_parts, method=cfg.method, mesh=mesh)
    elapsed = timer.stop(probs)
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    hist = np.bincount(probs.argmax(-1), minlength=cfg.labels)
    print("argmax label histogram: "
          + ", ".join(f"c{i}={int(n)}" for i, n in enumerate(hist)))
    return _check_verdict(
        cfg, "labelprop",
        workloads.check_labelprop(probs, cfg.labels, cfg.seed_stride))


def _run_triangles(cfg) -> int:
    g0 = common.load_graph(cfg)
    if cfg.directed:
        if g0.weights is None:
            raise SystemExit("triangles --directed needs a weighted graph "
                             "(the closing-edge weight)")
        g = g0
    else:
        g = workloads.symmetrize(g0)
    if cfg.distributed or cfg.route_gather:
        raise SystemExit(
            "triangles is a single-device two-phase program; "
            "--distributed/--route-gather are not wired (see "
            "docs/PROGRAMS.md)")
    _require_allgather(cfg, "triangles")
    timer = Timer()
    incidence, stats = workloads.triangles(
        g, num_parts=cfg.num_parts, method=cfg.method)
    elapsed = timer.stop(incidence)
    report_elapsed(elapsed, g.ne, 2)  # two phases, one edge sweep each
    print(f"weighted triangle incidence total = "
          f"{stats['total_weighted_incidence']:.1f} "
          f"(bitset words/vertex: {stats['bitset_words']})")
    if g0.weights is None and not cfg.directed:
        print(f"triangles (unit weights, exact) = "
              f"{stats['triangles_if_unit']:.0f}")
    return _check_verdict(cfg, "triangles",
                          workloads.check_triangles(g, incidence))


#: name -> (parse_args surface, runner)
PROGRAMS = {
    "bfs": ("push", _run_bfs),
    "kcore": ("pull", _run_kcore),
    "labelprop": ("pull", _run_labelprop),
    "triangles": ("pull", _run_triangles),
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("usage: python -m lux_tpu.apps.run "
              f"{{{','.join(sorted(PROGRAMS))}}} [flags]   "
              "(-h after a program name for its flags)")
        return 0 if argv else 2
    name = argv[0]
    if name not in PROGRAMS:
        print(f"unknown program {name!r}; available: "
              + ", ".join(sorted(PROGRAMS))
              + " (the reference apps keep their own CLIs: "
                "python -m lux_tpu.apps.<pagerank|sssp|components|"
                "colfilter>)", file=sys.stderr)
        return 2
    kind, runner = PROGRAMS[name]
    cfg = parse_args(argv[1:], description=__doc__,
                     pull=kind == "pull", push=kind == "push",
                     program=True, prog=name)
    return runner(cfg)


if __name__ == "__main__":
    sys.exit(main())
