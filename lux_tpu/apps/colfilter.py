"""Collaborative Filtering CLI app (`python -m lux_tpu.apps.colfilter`).

Driver parity with col_filter/colfilter.cc: fixed -ni gradient iterations
on a weighted rating graph; reports training RMSE (the reference prints
only elapsed time — RMSE is our addition for observability).
"""
from __future__ import annotations

import sys

import jax

from lux_tpu.apps import common
from lux_tpu.engine import pull
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import colfilter as cf_model
from lux_tpu.utils import preflight
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import Timer, report_elapsed


def main(argv=None):
    cfg = parse_args(argv, description=__doc__)
    g = common.load_graph(cfg, weighted=True)
    shards = build_pull_shards(g, cfg.num_parts)
    est = preflight.estimate_pull(shards.spec, state_width=cf_model.K)
    print(est)
    preflight.check_fits(est)

    prog = cf_model.CFProgram()
    arrays = jax.tree.map(jax.numpy.asarray, shards.arrays)
    state = pull.init_state(prog, arrays)
    mesh = common.make_mesh_if(cfg)

    from lux_tpu.utils import profiling

    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        if cfg.verbose and mesh is None:
            state, _ = common.run_pull_stepwise(
                prog, shards.spec, arrays, state, 0, cfg.num_iters, cfg, g.nv
            )
        elif mesh is None:
            state = pull.run_pull_fixed(
                prog, shards.spec, arrays, state, cfg.num_iters, cfg.method
            )
        else:
            from lux_tpu.parallel import dist

            state = dist.run_pull_fixed_dist(
                prog, shards.spec, shards.arrays, state, cfg.num_iters, mesh,
                cfg.method,
            )
        elapsed = timer.stop(state)
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    v = shards.scatter_to_global(jax.device_get(state))
    print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
