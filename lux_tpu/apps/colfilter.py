"""Collaborative Filtering CLI app (`python -m lux_tpu.apps.colfilter`).

Driver parity with col_filter/colfilter.cc: fixed -ni gradient iterations
on a weighted rating graph; reports training RMSE (the reference prints
only elapsed time — RMSE is our addition for observability).
"""
from __future__ import annotations

import sys

import jax

from lux_tpu.apps import common
from lux_tpu.engine import pull
from lux_tpu.models import colfilter as cf_model
from lux_tpu.utils import preflight
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import Timer, report_elapsed


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, pull=True)
    g = common.load_graph(cfg, weighted=True, bipartite=True)
    prog = cf_model.CFProgram(dtype=cfg.dtype)
    common.validate_exchange(cfg, prog)
    shards = common.build_exchange_shards(g, cfg)
    est = common.estimate_exchange(shards, cfg, state_width=cf_model.K)
    print(est)
    preflight.check_fits(est)

    mesh = common.make_mesh_if(cfg)
    # single-device paths use device-placed arrays; distributed drivers
    # shard host arrays themselves (see apps/pagerank.py)
    arrays = (
        jax.tree.map(jax.numpy.asarray, shards.arrays)
        if mesh is None
        else shards.arrays
    )
    state = pull.init_state(prog, arrays)

    from lux_tpu.utils import profiling

    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        if cfg.verbose and mesh is None:
            state, _ = common.run_pull_stepwise(
                prog, shards.spec, arrays, state, 0, cfg.num_iters, cfg, g.nv
            )
        elif mesh is None:
            state = pull.run_pull_fixed(
                prog, shards.spec, arrays, state, cfg.num_iters, cfg.method
            )
        else:
            state = common.run_fixed_dist(
                prog, shards, state, cfg.num_iters, mesh, cfg
            )
        elapsed = timer.stop(state)
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    v = shards.scatter_to_global(jax.device_get(state)).astype("float32")
    print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
