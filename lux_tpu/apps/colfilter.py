"""Collaborative Filtering CLI app (`python -m lux_tpu.apps.colfilter`).

Driver parity with col_filter/colfilter.cc: fixed -ni gradient iterations
on a weighted rating graph; reports training RMSE (the reference prints
only elapsed time — RMSE is our addition for observability).
"""
from __future__ import annotations

import sys

import jax

from lux_tpu.apps import common
from lux_tpu.engine import pull
from lux_tpu.models import colfilter as cf_model
from lux_tpu.utils import preflight
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import Timer, report_elapsed


def _run_pallas(cfg, g):
    """--method pallas: the fused 2-D MXU kernel (err·srcVec accumulation
    as (V_BLK,T)x(T,K) matmuls, colfilter_gpu.cu:85-101's role)."""
    import numpy as np

    if cfg.verbose or cfg.ckpt_every or cfg.ckpt_dir:
        raise SystemExit(
            "--method pallas: -verbose/checkpointing are not wired to the "
            "kernel path; use --method scan/scatter for those"
        )
    interp = jax.devices()[0].platform not in ("tpu", "axon")
    from lux_tpu.utils import profiling

    with profiling.trace(cfg.profile_dir):
        if cfg.distributed:
            from lux_tpu.parallel import pallas_dist as pd

            prog = cf_model.CFProgram(
                dtype=cfg.dtype,
                err_dot=cf_model._resolve_err_dot(None))
            pp = pd.build_pallas_parts(g, cfg.num_parts)
            est = preflight.estimate_pallas_pull(
                pp.arrays.e_src_pos.shape[1], pp.t_chunk, pp.spec.nv_pad,
                pp.spec.gathered_size * cf_model.K, True,
                2 if cfg.dtype == "bfloat16" else 4,
            )
            print(est)
            preflight.check_fits(est)
            mesh = common.make_mesh_if(cfg)
            s0 = pd.init_state_pallas(prog, pp)
            timer = Timer()
            out = pd.run_cf_pallas_dist(
                prog, pp, s0, cfg.num_iters, mesh, interpret=interp
            )
            elapsed = timer.stop(out)
            v = pp.scatter_to_global(jax.device_get(out)).astype("float32")
        else:
            run, s0 = cf_model.make_pallas_runner(
                g, interpret=interp, dtype=cfg.dtype
            )
            timer = Timer()
            out = run(s0, cfg.num_iters)
            elapsed = timer.stop(out)
            v = np.asarray(jax.device_get(out))[: g.nv].astype("float32")
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
    return _check_tail(cfg, g, v)


def _check_tail(cfg, g, v) -> int:
    """-check verdict shared by EVERY colfilter path (incl. pallas and
    feat-sharded) — EXTENSION: the reference ships no CF check task; we
    validate training progress anyway (float64 RMSE must not regress
    above the untrained closed form; finite state)."""
    if not cfg.check:
        return 0
    ok = common.print_check(
        "colfilter (training progress; extension — no reference "
        "check task)", cf_model.check_training(g, v),
    )
    return 0 if ok else 1


def _run_feat(cfg, g, prog):
    """--feat-shards N: CF on the 2-D (parts x feat) mesh — the latent K
    dim split over FEAT_AXIS, per-chip state and exchange volume /N, one
    (E,)-sized error-dot psum per iteration (parallel/feat.py).  With
    --exchange ring the parts axis streams state blocks instead of
    all-gathering: per-chip state O(nv/P x K/F) — both big axes sharded
    at once (the RMAT27 K=20 case, SURVEY.md §7.3)."""
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.parallel import feat

    if cfg.verbose or cfg.ckpt_every or cfg.ckpt_dir:
        raise SystemExit(
            "--feat-shards: -verbose/checkpointing are not wired to the "
            "2-D feat mesh; drop --feat-shards for those"
        )
    shards = build_pull_shards(g, cfg.num_parts)
    if cfg.exchange == "ring":
        from lux_tpu.parallel import ring

        shards = ring.build_ring_shards(g, cfg.num_parts, pull=shards)
    # the exchange carries K/F features per chip
    est = common.estimate_exchange(
        shards, cfg, state_width=cf_model.K // cfg.feat_shards
    )
    common.report_preflight(
        est, cfg, shards, state_width=cf_model.K // cfg.feat_shards
    )
    # k-resident parts when num_parts exceeds the available parts slots
    # (the mapper-slicing analog, same as every other distributed driver)
    mesh = feat.make_mesh_feat_for_parts(cfg.num_parts, cfg.feat_shards)
    # state is born sharded on the 2-D mesh: no chip ever holds (V, K)
    state = feat.init_state_feat(prog, shards.arrays, mesh)
    from lux_tpu.utils import profiling

    f_route = None
    if cfg.route_gather and cfg.exchange != "ring":
        # host-side plan construction stays OUTSIDE the reported time
        from lux_tpu.ops import expand

        f_route = expand.plan_cf_route_shards_cached(
            shards, pf=common.route_is_pf(cfg.route_gather))
    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        if cfg.exchange == "ring":
            state = feat.run_cf_feat_ring(
                prog, shards, state, cfg.num_iters, mesh, cfg.method
            )
        else:
            state = feat.run_cf_feat_dist(
                prog, shards.spec, shards.arrays, state, cfg.num_iters,
                mesh, cfg.method, route=f_route,
            )
        elapsed = timer.stop(state)
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    v = shards.scatter_to_global(jax.device_get(state)).astype("float32")
    print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
    return _check_tail(cfg, g, v)


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, pull=True, stream=True)
    g = common.load_graph(cfg, weighted=True, bipartite=True)
    prog = cf_model.CFProgram(
        dtype=cfg.dtype, err_dot=cf_model._resolve_err_dot(None))
    common.validate_exchange(cfg, prog)
    if cfg.stream_hbm_gib:
        # host-offload streaming for the WIDE-state app (the (V, K)
        # latent matrix is the memory case SURVEY.md §7.3 flags)
        v, elapsed, _ = common.run_streamed(
            cfg, g, prog, state_width=cf_model.K
        )
        report_elapsed(elapsed, g.ne, cfg.num_iters)
        v = v.astype("float32")
        print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
        return _check_tail(cfg, g, v)
    if cfg.method == "pallas":
        return _run_pallas(cfg, g)
    if cfg.feat_shards > 1:
        return _run_feat(cfg, g, prog)
    shards = common.build_exchange_shards(g, cfg)
    est = common.estimate_exchange(shards, cfg, state_width=cf_model.K)
    common.report_preflight(est, cfg, shards, state_width=cf_model.K,
                            stream_hint=True)

    mesh = common.make_mesh_if(cfg)
    # single-device paths use device-placed arrays; distributed drivers
    # shard host arrays themselves (see apps/pagerank.py)
    arrays = (
        jax.tree.map(jax.numpy.asarray, shards.arrays)
        if mesh is None
        else shards.arrays
    )
    state = pull.init_state(prog, arrays)

    state, start_it = common.resume_or_init(
        cfg, "colfilter", shards, state, g.nv
    )

    from lux_tpu.utils import profiling

    def on_iter(it, st):
        if cfg.ckpt_every and cfg.ckpt_dir and (it + 1) % cfg.ckpt_every == 0:
            common.save_global(cfg, "colfilter", shards, it + 1, st)

    # host-side plan construction stays OUTSIDE the reported time
    route = (common.build_pull_route(cfg, shards, prog)
             if mesh is None else None)
    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        elapsed = None
        if (cfg.verbose or cfg.ckpt_every) and mesh is None:
            state, _ = common.run_pull_stepwise(
                prog, shards.spec, arrays, state, start_it, cfg.num_iters,
                cfg, g.nv, on_iter, route=route,
            )
        elif mesh is None:
            state = pull.run_pull_fixed(
                prog, shards.spec, arrays, state, cfg.num_iters - start_it,
                cfg.method, route=route,
            )
        elif cfg.verbose and cfg.exchange == "allgather" and cfg.edge_shards == 1:
            # step-wise distributed observability (see apps/pagerank.py);
            # checkpointing composes via the same on_iter hook
            state, _ = common.run_pull_stepwise_dist(
                prog, shards, state, start_it, cfg.num_iters, mesh, cfg,
                g.nv, on_iter,
            )
        elif cfg.ckpt_every:
            state, elapsed = common.run_fixed_dist_chunked(
                prog, shards, state, start_it, cfg.num_iters, mesh, cfg,
                "colfilter",
            )
        else:
            if cfg.verbose:
                print(
                    "note: -verbose per-iteration stepping is an "
                    "allgather-exchange 1-D-mesh mode; this run stays "
                    "fused on device"
                )
            state = common.run_fixed_dist(
                prog, shards, state, cfg.num_iters - start_it, mesh, cfg
            )
        if elapsed is None:
            elapsed = timer.stop(state)
    # GTEPS over the iterations THIS run executed (resume runs fewer)
    report_elapsed(elapsed, g.ne, cfg.num_iters - start_it)
    v = shards.scatter_to_global(jax.device_get(state)).astype("float32")
    print(f"training RMSE = {cf_model.rmse(g, v):.4f}")
    return _check_tail(cfg, g, v)


if __name__ == "__main__":
    sys.exit(main())
