"""Connected Components CLI app (`python -m lux_tpu.apps.components`).

Driver parity with components/components.cc: convergence-driven label
propagation, -check label-dominance validation, -verbose per-iteration
active counts.
"""
from __future__ import annotations

import sys

import numpy as np

from lux_tpu.apps import common
from lux_tpu.apps.sssp import build_push_app_shards, run_convergence_app
from lux_tpu.models import components as cc_model
from lux_tpu.utils.config import parse_args


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, push=True)
    g = common.load_graph(cfg)
    shards = build_push_app_shards(g, cfg)
    prog = cc_model.MaxLabelProgram()
    labels, state, shards = run_convergence_app(
        prog, shards, cfg, "components", g=g
    )
    n_comp = len(np.unique(labels))
    print(f"{n_comp} distinct labels")
    if cfg.check:
        if cfg.distributed:
            # on-device label-dominance walk (CHECK_TASK_ID analog,
            # components_gpu.cu:768-792) — no host gather needed
            from lux_tpu.engine import validate

            violations = validate.count_violations(
                shards.pull, state, validate.cc_violation()
            )
        else:
            violations = cc_model.check_labels(g, labels)
        ok = common.print_check("components", violations)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
