"""Connected Components CLI app (`python -m lux_tpu.apps.components`).

Driver parity with components/components.cc: convergence-driven label
propagation, -check label-dominance validation, -verbose per-iteration
active counts.
"""
from __future__ import annotations

import sys

import numpy as np

from lux_tpu.apps import common
from lux_tpu.apps.sssp import build_push_app_shards, run_convergence_app
from lux_tpu.models import components as cc_model
from lux_tpu.utils.config import parse_args


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, push=True, stream=True)
    g = common.load_graph(cfg)
    prog = cc_model.MaxLabelProgram()
    if cfg.stream_hbm_gib:
        # host-offload streaming: CC's pull form to convergence (the
        # reference's CC starts DENSE anyway, components_gpu.cu:733-737
        # — the all-in-edges sweep is the natural streamed shape);
        # falls through to the SHARED report/check tail (run_streamed
        # already forbids --distributed)
        from lux_tpu.utils.timing import report_elapsed

        labels, elapsed, iters = common.run_streamed(
            cfg, g, prog, active_fn=cc_model.active_count
        )
        print(f"components converged in {iters} iterations")
        report_elapsed(elapsed, g.ne, iters)
        state = shards = None
    else:
        shards = build_push_app_shards(g, cfg)
        labels, state, shards = run_convergence_app(
            prog, shards, cfg, "components", g=g
        )
    n_comp = len(np.unique(labels))
    print(f"{n_comp} distinct labels")
    if cfg.check:
        if cfg.distributed:
            # on-device label-dominance walk (CHECK_TASK_ID analog,
            # components_gpu.cu:768-792) — no host gather needed
            from lux_tpu.engine import validate

            violations = validate.count_violations(
                shards.pull, state, validate.cc_violation()
            )
        else:
            violations = cc_model.check_labels(g, labels)
        ok = common.print_check("components", violations)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
