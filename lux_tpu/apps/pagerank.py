"""PageRank CLI app (`python -m lux_tpu.apps.pagerank`).

Driver parity with pagerank/pagerank.cc: -ng parts, -ni fixed iterations,
ELAPSED TIME + derived GTEPS on exit; -verbose steps the jitted iteration
one at a time with per-iteration wall times.
"""
from __future__ import annotations

import sys

import jax

from lux_tpu.apps import common
from lux_tpu.engine import pull
from lux_tpu.models.pagerank import PageRankProgram
from lux_tpu.utils import preflight
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import Timer, report_elapsed


def _run_pallas(cfg, g, prog):
    """--method pallas: block-CSR one-hot MXU reduce (single-chip runner or
    the distributed pallas_dist engine).  Interpret mode off-TPU so CPU
    smoke runs work; Mosaic on hardware."""
    import numpy as np

    if cfg.verbose or cfg.ckpt_every or cfg.ckpt_dir:
        raise SystemExit(
            "--method pallas: -verbose/checkpointing are not wired to the "
            "kernel path; use --method scan/scatter for those"
        )
    interp = jax.devices()[0].platform not in ("tpu", "axon")
    from lux_tpu.utils import profiling

    with profiling.trace(cfg.profile_dir):
        if cfg.distributed:
            from lux_tpu.parallel import pallas_dist as pd

            pp = pd.build_pallas_parts(g, cfg.num_parts)
            est = preflight.estimate_pallas_pull(
                pp.arrays.e_src_pos.shape[1], pp.t_chunk, pp.spec.nv_pad,
                pp.spec.gathered_size, pp.spec.weighted,
                2 if cfg.dtype == "bfloat16" else 4,
            )
            print(est)
            preflight.check_fits(est)
            mesh = common.make_mesh_if(cfg)
            s0 = pd.init_state_pallas(prog, pp)
            # timer starts AFTER the host-side block-CSR build, like main()
            # starts it after the shard build — GTEPS measures iterations
            timer = Timer()
            out = pd.run_pull_fixed_pallas_dist(
                prog, pp, s0, cfg.num_iters, mesh, interpret=interp
            )
            elapsed = timer.stop(out)
            ranks = pp.scatter_to_global(jax.device_get(out))
        else:
            if cfg.num_parts != 1:
                raise SystemExit(
                    "--method pallas single-device runs one part (-ng 1); "
                    "use --distributed for multi-part"
                )
            from lux_tpu.models.pagerank import make_pallas_runner

            run, s0 = make_pallas_runner(g, interpret=interp, dtype=cfg.dtype)
            timer = Timer()
            out = run(s0, cfg.num_iters)
            elapsed = timer.stop(out)
            ranks = np.asarray(jax.device_get(out))[: g.nv]
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    common.top_k("rank (pre-divided)", ranks)
    return _check_tail(cfg, g, ranks)


def _check_tail(cfg, g, ranks) -> int:
    """-check verdict shared by EVERY pagerank path (incl. pallas) —
    EXTENSION: the reference ships no pagerank check task (only
    sssp/components have CHECK_TASK_ID); we validate the fixed point
    anyway with one exact host iteration, tolerance scaled to the run's
    iteration count and state dtype."""
    if not cfg.check:
        return 0
    from lux_tpu.models.pagerank import check_ranks

    ok = common.print_check(
        "pagerank (fixed-point residual; extension — no reference "
        "check task)",
        check_ranks(g, ranks, num_iters=cfg.num_iters, dtype=cfg.dtype),
    )
    return 0 if ok else 1


def _run_streamed(cfg, g, prog):
    """--stream-hbm-gib: host-offload edge streaming under a device-byte
    budget (common.run_streamed; engine/stream.py — the -ll:zsize
    zero-copy analog, core/lux_mapper.cc:146-165)."""
    ranks, elapsed, _ = common.run_streamed(cfg, g, prog)
    report_elapsed(elapsed, g.ne, cfg.num_iters)
    common.top_k("rank (pre-divided)", ranks)
    return _check_tail(cfg, g, ranks)


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, pull=True, stream=True,
                     serve=True)
    g = common.load_graph(cfg)
    if cfg.serve:
        # personalized-PageRank query service: Q seed vectors per batched
        # iteration (lux_tpu.serve; -ni is the per-query iteration count)
        if cfg.dtype != "float32":
            raise SystemExit("--serve runs the float32 batched engines")
        from lux_tpu.serve.driver import run_serve_cli

        return run_serve_cli(cfg, g, "ppr")
    prog = PageRankProgram(nv=g.nv, dtype=cfg.dtype)
    common.validate_exchange(cfg, prog)
    if cfg.stream_hbm_gib:
        return _run_streamed(cfg, g, prog)
    if cfg.method == "pallas":
        return _run_pallas(cfg, g, prog)
    shards = common.build_exchange_shards(g, cfg)
    est = common.estimate_exchange(shards, cfg)
    common.report_preflight(est, cfg, shards, stream_hint=True)

    mesh = common.make_mesh_if(cfg)
    # device-place the pull arrays only on the single-device paths: the
    # distributed drivers shard host arrays themselves, and ring/scatter
    # must never commit the O(E) pull layout to one device (their memory
    # model — and the preflight above — accounts buckets only)
    arrays = (
        jax.tree.map(jax.numpy.asarray, shards.arrays)
        if mesh is None
        else shards.arrays
    )
    state = pull.init_state(prog, arrays)

    state, start_it = common.resume_or_init(cfg, "pagerank", shards, state, g.nv)

    from lux_tpu.utils import profiling

    def on_iter(it, st):
        if cfg.ckpt_every and cfg.ckpt_dir and (it + 1) % cfg.ckpt_every == 0:
            common.save_global(cfg, "pagerank", shards, it + 1, st)

    # host-side plan construction stays OUTSIDE the reported time
    route = (common.build_pull_route(cfg, shards, prog)
             if mesh is None else None)
    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        elapsed = None  # chunked path reports compute-only time
        if (cfg.verbose or cfg.ckpt_every) and mesh is None:
            state, _ = common.run_pull_stepwise(
                prog, shards.spec, arrays, state, start_it, cfg.num_iters,
                cfg, g.nv, on_iter, route=route,
            )
        elif mesh is None:
            state = pull.run_pull_fixed(
                prog, shards.spec, arrays, state, cfg.num_iters - start_it,
                cfg.method, route=route,
            )
        elif cfg.verbose and cfg.exchange == "allgather" and cfg.edge_shards == 1:
            # step-wise DISTRIBUTED observability with the 3-phase
            # load/comp/update fence; checkpointing composes via the same
            # on_iter hook
            state, _ = common.run_pull_stepwise_dist(
                prog, shards, state, start_it, cfg.num_iters, mesh, cfg,
                g.nv, on_iter,
            )
        elif cfg.ckpt_every:
            # distributed checkpointing: ckpt_every-sized on-device chunks,
            # host checkpoint I/O excluded from the reported time
            state, elapsed = common.run_fixed_dist_chunked(
                prog, shards, state, start_it, cfg.num_iters, mesh, cfg,
                "pagerank",
            )
        else:
            if cfg.verbose:
                print(
                    "note: -verbose per-iteration stepping is an "
                    "allgather-exchange 1-D-mesh mode; this run stays "
                    "fused on device"
                )
            state = common.run_fixed_dist(
                prog, shards, state, cfg.num_iters - start_it, mesh, cfg
            )
        if elapsed is None:
            elapsed = timer.stop(state)
    # GTEPS over the iterations THIS run executed (resume runs fewer)
    report_elapsed(elapsed, g.ne, cfg.num_iters - start_it)
    ranks = shards.scatter_to_global(jax.device_get(state))
    common.top_k("rank (pre-divided)", ranks)
    return _check_tail(cfg, g, ranks)


if __name__ == "__main__":
    sys.exit(main())
