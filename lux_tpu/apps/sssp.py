"""SSSP CLI app (`python -m lux_tpu.apps.sssp`).

Driver parity with sssp/sssp.cc: -start source, convergence-driven loop,
-check triangle-inequality validation, -verbose per-iteration active
counts (the activeNodes/compTime breakdown of sssp_gpu.cu:516-518).
"""
from __future__ import annotations

import sys

import numpy as np

from lux_tpu.apps import common
from lux_tpu.engine import push
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import sssp as sssp_model
from lux_tpu.utils import preflight
from lux_tpu.utils.config import parse_args
from lux_tpu.utils.timing import IterStats, Timer, report_elapsed


def build_push_app_shards(g, cfg):
    """Push shards for the selected dense-round --exchange strategy (or
    the block-CSR layout when the dense rounds run the Pallas kernel)."""
    if cfg.sort_segments and (
        cfg.exchange != "allgather" or cfg.method == "pallas"
    ):
        raise SystemExit(
            "--sort-segments relays out the allgather dense-round pull "
            "layout; the ring-bucket and block-CSR (pallas) layouts have "
            "their own edge orders"
        )
    if cfg.compact_gather and (
        cfg.exchange != "allgather" or cfg.method == "pallas"
    ):
        raise SystemExit(
            "--compact-gather mirrors the allgather dense-round pull "
            "layout's src_pos; ring and pallas have their own layouts"
        )
    if cfg.method == "pallas":
        if cfg.exchange != "allgather":
            raise SystemExit(
                "--method pallas has its own dense path; it cannot combine "
                "with --exchange ring"
            )
        if not cfg.distributed:
            raise SystemExit(
                "--method pallas (push) runs on a device mesh: add "
                "--distributed (single chip = -ng 1 --distributed)"
            )
        common.require_parts_fit_devices(cfg, "--method pallas")
        from lux_tpu.parallel.pallas_dist import build_push_pallas_shards

        return build_push_pallas_shards(g, cfg.num_parts)
    if cfg.exchange == "ring":
        if not cfg.distributed:
            raise SystemExit("--exchange ring requires --distributed")
        from lux_tpu.parallel.ring import build_push_ring_shards

        return build_push_ring_shards(g, cfg.num_parts)
    return build_push_shards(
        g, cfg.num_parts, sort_segments=cfg.sort_segments,
        compact_gather=cfg.compact_gather,
    )


def _save_frontier_ckpt(cfg, name, shards, carry):
    """One elastic frontier checkpoint from the in-flight carry: global
    state + changed-vertex mask + exact edge counter."""
    from lux_tpu.engine import repartition
    from lux_tpu.utils import checkpoint as ckpt

    state_g = shards.scatter_to_global(np.asarray(carry.state))
    counts = np.asarray(carry.count)
    f_cap = shards.pspec.f_cap
    if counts.max() > f_cap:
        # overflowed queues are truncated; the exact frontier is not
        # recoverable — save the dense superset (min/max relaxation is
        # confluent: extra active vertices cost work, never correctness)
        changed_g = np.ones(shards.spec.nv, bool)
    else:
        changed_g = repartition._changed_mask_from_queues(
            np.asarray(carry.q_vid), counts, f_cap, shards.spec.nv
        )
    ckpt.save_frontier(
        cfg.ckpt_dir, int(carry.it), state_g, changed_g,
        np.asarray(carry.edges), name,
    )


def run_push_checkpointed(prog, shards, cfg, mesh, name: str):
    """Windowed push run with an elastic frontier checkpoint between
    windows (--ckpt-every iterations), resuming from cfg.ckpt_dir when a
    checkpoint exists — any part count / exchange / mesh can resume any
    other's checkpoint (the queues rebuild from the saved changed mask,
    engine.repartition._rebuild_carry).  Returns (stacked_state, iters,
    edges, compute_seconds); compute EXCLUDES the host-side checkpoint
    I/O so reported GTEPS stays an engine number (same contract as
    common.run_fixed_dist_chunked)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import push, repartition
    from lux_tpu.utils import checkpoint as ckpt
    from lux_tpu.utils.timing import Timer

    nv = shards.spec.nv
    statics, loop = repartition._place_statics(
        prog, shards, mesh, cfg.method, cfg.exchange
    )
    s_g, c_g, e_acc, it0, prev = ckpt.load_resume_frontier(
        cfg.ckpt_dir, name, nv
    )
    if s_g is not None:
        carry = repartition._rebuild_carry(prog, shards, s_g, c_g, it0, e_acc)
        print(f"resumed from {prev} at iteration {it0}")
    else:
        carry = push._init_carry(
            prog, shards.pspec,
            jax.tree.map(jnp.asarray, push.vertex_view(shards.arrays)),
        )
    if mesh is not None:
        carry = push.shard_carry(mesh, carry)
    compute = 0.0
    while int(carry.active) > 0 and int(carry.it) < cfg.max_iters:
        it_stop = min(int(carry.it) + cfg.ckpt_every, cfg.max_iters)
        t = Timer()
        carry = loop(*statics, carry, jnp.int32(it_stop))
        compute += t.stop(carry.state)
        _save_frontier_ckpt(cfg, name, shards, carry)
    return carry.state, int(carry.it), carry.edges, compute


def run_delta_checkpointed(prog, shards, cfg, mesh, name: str):
    """Windowed delta-stepping with elastic checkpoints between windows:
    GLOBAL state + pending mask + exact edge counter + the bucket
    threshold (utils/checkpoint.save_delta).  A resume restacks onto ANY
    part count, single-device or distributed — same contract as the
    frontier checkpoints."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.utils import checkpoint as ckpt
    from lux_tpu.utils.timing import Timer

    # same driver-entry contract as run_push_delta: validate AND resolve
    # the method, so direct callers fail fast instead of deep in the
    # segment kernel with method='auto'
    delta_mod._validate(prog, cfg.delta)
    from lux_tpu.engine import methods

    cfg.method = methods.resolve_sum(cfg.method, prog.reduce)
    spec, pspec = shards.spec, shards.pspec
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    parrays = jax.tree.map(jnp.asarray, shards.parrays)
    s_g, p_g, e_acc, thr, it0, prev = ckpt.load_resume_delta(
        cfg.ckpt_dir, name, spec.nv
    )
    if s_g is None:
        carry = delta_mod._init_carry(prog, pspec, arrays, cfg.delta)
    else:
        st = jnp.asarray(shards.pull.global_to_stacked(s_g))
        pend = jnp.asarray(shards.pull.global_to_stacked(p_g))
        carry = delta_mod.DeltaCarry(
            st, pend, jnp.int32(thr), jnp.int32(it0),
            jnp.sum(pend.astype(jnp.int32)), jnp.asarray(e_acc),
        )
        print(f"resumed from {prev} at iteration {it0}")
    if mesh is not None:
        from lux_tpu.parallel.mesh import shard_stacked

        arrays = shard_stacked(mesh, arrays)
        parrays = shard_stacked(mesh, parrays)
        carry = delta_mod.DeltaCarry(
            *shard_stacked(mesh, (carry.state, carry.pending)),
            carry.thr, carry.it, carry.active, carry.edges,
        )
        loop = delta_mod._compile_delta_dist(
            prog, mesh, pspec, spec, cfg.method, cfg.delta
        )
    else:
        loop = delta_mod._compile_delta_loop(
            prog, pspec, spec, cfg.method, cfg.delta
        )
    compute = 0.0
    while int(carry.active) > 0 and int(carry.it) < cfg.max_iters:
        it_stop = min(int(carry.it) + cfg.ckpt_every, cfg.max_iters)
        t = Timer()
        carry = loop(arrays, parrays, carry, jnp.int32(it_stop))
        compute += t.stop(carry.state)
        ckpt.save_delta(
            cfg.ckpt_dir, int(carry.it),
            shards.scatter_to_global(np.asarray(carry.state)),
            shards.scatter_to_global(np.asarray(carry.pending)),
            np.asarray(carry.edges), int(carry.thr), name,
        )
    return carry.state, int(carry.it), carry.edges, compute


def run_convergence_app(prog, shards, cfg, name: str, g=None):
    """Shared driver for frontier apps (SSSP + CC).  Returns
    (global_state, stacked_device_state, effective_shards) — the shard
    layout can change mid-run under --repartition-every, so validation
    must use the returned layout, not the one passed in."""
    from lux_tpu.engine import methods

    cfg.method = methods.resolve_sum(cfg.method, prog.reduce)
    common.resolve_route_auto(cfg)
    if (getattr(cfg, "route_gather", "") == "expand-pf"
            and cfg.exchange == "ring"):
        common.downgrade_pf(cfg, "the ring exchange")
    if getattr(cfg, "route_gather", "") and (
            cfg.ckpt_every or cfg.repartition_every
            or cfg.verbose or cfg.method == "pallas"
            or (cfg.exchange == "ring" and not cfg.distributed)
            or cfg.exchange not in ("allgather", "ring")
            or cfg.compact_gather
            or (cfg.distributed and getattr(cfg, "delta", 0))):
        raise SystemExit(
            "--route-gather on push apps routes the allgather dense "
            "rounds (single-device or --distributed; composes with "
            "single-device --delta) and the distributed ring dense "
            "rounds; it cannot combine with checkpointing/"
            "--repartition-every/-verbose/--method pallas/"
            "--compact-gather"
        )
    if cfg.method in ("cumsum", "mxsum"):
        raise SystemExit(
            f"--method {cfg.method} is a prefix-diff strategy: sum-reduce "
            f"programs only (this app reduces with {prog.reduce})"
        )
    if cfg.method == "pallas":
        if cfg.verbose or cfg.repartition_every:
            raise SystemExit(
                "--method pallas: -verbose/--repartition-every are not "
                "wired to the kernel path; use --method scan/scatter"
            )
    if cfg.ckpt_every or cfg.ckpt_dir:
        if not (cfg.ckpt_every and cfg.ckpt_dir):
            raise SystemExit(
                "frontier-app checkpointing runs in windows: pass BOTH "
                "--ckpt-dir and --ckpt-every"
            )
        if cfg.verbose or cfg.repartition_every or cfg.method == "pallas":
            raise SystemExit(
                "--ckpt-every (frontier apps) is a windowed driver; it "
                "does not combine with -verbose, --repartition-every, or "
                "--method pallas"
            )
    if cfg.repartition_every:
        if cfg.repartition_every < 0:
            raise SystemExit("--repartition-every must be positive")
        if cfg.verbose:
            raise SystemExit(
                "--repartition-every runs the engine in windows; the "
                "per-iteration -verbose fence is not available"
            )
    if getattr(cfg, "delta", 0):
        if cfg.delta < 0:
            raise SystemExit("--delta must be positive")
        if not getattr(cfg, "weighted", False):
            raise SystemExit(
                "--delta orders WEIGHTED distances into buckets; "
                "unweighted BFS already expands one hop-bucket per "
                "iteration — add --weighted"
            )
        if (cfg.exchange != "allgather" or cfg.method == "pallas"
                or cfg.verbose or cfg.repartition_every):
            raise SystemExit(
                "--delta is the allgather bucketed driver (single-device "
                "or --distributed; --ckpt-every composes): it does not "
                "combine with --exchange ring/--method pallas/-verbose/"
                "--repartition-every"
            )
    if cfg.method == "pallas":
        est = preflight.estimate_push_pallas(
            shards.spec, shards.pspec, shards.pl.e_src_pos.shape[1],
            shards.t_chunk,
        )
    elif cfg.exchange == "ring":
        est = preflight.estimate_push_ring(
            shards.spec, shards.pspec, shards.e_bucket_pad
        )
    else:
        est = preflight.estimate_push(shards.spec, shards.pspec)
    est = preflight.scale_residency(est, common._residency(cfg))
    if getattr(cfg, "route_gather", ""):
        # the dense rounds' routed plan is a real per-part HBM slice
        est = preflight.add_routed_bytes(
            est,
            preflight.routed_plan_bytes_analytic(shards.spec, "expand")
            * common._residency(cfg),
        )
    print(est)
    preflight.check_fits(est)
    mesh = common.make_mesh_if(cfg)

    from lux_tpu.utils import profiling

    ckpt_compute = None
    with profiling.trace(cfg.profile_dir):
        # ONE plan computation for every routed branch — built outside
        # the timed region.  The ring exchange plans per-bucket; every
        # other branch plans on the pull layout (common.build_push_route).
        route = common.build_push_route(cfg, shards)

        timer = Timer()
        if cfg.ckpt_every and getattr(cfg, "delta", 0):
            state, iters, edges, ckpt_compute = run_delta_checkpointed(
                prog, shards, cfg, mesh, name
            )
        elif cfg.ckpt_every:
            state, iters, edges, ckpt_compute = run_push_checkpointed(
                prog, shards, cfg, mesh, name
            )
        elif cfg.repartition_every:
            from lux_tpu.engine import repartition

            def note(it, old_cuts, new_cuts, work):
                moved = int(np.abs(new_cuts - old_cuts).max())
                print(
                    f"iter {it}: repartition (imbalance "
                    f"{repartition.imbalance(work):.2f}, max boundary "
                    f"move {moved} vertices)"
                )

            res = repartition.run_push_adaptive(
                prog, g, cfg.num_parts, chunk=cfg.repartition_every,
                threshold=cfg.repartition_threshold,
                max_iters=cfg.max_iters, method=cfg.method, mesh=mesh,
                on_repartition=note, shards=shards, exchange=cfg.exchange,
                sort_segments=cfg.sort_segments,
                compact_gather=cfg.compact_gather,
            )
            state, iters, edges = res.stacked, res.iters, res.edges
            shards = res.shards
            print(f"{res.reparts} repartition(s)")
        elif cfg.verbose and mesh is None:
            arrays, parrays, carry = push.push_init(prog, shards)
            load, comp, update = push.compile_push_phases(
                prog, shards.pspec, shards.spec, cfg.method
            )
            stats = IterStats(verbose=True)
            it = 0
            while int(carry.active) > 0 and it < cfg.max_iters:
                t = Timer()
                plan = load(parrays, carry)
                lt = t.stop(plan)
                t = Timer()
                new = comp(arrays, parrays, carry, plan)
                ct = t.stop(new)
                t = Timer()
                carry = update(arrays, carry, new, plan)
                ut = t.stop(carry)
                stats.record_phases(it, int(carry.active), lt, ct, ut)
                it += 1
            state, iters, edges = carry.state, it, carry.edges
        elif cfg.verbose and cfg.exchange == "allgather":
            # step-wise DISTRIBUTED observability with the SAME 3-phase
            # load/comp/update fence as the single-device split — the
            # reference prints per-GPU loadTime/compTime/updateTime on
            # multi-GPU runs too (sssp_gpu.cu:513-518)
            arrays, parrays, carry = push.push_init_dist(prog, shards, mesh)
            load, comp, update = push.compile_push_phases_dist(
                prog, mesh, shards.pspec, shards.spec, cfg.method
            )
            stats = IterStats(verbose=True)
            it = 0
            while int(carry.active) > 0 and it < cfg.max_iters:
                t = Timer()
                plan = load(parrays, carry)
                lt = t.stop(plan)
                t = Timer()
                new = comp(arrays, parrays, carry, plan)
                ct = t.stop(new)
                t = Timer()
                carry = update(arrays, carry, new, plan)
                ut = t.stop(carry)
                stats.record_phases(it, int(carry.active), lt, ct, ut)
                it += 1
            state, iters, edges = carry.state, it, carry.edges
        elif cfg.method == "pallas":
            import jax

            from lux_tpu.parallel import pallas_dist as pd

            # interpret mode off-TPU so CPU smoke runs work; Mosaic on chip
            interp = jax.devices()[0].platform not in ("tpu", "axon")
            state, iters, edges = pd.run_push_pallas_dist(
                prog, shards, mesh, cfg.max_iters, interpret=interp
            )
        elif getattr(cfg, "delta", 0):
            from lux_tpu.engine import delta as delta_mod

            if mesh is None:
                state, iters, edges = delta_mod.run_push_delta(
                    prog, shards, cfg.delta, cfg.max_iters, cfg.method,
                    route=route
                )
            else:
                state, iters, edges = delta_mod.run_push_delta_dist(
                    prog, shards, cfg.delta, mesh, cfg.max_iters,
                    cfg.method
                )
        elif mesh is None:
            state, iters, edges = push.run_push(
                prog, shards, cfg.max_iters, cfg.method, route=route
            )
        elif cfg.exchange == "ring":
            if cfg.verbose:
                print(
                    "note: -verbose per-iteration stepping is an "
                    "allgather-exchange mode; ring runs fused on device"
                )
            state, iters, edges = push.run_push_ring(
                prog, shards, mesh, cfg.max_iters, cfg.method, route=route
            )
        else:
            state, iters, edges = push.run_push_dist(
                prog, shards, mesh, cfg.max_iters, cfg.method, route=route
            )
        elapsed = timer.stop(state)
    if ckpt_compute is not None:
        # checkpoint I/O (device_get + disk) is not engine time
        elapsed = ckpt_compute
    iters = int(iters)
    print(f"{name} converged in {iters} iterations")
    # GTEPS on edges ACTUALLY traversed (dense rounds walk every edge,
    # sparse rounds only the frontier's) — the reference's per-iteration
    # traversal accounting, SURVEY.md §6.
    report_elapsed(elapsed, shards.spec.ne, iters, traversed=push.edges_total(edges))
    # return the stacked device state too: distributed -check validates it
    # on device (CHECK_TASK_ID analog) without a host gather
    return shards.scatter_to_global(np.asarray(state)), state, shards


def main(argv=None):
    cfg = parse_args(argv, description=__doc__, sssp=True, push=True,
                     serve=True)
    g = common.load_graph(cfg, weighted=cfg.weighted)
    if cfg.serve:
        # batched multi-source query service (lux_tpu.serve): warm
        # Q-bucket engines + micro-batching scheduler; one JSON metrics
        # line instead of the one-shot GTEPS report
        from lux_tpu.serve.driver import run_serve_cli

        return run_serve_cli(cfg, g, "sssp")
    if cfg.weighted and not np.issubdtype(g.weights.dtype, np.integer):
        # same contract the sssp() library entry enforces: int costs
        # (reference WeightType=int); silent truncation would corrupt
        # distances AND the -check oracle consistently
        raise SystemExit(
            "weighted SSSP uses integer edge costs; got dtype "
            + str(g.weights.dtype)
        )
    if cfg.delta and cfg.weighted and int(g.weights.min()) < 0:
        raise SystemExit("--delta needs non-negative edge weights "
                         "(bucket order breaks under negative costs)")
    shards = build_push_app_shards(g, cfg)
    cls = (
        sssp_model.WeightedSSSPProgram if cfg.weighted
        else sssp_model.SSSPProgram
    )
    prog = cls(nv=shards.spec.nv, start=cfg.start)
    dist_result, state, shards = run_convergence_app(
        prog, shards, cfg, "sssp", g=g
    )
    reached = int(np.sum(dist_result < prog.inf))
    print(f"reached {reached}/{g.nv} vertices from {cfg.start}")
    if cfg.check:
        if cfg.distributed:
            # on-device edge walk over the sharded state — validates graphs
            # too large for a host gather (the reference's CHECK_TASK_ID
            # GPU task, core/graph.h:46 + sssp_gpu.cu:773-798)
            from lux_tpu.engine import validate

            violations = validate.count_violations(
                shards.pull, state,
                validate.sssp_violation(prog.inf, weighted=cfg.weighted),
            )
        else:
            violations = sssp_model.check_distances(
                g, dist_result, weighted=cfg.weighted
            )
        ok = common.print_check("sssp", violations)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
