"""Shared app-driver scaffolding (the role of each reference app's
top_level_task: load graph -> preflight -> init -> iterate -> report,
e.g. pagerank/pagerank.cc:32-118)."""
from __future__ import annotations

import logging

import numpy as np

from lux_tpu.graph import generate
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.format import read_lux
from lux_tpu.utils.config import RunConfig

log = logging.getLogger("lux_tpu")


def load_graph(cfg: RunConfig, weighted: bool = False) -> HostGraph:
    if cfg.file:
        g = read_lux(cfg.file)
        if weighted and not g.weighted:
            raise SystemExit(f"{cfg.file} has no edge weights")
        log.info("loaded %s: nv=%d ne=%d", cfg.file, g.nv, g.ne)
        return g
    if weighted:
        n_half = (1 << cfg.rmat_scale) // 2
        g = generate.bipartite_ratings(
            n_half, n_half, (1 << cfg.rmat_scale) * cfg.rmat_ef // 2,
            seed=cfg.seed,
        )
    else:
        g = generate.rmat(cfg.rmat_scale, cfg.rmat_ef, seed=cfg.seed)
    log.info("synthetic graph: nv=%d ne=%d", g.nv, g.ne)
    return g


def make_mesh_if(cfg: RunConfig):
    if not cfg.distributed:
        return None
    from lux_tpu.parallel.mesh import make_mesh

    return make_mesh(cfg.num_parts)


def run_pull_stepwise(prog, spec, arrays, state, start_it, num_iters, cfg,
                      nv, on_iter=None):
    """Step-wise pull loop for -verbose / -ckpt-every runs.  Verbose mode
    fences each iteration into load/comp/update sub-steps (the reference's
    per-phase kernel timers, sssp_gpu.cu:513-518); otherwise the iteration
    runs as one jitted step.  Returns (final_state, IterStats)."""
    from lux_tpu.engine import pull
    from lux_tpu.utils.timing import IterStats, Timer

    stats = IterStats(verbose=cfg.verbose)
    if cfg.verbose:
        load, comp, update = pull.compile_pull_phases(prog, spec, cfg.method)
    else:
        step = pull.compile_pull_step(prog, spec, cfg.method)
    for it in range(start_it, num_iters):
        if cfg.verbose:
            t = Timer()
            gath = load(arrays, state)
            lt = t.stop(gath)
            t = Timer()
            acc = comp(arrays, gath)
            ct = t.stop(acc)
            t = Timer()
            state = update(arrays, state, acc)
            ut = t.stop(state)
            stats.record_phases(it, nv, lt, ct, ut)
        else:
            t = Timer()
            state = step(arrays, state)
            stats.record(it, nv, t.stop(state))
        if on_iter is not None:
            on_iter(it, state)
    return state, stats


def print_check(name: str, violations: int):
    """Reference-parity [PASS]/[FAIL] verdict (sssp_gpu.cu:837-842)."""
    verdict = "[PASS]" if violations == 0 else "[FAIL]"
    print(f"{verdict} {name} check: {violations} violations")
    return violations == 0


def top_k(label: str, values: np.ndarray, k: int = 5):
    idx = np.argsort(values)[::-1][:k]
    print(f"top-{k} {label}: " + ", ".join(f"v{int(i)}={values[i]:.3e}" for i in idx))
