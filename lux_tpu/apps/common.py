"""Shared app-driver scaffolding (the role of each reference app's
top_level_task: load graph -> preflight -> init -> iterate -> report,
e.g. pagerank/pagerank.cc:32-118)."""
from __future__ import annotations

import logging

import numpy as np

from lux_tpu.graph import generate
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.format import read_lux
from lux_tpu.utils.config import RunConfig

log = logging.getLogger("lux_tpu")


def load_graph(cfg: RunConfig, weighted: bool = False) -> HostGraph:
    if cfg.file:
        g = read_lux(cfg.file)
        if weighted and not g.weighted:
            raise SystemExit(f"{cfg.file} has no edge weights")
        log.info("loaded %s: nv=%d ne=%d", cfg.file, g.nv, g.ne)
        return g
    if weighted:
        n_half = (1 << cfg.rmat_scale) // 2
        g = generate.bipartite_ratings(
            n_half, n_half, (1 << cfg.rmat_scale) * cfg.rmat_ef // 2,
            seed=cfg.seed,
        )
    else:
        g = generate.rmat(cfg.rmat_scale, cfg.rmat_ef, seed=cfg.seed)
    log.info("synthetic graph: nv=%d ne=%d", g.nv, g.ne)
    return g


def make_mesh_if(cfg: RunConfig):
    if not cfg.distributed:
        return None
    from lux_tpu.parallel.mesh import make_mesh

    return make_mesh(cfg.num_parts)


def print_check(name: str, violations: int):
    """Reference-parity [PASS]/[FAIL] verdict (sssp_gpu.cu:837-842)."""
    verdict = "[PASS]" if violations == 0 else "[FAIL]"
    print(f"{verdict} {name} check: {violations} violations")
    return violations == 0


def top_k(label: str, values: np.ndarray, k: int = 5):
    idx = np.argsort(values)[::-1][:k]
    print(f"top-{k} {label}: " + ", ".join(f"v{int(i)}={values[i]:.3e}" for i in idx))
