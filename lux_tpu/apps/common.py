"""Shared app-driver scaffolding (the role of each reference app's
top_level_task: load graph -> preflight -> init -> iterate -> report,
e.g. pagerank/pagerank.cc:32-118)."""
from __future__ import annotations

import logging

import numpy as np

from lux_tpu.graph import generate
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.format import read_lux
from lux_tpu.utils.config import RunConfig

log = logging.getLogger("lux_tpu")


def load_graph(cfg: RunConfig, weighted: bool = False,
               bipartite: bool = False) -> HostGraph:
    """``weighted`` requires/generates edge weights; ``bipartite`` shapes
    the synthetic graph as a rating graph (CF)."""
    if cfg.file:
        try:
            g = read_lux(cfg.file)
        except (OSError, ValueError) as e:
            raise SystemExit(f"cannot read {cfg.file}: {e}")
        if weighted and not g.weighted:
            raise SystemExit(f"{cfg.file} has no edge weights")
        log.info("loaded %s: nv=%d ne=%d", cfg.file, g.nv, g.ne)
        return g
    if bipartite:
        n_half = (1 << cfg.rmat_scale) // 2
        g = generate.bipartite_ratings(
            n_half, n_half, (1 << cfg.rmat_scale) * cfg.rmat_ef // 2,
            seed=cfg.seed,
        )
    else:
        g = generate.rmat(
            cfg.rmat_scale, cfg.rmat_ef, seed=cfg.seed, weighted=weighted
        )
    log.info("synthetic graph: nv=%d ne=%d", g.nv, g.ne)
    return g


def make_mesh_if(cfg: RunConfig):
    if not cfg.distributed:
        return None
    if cfg.edge_shards > 1:
        from lux_tpu.parallel.edge2d import make_mesh2d

        return make_mesh2d(cfg.num_parts, cfg.edge_shards)
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    # -ng may exceed the device count: k = parts/mesh-size parts stay
    # resident per device (the reference mapper's slicing analog)
    return make_mesh_for_parts(cfg.num_parts)


def require_parts_fit_devices(cfg: RunConfig, what: str) -> None:
    """One part per device: the pallas engines (pull and push) don't
    support k resident parts (allgather/ring/scatter do)."""
    import jax

    if cfg.num_parts > len(jax.devices()):
        raise SystemExit(
            f"{what} keeps one part per device; -ng must not exceed the "
            f"device count ({len(jax.devices())} available; "
            "allgather/ring/scatter support multiple resident parts per "
            "device)"
        )


_ROUTE_VERBOSE_ERR = (
    "-verbose 3-phase fencing is a direct-gather observability mode; "
    "drop --route-gather or -verbose")


def route_base(rg: str) -> str:
    """Layout family of a --route-gather mode: 'expand-pf'/'fused-pf'/
    'fused-mx' bind the same shard layouts as their base — pass fusion
    (and the mxreduce in-kernel reduction) only changes the device
    kernel grouping (ops/expand.to_pf / plan_fused mx=True), never the
    plan's layout contract."""
    return rg[:-3] if rg.endswith(("-pf", "-mx")) else rg


def route_is_pf(rg: str) -> bool:
    # fused-mx is inherently pass-fused (its prefix groups + the
    # in-kernel reduce group all run the pf kernels)
    return rg.endswith(("-pf", "-mx"))


def route_mx(rg: str):
    """The ``mx`` argument of the fused planners for a --route-gather
    mode: 'fused-mx' plans the MXREDUCE form explicitly; 'fused-pf'
    follows the chip-measured ``tpu:reduce_mode`` winner (None —
    ops/expand.resolve_fused_mx), so a banked mxreduce measurement
    upgrades the pass-fused flag without a code edit; plain 'fused'
    stays the unfused family (False)."""
    if rg == "fused-mx":
        return True
    return None if rg == "fused-pf" else False


def resolve_route_auto(cfg) -> None:
    """Bare ``--route-gather`` (const 'auto') follows the chip-measured
    routed-vs-routed-pf winner (engine/methods.route_mode: overlay
    entry ``tpu:route_mode``, recorded by the default TPU bench race;
    LUX_ROUTE_MODE env override) — an unattended window's measurement
    changes what the bare flag runs without a code edit.  Both modes
    are bitwise-identical, so this is a perf decision only."""
    if getattr(cfg, "route_gather", "") != "auto":
        return
    from lux_tpu.engine import methods

    cfg.route_gather = ("expand-pf" if methods.route_mode() == "routed-pf"
                        else "expand")


def downgrade_pf(cfg, layout: str) -> None:
    """expand-pf -> expand with a stderr note.  Pass-fused plans bind
    the allgather pull layout; pf is a bitwise-identical kernel-grouping
    detail, so layouts that plan per-bucket run the unfused family
    rather than erroring — ONE policy shared by the pull validation and
    the push apps' ring path."""
    import sys

    print(f"# --route-gather expand-pf: {layout} plans per-bucket; "
          "running the unfused 'expand' family (bitwise-identical)",
          file=sys.stderr)
    cfg.route_gather = "expand"


def _downgrade_scan_family(cfg: RunConfig, was_auto: bool, prog,
                           where: str) -> None:
    """An AUTO-refined scan-family winner (mxsum/mxscan via the banked
    ``tpu:sum`` entry, engine/methods.sum_mode) downgrades to the
    blanket winner on the bucketed exchanges — their drivers run
    scan/scatter only, exactly like segment_reduce_by_ends' own
    downgrade.  An EXPLICIT --method choice is left for the branch's
    own SystemExit (loud CLI failure, never a silent swap)."""
    if was_auto and cfg.method in ("mxsum", "mxscan"):
        import sys

        from lux_tpu.engine import methods

        blanket = methods.resolve("auto", prog.reduce)
        print(f"# --method auto: banked {cfg.method} winner downgraded "
              f"to {blanket} on {where} (bucketed reductions run "
              "scan/scatter)", file=sys.stderr)
        cfg.method = blanket


def validate_exchange(cfg: RunConfig, prog) -> None:
    """Reject incompatible --exchange combinations BEFORE the O(ne) shard
    build, with a CLI-level message (not a deep driver assert).  Resolves
    ``--method auto`` to the platform's measured winner first — through
    ``resolve_sum``, so the banked ``tpu:sum`` scan-family winner
    (ISSUE 11) actually reaches the engines from the CLI — and every
    later check (and the run itself) sees a concrete strategy."""
    from lux_tpu.engine import methods

    was_auto = cfg.method == "auto"
    cfg.method = methods.resolve_sum(cfg.method, prog.reduce)
    if cfg.method in ("cumsum", "mxsum") and prog.reduce != "sum":
        raise SystemExit(
            f"--method {cfg.method} is a prefix-diff strategy: sum-reduce "
            f"programs only (this app reduces with {prog.reduce})"
        )
    if cfg.method == "pallas":
        if prog.reduce != "sum":
            raise SystemExit(
                "--method pallas: sum-reduce programs only; min/max apps "
                "use scan/scatter"
            )
        if cfg.exchange != "allgather" or cfg.edge_shards > 1:
            raise SystemExit(
                "--method pallas runs on the allgather exchange, 1-D mesh"
            )
        if cfg.distributed:
            require_parts_fit_devices(cfg, "--method pallas")
    # layout relayouts bind to the allgather pull layout's src_pos; check
    # BEFORE the allgather early-return so pallas combos are caught too
    if cfg.sort_segments and (
        cfg.exchange != "allgather" or cfg.edge_shards > 1
        or cfg.feat_shards > 1 or cfg.method == "pallas"
    ):
        raise SystemExit(
            "--sort-segments relays out the allgather pull layout; the "
            "bucket (ring/scatter/edge2d), feat-sharded, and block-CSR "
            "(pallas) layouts have their own edge orders"
        )
    if cfg.compact_gather and (
        cfg.exchange != "allgather" or cfg.edge_shards > 1
        or cfg.feat_shards > 1 or cfg.method == "pallas"
    ):
        raise SystemExit(
            "--compact-gather mirrors the allgather pull layout's "
            "src_pos; the bucket (ring/scatter/edge2d) and feat-sharded "
            "layouts ship their own slices and pallas has its own "
            "block-CSR gather"
        )
    if getattr(cfg, "route_gather", ""):
        resolve_route_auto(cfg)
        if (cfg.route_gather == "expand-pf"
                and (cfg.exchange != "allgather" or cfg.edge_shards > 1
                     or cfg.feat_shards > 1)):
            downgrade_pf(cfg, "this exchange/layout")
        if getattr(prog, "k", 1) > 1 and route_base(cfg.route_gather) == "fused":
            raise SystemExit(
                "--route-gather fused supports scalar vertex state; "
                "colfilter's wide dst-dependent load routes with "
                "--route-gather expand (per-column src + dst plans)"
            )
        # the bucketed / sharded exchanges plan per-bucket and are
        # served by the UNFUSED family only ('expand'); the pass-fused
        # variants bind the allgather pull layout
        bucket_ok = (cfg.exchange in ("ring", "scatter")
                     and cfg.route_gather == "expand"
                     and getattr(prog, "k", 1) == 1)
        feat_ok = (cfg.feat_shards > 1 and cfg.route_gather == "expand"
                   and cfg.exchange == "allgather")
        e2d_ok = (cfg.edge_shards > 1 and cfg.route_gather == "expand"
                  and cfg.exchange == "allgather"
                  and getattr(prog, "k", 1) == 1)
        if ((cfg.exchange != "allgather" and not bucket_ok)
                or (cfg.edge_shards > 1 and not e2d_ok)
                or (cfg.feat_shards > 1 and not feat_ok)
                or cfg.method == "pallas" or cfg.compact_gather
                or cfg.stream_hbm_gib):
            raise SystemExit(
                "--route-gather expand covers every pull layout "
                "(allgather, ring/scatter buckets, edge-sharded chunks, "
                "feat-sharded columns); 'fused' and the pass-fused "
                "'-pf' variants are allgather-only, and no mode "
                "combines with --method pallas/--compact-gather/"
                "--stream-hbm-gib"
            )
        if cfg.verbose:
            raise SystemExit(_ROUTE_VERBOSE_ERR)
        if cfg.ckpt_every and cfg.distributed:
            raise SystemExit(
                "--route-gather with checkpointing is a single-device "
                "stepping mode; the distributed chunked driver runs the "
                "direct gather — drop one of the flags"
            )
    if cfg.feat_shards > 1:
        if getattr(prog, "k", 1) <= 1:
            raise SystemExit(
                "--feat-shards shards a wide (V, K) latent state; this "
                "app's state has no feature dim (colfilter only)"
            )
        if not cfg.distributed:
            raise SystemExit("--feat-shards requires --distributed")
        if cfg.exchange not in ("allgather", "ring") or cfg.edge_shards > 1:
            raise SystemExit(
                "--feat-shards (2-D parts x feat mesh) runs on the "
                "allgather or ring exchange; it cannot combine with "
                "--exchange scatter or --edge-shards"
            )
        if cfg.exchange == "ring":
            _downgrade_scan_family(cfg, was_auto, prog,
                                   "--feat-shards --exchange ring")
        if cfg.exchange == "ring" and cfg.method not in ("scan", "scatter"):
            raise SystemExit(
                "--feat-shards --exchange ring supports --method "
                "scan/scatter (bucketed reductions carry no row_ptr)"
            )
        if cfg.method == "pallas":
            raise SystemExit(
                "--feat-shards supports --method scan/scatter/cumsum/"
                "mxsum (the kernel path has its own distribution)"
            )
        if prog.k % cfg.feat_shards:
            raise SystemExit(
                f"--feat-shards {cfg.feat_shards} must divide the latent "
                f"dim K={prog.k}"
            )
        import jax

        if len(jax.devices()) < cfg.feat_shards:
            # the parts axis shrinks to k-resident layouts, but each feat
            # shard needs its own chip column
            raise SystemExit(
                f"--feat-shards {cfg.feat_shards}: needs at least that "
                f"many devices, {len(jax.devices())} available"
            )
        return
    if cfg.edge_shards > 1:
        if not cfg.distributed:
            raise SystemExit("--edge-shards requires --distributed")
        import jax

        need = cfg.num_parts * cfg.edge_shards
        if len(jax.devices()) < need:
            raise SystemExit(
                f"--edge-shards: {cfg.num_parts} x {cfg.edge_shards} = "
                f"{need} devices needed, {len(jax.devices())} available"
            )
        if cfg.exchange != "allgather":
            raise SystemExit(
                "--edge-shards (2-D mesh) has its own exchange; it cannot "
                "combine with --exchange ring/scatter"
            )
        _downgrade_scan_family(cfg, was_auto, prog, "--edge-shards")
        if cfg.method in ("cumsum", "mxsum", "mxscan"):
            raise SystemExit(
                "--edge-shards supports --method scan or scatter "
                "(edge chunks carry no row_ptr for prefix-diff reduces; "
                "the mxscan kernel is confined to the csc engines here)"
            )
        return
    if cfg.exchange == "allgather":
        return
    if not cfg.distributed:
        raise SystemExit(f"--exchange {cfg.exchange} requires --distributed")
    _downgrade_scan_family(cfg, was_auto, prog,
                           f"--exchange {cfg.exchange}")
    if cfg.method in ("cumsum", "mxsum", "mxscan"):
        raise SystemExit(
            "--exchange ring/scatter supports --method scan or scatter "
            "(bucketed reductions carry no row_ptr for prefix-diff "
            "reduces; the mxscan kernel is confined to the csc engines "
            "here)"
        )
    if cfg.exchange == "scatter":
        if prog.reduce != "sum" or getattr(prog, "needs_dst_state", False):
            raise SystemExit(
                "--exchange scatter needs a sum-reducible program without "
                "per-edge destination reads; use --exchange ring or allgather"
            )


def build_pull_route(cfg: RunConfig, shards, prog):
    """ONE --route-gather plan construction for a pull-layout run
    (host-side — call it OUTSIDE the timed region): fused plans for the
    'fused*' modes, the CF per-column src+dst plan for wide
    dst-dependent programs, the expand plan otherwise; '' = None.
    Shared by the pagerank/colfilter mains, the generic program driver
    (apps/run.py), and run_fixed_dist, so the mode->planner dispatch
    cannot drift per driver."""
    rg = getattr(cfg, "route_gather", "")
    if not rg:
        return None
    from lux_tpu.ops import expand

    pf = route_is_pf(rg)
    if route_base(rg) == "fused":
        if getattr(prog, "k", 1) > 1:
            # defense-in-depth twin of validate_exchange's CLI guard:
            # a library caller skipping validation must get the clear
            # error here, not a mid-iteration fused-shape crash
            raise SystemExit(
                "--route-gather fused supports scalar vertex state; "
                "wide dst-dependent programs route with "
                "--route-gather expand (per-column src + dst plans)")
        return expand.plan_fused_shards_cached(shards, prog.reduce, pf=pf,
                                               mx=route_mx(rg))
    if getattr(prog, "k", 1) > 1:
        # wide states route through the CF per-column src+dst plans (a
        # program that ignores dst still reads it exactly; XLA DCEs it)
        return expand.plan_cf_route_shards_cached(shards, pf=pf)
    return expand.plan_expand_shards_cached(shards, pf=pf)


def build_push_route(cfg: RunConfig, shards):
    """The push apps' --route-gather twin of build_pull_route: ring
    exchanges plan per-bucket, every other push branch routes the dense
    rounds on the pull layout.  Shared by the sssp/components
    convergence driver and the generic program driver's frontier
    workloads."""
    if not getattr(cfg, "route_gather", ""):
        return None
    from lux_tpu.ops import expand

    if cfg.exchange == "ring":
        return expand.plan_ring_route_shards_cached(shards)
    return expand.plan_expand_shards_cached(
        shards, pf=route_is_pf(cfg.route_gather))


def build_exchange_shards(g: HostGraph, cfg: RunConfig):
    """Shard builder for the selected --exchange strategy (SURVEY.md §2.5).
    ring/scatter bucket the graph for their collectives; allgather uses the
    plain pull layout."""
    from lux_tpu.graph.shards import build_pull_shards

    if cfg.edge_shards > 1:
        from lux_tpu.parallel.edge2d import build_edge2d_shards

        return build_edge2d_shards(g, cfg.num_parts, cfg.edge_shards)
    if cfg.exchange == "allgather":
        return build_pull_shards(
            g, cfg.num_parts, sort_segments=cfg.sort_segments,
            compact_gather=cfg.compact_gather,
        )
    if not cfg.distributed:
        raise SystemExit(f"--exchange {cfg.exchange} requires --distributed")
    if cfg.exchange == "ring":
        from lux_tpu.parallel.ring import build_ring_shards

        return build_ring_shards(g, cfg.num_parts)
    from lux_tpu.parallel.scatter import build_scatter_shards

    return build_scatter_shards(g, cfg.num_parts)


def _residency(cfg: RunConfig) -> int:
    """k = parts RESIDENT per device for this config (1 when every part
    gets its own chip).  Mirrors make_mesh_for_parts /
    make_mesh_feat_for_parts slot arithmetic."""
    if cfg.edge_shards > 1:
        return 1  # edge2d estimate already counts the whole footprint
    if not cfg.distributed:
        # single-device drivers place ALL parts on the one device: the
        # stacked (P, ...) shard arrays and per-part state are all
        # resident at once, so the per-part estimate scales by P.
        return cfg.num_parts
    import jax

    slots = len(jax.devices())
    if cfg.feat_shards > 1:
        slots //= cfg.feat_shards
    d = min(slots, cfg.num_parts)
    while cfg.num_parts % d:
        d -= 1
    return cfg.num_parts // d


def estimate_exchange(shards, cfg: RunConfig, state_width: int = 1):
    """Preflight estimate matching the selected exchange strategy.
    Per-part estimates are scaled by the residency factor k (k parts
    resident per chip when num_parts exceeds the parts slots) — the
    gathered/exchange buffer is global-sized and does not scale."""
    from lux_tpu.utils import preflight

    sbytes = 2 if cfg.dtype == "bfloat16" else 4
    if cfg.edge_shards > 1:
        est = preflight.estimate_edge2d(
            shards.spec, shards.e2_pad, state_width, sbytes
        )
        if getattr(cfg, "route_gather", ""):
            # one chunk plan per device: n from the chunk pad + the
            # parts-gathered state
            est = preflight.add_routed_bytes(
                est,
                preflight.routed_bucket_plan_bytes_analytic(
                    1, shards.e2_pad,
                    shards.spec.num_parts * shards.spec.nv_pad),
            )
        return est
    if cfg.exchange == "ring":
        est = preflight.estimate_ring(
            shards.spec, shards.e_bucket_pad, state_width, sbytes
        )
    elif cfg.exchange == "scatter":
        est = preflight.estimate_scatter(
            shards.spec, shards.e_bucket_pad, state_width, sbytes
        )
    else:
        est = preflight.estimate_pull(shards.spec, state_width, sbytes)
    est = preflight.scale_residency(est, _residency(cfg))
    if getattr(cfg, "route_gather", ""):
        # routed plans are static per-graph device arrays — a real HBM
        # slice (~270 MB expand / ~630 MB fused at rmat20).  Bucketed
        # exchanges carry P per-peer plans per resident part, a
        # different (usually larger) geometry than the allgather plan.
        if cfg.exchange in ("ring", "scatter"):
            extra = preflight.routed_bucket_plan_bytes_analytic(
                shards.spec.num_parts, shards.e_bucket_pad,
                shards.spec.nv_pad)
        else:
            extra = preflight.routed_plan_bytes_analytic(
                shards.spec, cfg.route_gather, wide=state_width > 1)
        est = preflight.add_routed_bytes(est, extra * _residency(cfg))
    return est


def report_preflight(est, cfg: RunConfig, shards, state_width: int = 1,
                     stream_hint: bool = False):
    """Print the estimate and warn if it exceeds device HBM — with the
    --edge-shards hint when (and only when) a 2-D run could actually
    execute here: 1-D allgather pull layout, non-pallas, and enough
    devices for num_parts * EP part-columns (edge2d has no
    k-residency).  One implementation for every pull app, so the hint
    can't drift per driver."""
    from lux_tpu.utils import preflight

    print(est)
    spec = None
    max_ep = 0
    if (cfg.exchange == "allgather" and cfg.edge_shards == 1
            and cfg.feat_shards == 1 and cfg.method != "pallas"):
        import jax

        spec = shards.spec
        max_ep = len(jax.devices()) // max(cfg.num_parts, 1)
    return preflight.check_fits(
        est, spec=spec, state_width=state_width,
        state_dtype_bytes=2 if cfg.dtype == "bfloat16" else 4,
        max_edge_shards=max_ep,
        # only apps that EXPOSE --stream-hbm-gib may advertise it
        stream_hint=stream_hint,
    )


def run_streamed(cfg: RunConfig, g: HostGraph, prog, state_width: int = 1,
                 active_fn=None):
    """Shared --stream-hbm-gib runner for pull apps (the -ll:zsize
    zero-copy analog, core/lux_mapper.cc:146-165): host-resident edges
    streamed through a device-byte budget (engine/stream.py).  Validates
    the combination, builds + prints the streamed geometry, runs, and
    returns (global_state, elapsed_s, iters).  ``active_fn`` selects the
    convergence driver (components) instead of the fixed-iteration one.
    Each app owns its report tail."""
    if (cfg.distributed or cfg.exchange != "allgather"
            or cfg.method == "pallas" or cfg.compact_gather
            or cfg.edge_shards > 1 or cfg.feat_shards > 1 or cfg.verbose
            or cfg.ckpt_every or cfg.ckpt_dir or cfg.repartition_every):
        raise SystemExit(
            "--stream-hbm-gib is the single-process host-offload mode; "
            "it does not combine with --distributed/--exchange/"
            "--edge-shards/--feat-shards/--method pallas/"
            "--compact-gather/-verbose/checkpointing/"
            "--repartition-every"
        )
    import jax

    from lux_tpu.engine import pull, stream as stream_eng
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils.timing import Timer

    sbytes = 2 if cfg.dtype == "bfloat16" else 4
    shards = build_pull_shards(
        g, cfg.num_parts, sort_segments=cfg.sort_segments
    )
    budget = int(cfg.stream_hbm_gib * (1 << 30))
    chunk_e = stream_eng.chunk_edges_for_budget(
        shards.spec, budget, sbytes, state_width
    )
    resident = stream_eng.streamed_hbm_bytes(
        shards.spec, chunk_e, sbytes, state_width
    )
    total = stream_eng.edge_bytes_total(shards.spec)
    ssh = stream_eng.build_streamed_pull(shards, chunk_e)
    print(
        f"streamed: {len(ssh.chunks[0])} chunk(s) of {chunk_e} edges/part; "
        f"resident {resident/(1<<30):.3f} GiB <= budget "
        f"{budget/(1<<30):.3f} GiB (monolithic edge arrays "
        f"{total/(1<<30):.3f} GiB)"
    )
    state0 = pull.init_state(prog, ssh.varrays)
    from lux_tpu.utils import profiling

    with profiling.trace(cfg.profile_dir):
        timer = Timer()
        if active_fn is not None:
            out, iters = stream_eng.run_pull_until_streamed(
                prog, ssh, state0, cfg.max_iters, active_fn,
                method=cfg.method,
            )
        else:
            out = stream_eng.run_pull_fixed_streamed(
                prog, ssh, state0, cfg.num_iters, method=cfg.method
            )
            iters = cfg.num_iters
        elapsed = timer.stop(out)
    return ssh.scatter_to_global(jax.device_get(out)), elapsed, iters


def resume_or_init(cfg: RunConfig, app: str, shards, state, nv):
    """Elastic resume: restack the latest global checkpoint (any previous
    -ng/--exchange) onto THIS run's layout; returns (state, start_it)."""
    if not cfg.ckpt_dir:
        return state, 0
    import jax.numpy as jnp

    from lux_tpu.graph.shards import global_to_stacked
    from lux_tpu.utils import checkpoint

    saved, start_it, prev = checkpoint.load_resume(cfg.ckpt_dir, app, nv)
    if saved is None:
        return state, 0
    stacked = global_to_stacked(shards.cuts, shards.spec.nv_pad, saved)
    print(f"resumed from {prev} at iteration {start_it}")
    # cast to THIS run's state dtype (a resume may change --dtype)
    return jnp.asarray(stacked).astype(state.dtype), start_it


def save_global(cfg: RunConfig, app: str, shards, iteration: int, state):
    """Checkpoint the stacked device state as the layout-independent
    global vector (elastic: any later -ng/--exchange can resume it)."""
    import jax

    from lux_tpu.utils import checkpoint

    checkpoint.save_iteration(
        cfg.ckpt_dir, iteration, shards.scatter_to_global(jax.device_get(state)),
        app,
    )


def run_pull_stepwise_dist(prog, shards, state, start_it, num_iters, mesh,
                           cfg: RunConfig, nv, on_iter=None):
    """Step-wise DISTRIBUTED pull loop (-verbose --distributed only):
    each shard_map iteration fences into load/comp/update sub-steps —
    the reference prints the per-GPU phase timers on multi-GPU runs too
    (sssp_gpu.cu:513-518).  Same on_iter hook as run_pull_stepwise so
    checkpointing composes with verbose.  (Non-verbose distributed runs
    use the fused run_fixed_dist/run_fixed_dist_chunked paths.)"""
    import jax

    from lux_tpu.parallel import dist
    from lux_tpu.parallel.mesh import shard_stacked
    from lux_tpu.utils.timing import IterStats, Timer

    arrays = shard_stacked(mesh, jax.tree.map(jax.numpy.asarray, shards.arrays))
    state = shard_stacked(mesh, state)
    stats = IterStats(verbose=cfg.verbose)
    load, comp, update = dist.compile_pull_phases_dist(
        prog, mesh, cfg.method
    )
    for it in range(start_it, num_iters):
        t = Timer()
        gath = load(arrays, state)
        lt = t.stop(gath)
        t = Timer()
        acc = comp(arrays, gath)
        ct = t.stop(acc)
        t = Timer()
        state = update(arrays, state, acc)
        ut = t.stop(state)
        stats.record_phases(it, nv, lt, ct, ut)
        if on_iter is not None:
            on_iter(it, state)
    return state, stats


def run_fixed_dist_chunked(prog, shards, state, start_it, num_iters, mesh,
                           cfg: RunConfig, app: str):
    """Distributed fixed-iteration run in --ckpt-every-sized on-device
    chunks with a checkpoint between chunks.  Returns (state,
    compute_seconds) where compute_seconds EXCLUDES the host-side
    checkpoint I/O (device_get + disk) so reported GTEPS stays an engine
    number."""
    from lux_tpu.utils.timing import Timer

    compute = 0.0
    it = start_it
    while it < num_iters:
        n = min(cfg.ckpt_every, num_iters - it)
        t = Timer()
        state = run_fixed_dist(prog, shards, state, n, mesh, cfg)
        compute += t.stop(state)
        it += n
        if it < num_iters or num_iters % cfg.ckpt_every == 0:
            save_global(cfg, app, shards, it, state)
    return state, compute


def run_fixed_dist(prog, shards, state, num_iters, mesh, cfg: RunConfig):
    """Distributed fixed-iteration driver for the selected exchange."""
    if cfg.edge_shards > 1:
        from lux_tpu.parallel import edge2d

        e2_route = None
        if getattr(cfg, "route_gather", "") == "expand":
            from lux_tpu.ops import expand

            e2_route = expand.plan_edge2d_route_shards_cached(shards)
        return edge2d.run_pull_fixed_2d(
            prog, shards, state, num_iters, mesh, cfg.method,
            route=e2_route,
        )
    if cfg.exchange == "ring":
        from lux_tpu.parallel import ring

        ring_route = None
        if getattr(cfg, "route_gather", "") == "expand":
            from lux_tpu.ops import expand

            ring_route = expand.plan_ring_route_shards_cached(shards)
        return ring.run_pull_fixed_ring(
            prog, shards, state, num_iters, mesh, cfg.method,
            route=ring_route,
        )
    if cfg.exchange == "scatter":
        from lux_tpu.parallel import scatter

        sc_route = None
        if getattr(cfg, "route_gather", "") == "expand":
            from lux_tpu.ops import expand

            sc_route = expand.plan_scatter_route_shards_cached(shards)
        return scatter.run_pull_fixed_scatter(
            prog, shards, state, num_iters, mesh, cfg.method,
            route=sc_route,
        )
    from lux_tpu.parallel import dist

    route = build_pull_route(cfg, shards, prog)
    return dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, state, num_iters, mesh, cfg.method,
        route=route,
    )


def run_pull_stepwise(prog, spec, arrays, state, start_it, num_iters, cfg,
                      nv, on_iter=None, route=None):
    """Step-wise pull loop for -verbose / -ckpt-every runs.  Verbose mode
    fences each iteration into load/comp/update sub-steps (the reference's
    per-phase kernel timers, sssp_gpu.cu:513-518); otherwise the iteration
    runs as one jitted step.  ``route`` applies to the fused-step path
    only (the 3-phase verbose fence keeps the direct gather — its LOAD
    boundary is the observability contract).  Returns
    (final_state, IterStats)."""
    from lux_tpu.engine import pull
    from lux_tpu.utils.timing import IterStats, Timer

    stats = IterStats(verbose=cfg.verbose)
    if cfg.verbose:
        if route is not None:
            raise SystemExit(_ROUTE_VERBOSE_ERR)
        load, comp, update = pull.compile_pull_phases(prog, spec, cfg.method)
    else:
        step = pull.compile_pull_step(prog, spec, cfg.method, route=route)
    for it in range(start_it, num_iters):
        if cfg.verbose:
            t = Timer()
            gath = load(arrays, state)
            lt = t.stop(gath)
            t = Timer()
            acc = comp(arrays, gath)
            ct = t.stop(acc)
            t = Timer()
            state = update(arrays, state, acc)
            ut = t.stop(state)
            stats.record_phases(it, nv, lt, ct, ut)
        else:
            t = Timer()
            state = step(arrays, state)
            stats.record(it, nv, t.stop(state))
        if on_iter is not None:
            on_iter(it, state)
    return state, stats


def print_check(name: str, violations: int):
    """Reference-parity [PASS]/[FAIL] verdict (sssp_gpu.cu:837-842)."""
    verdict = "[PASS]" if violations == 0 else "[FAIL]"
    print(f"{verdict} {name} check: {violations} violations")
    return violations == 0


def top_k(label: str, values: np.ndarray, k: int = 5):
    idx = np.argsort(values)[::-1][:k]
    # float() so non-native dtypes (bfloat16) format cleanly
    print(
        f"top-{k} {label}: "
        + ", ".join(f"v{int(i)}={float(values[i]):.3e}" for i in idx)
    )
