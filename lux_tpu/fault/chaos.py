"""Chaos soak: a seeded randomized fault schedule over a live fleet.

One soak builds a 2-worker (default) mutation-aware fleet
(``serve/live``), installs a seeded wire-fault plan, and drives a
seeded event stream — edge-churn writes, bounded reads, stale-degrade
reads, fleet refreshes, worker kills + rejoins, optionally a
controller kill + promotion — asserting the STANDING INVARIANTS at
every step and again after recovery:

1. **No acked write lost** — every admit that returned is applied to
   an independent mirror DeltaLog; at the end the controller journal's
   merged graph must equal the mirror's bitwise (and after a failover,
   the promoted controller's generation line must cover every ack).
2. **Read-your-writes** — a read bounded by ``min_generation=g``
   either carries a tag >= g or raised StaleReadError; with
   ``stale_ok`` it carries the explicit ``stale`` tag instead.  Every
   answer is compared BITWISE against ``bfs_reference`` of the merged
   graph at exactly the generation its tag names — a stale answer must
   be a CORRECT old answer, never a wrong one.
3. **Post-recovery convergence** — after the soak (kills, faults,
   failover and all), a fleet refresh + standing reads from EVERY
   replica are bitwise-equal to the merged reference.

Determinism: the event stream and the fault plan both derive from the
ONE ``seed``; a failure raises :class:`ChaosFailure` whose message
prints the seed, the plan (with live fire counts) and the event tail —
the reproduction recipe, per the acceptance criterion.

Scope note: the default insert capacity is sized so the soak never
crosses a compaction epoch — overflow escalation has its own dedicated
drills (tests/test_live.py) and folding epochs into the soak would
mostly re-test them slowly.  Worker rejoin therefore replays the local
journal prefix and catches up from the controller, the same path a
production same-epoch crash takes.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, List, Optional

import numpy as np

from lux_tpu import fault
from lux_tpu.fault.drills import wire_chaos
from lux_tpu.mutate.deltalog import DeltaLog


class ChaosFailure(AssertionError):
    """An invariant broke; the message carries seed + plan + events."""


def _fail(seed: int, plan, events: List[dict], why: str,
          cause: Optional[BaseException] = None) -> "ChaosFailure":
    tail = events[-12:]
    msg = (f"chaos soak FAILED (seed={seed}): {why}\n"
           f"reproduce: chaos_soak(seed={seed})\n"
           f"{plan.describe() if plan is not None else 'no wire plan'}\n"
           "event tail:\n" +
           "\n".join(f"  {json.dumps(e, default=str)}" for e in tail))
    err = ChaosFailure(msg)
    if cause is not None:
        err.__cause__ = cause
    return err


def chaos_soak(seed: int, steps: int = 16, workers: int = 2,
               scale: int = 8, ef: int = 4, rows: int = 10,
               cap: int = 4096, controller_kill: bool = False,
               wire_faults: bool = True,
               journal_root: Optional[str] = None,
               read_deadline_s: float = 60.0) -> dict:
    """Run one seeded soak; returns the report dict or raises
    :class:`ChaosFailure`."""
    from lux_tpu import obs
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.sssp import bfs_reference
    from lux_tpu.serve.live.bench import churn_batch
    from lux_tpu.serve.live.controller import (
        promote_live_controller,
        start_live_fleet,
    )
    from lux_tpu.serve.live.replica import LiveReplica

    rng = np.random.default_rng(seed)
    g = generate.rmat(scale, ef, seed=int(rng.integers(1 << 30)))
    own_tmp = None
    if journal_root is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lux_chaos_")
        journal_root = own_tmp.name
    snapshot_path = os.path.join(journal_root, "snap.lux")
    standing = (("sssp", 0),)
    parts = 2
    plan = wire_chaos(seed=seed + 1) if wire_faults else None
    events: List[dict] = []
    graphs = {0: g}  # generation -> merged HostGraph (tiny at scale 8)
    mirror = DeltaLog(g)  # the independent acked-writes mirror
    acked_gen = 0
    kills = rejoins = failovers = 0
    dead: Dict[str, object] = {}  # wid -> killed worker (to rejoin)

    fleet = start_live_fleet(
        workers, g, parts=parts, cap=cap, buckets=(1, 4),
        standing=standing, journal_root=journal_root,
        snapshot_path=snapshot_path)
    ctl = fleet.controller
    shards = build_pull_shards(g, parts)

    def bounded_read(src: int, bound: int, stale_ok: bool):
        fut = ctl.submit_retrying(
            int(src), deadline_s=read_deadline_s, min_generation=bound,
            stale_ok=stale_ok,
            request_id=f"chaos-{seed}-r{len(events)}")
        ans = fut.result(timeout=0)
        tag = fut.generation if fut.generation is not None else 0
        if not stale_ok and tag < bound:
            raise AssertionError(
                f"read-your-writes broke: bound {bound}, tag {tag}")
        if stale_ok and tag < bound and not fut.stale:
            raise AssertionError(
                f"stale answer (tag {tag} < bound {bound}) missing the "
                "explicit stale tag")
        ref = bfs_reference(graphs[tag], int(src))
        if not np.array_equal(ans, ref):
            raise AssertionError(
                f"answer at generation {tag} (src {src}) is not the "
                "merged reference — wrong, not just stale")
        return tag, bool(fut.stale)

    def rejoin(wid: str):
        from lux_tpu.serve.fleet.worker import ReplicaWorker

        live = LiveReplica(
            g, shards, cap=cap,
            journal_dir=os.path.join(journal_root, wid),
            standing=standing)
        w = ReplicaWorker(shards, worker_id=wid, graph_id="live",
                          q_buckets=(1, 4), live=live).start()
        fleet.thread_workers.append(w)
        ctl.add_worker("127.0.0.1", w.port)
        return w

    try:
        with obs.span("fault.chaos", seed=seed, steps=steps,
                      workers=workers):
            if plan is not None:
                fault.install(plan)
            kill_step = (int(rng.integers(steps // 3, 2 * steps // 3))
                         if controller_kill else -1)
            for i in range(steps):
                if i == kill_step:
                    ctl.kill()
                    failovers += 1
                    endpoints = [("127.0.0.1", w.port)
                                 for w in fleet.thread_workers
                                 if w._running]
                    ctl, rep = promote_live_controller(
                        g, os.path.join(journal_root, "controller"),
                        snapshot_path, endpoints, seed=seed + 2)
                    fleet.controller = ctl
                    events.append({"i": i, "ev": "failover",
                                   "joined": rep["joined"],
                                   "refused": rep["refused"],
                                   "gen": ctl.generation()})
                    if ctl.generation() < acked_gen:
                        raise AssertionError(
                            f"promotion lost acked writes: journal at "
                            f"{ctl.generation()}, acked {acked_gen}")
                    continue
                ev = rng.choice(
                    ["write", "read", "read_stale", "refresh", "kill"],
                    p=[0.45, 0.25, 0.10, 0.10, 0.10])
                if ev == "kill" and len(ctl.live_workers()) <= 1:
                    ev = "write"  # never kill the last live replica
                if ev == "write":
                    src, dst, op = churn_batch(mirror, rng, rows)
                    rep = ctl.admit_writes(
                        src, dst, op,
                        write_id=f"chaos-{seed}-w{i}")
                    if not rep.get("deduped"):
                        mirror.apply(src, dst, op)
                        graphs[rep["generation"]] = mirror.merged_graph()
                    acked_gen = max(acked_gen, rep["generation"])
                    events.append({"i": i, "ev": "write",
                                   "gen": rep["generation"],
                                   "acked": rep["acked"]})
                elif ev in ("read", "read_stale"):
                    src = int(rng.integers(0, g.nv))
                    stale_ok = ev == "read_stale"
                    bound = acked_gen + (1 if stale_ok else 0)
                    tag, stale = bounded_read(src, bound, stale_ok)
                    events.append({"i": i, "ev": ev, "src": src,
                                   "bound": bound, "tag": tag,
                                   "stale": stale})
                elif ev == "refresh":
                    if dead:  # rejoin before refreshing (refresh_fleet
                        # needs every live replica to answer)
                        for wid in sorted(dead):
                            rejoin(wid)
                            rejoins += 1
                        dead.clear()
                    ctl.refresh_fleet()
                    for wid, ent in ctl.read_standing_all("sssp").items():
                        tag = int(ent["generation"])
                        if not np.array_equal(
                                ent["state"],
                                bfs_reference(graphs[tag], 0)):
                            raise AssertionError(
                                f"standing state on {wid} at generation "
                                f"{tag} != merged reference")
                    events.append({"i": i, "ev": "refresh",
                                   "gen": acked_gen})
                else:  # kill one worker (rejoined on a later refresh
                    # or at the end)
                    victim = sorted(ctl.live_workers())[
                        int(rng.integers(0, len(ctl.live_workers())))]
                    w = next(x for x in fleet.thread_workers
                             if x.worker_id == victim and x._running)
                    w.kill()
                    dead[victim] = w
                    kills += 1
                    events.append({"i": i, "ev": "kill", "wid": victim})
            # ---- post-recovery acceptance --------------------------------
            for wid in sorted(dead):
                rejoin(wid)
                rejoins += 1
            dead.clear()
            merged = ctl.journal.log.merged_graph()
            mref = mirror.merged_graph()
            if not (np.array_equal(merged.row_ptr, mref.row_ptr)
                    and np.array_equal(merged.col_idx, mref.col_idx)):
                raise AssertionError(
                    "controller journal merged graph != acked-writes "
                    "mirror (acked write lost or corrupted)")
            for src in rng.integers(0, g.nv, 3):
                bounded_read(int(src), acked_gen, stale_ok=False)
            ctl.refresh_fleet()
            allr = ctl.read_standing_all("sssp")
            final_ref = bfs_reference(graphs[acked_gen], 0)
            for wid, ent in allr.items():
                if int(ent["generation"]) < acked_gen:
                    raise AssertionError(
                        f"{wid} standing tag {ent['generation']} < "
                        f"acked {acked_gen} after final refresh")
                if not np.array_equal(ent["state"], final_ref):
                    raise AssertionError(
                        f"{wid} post-recovery standing state != merged "
                        "reference")
    except ChaosFailure:
        raise
    except BaseException as e:  # noqa: BLE001 — every failure must
        # carry its reproduction recipe (seed + plan + events)
        raise _fail(seed, plan, events, f"{type(e).__name__}: {e}",
                    cause=e) from e
    finally:
        if plan is not None:
            fault.uninstall()
        try:
            fleet.close()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        if own_tmp is not None:
            own_tmp.cleanup()
    return {
        "seed": seed, "steps": steps, "generation": acked_gen,
        "writes": sum(1 for e in events if e["ev"] == "write"),
        "reads": sum(1 for e in events if e["ev"].startswith("read")),
        "worker_kills": kills, "rejoins": rejoins,
        "failovers": failovers,
        "faults_injected": plan.total_fired() if plan else 0,
        "fault_counters": plan.counters() if plan else [],
        "events": events,
    }
