"""Chaos soak: a seeded randomized fault schedule over a live fleet.

One soak builds a 2-worker (default) mutation-aware fleet
(``serve/live``), installs a seeded wire-fault plan, and drives a
seeded event stream — edge-churn writes, bounded reads, stale-degrade
reads, fleet refreshes, worker kills + rejoins, optionally a
controller kill + promotion — asserting the STANDING INVARIANTS at
every step and again after recovery:

1. **No acked write lost** — every admit that returned is applied to
   an independent mirror DeltaLog; at the end the controller journal's
   merged graph must equal the mirror's bitwise (and after a failover,
   the promoted controller's generation line must cover every ack).
2. **Read-your-writes** — a read bounded by ``min_generation=g``
   either carries a tag >= g or raised StaleReadError; with
   ``stale_ok`` it carries the explicit ``stale`` tag instead.  Every
   answer is compared BITWISE against ``bfs_reference`` of the merged
   graph at exactly the generation its tag names — a stale answer must
   be a CORRECT old answer, never a wrong one.
3. **Post-recovery convergence** — after the soak (kills, faults,
   failover and all), a fleet refresh + standing reads from EVERY
   replica are bitwise-equal to the merged reference.

Determinism: the event stream and the fault plan both derive from the
ONE ``seed``; a failure raises :class:`ChaosFailure` whose message
prints the seed, the plan (with live fire counts) and the event tail —
the reproduction recipe, per the acceptance criterion.

Scope note: the default insert capacity is sized so the soak never
crosses a compaction epoch — overflow escalation has its own dedicated
drills (tests/test_live.py) and folding epochs into the soak would
mostly re-test them slowly.  Worker rejoin therefore replays the local
journal prefix and catches up from the controller, the same path a
production same-epoch crash takes.

:func:`autopilot_soak` (ISSUE 16) is the AUTONOMOUS variant: the same
invariants, but every operational action is taken by the autopilot
subsystems instead of the harness — a load ramp trips the
:class:`~lux_tpu.serve.autopilot.autoscaler.Autoscaler` into a
scale-up, the controller kill is detected and repaired by a
:class:`~lux_tpu.serve.autopilot.election.Standby` election, a small
insert capacity forces an overflow-escalated compaction, and a
standing-query subscription must keep delivering across all of it.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from lux_tpu import fault
from lux_tpu.fault.drills import wire_chaos
from lux_tpu.mutate.deltalog import DeltaLog


class ChaosFailure(AssertionError):
    """An invariant broke; the message carries seed + plan + events."""


def _fail(seed: int, plan, events: List[dict], why: str,
          cause: Optional[BaseException] = None,
          repro: str = "chaos_soak") -> "ChaosFailure":
    tail = events[-12:]
    msg = (f"chaos soak FAILED (seed={seed}): {why}\n"
           f"reproduce: {repro}(seed={seed})\n"
           f"{plan.describe() if plan is not None else 'no wire plan'}\n"
           "event tail:\n" +
           "\n".join(f"  {json.dumps(e, default=str)}" for e in tail))
    err = ChaosFailure(msg)
    if cause is not None:
        err.__cause__ = cause
    return err


def chaos_soak(seed: int, steps: int = 16, workers: int = 2,
               scale: int = 8, ef: int = 4, rows: int = 10,
               cap: int = 4096, controller_kill: bool = False,
               wire_faults: bool = True,
               journal_root: Optional[str] = None,
               read_deadline_s: float = 60.0) -> dict:
    """Run one seeded soak; returns the report dict or raises
    :class:`ChaosFailure`."""
    from lux_tpu import obs
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.sssp import bfs_reference
    from lux_tpu.serve.live.bench import churn_batch
    from lux_tpu.serve.live.controller import (
        promote_live_controller,
        start_live_fleet,
    )
    from lux_tpu.serve.live.replica import LiveReplica

    rng = np.random.default_rng(seed)
    g = generate.rmat(scale, ef, seed=int(rng.integers(1 << 30)))
    own_tmp = None
    if journal_root is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lux_chaos_")
        journal_root = own_tmp.name
    snapshot_path = os.path.join(journal_root, "snap.lux")
    standing = (("sssp", 0),)
    parts = 2
    plan = wire_chaos(seed=seed + 1) if wire_faults else None
    events: List[dict] = []
    graphs = {0: g}  # generation -> merged HostGraph (tiny at scale 8)
    mirror = DeltaLog(g)  # the independent acked-writes mirror
    acked_gen = 0
    kills = rejoins = failovers = 0
    dead: Dict[str, object] = {}  # wid -> killed worker (to rejoin)

    fleet = start_live_fleet(
        workers, g, parts=parts, cap=cap, buckets=(1, 4),
        standing=standing, journal_root=journal_root,
        snapshot_path=snapshot_path)
    ctl = fleet.controller
    shards = build_pull_shards(g, parts)

    def bounded_read(src: int, bound: int, stale_ok: bool):
        fut = ctl.submit_retrying(
            int(src), deadline_s=read_deadline_s, min_generation=bound,
            stale_ok=stale_ok,
            request_id=f"chaos-{seed}-r{len(events)}")
        ans = fut.result(timeout=0)
        tag = fut.generation if fut.generation is not None else 0
        if not stale_ok and tag < bound:
            raise AssertionError(
                f"read-your-writes broke: bound {bound}, tag {tag}")
        if stale_ok and tag < bound and not fut.stale:
            raise AssertionError(
                f"stale answer (tag {tag} < bound {bound}) missing the "
                "explicit stale tag")
        ref = bfs_reference(graphs[tag], int(src))
        if not np.array_equal(ans, ref):
            raise AssertionError(
                f"answer at generation {tag} (src {src}) is not the "
                "merged reference — wrong, not just stale")
        return tag, bool(fut.stale)

    def rejoin(wid: str):
        from lux_tpu.serve.fleet.worker import ReplicaWorker

        live = LiveReplica(
            g, shards, cap=cap,
            journal_dir=os.path.join(journal_root, wid),
            standing=standing)
        w = ReplicaWorker(shards, worker_id=wid, graph_id="live",
                          q_buckets=(1, 4), live=live).start()
        fleet.thread_workers.append(w)
        ctl.add_worker("127.0.0.1", w.port)
        return w

    try:
        with obs.span("fault.chaos", seed=seed, steps=steps,
                      workers=workers):
            if plan is not None:
                fault.install(plan)
            kill_step = (int(rng.integers(steps // 3, 2 * steps // 3))
                         if controller_kill else -1)
            for i in range(steps):
                if i == kill_step:
                    # standby-driven failover (ISSUE 16): the harness
                    # only KILLS; a Standby detects the silence,
                    # wins the incarnation-fenced election and runs
                    # promote_live_controller itself — the soak then
                    # adopts whatever the group promoted.
                    from lux_tpu.serve.autopilot.election import (
                        Standby,
                        StandbyGroup,
                    )

                    def _promote(tc=None):
                        endpoints = [("127.0.0.1", w.port)
                                     for w in fleet.thread_workers
                                     if w._running]
                        return promote_live_controller(
                            g, os.path.join(journal_root, "controller"),
                            snapshot_path, endpoints, seed=seed + 2)

                    group = StandbyGroup()
                    standbys = [Standby(group, sid, ctl, _promote,
                                        hb_interval_s=0.02,
                                        death_after_s=0.15,
                                        seed=seed).start()
                                for sid in range(2)]
                    ctl.kill()
                    failovers += 1
                    got = group.wait_promoted(timeout_s=60.0)
                    for s in standbys:
                        s.stop()
                    if got is None:
                        raise AssertionError(
                            "no standby promoted a controller within "
                            "60s of the incumbent's death")
                    ctl, rep = got
                    fleet.controller = ctl
                    events.append({"i": i, "ev": "failover",
                                   "joined": rep["joined"],
                                   "refused": rep["refused"],
                                   "winner": group.claimed_by(
                                       standbys[0].incumbent_incarnation),
                                   "gen": ctl.generation()})
                    if ctl.generation() < acked_gen:
                        raise AssertionError(
                            f"promotion lost acked writes: journal at "
                            f"{ctl.generation()}, acked {acked_gen}")
                    continue
                ev = rng.choice(
                    ["write", "read", "read_stale", "refresh", "kill"],
                    p=[0.45, 0.25, 0.10, 0.10, 0.10])
                if ev == "kill" and len(ctl.live_workers()) <= 1:
                    ev = "write"  # never kill the last live replica
                if ev == "write":
                    src, dst, op = churn_batch(mirror, rng, rows)
                    rep = ctl.admit_writes(
                        src, dst, op,
                        write_id=f"chaos-{seed}-w{i}")
                    if not rep.get("deduped"):
                        mirror.apply(src, dst, op)
                        graphs[rep["generation"]] = mirror.merged_graph()
                    acked_gen = max(acked_gen, rep["generation"])
                    events.append({"i": i, "ev": "write",
                                   "gen": rep["generation"],
                                   "acked": rep["acked"]})
                elif ev in ("read", "read_stale"):
                    src = int(rng.integers(0, g.nv))
                    stale_ok = ev == "read_stale"
                    bound = acked_gen + (1 if stale_ok else 0)
                    tag, stale = bounded_read(src, bound, stale_ok)
                    events.append({"i": i, "ev": ev, "src": src,
                                   "bound": bound, "tag": tag,
                                   "stale": stale})
                elif ev == "refresh":
                    if dead:  # rejoin before refreshing (refresh_fleet
                        # needs every live replica to answer)
                        for wid in sorted(dead):
                            rejoin(wid)
                            rejoins += 1
                        dead.clear()
                    ctl.refresh_fleet()
                    for wid, ent in ctl.read_standing_all("sssp").items():
                        tag = int(ent["generation"])
                        if not np.array_equal(
                                ent["state"],
                                bfs_reference(graphs[tag], 0)):
                            raise AssertionError(
                                f"standing state on {wid} at generation "
                                f"{tag} != merged reference")
                    events.append({"i": i, "ev": "refresh",
                                   "gen": acked_gen})
                else:  # kill one worker (rejoined on a later refresh
                    # or at the end)
                    victim = sorted(ctl.live_workers())[
                        int(rng.integers(0, len(ctl.live_workers())))]
                    w = next(x for x in fleet.thread_workers
                             if x.worker_id == victim and x._running)
                    w.kill()
                    dead[victim] = w
                    kills += 1
                    events.append({"i": i, "ev": "kill", "wid": victim})
            # ---- post-recovery acceptance --------------------------------
            for wid in sorted(dead):
                rejoin(wid)
                rejoins += 1
            dead.clear()
            merged = ctl.journal.log.merged_graph()
            mref = mirror.merged_graph()
            if not (np.array_equal(merged.row_ptr, mref.row_ptr)
                    and np.array_equal(merged.col_idx, mref.col_idx)):
                raise AssertionError(
                    "controller journal merged graph != acked-writes "
                    "mirror (acked write lost or corrupted)")
            for src in rng.integers(0, g.nv, 3):
                bounded_read(int(src), acked_gen, stale_ok=False)
            ctl.refresh_fleet()
            allr = ctl.read_standing_all("sssp")
            final_ref = bfs_reference(graphs[acked_gen], 0)
            for wid, ent in allr.items():
                if int(ent["generation"]) < acked_gen:
                    raise AssertionError(
                        f"{wid} standing tag {ent['generation']} < "
                        f"acked {acked_gen} after final refresh")
                if not np.array_equal(ent["state"], final_ref):
                    raise AssertionError(
                        f"{wid} post-recovery standing state != merged "
                        "reference")
    except ChaosFailure:
        raise
    except BaseException as e:  # noqa: BLE001 — every failure must
        # carry its reproduction recipe (seed + plan + events)
        raise _fail(seed, plan, events, f"{type(e).__name__}: {e}",
                    cause=e) from e
    finally:
        if plan is not None:
            fault.uninstall()
        try:
            fleet.close()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        if own_tmp is not None:
            own_tmp.cleanup()
    return {
        "seed": seed, "steps": steps, "generation": acked_gen,
        "writes": sum(1 for e in events if e["ev"] == "write"),
        "reads": sum(1 for e in events if e["ev"].startswith("read")),
        "worker_kills": kills, "rejoins": rejoins,
        "failovers": failovers,
        "faults_injected": plan.total_fired() if plan else 0,
        "fault_counters": plan.counters() if plan else [],
        "events": events,
    }


def autopilot_soak(seed: int, steps: int = 8, scale: int = 7,
                   ef: int = 4, rows: int = 8, cap: int = 64,
                   start_workers: int = 1, max_workers: int = 3,
                   journal_root: Optional[str] = None,
                   read_deadline_s: float = 60.0) -> dict:
    """The FULL autonomous loop under one seed (ISSUE 16 acceptance):

    1. a load ramp (offered qps above the per-worker knee) must trip
       the Autoscaler into a previewed, cooldown-gated scale-up;
    2. a controller kill must be DETECTED and repaired by a standby
       election — the harness only kills; a Standby runs
       ``promote_live_controller`` and the standing-query subscription
       keeps delivering across the failover via hub rebind;
    3. a small insert capacity must overflow into an escalated
       fleet-wide compaction;

    with the chaos invariants held throughout: zero acked-write loss,
    every read bitwise-equal to the merged reference at its tag, and
    post-recovery standing answers bitwise from every replica.
    Returns the report (incident keys included, so a recording caller
    can assert the stitched traces) or raises :class:`ChaosFailure`.
    """
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.sssp import bfs_reference
    from lux_tpu.obs.slo import default_fleet_slos
    from lux_tpu.serve.autopilot import (
        Autoscaler,
        AutoscalerConfig,
        Standby,
        StandbyGroup,
        default_fleet_policy,
    )
    from lux_tpu.serve.fleet.worker import ReplicaWorker
    from lux_tpu.serve.live.bench import churn_batch
    from lux_tpu.serve.live.controller import (
        promote_live_controller,
        start_live_fleet,
    )
    from lux_tpu.serve.live.replica import LiveReplica

    rng = np.random.default_rng(seed)
    g = generate.rmat(scale, ef, seed=int(rng.integers(1 << 30)))
    own_tmp = None
    if journal_root is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lux_pilot_")
        journal_root = own_tmp.name
    snapshot_path = os.path.join(journal_root, "snap.lux")
    standing = (("sssp", 0),)
    parts = 2
    events: List[dict] = []
    graphs = {0: g}
    mirror = DeltaLog(g)
    acked_gen = 0
    delivered: List[int] = []
    knee_qps = 50.0  # the "measured" per-worker knee the ramp beats

    fleet = start_live_fleet(
        start_workers, g, parts=parts, cap=cap, buckets=(1, 4),
        standing=standing, journal_root=journal_root,
        snapshot_path=snapshot_path, hb_interval_s=0.05)
    shards = build_pull_shards(g, parts)
    policy = default_fleet_policy(max_shed_frac=0.5)
    fleet.controller.set_slos(default_fleet_slos())
    fleet.controller.set_policy(policy)
    sub = fleet.controller.subscribe("sssp")
    hub = fleet.controller._sub_hub
    inc0 = str(fleet.controller.incarnation)

    def do_write(tag: str, n_rows: Optional[int] = None) -> dict:
        nonlocal acked_gen
        src, dst, op = churn_batch(mirror, rng,
                                   rows if n_rows is None else n_rows)
        rep = fleet.controller.admit_writes(
            src, dst, op, write_id=f"pilot-{seed}-{tag}")
        mirror.apply(src, dst, op)
        graphs[rep["generation"]] = mirror.merged_graph()
        acked_gen = max(acked_gen, rep["generation"])
        events.append({"ev": "write", "tag": tag,
                       "gen": rep["generation"],
                       "compacted": rep.get("compacted", False)})
        return rep

    def bounded_read(src: int) -> None:
        fut = fleet.controller.submit_retrying(
            int(src), deadline_s=read_deadline_s,
            min_generation=acked_gen,
            request_id=f"pilot-{seed}-r{len(events)}")
        ans = fut.result(timeout=0)
        gen_tag = fut.generation if fut.generation is not None else 0
        if gen_tag < acked_gen:
            raise AssertionError(
                f"read-your-writes broke: bound {acked_gen}, tag "
                f"{gen_tag}")
        if not np.array_equal(ans, bfs_reference(graphs[gen_tag],
                                                 int(src))):
            raise AssertionError(
                f"answer at generation {gen_tag} (src {src}) != merged "
                "reference")
        events.append({"ev": "read", "src": int(src), "tag": gen_tag})

    def drain_sub(min_gen: int, why: str) -> None:
        deadline = time.monotonic() + 30.0
        while True:
            upd = sub.get(timeout_s=max(deadline - time.monotonic(),
                                        0.1))
            delivered.append(int(upd["generation"]))
            if upd["generation"] >= min_gen:
                events.append({"ev": "sub", "why": why,
                               "gen": upd["generation"]})
                return
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"subscription stuck below generation {min_gen} "
                    f"({why}); delivered {delivered[-4:]}")

    def spawn(i: int):
        wid = f"w{start_workers + i}"
        live = LiveReplica(
            g, shards, cap=cap,
            journal_dir=os.path.join(journal_root, wid),
            standing=standing)
        w = ReplicaWorker(shards, worker_id=wid, graph_id="live",
                          q_buckets=(1, 4), live=live).start()
        fleet.thread_workers.append(w)
        return w

    scaler = Autoscaler(
        fleet.controller, spawn,
        config=AutoscalerConfig(
            min_workers=start_workers, max_workers=max_workers,
            up_occupancy=0.6, down_occupancy=0.15, up_consecutive=2,
            down_consecutive=1000, cooldown_s=0.0, interval_s=0.05,
            max_move_frac=0.95),
        knee_qps_per_worker=knee_qps)
    standbys: List[Standby] = []
    try:
        # ---- phase A: load ramp -> autoscaler scale-up ---------------
        scaler.note_offered_qps(knee_qps * (start_workers + 1))
        for i in range(max(int(steps), 3)):
            do_write(f"a{i}")
            bounded_read(int(rng.integers(0, g.nv)))
            act = scaler.tick()
            if act is not None:
                events.append({"ev": "scale", **{
                    k: act[k] for k in ("action", "worker",
                                        "moved_frac", "seq")}})
        scale_ups = [a for a in scaler.actions()
                     if a["action"] == "scale_up"]
        if not scale_ups:
            raise AssertionError(
                "the load ramp never tripped the autoscaler "
                f"(signals: {scaler.signals()})")
        fleet.controller.refresh_fleet()
        drain_sub(acked_gen, "post-ramp refresh")

        # ---- phase B: controller kill -> standby election ------------
        def _promote(tc=None):
            endpoints = [("127.0.0.1", w.port)
                         for w in fleet.thread_workers if w._running]
            return promote_live_controller(
                g, os.path.join(journal_root, "controller"),
                snapshot_path, endpoints, seed=seed + 2)

        group = StandbyGroup()
        standbys = [Standby(group, sid, fleet.controller, _promote,
                            on_promoted=lambda c, r: hub.rebind(c),
                            hb_interval_s=0.02, death_after_s=0.15,
                            seed=seed).start()
                    for sid in range(2)]
        fleet.controller.kill()
        got = group.wait_promoted(timeout_s=60.0)
        if got is None:
            raise AssertionError(
                "no standby promoted a controller within 60s")
        ctl2, rep = got
        if ctl2.generation() < acked_gen:
            raise AssertionError(
                f"promotion lost acked writes: journal at "
                f"{ctl2.generation()}, acked {acked_gen}")
        fleet.controller = ctl2
        ctl2.set_slos(default_fleet_slos())
        ctl2.set_policy(policy)
        events.append({"ev": "failover", "winner": group.claimed_by(inc0),
                       "joined": rep["joined"], "refused": rep["refused"],
                       "gen": ctl2.generation()})
        drain_sub(0, "rebind after election")  # delivery survived

        # ---- phase C: overflow -> escalated compaction ---------------
        # fat churn batches: the overlay capacity is per-part and
        # LANE-rounded (mutate/overlay.delta_cap), so thin batches
        # would take ~cap writes to fill it — the drill wants the
        # OVERFLOW, not the grind
        compactions = 0
        for i in range(40):
            if do_write(f"c{i}", n_rows=rows * 8).get("compacted"):
                compactions += 1
                break
        if not compactions:
            raise AssertionError(
                f"insert cap {cap} never overflowed into a compaction "
                f"after 40 post-election fat batches")
        do_write("post-compact")

        # ---- acceptance ----------------------------------------------
        merged = fleet.controller.journal.log.merged_graph()
        mref = mirror.merged_graph()
        if not (np.array_equal(merged.row_ptr, mref.row_ptr)
                and np.array_equal(merged.col_idx, mref.col_idx)):
            raise AssertionError(
                "controller journal merged graph != acked-writes "
                "mirror (acked write lost or corrupted)")
        for src in rng.integers(0, g.nv, 3):
            bounded_read(int(src))
        fleet.controller.refresh_fleet()
        final_ref = bfs_reference(graphs[acked_gen], 0)
        for wid, ent in fleet.controller.read_standing_all(
                "sssp").items():
            if int(ent["generation"]) < acked_gen:
                raise AssertionError(
                    f"{wid} standing tag {ent['generation']} < acked "
                    f"{acked_gen} after final refresh")
            if not np.array_equal(ent["state"], final_ref):
                raise AssertionError(
                    f"{wid} post-recovery standing state != merged "
                    "reference")
        drain_sub(acked_gen, "final refresh")
    except ChaosFailure:
        raise
    except BaseException as e:  # noqa: BLE001 — carry the recipe
        raise _fail(seed, None, events, f"{type(e).__name__}: {e}",
                    cause=e, repro="autopilot_soak") from e
    finally:
        for s in standbys:
            s.stop()
        scaler.stop()
        try:
            fleet.close()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        if own_tmp is not None:
            own_tmp.cleanup()
    return {
        "seed": seed, "generation": acked_gen,
        "writes": sum(1 for e in events if e["ev"] == "write"),
        "reads": sum(1 for e in events if e["ev"] == "read"),
        "scale_ups": len(scale_ups), "elections": group.elections,
        "winner": group.claimed_by(inc0), "compactions": compactions,
        "sub_delivered": delivered,
        "incident_keys": {
            "election": f"election:{inc0}",
            "scale": [f"scale:{inc0}:{a['seq']}"
                      for a in scaler.actions()],
        },
        "events": events,
    }


def election_drill(plan: "fault.FaultPlan", fenced: bool = True,
                   timeout_s: float = 30.0) -> dict:
    """Replay a luxproto election counterexample against the REAL
    election code (ISSUE 18's model→implementation round-trip).

    ``plan`` is an exported counterexample schedule
    (``lux_tpu.analysis.proto.export.export_faultplan``): delay rules
    at the ``election.detect`` / ``election.promote`` process points
    that hold the first winner's promotion window open while a second
    standby detects late — the detached-promotion + TOCTOU schedule.
    The drill runs real :class:`Standby` threads over a dead incumbent
    and imposes the schedule:

    * ``fenced=True`` — the real :class:`StandbyGroup`: the late
      claimant is fenced out and must adopt; ``elections == 1``;
    * ``fenced=False`` — the model's broken twin
      (``UnfencedStandbyGroup``): the SAME schedule completes a second
      promotion; ``elections == 2`` — the model's abstract split-brain
      reproduced as a real one.

    Returns ``{"elections", "outcomes", "winner", "fired"}``; the
    caller asserts on ``elections``.  The incumbent and the promoted
    controllers are inert stand-ins — the protocol under drill is the
    election, not the promotion payload (chaos_soak covers that
    integration end-to-end).
    """
    from lux_tpu.serve.autopilot.election import Standby, StandbyGroup

    class _DeadIncumbent:
        incarnation = "inc-0"
        hb_interval_s = 0.01
        hb_timeout_s = 0.03

        def ping(self):
            raise ConnectionError("incumbent is dead")

    class _PromotedController:
        def __init__(self, sid: int):
            self.incarnation = f"inc-1-s{sid}"

    if fenced:
        group = StandbyGroup()
    else:
        from lux_tpu.analysis.proto.election_model import (
            UnfencedStandbyGroup,
        )

        group = UnfencedStandbyGroup()
    incumbent = _DeadIncumbent()
    standbys: List[Standby] = []
    with fault.installed(plan):
        for sid in range(2):
            def _promote(tc=None, sid=sid):
                return (_PromotedController(sid),
                        {"joined": [], "refused": []})

            standbys.append(Standby(
                group, sid, incumbent, _promote,
                hb_interval_s=incumbent.hb_interval_s,
                death_after_s=incumbent.hb_timeout_s,
                seed=sid).start())
        try:
            # wait for the first claim, then stop the claimant MID-
            # promotion (its promote is held open by the plan's delay
            # rule): stop() deregisters it, shifting min(live ids) to
            # the late detector while the promotion is still running —
            # the fence is now the ONLY thing standing between the
            # late claim and a second election
            deadline = time.monotonic() + timeout_s
            first = None
            while time.monotonic() < deadline:
                first = group.claimed_by(incumbent.incarnation)
                if first is not None:
                    break
                time.sleep(0.002)
            if first is None:
                raise AssertionError(
                    "election drill: no standby claimed within "
                    f"{timeout_s}s (plan: {plan.describe()})")
            group.deregister(first)  # stop() would join the held
            # promotion; the drill needs the deregistration NOW
            standbys[first]._stop.set()
            # let both the detached promotion and the late detector
            # run to completion
            settle = time.monotonic() + timeout_s
            while time.monotonic() < settle:
                done = all(s.outcome is not None or not
                           (s._thread is not None
                            and s._thread.is_alive())
                           for s in standbys)
                if done and group.promoted is not None:
                    break
                time.sleep(0.01)
        finally:
            for s in standbys:
                s.stop()
    return {
        "elections": group.elections,
        "outcomes": {s.standby_id: s.outcome for s in standbys},
        "winner": group.claimed_by(incumbent.incarnation),
        "fired": plan.total_fired(),
    }
