"""The named drill library: every pre-existing ad-hoc fault drill as a
seeded :class:`FaultPlan`.

Each factory returns a plan whose JSON form fully describes the drill
(``plan.to_json()``), so "which fault, where, when" is data a failing
test prints instead of logic buried in a monkeypatch:

* :func:`worker_kill_mid_burst`   — PR 8's drill: SIGKILL-shaped socket
  drop on a named worker after its Nth query frame (the controller must
  re-dispatch the orphans to ring successors; degraded, never wrong).
* :func:`kill_before_marker`      — PR 12's drill: crash between delta
  receipt (batch npz durable) and the ``.ok`` marker — recovery must
  land on the exact committed prefix.  The issue's documented spelling
  ``after_delta_before_marker`` aliases to this point.
* :func:`torn_journal_write`      — PR 10's torn-journal drill: a batch
  npz half-written straight to its final name (a non-atomic writer /
  reordered flush), then the crash — replay must drop exactly that
  batch and keep the prefix.
* :func:`controller_kill_at_heartbeat` — ISSUE 16's election drill:
  kill the controller at its Nth heartbeat sweep; a standby (not the
  harness) must detect the silence and promote a successor.
* :func:`wire_chaos`              — the chaos soak's background noise:
  seeded probabilistic frame delays/drops on query traffic.

Callers bind the kill callbacks the rules name (``plan.bind``) or use
``ReplicaWorker.kill_at``, which arms the same rules directly.
"""
from __future__ import annotations

from typing import Optional, Sequence

from lux_tpu.fault.plan import FaultPlan, FaultRule


def worker_kill_mid_burst(victim: str, nth_query: int = 5,
                          seed: int = 0) -> FaultPlan:
    """Kill ``victim`` when it RECEIVES its ``nth_query``-th query frame
    (mid-burst by construction when the burst is larger).  Bind the
    trigger: ``plan.bind(f"kill:{victim}", worker.kill)``."""
    return FaultPlan([FaultRule(
        "wire.recv", "kill", owner=victim, op="query",
        after=max(int(nth_query) - 1, 0), count=1,
        callback=f"kill:{victim}",
        note=f"PR8 drill: kill {victim} at query #{nth_query}")],
        seed=seed, name=f"worker_kill_mid_burst[{victim}]")


def kill_before_marker(owner: Optional[str] = None, nth_batch: int = 1,
                       seed: int = 0) -> FaultPlan:
    """Crash at ``journal.before_marker`` (batch npz durable, ``.ok``
    marker never written) on the ``nth_batch``-th journaled batch —
    the kill-between-receipt-and-marker window."""
    return FaultPlan([FaultRule(
        "proc", "kill", point="journal.before_marker", owner=owner,
        after=max(int(nth_batch) - 1, 0), count=1,
        note="PR12 drill: kill between batch append and .ok marker")],
        seed=seed, name="kill_before_marker")


def torn_journal_write(owner: Optional[str] = None,
                       file: str = "batch_*.npz", nth: int = 1,
                       seed: int = 0) -> FaultPlan:
    """Tear the ``nth``-th matching journal file write: half the bytes
    land at the FINAL path (no marker ever follows), then the injected
    crash — the npz+``.ok`` replay protocol must discard it."""
    return FaultPlan([FaultRule(
        "proc", "torn", point="journal.write", owner=owner, file=file,
        after=max(int(nth) - 1, 0), count=1,
        note="PR10 drill: torn journal write (partial npz, no marker)")],
        seed=seed, name="torn_journal_write")


def controller_kill_at_heartbeat(nth: int = 3, seed: int = 0
                                 ) -> FaultPlan:
    """ISSUE 16's election drill: kill the CONTROLLER at its ``nth``
    heartbeat sweep (the ``controller.heartbeat`` proc point at the top
    of ``_hb_loop``) — mid-flight, not at a quiet boundary.  Bind the
    trigger: ``plan.bind("kill:controller", ctl.kill)``; a Standby
    (serve/autopilot/election.py) must then detect the silence and
    promote, with the harness doing NOTHING."""
    return FaultPlan([FaultRule(
        "proc", "kill", owner="controller",
        point="controller.heartbeat", after=max(int(nth) - 1, 0),
        count=1, callback="kill:controller",
        note=f"ISSUE16 drill: kill controller at heartbeat #{nth}")],
        seed=seed, name="controller_kill_at_heartbeat")


def wire_chaos(seed: int, delay_ms: float = 3.0, delay_prob: float = 0.10,
               drop_prob: float = 0.03,
               ops: Sequence[str] = ("query",)) -> FaultPlan:
    """Background wire noise for the chaos soak: per matching frame,
    a seeded coin delays it ``delay_ms`` or (controller-side sends
    only) drops it entirely — dropped queries are exactly what the
    client envelope's deadline+retry must absorb."""
    rules = []
    for op in ops:
        rules.append(FaultRule("wire.send", "drop", op=op,
                               owner="controller", prob=float(drop_prob),
                               note="chaos: dropped request frame"))
        rules.append(FaultRule("wire.send", "delay", op=op,
                               delay_ms=float(delay_ms),
                               prob=float(delay_prob),
                               note="chaos: delayed request frame"))
        rules.append(FaultRule("wire.recv", "delay", op=op,
                               delay_ms=float(delay_ms),
                               prob=float(delay_prob),
                               note="chaos: delayed delivery"))
    return FaultPlan(rules, seed=seed, name=f"wire_chaos[s{seed}]")
