"""lux_tpu.fault — luxfault: deterministic fault injection + chaos.

ISSUE 14 / ROADMAP item 2's robustness layer.  Three pieces:

* ``plan.py``  — :class:`FaultPlan`/:class:`FaultRule`: seeded,
  JSON-serializable fault schedules fired at wire sites
  (``fleet/wire.py``), the journal protocol (``mutate/deltalog.py``)
  and named process points; every injection is a luxtrace event and a
  counter.
* ``drills.py`` — the named plan library: every pre-existing ad-hoc
  fault drill (PR 8 worker kill mid-burst, PR 10 torn journal marker,
  PR 12 kill between delta receipt and marker) re-expressed as a
  seeded plan.
* ``chaos.py`` — the seeded randomized soak over a live 2-worker fleet
  asserting the standing invariants (no acked write lost,
  read-your-writes, bitwise post-recovery answers); a failure prints
  the seed + plan, which IS the reproduction.

This module owns the process-global installation point.  The fast path
is one attribute read (``_PLAN is None``) so shipped code consults it
for free; installation is locked and either explicit (``install``/
``installed``) or environment-driven (``LUX_FAULT_PLAN`` JSON/path,
resolved once per process on first consultation).

``owner(name)`` sets a thread-local identity so process points fired
from shared code (the journal protocol runs inside every worker) match
per-worker rules — the worker's op threads wrap themselves in it.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from lux_tpu.fault.plan import (  # noqa: F401
    ACTIONS,
    POINT_ALIASES,
    FaultPlan,
    FaultPlanError,
    FaultRule,
    InjectedKill,
)

_LOCK = threading.Lock()
_PLAN: Optional[FaultPlan] = None
#: None until the env var was consulted once (False = consulted, unset)
_ENV_CHECKED = False
_TLS = threading.local()


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process's active plan (replacing any)."""
    global _PLAN, _ENV_CHECKED
    with _LOCK:
        _ENV_CHECKED = True  # an explicit install outranks the env
        _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    with _LOCK:
        _PLAN = None


class installed:
    """``with fault.installed(plan): ...`` — scoped installation; the
    previous plan (usually None) is restored on exit even when the body
    raises InjectedKill (which drills do by design)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        global _PLAN, _ENV_CHECKED
        with _LOCK:
            _ENV_CHECKED = True
            self._prev = _PLAN
            _PLAN = self.plan
        return self.plan

    def __exit__(self, *exc) -> bool:
        global _PLAN
        with _LOCK:
            _PLAN = self._prev
        return False


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, resolving ``LUX_FAULT_PLAN`` once per
    process when nothing was installed explicitly."""
    global _PLAN, _ENV_CHECKED
    if _PLAN is not None:
        return _PLAN
    if _ENV_CHECKED:
        return None
    with _LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            _PLAN = FaultPlan.from_env()
    return _PLAN


class owner:
    """Thread-local identity context: ``with fault.owner("w1"): ...``
    makes every site fired on this thread match rules whose ``owner``
    glob names w1 — how the shared journal code attributes its process
    points to the worker running them."""

    def __init__(self, name: Optional[str]):
        self.name = name
        self._prev: Optional[str] = None

    def __enter__(self):
        self._prev = getattr(_TLS, "owner", None)
        _TLS.owner = self.name
        return self

    def __exit__(self, *exc) -> bool:
        _TLS.owner = self._prev
        return False


def current_owner() -> Optional[str]:
    return getattr(_TLS, "owner", None)


def fire(site: str, **ctx) -> Optional[FaultRule]:
    """Consult the active plan at ``site`` (owner auto-filled from the
    thread-local context when the caller did not pass one).  Returns
    the fired rule or None; the CALLER interprets the action."""
    plan = active_plan()
    if plan is None:
        return None
    if "owner" not in ctx or ctx["owner"] is None:
        ctx["owner"] = current_owner()
    return plan.fire(site, **ctx)


def ppoint(point: str, **ctx) -> Optional[FaultRule]:
    """A named PROCESS point (``fault.ppoint("journal.before_marker")``)
    — the generalization of the hand-placed kill drills.  ``kill``
    raises :class:`InjectedKill` here (after the rule's callback, e.g.
    ``worker.kill``, dropped the sockets — the peer-visible shape of a
    SIGKILL at exactly this point); ``delay`` sleeps in place; any
    other action is returned for the site to interpret (``torn`` in
    the journal writer)."""
    rule = fire("proc", point=plan_point(point), **ctx)
    if rule is None:
        return None
    if rule.action == "kill":
        raise InjectedKill(f"injected kill at {point}")
    if rule.action == "delay" and rule.delay_ms > 0:
        import time

        time.sleep(rule.delay_ms / 1e3)
    return rule


def plan_point(point: str) -> str:
    """Resolve documented alias spellings to the placed point names."""
    return POINT_ALIASES.get(point, point)


def arm_kill(point: str, kill_fn: Callable, *,
             owner_id: Optional[str] = None, count: int = 1,
             after: int = 0) -> FaultRule:
    """Arm a one-shot (by default) kill at a named process point —
    ``worker.kill_at`` routes here.  Installs a fresh empty plan when
    none is active, binds ``kill_fn`` and appends the rule, so a test
    can write ``w.kill_at("after_delta_before_marker")`` with no plan
    plumbing at all."""
    global _PLAN, _ENV_CHECKED
    with _LOCK:
        _ENV_CHECKED = True
        if _PLAN is None:
            _PLAN = FaultPlan([], name="armed")
        plan = _PLAN
    cb = f"kill:{owner_id or 'any'}:{plan_point(point)}"
    plan.bind(cb, kill_fn)
    return plan.add(FaultRule(
        "proc", "kill", point=plan_point(point), owner=owner_id,
        count=count, after=after, callback=cb,
        note=f"kill_at({point})"))
