"""FaultPlan: deterministic, seeded fault injection as DATA.

Every fault drill in the repo used to be a hand-placed one-off — a
``monkeypatch`` on ``DeltaLog._journal_mark`` (PR 12), a direct
``worker.kill()`` mid-burst (PR 8), a crafted torn journal (PR 10).
Those drills proved their one window each, but the windows were baked
into test code: not composable, not schedulable, not reproducible by a
seed printed from a failing run.  A ``FaultPlan`` turns them into data:

* a plan is a list of :class:`FaultRule` rows, each naming a SITE
  (``wire.send`` / ``wire.recv`` / ``proc``), a match (owner / peer /
  op / process-point / file globs), an ACTION (``kill`` / ``drop`` /
  ``delay`` / ``truncate`` / ``corrupt`` / ``reset`` / ``partial`` /
  ``torn``), and firing controls (``after`` skips the first N matches,
  ``count`` bounds total fires, ``prob`` draws from the plan's OWN
  seeded ``random.Random`` — never the process-global RNG, LUX-D003);
* plans serialize to/from JSON (``to_json``/``from_json``) and install
  from the environment (``LUX_FAULT_PLAN`` = inline JSON or a path), so
  a chaos soak's failure report IS its reproduction recipe;
* every fire logs a ``fault.inject`` luxtrace point and increments a
  per-(site, target, action) counter that ``controller.prom_dump()``
  exposes as ``lux_fault_injected_total`` — injected faults are
  first-class observability, not silent test magic.

The sites are consulted by the production code itself (``fleet/wire.py``
frames, ``mutate/deltalog.py``'s npz+``.ok`` journal protocol, named
``fault.ppoint(...)`` process points in the worker/replica write path),
behind a single module-global fast path that costs one attribute read
when no plan is installed.
"""
from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple

#: actions the engine knows; sites ignore actions they cannot express
#: (a ``torn`` rule matched at ``wire.send`` does nothing, loudly — see
#: FaultRule.validate)
ACTIONS = ("kill", "drop", "delay", "truncate", "corrupt", "reset",
           "partial", "torn", "noop")

#: which actions each site can express — validated at plan build so a
#: typo'd plan fails at install, not silently mid-drill
SITE_ACTIONS = {
    "wire.send": ("drop", "delay", "truncate", "corrupt", "reset",
                  "partial", "kill", "noop"),
    "wire.recv": ("drop", "delay", "corrupt", "reset", "kill", "noop"),
    "proc": ("kill", "delay", "torn", "noop"),
}

#: documented spellings from the issue/ROADMAP mapped onto the placed
#: process points (``worker.kill_at("after_delta_before_marker")`` is
#: the PR 12 drill's name for the journal's marker window)
POINT_ALIASES = {
    "after_delta_before_marker": "journal.before_marker",
}


class InjectedKill(BaseException):
    """An injected crash.  BaseException on purpose: the worker code's
    blanket ``except Exception`` error-reply handlers must NOT convert
    an injected kill into a polite error frame — a killed process sends
    nothing, and the drills assert on exactly that silence."""


class FaultPlanError(ValueError):
    """Malformed plan/rule (unknown site/action, bad JSON, bad bounds)."""


_MATCH_FIELDS = ("owner", "peer", "op", "point", "file")
_RULE_FIELDS = _MATCH_FIELDS + (
    "site", "action", "after", "count", "prob", "delay_ms", "trunc_bytes",
    "callback", "note")


class FaultRule:
    """One schedulable fault.  Match fields are fnmatch globs (None =
    match anything); ``callback`` names a plan binding (``plan.bind``)
    invoked on fire — how a ``kill`` action reaches the right
    ``worker.kill`` without the plan holding object references in its
    JSON form."""

    def __init__(self, site: str, action: str, *,
                 owner: Optional[str] = None, peer: Optional[str] = None,
                 op: Optional[str] = None, point: Optional[str] = None,
                 file: Optional[str] = None, after: int = 0,
                 count: Optional[int] = None, prob: float = 1.0,
                 delay_ms: float = 0.0, trunc_bytes: int = 8,
                 callback: Optional[str] = None, note: str = ""):
        self.site = str(site)
        self.action = str(action)
        self.owner = owner
        self.peer = peer
        self.op = op
        self.point = (POINT_ALIASES.get(point, point)
                      if point is not None else None)
        self.file = file
        self.after = int(after)
        self.count = None if count is None else int(count)
        self.prob = float(prob)
        self.delay_ms = float(delay_ms)
        self.trunc_bytes = int(trunc_bytes)
        self.callback = callback
        self.note = str(note)
        self.seen = 0   # matches observed (pre-after/prob/count gates)
        self.fired = 0  # faults actually injected
        self.validate()

    def validate(self) -> None:
        if self.site not in SITE_ACTIONS:
            raise FaultPlanError(
                f"unknown site {self.site!r}; expected one of "
                f"{sorted(SITE_ACTIONS)}")
        if self.action not in SITE_ACTIONS[self.site]:
            raise FaultPlanError(
                f"action {self.action!r} is not expressible at site "
                f"{self.site!r} (allowed: {SITE_ACTIONS[self.site]})")
        if not (0.0 <= self.prob <= 1.0):
            raise FaultPlanError(f"prob must be in [0, 1], got {self.prob}")
        if self.after < 0 or (self.count is not None and self.count < 0):
            raise FaultPlanError("after/count must be >= 0")

    def matches(self, site: str, ctx: Dict[str, Optional[str]]) -> bool:
        if site != self.site:
            return False
        for field in _MATCH_FIELDS:
            pat = getattr(self, field)
            if pat is None:
                continue
            val = ctx.get(field)
            if val is None or not fnmatch.fnmatchcase(str(val), pat):
                return False
        return True

    def to_dict(self) -> dict:
        out = {"site": self.site, "action": self.action}
        for field in _RULE_FIELDS:
            if field in ("site", "action"):
                continue
            val = getattr(self, field)
            default = {"after": 0, "prob": 1.0, "delay_ms": 0.0,
                       "trunc_bytes": 8, "note": ""}.get(field)
            if val is not None and val != default:
                out[field] = val
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        unknown = set(d) - set(_RULE_FIELDS)
        if unknown:
            raise FaultPlanError(
                f"unknown rule fields {sorted(unknown)} (known: "
                f"{sorted(_RULE_FIELDS)})")
        if "site" not in d or "action" not in d:
            raise FaultPlanError(f"rule needs site + action: {d}")
        return cls(**d)


class FaultPlan:
    """A named, seeded schedule of FaultRules.

    ``fire(site, **ctx)`` is the single consultation point: the FIRST
    rule whose match fields accept the context is advanced through its
    ``after``/``count``/``prob`` gates; a passing rule is returned to
    the site (which interprets the action) after its callback ran and a
    ``fault.inject`` event hit the flight recorder.  Thread-safe: sites
    fire from connection readers, op threads, and the heartbeat loop
    concurrently."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 name: str = "plan"):
        self.rules = list(rules)
        self.seed = int(seed)
        self.name = str(name)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._callbacks: Dict[str, Callable] = {}
        self._counters: Dict[Tuple[str, str, str], int] = {}

    # -- construction ---------------------------------------------------

    def bind(self, name: str, fn: Callable) -> "FaultPlan":
        """Attach the callable a rule's ``callback`` field names (e.g.
        ``plan.bind("kill:w1", w1.kill)``).  Returns self for chaining."""
        with self._lock:
            self._callbacks[str(name)] = fn
        return self

    def add(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self.rules.append(rule)
        return rule

    def to_dict(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "rules": [r.to_dict() for r in self.rules]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        if not isinstance(d, dict) or "rules" not in d:
            raise FaultPlanError(
                f"plan must be an object with a 'rules' list, got {d!r}")
        rules = [FaultRule.from_dict(r) for r in d["rules"]]
        return cls(rules, seed=int(d.get("seed", 0)),
                   name=str(d.get("name", "plan")))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        try:
            d = json.loads(text)
        except ValueError as e:
            raise FaultPlanError(f"bad plan JSON: {e}") from None
        return cls.from_dict(d)

    @classmethod
    def from_env(cls, var: str = "LUX_FAULT_PLAN"
                 ) -> Optional["FaultPlan"]:
        """``LUX_FAULT_PLAN`` holds inline JSON (starts with ``{``) or
        a path to a JSON file; unset/empty -> None."""
        raw = os.environ.get(var, "").strip()
        if not raw:
            return None
        if raw.startswith("{"):
            return cls.from_json(raw)
        with open(raw, "r", encoding="utf-8") as f:
            return cls.from_json(f.read())

    # -- firing ---------------------------------------------------------

    def fire(self, site: str, **ctx) -> Optional[FaultRule]:
        """Consult the plan at ``site``; returns the fired rule (the
        site interprets its action) or None."""
        rule = None
        with self._lock:
            for r in self.rules:
                if not r.matches(site, ctx):
                    continue
                r.seen += 1
                if r.seen <= r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.prob < 1.0 and self._rng.random() >= r.prob:
                    continue
                r.fired += 1
                key = (site, str(ctx.get("owner") or ctx.get("peer")
                                 or ctx.get("file") or ""), r.action)
                self._counters[key] = self._counters.get(key, 0) + 1
                rule = r
                break
            cb = (self._callbacks.get(rule.callback)
                  if rule is not None and rule.callback else None)
        if rule is None:
            return None
        from lux_tpu import obs

        # plan name + SEED ride the event (ISSUE 15 satellite): a
        # stitched timeline showing an injected fault next to the spans
        # it perturbed must also name the exact reproduction recipe
        obs.point("fault.inject", plan=self.name, seed=self.seed,
                  site=site, action=rule.action, note=rule.note,
                  **{k: v for k, v in ctx.items() if v is not None})
        if cb is not None:
            cb()
        return rule

    # -- observability --------------------------------------------------

    def counters(self) -> List[dict]:
        """[{site, target, action, count}] — the prom_dump rows."""
        with self._lock:
            return [{"site": s, "target": t, "action": a, "count": n}
                    for (s, t, a), n in sorted(self._counters.items())]

    def total_fired(self) -> int:
        with self._lock:
            return sum(self._counters.values())

    def describe(self) -> str:
        """One line per rule with live seen/fired counts — printed by
        the chaos soak's failure report next to the seed."""
        lines = [f"FaultPlan {self.name!r} seed={self.seed}"]
        for i, r in enumerate(self.rules):
            lines.append(f"  [{i}] {json.dumps(r.to_dict(), sort_keys=True)}"
                         f" seen={r.seen} fired={r.fired}")
        return "\n".join(lines)
