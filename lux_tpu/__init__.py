"""lux_tpu — a TPU-native distributed graph-processing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of LuxGraph/Lux
(the distributed multi-GPU graph system of Jia et al., PVLDB 11(3) 2017):
pull/push gather-scatter engines, edge-balanced partitioning, frontier-based
convergence, and the PageRank / Connected Components / SSSP / Collaborative
Filtering application suite — built for TPU meshes (SPMD via shard_map +
XLA collectives over ICI) rather than Legion/GASNet/CUDA.
"""

from lux_tpu.graph.csc import HostGraph, from_edge_list
from lux_tpu.graph.format import read_lux, read_lux_range, write_lux
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.sharded_load import load_pull_shards
from lux_tpu.graph.shards import build_pull_shards

__all__ = [
    "HostGraph", "from_edge_list", "read_lux", "read_lux_range",
    "write_lux", "build_push_shards", "load_pull_shards",
    "build_pull_shards",
    # exchange-layout builders (lazy subpackages carry the drivers):
    #   parallel.ring.build_ring_shards / build_push_ring_shards
    #   parallel.scatter.build_scatter_shards
    #   parallel.edge2d.build_edge2d_shards
]

__version__ = "0.5.0"


def __getattr__(name):
    # lazy subpackage access: lux_tpu.models / apps / parallel / ops / utils
    if name in ("models", "apps", "parallel", "ops", "utils", "graph",
                "engine", "native"):
        import importlib

        return importlib.import_module(f"lux_tpu.{name}")
    raise AttributeError(name)
