"""lux_tpu — a TPU-native distributed graph-processing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of LuxGraph/Lux
(the distributed multi-GPU graph system of Jia et al., PVLDB 11(3) 2017):
pull/push gather-scatter engines, edge-balanced partitioning, frontier-based
convergence, and the PageRank / Connected Components / SSSP / Collaborative
Filtering application suite — built for TPU meshes (SPMD via shard_map +
XLA collectives over ICI) rather than Legion/GASNet/CUDA.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under experimental with check_rep instead
    # of check_vma; every engine writes the modern spelling — adapt once
    # here (the first lux_tpu import runs before any engine module).
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, mesh=None, in_specs=None, out_specs=None, **kw):
        # check_rep is a purely diagnostic static analysis and the old
        # checker has no rule for while_loop (every engine loop here);
        # disable it unless the caller explicitly asked for a check
        kw["check_rep"] = bool(kw.pop("check_vma", False))
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    _jax.shard_map = _compat_shard_map

from lux_tpu.graph.csc import HostGraph, from_edge_list
from lux_tpu.graph.format import read_lux, read_lux_range, write_lux
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.sharded_load import load_pull_shards
from lux_tpu.graph.shards import build_pull_shards

__all__ = [
    "HostGraph", "from_edge_list", "read_lux", "read_lux_range",
    "write_lux", "build_push_shards", "load_pull_shards",
    "build_pull_shards",
    # exchange-layout builders (lazy subpackages carry the drivers):
    #   parallel.ring.build_ring_shards / build_push_ring_shards
    #   parallel.scatter.build_scatter_shards
    #   parallel.edge2d.build_edge2d_shards
]

__version__ = "0.5.0"


def __getattr__(name):
    # lazy subpackage access: lux_tpu.models / apps / parallel / ops / utils
    if name in ("models", "apps", "parallel", "ops", "utils", "graph",
                "engine", "native", "obs", "analysis", "serve"):
        import importlib

        return importlib.import_module(f"lux_tpu.{name}")
    raise AttributeError(name)
