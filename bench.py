"""Benchmark harness: PageRank GTEPS on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric derivation (BASELINE.md): GTEPS = iterations * ne / elapsed / 1e9 on
a fixed-iteration PageRank run — the reference's headline workload
(pagerank 10 iters, README.md:41; ELAPSED TIME timer at
pagerank/pagerank.cc:108-118).  The reference repo publishes no numbers
(BASELINE.md), so vs_baseline is computed against BASELINE_GTEPS_PER_CHIP,
our documented estimate of the paper-era per-GPU rate.

Env knobs:
  LUX_BENCH_SCALE  (default 20)  RMAT scale, nv = 2**scale
  LUX_BENCH_EF     (default 16)  edge factor, ne = nv * ef
  LUX_BENCH_ITERS  (default 10)
  LUX_BENCH_METHOD (default auto: race scan vs scatter [vs pallas on TPU])
  LUX_BENCH_DTYPE  (default float32; bfloat16 halves state bandwidth)
"""
from __future__ import annotations

import json
import os
import sys
import time

# Paper-era Lux runs ~1 GTEPS/GPU-class-chip on PageRank per the PVLDB paper
# family of results; the repo itself publishes nothing (BASELINE.md).
BASELINE_GTEPS_PER_CHIP = 1.0


def _arm_watchdog():
    """The TPU tunnel in this environment can wedge and hang device init
    forever (docs/NOTES_ROUND1.md); emit a diagnostic JSON line instead of
    hanging the driver."""
    import signal

    timeout = int(os.environ.get("LUX_BENCH_WATCHDOG_S", "900"))

    def _fire(signum, frame):
        print(
            json.dumps(
                {
                    "metric": "pagerank_gteps_watchdog_timeout",
                    "value": 0.0,
                    "unit": "GTEPS",
                    "vs_baseline": 0.0,
                }
            ),
            flush=True,
        )
        os._exit(2)

    if timeout > 0 and hasattr(signal, "SIGALRM"):
        signal.signal(signal.SIGALRM, _fire)
        signal.alarm(timeout)


def main():
    _arm_watchdog()
    import jax
    import jax.numpy as jnp

    try:  # persistent compile cache: repeat bench runs skip the 20-40s compile
        jax.config.update("jax_compilation_cache_dir", "/tmp/lux_jax_cache")
    except Exception:
        pass

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    scale = int(os.environ.get("LUX_BENCH_SCALE", "20"))
    ef = int(os.environ.get("LUX_BENCH_EF", "16"))
    iters = int(os.environ.get("LUX_BENCH_ITERS", "10"))
    method_env = os.environ.get("LUX_BENCH_METHOD", "auto")

    dtype = os.environ.get("LUX_BENCH_DTYPE", "float32")
    g = generate.rmat(scale, ef, seed=0)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv, dtype=dtype)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    state0 = pull.init_state(prog, arrays)

    def timed(method):
        if method == "pallas":
            return timed_pallas()
        run = jax.jit(
            lambda s: pull.run_pull_fixed(prog, shards.spec, arrays, s, iters, method)
        )
        run(state0).block_until_ready()  # compile + warm
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run(state0)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps, out

    def timed_pallas():
        from lux_tpu.models.pagerank import make_pallas_runner

        run, ps0 = make_pallas_runner(g, dtype=dtype)
        run(ps0, iters).block_until_ready()  # compile + warm
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run(ps0, iters)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps, out

    # pallas path is TPU-only (axon is the tunneled TPU plugin)
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if method_env == "auto":
        methods = ["scan", "scatter"] + (["pallas"] if on_tpu else [])
    else:
        methods = [method_env]
    results = {}
    for m in methods:
        try:
            results[m] = timed(m)
        except Exception as e:  # noqa: BLE001 — a method may be unsupported
            print(f"# method {m} failed: {e}", file=sys.stderr, flush=True)
    if not results:
        raise RuntimeError(f"all benchmark methods failed: {methods}")
    method, (elapsed, out) = min(results.items(), key=lambda kv: kv[1][0])
    gteps = iters * g.ne / elapsed / 1e9

    platform = jax.devices()[0].platform
    # diagnostics on stderr: stdout carries EXACTLY one JSON line
    print(
        f"# platform={platform} nv={g.nv} ne={g.ne} iters={iters} "
        f"method={method} dtype={dtype} elapsed={elapsed:.4f}s",
        file=sys.stderr,
        flush=True,
    )
    print(
        json.dumps(
            {
                "metric": f"pagerank_gteps_rmat{scale}_1chip",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
