"""Benchmark harness: all four reference apps on one chip.

Prints one JSON metric line per app family, HEADLINE LAST: the final
stdout line is always the PageRank number ({"metric", "value", "unit",
"vs_baseline"}) the driver records; the preceding lines carry the SSSP
and CC (traversed-edges GTEPS) and CF (edge-update GTEPS +
per-iteration ms + RMSE) datapoints so every reference app has a
tracked perf signal (VERDICT r2 #4).

Metric derivation (BASELINE.md): GTEPS = iterations * ne / elapsed / 1e9 on
a fixed-iteration PageRank run — the reference's headline workload
(pagerank 10 iters, README.md:41; ELAPSED TIME timer at
pagerank/pagerank.cc:108-118); SSSP divides edges ACTUALLY traversed
(the engine's exact on-device counter) by elapsed.  The reference repo
publishes no numbers (BASELINE.md), so vs_baseline is computed against
BASELINE_GTEPS_PER_CHIP, our documented estimate of the paper-era
per-GPU rate.

Process architecture (docs/NOTES_ROUND1.md hard lessons): the TPU tunnel in
this environment can hang INSIDE PJRT C++ device init, where a same-process
SIGALRM handler never runs (signals only fire between Python bytecodes).
So the orchestrator below never imports jax: it spawns the real benchmark
as a worker subprocess, and if the TPU worker is still stuck near the
deadline it leaves it running (killing a claim-holder wedges the tunnel
relay for every later process) and reruns the same worker on the CPU
platform so the driver still records a real, clearly-labeled number.

Env knobs:
  LUX_BENCH_SCALE  (default 20)  RMAT scale, nv = 2**scale
  LUX_BENCH_EF     (default 16)  edge factor, ne = nv * ef
  LUX_BENCH_ITERS  (default 10)
  LUX_BENCH_METHOD (default auto: race scan vs scatter [vs cumsum/mxsum/
                   mxscan/pallas on TPU].  The default output also carries
                   a standing `scan_micro_mx_vs_vpu` row — scan vs mxsum
                   vs mxscan on one tiny csc census
                   (LUX_BENCH_SCAN_MICRO_SCALE, default 12), each flavor
                   oracle-gated, winner banked under "tpu:sum" on TPU
                   only, consumed by engine/methods.sum_mode)
  LUX_BENCH_DTYPE  (default float32; bfloat16 halves state bandwidth)
  LUX_BENCH_WATCHDOG_S (default 900) total wall budget for the orchestrator
                   (0 = unbounded)
  LUX_BENCH_TPU_S  (default budget-120) how long to wait for the TPU worker
  LUX_BENCH_CPU_SCALE (default min(scale, 18)) fallback worker's RMAT scale
                   — a 1-core CPU needs a smaller graph to finish in budget
  LUX_BENCH_APPS   (default pagerank,sssp,components,colfilter,serve,ba,
                   refresh,live,bfs,labelprop) which app metrics to
                   measure; pagerank is the headline and always prints
                   last.  "bfs"/"labelprop" are the spec-compiled
                   luxprog workload rows (ISSUE 13): bfs = multi-source
                   BFS on the headline graph's push layout, labelprop =
                   the wide-state dense-pull row on its own small graph
                   (LUX_BENCH_LABELPROP_SCALE, default min(scale, 12)).
                   "kcore" and "triangles" are OPT-IN luxprog rows
                   (LUX_BENCH_KCORE_SCALE / LUX_BENCH_TRIANGLES_SCALE):
                   the iterative peel compiles one program per level,
                   and the triangle bitsets are quadratic in nv — both
                   bounded-small by design.  "live" is
                   the mutation-aware serving row (lux_tpu.serve.live,
                   ISSUE 12): sssp_live_w2_* — a 2-worker thread-mode
                   live fleet under a concurrent writer + closed-loop
                   readers (write batches/s, read QPS, read-staleness
                   generations p50/p99, fleet warm-refresh latency;
                   LUX_BENCH_LIVE_SCALE, default 12).  "refresh" is the
                   dynamic-graph row family (lux_tpu.mutate, ISSUE 10):
                   pagerank_refresh_churn1pct_* / sssp_refresh_churn1pct_*
                   — warm overlay refresh after 1% edge churn vs a cold
                   recompute of the compacted snapshot, on its own graph
                   (LUX_BENCH_REFRESH_SCALE, default min(scale, 16), 8
                   parts; value = speedup, bar = 10x).  "serve" is the batched
                   query-serving row (lux_tpu.serve): sssp_qps_* — warm
                   Q=64 batched QPS vs warm Q=1 sequential.  "ba" is the
                   standing heavy-tail row: a Barabási-Albert graph
                   (LUX_BENCH_BA_SCALE, default min(scale, 20) vertices
                   = 2**bs; LUX_BENCH_BA_M out-edges/vertex, default 4)
                   through generator -> .lux round trip -> ROUTED-PF
                   pull, so hub skew is measured where routed-plan
                   padding bites (VERDICT r5 weak #4).  "fleet" (OPT-IN,
                   not in the default list: it spawns 1/2/4 worker
                   processes and ramps each to its knee, minutes of
                   wall) is the multi-replica serving row
                   (lux_tpu.serve.fleet): sssp_fleet_qps_w{1,2,4}_* —
                   offered-QPS ramp to the saturation knee per fleet
                   width on CPU, QPS + p99 at the knee, plus the paired
                   interleaved 2w-vs-1w probe (LUX_BENCH_FLEET_SCALE
                   overrides the rmat scale).  "pod" (OPT-IN, ISSUE 19)
                   is the placement-tree weak-scaling family:
                   sssp_pod_w{1,2,4}_rmat{16,18,20} — 1/2/4 REAL worker
                   processes over loopback, snapshot streamed over the
                   wire, answer bitwise vs single-host, with per-host
                   plan/exchange/converge phases and the weak_scaling
                   ratio on every row (LUX_BENCH_POD_SCALE base, default
                   16; LUX_BENCH_POD_PARTS, default 8).
  LUX_BENCH_ROUTE_PF=1 / LUX_BENCH_ROUTE_FUSED_PF=1  A/B the PASS-FUSED
                   routed pipelines (ops/expand.to_pf: 2-3 Benes passes
                   per Pallas kernel, VMEM-resident intermediates —
                   ~40% fewer HBM sweeps/iter); _routepf/_routefusedpf
                   metric suffixes.  The DEFAULT TPU race also measures
                   a _routepf line right after the _route line (same
                   plan build + a numpy transform) and records the
                   winner under "tpu:route_mode" in the overlay.
  LUX_BENCH_RELAY_CAP_S (default 240) grace past last-seen-alive while the
                   relay endpoint is down.  The TPU-claim wait is ADAPTIVE
                   (_wait_tpu): liveness is re-probed throughout, so a
                   relay that dies stops burning budget and one that comes
                   alive re-extends the wait to the full window.
  LUX_ROUTE_THREADS / LUX_PLAN_THREADS (default: all cores) native Euler-
                   colorer / Python planner fan-out for routed-plan
                   construction (ops/expand).  The routed-race plan builds
                   on background threads DURING the unrouted race
                   (expand.plan_async), and every row carries cumulative
                   cold/warm ``plan_build_seconds`` so amortization claims
                   are checkable from the driver artifact alone.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from concurrent.futures import TimeoutError as _FUTURE_TIMEOUT

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools"))
import _env  # noqa: E402 — jax-free env parsing shared with the tpu tools

# Paper-era Lux runs ~1 GTEPS/GPU-class-chip on PageRank per the PVLDB paper
# family of results; the repo itself publishes nothing (BASELINE.md).
BASELINE_GTEPS_PER_CHIP = 1.0


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _plan_build_field():
    """Cumulative routed-plan construction accounting for this worker
    (ops.expand cold=built / warm=cache-loaded seconds), attached to
    EVERY bench row so plan-build amortization is checkable from the
    driver artifact alone (VERDICT r5 #6; docs/PERF.md plan-build
    amortization).  Import stays lazy: only the worker (which already
    imported jax) ever calls this."""
    try:
        from lux_tpu.ops import expand

        s = expand.plan_stats_snapshot()
        return {"cold": round(s["cold_s"], 3), "warm": round(s["warm_s"], 3)}
    except Exception:  # noqa: BLE001 — accounting must never cost a row
        return {"cold": 0.0, "warm": 0.0}


def _obs_fields():
    """run_id + the ``phases`` dict for a bench row, read from the
    flight recorder's span totals (lux_tpu.obs) — ONE clock: the same
    span durations luxview's waterfall renders, cumulative for this
    worker like plan_build_seconds.  ``plan`` covers plan.build +
    plan.load ONLY — plan.color runs nested inside plan.build, so a
    flat sum over "plan." would count the coloring wall time twice;
    load/compile/iterate come from the graph.* / compile.* / iterate
    spans around the measured regions."""
    try:
        from lux_tpu import obs

        r = obs.recorder()

        def tot(*prefixes):
            return round(sum(v[1] for p in prefixes
                             for v in r.totals(p).values()), 3)

        return {
            "run_id": r.run_id,
            "phases": {"load": tot("graph."),
                       "plan": tot("plan.build", "plan.load"),
                       "compile": tot("compile."),
                       "iterate": tot("iterate")},
        }
    except Exception:  # noqa: BLE001 — accounting must never cost a row
        return {}


def _emit_row(obj):
    """Worker-side emit: every measured row carries plan_build_seconds,
    its run_id, and the recorder-sourced phases dict; a ``bench.row``
    point mirrors the row into the event log so luxview links them."""
    row = {**obj, "plan_build_seconds": _plan_build_field(),
           **_obs_fields()}
    try:
        from lux_tpu import obs

        obs.point("bench.row", metric=row.get("metric"),
                  value=row.get("value"), unit=row.get("unit"),
                  method=row.get("method"))
    except Exception:  # noqa: BLE001 — telemetry must never cost a row
        pass
    _emit(row)


def _zero(metric):
    return {
        "metric": metric,
        "value": 0.0,
        "unit": "GTEPS",
        "vs_baseline": 0.0,
        # the orchestrator never imports jax; static zeros keep the
        # every-row-carries-plan_build_seconds contract without it
        "plan_build_seconds": {"cold": 0.0, "warm": 0.0},
        "run_id": os.environ.get("LUX_OBS_RUN_ID", ""),
    }


def _env_int(name: str, default: int) -> int:
    """Integer env knob with an error that NAMES the variable (luxcheck
    LUX-P002).  Delegates to tools/_env.py, the jax-free twin of
    lux_tpu.utils.config.env_int: the orchestrator half of this file
    must never import lux_tpu — the package __init__ pulls in jax, and
    the watchdog has to stay healthy when the device tunnel (or the jax
    install) is wedged."""
    return _env.env_int(name, default)


def _total_unique(shards) -> int:
    """TOTAL real unique in-sources over all parts (roofline's
    compact_unique contract) — NOT the LANE-padded mirror width."""
    import numpy as np

    a = shards.arrays
    return sum(
        int(np.unique(a.src_pos[p][a.edge_mask[p]]).size)
        for p in range(a.src_pos.shape[0])
    )


def worker_main():
    """The actual benchmark; runs on whatever platform the env selects."""
    fake = os.environ.get("LUX_BENCH_FAKE_HANG")
    if fake == "1":
        # test hook: emulate the tunnel's claim-leg hang (a C-level block
        # the orchestrator must route around without killing this process)
        while True:
            time.sleep(3600)
    if fake == "emit":
        # test hook: bank one measurement, then wedge (the mid-run
        # server-side hang observed with the scan method) — the
        # orchestrator must harvest the banked line, not fall to insurance
        _emit({"metric": "pagerank_gteps_fake_banked", "value": 123.0,
               "unit": "GTEPS", "vs_baseline": 123.0, "method": "scatter",
               "dtype": "float32",
               "plan_build_seconds": {"cold": 0.0, "warm": 0.0}})
        while True:
            time.sleep(3600)
    # scale-up budget clock: from worker entry — the stagger sleep below
    # counts against it, because the orchestrator's tpu_wait deadline
    # started at spawn time
    t_worker0 = time.monotonic()
    # the orchestrator staggers the primary behind the CPU insurance so
    # the insurance's CPU-bound timed region runs on a quiet machine
    # (measured: concurrent graph gen halves the fallback GTEPS)
    time.sleep(_env_int("LUX_BENCH_PRIMARY_DELAY_S", 0))
    import jax
    import jax.numpy as jnp

    try:  # persistent compile cache: repeat bench runs skip the 20-40s
        # compile.  Keyed by the TARGET platform env (not
        # jax.default_backend(), which would force backend init right here
        # and turn a slow tunnel into a pre-benchmark hang) — a TPU-side
        # AOT entry must never be loaded by the CPU fallback worker.
        platform0 = (
            os.environ.get("JAX_PLATFORMS", "default").split(",")[0] or "default"
        )
        jax.config.update(
            "jax_compilation_cache_dir", f"/tmp/lux_jax_cache_{platform0}"
        )
    except Exception:
        pass

    from lux_tpu import obs
    from lux_tpu.engine import pull
    from lux_tpu.engine.methods import resolve as resolve_method
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    scale = _env_int("LUX_BENCH_SCALE", 20)
    ef = _env_int("LUX_BENCH_EF", 16)
    iters = _env_int("LUX_BENCH_ITERS", 10)
    method_env = os.environ.get("LUX_BENCH_METHOD", "auto")

    dtype_env = os.environ.get("LUX_BENCH_DTYPE")
    dtype = dtype_env or "float32"
    g = generate.rmat(scale, ef, seed=0)
    # LUX_BENCH_SORT_SEGMENTS=1: A/B the gather-locality relayout
    # (docs/PERF.md gather-amplification band); pagerank metric names
    # gain a _sortseg suffix so the two layouts never mix in _relay
    sort_seg = os.environ.get("LUX_BENCH_SORT_SEGMENTS") == "1"
    # LUX_BENCH_COMPACT_GATHER=1: A/B the unique-in-source mirror layout
    # (reference load_kernel staging); metrics gain a _compact suffix
    compact = os.environ.get("LUX_BENCH_COMPACT_GATHER") == "1"
    # LUX_BENCH_ROUTE_GATHER=1: A/B the routed-shuffle expand (the LOAD
    # phase as Benes lane shuffles, ops/expand.py); metrics gain a
    # _route suffix.  Mutually exclusive with the mirror layout (the
    # routed path never reads the mirror).
    route_gather = os.environ.get("LUX_BENCH_ROUTE_GATHER") == "1"
    # LUX_BENCH_ROUTE_FUSED=1: the FULL fused routed pipeline (load AND
    # reduce as routed movement, ops/expand.apply_fused); _routefused
    # suffix.  The reduce-method race is meaningless here (the fused
    # path replaces the reducer), so exactly one line is measured.
    route_fused = os.environ.get("LUX_BENCH_ROUTE_FUSED") == "1"
    # LUX_BENCH_ROUTE_PF / LUX_BENCH_ROUTE_FUSED_PF: the PASS-FUSED
    # variants (expand.to_pf — 2-3 Benes passes per kernel, one HBM
    # read+write per group); _routepf/_routefusedpf suffixes.
    route_pf = os.environ.get("LUX_BENCH_ROUTE_PF") == "1"
    route_fused_pf = os.environ.get("LUX_BENCH_ROUTE_FUSED_PF") == "1"
    # LUX_BENCH_ROUTE_FUSED_MX=1: the MXREDUCE fused pipeline (the
    # segmented reduction computed INSIDE the final routed Pallas
    # kernel as an MXU one-hot contraction, ops/expand plan_fused
    # mx=True); _routefusedmx suffix.
    route_fused_mx = os.environ.get("LUX_BENCH_ROUTE_FUSED_MX") == "1"
    if sum([route_gather, route_fused, route_pf, route_fused_pf,
            route_fused_mx, compact]) > 1:
        raise SystemExit("LUX_BENCH_ROUTE_GATHER / LUX_BENCH_ROUTE_FUSED "
                         "/ LUX_BENCH_ROUTE_PF / LUX_BENCH_ROUTE_FUSED_PF "
                         "/ LUX_BENCH_ROUTE_FUSED_MX "
                         "/ LUX_BENCH_COMPACT_GATHER are mutually exclusive")
    shards = build_pull_shards(g, 1, sort_segments=sort_seg,
                               compact_gather=compact)
    compact_unique = _total_unique(shards) if compact else 0
    # _layout["route"] is read by measure()/timed() so the default TPU
    # race can temporarily switch the routed line on (see below) without
    # threading a parameter through every closure
    _layout = {"route": None, "route_tag": ""}
    route_plan = None
    if (route_gather or route_fused or route_pf or route_fused_pf
            or route_fused_mx):
        from lux_tpu.ops import expand

        t_plan = time.time()
        if route_fused or route_fused_pf or route_fused_mx:
            route_plan = expand.plan_fused_shards_cached(
                shards, "sum", pf=route_fused_pf, mx=route_fused_mx)
        else:
            route_plan = expand.plan_expand_shards_cached(
                shards, pf=route_pf)
        # device-resident once, like the graph arrays below — NOT per
        # run(n) call (the stacked pass arrays are ~1 GB at scale 20;
        # re-transfer would burn the TPU budget inside the timed loop)
        route_plan = (route_plan[0],
                      jax.tree.map(jnp.asarray, route_plan[1]))
        jax.block_until_ready(route_plan[1])
        print(f"# worker: routed-expand plan ready in "
              f"{time.time() - t_plan:.1f}s (n={route_plan[0].n}, "
              f"{len(route_plan[1])} pass arrays, on device)",
              file=sys.stderr, flush=True)
        _layout["route"] = route_plan
        _layout["route_tag"] = {
            (True, False, False, False, False): "_route",
            (False, True, False, False, False): "_routefused",
            (False, False, True, False, False): "_routepf",
            (False, False, False, True, False): "_routefusedpf",
            (False, False, False, False, True): "_routefusedmx",
        }[(route_gather, route_fused, route_pf, route_fused_pf,
           route_fused_mx)]
    print(f"# worker: graph ready nv={g.nv} ne={g.ne}", file=sys.stderr, flush=True)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    jax.block_until_ready(arrays)
    print("# worker: arrays on device", file=sys.stderr, flush=True)

    def fetch_timed(run, reps=2):
        """Wall time of run(n) ended by a device->host scalar fetch.

        block_until_ready is NOT trustworthy through the axon tunnel —
        measured: readiness acked before execution (100 fori_loop
        iterations 'finishing' faster than 10).  A transfer of the result
        cannot lie: the bytes exist only after the computation ran.  The
        constant tunnel/dispatch latency is removed by differencing a
        1-iteration run, so the reported time is the honest marginal cost
        of (iters - 1) iterations scaled back up to iters.
        """

        def once(n):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                out = run(n)
                float(jax.device_get(out.ravel()[0]))
                best = min(best, time.perf_counter() - t0)
            return best, out

        # compile/iterate spans: the bench row's ``phases`` dict and
        # luxview's waterfall are views over these same durations
        with obs.span("compile.warm", iters=iters):
            for n in (1, iters):  # compile + warm both programs
                float(jax.device_get(run(n).ravel()[0]))
        with obs.span("iterate", iters=iters, reps=reps):
            t1, _ = once(1)
            tn, out = once(iters)
        per_iter = max((tn - t1) / (iters - 1), 1e-9) if iters > 1 else tn
        return per_iter * iters, out

    def timed(method, dt):
        if method == "pallas":
            from lux_tpu.models.pagerank import make_pallas_runner

            run, s0 = make_pallas_runner(g, dtype=dt)
            return fetch_timed(lambda n: run(s0, n))

        # run_pull_fixed's inner jit takes arrays as explicit args — no outer
        # jit wrapper, which would bake the device-resident graph into the
        # jaxpr as constants and double-buffer it in HBM (ADVICE r1)
        prog = PageRankProgram(nv=shards.spec.nv, dtype=dt)
        s0 = pull.init_state(prog, arrays)

        run_method = "scan" if method == "fused" else method
        rp = _layout["route"]

        def run(n):
            return pull.run_pull_fixed(prog, shards.spec, arrays, s0, n,
                                       run_method, route=rp)

        return fetch_timed(run)

    # pallas path is TPU-only (axon is the tunneled TPU plugin)
    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")
    if method_env == "auto":
        # scatter first (known to complete on the chip), scan LAST: the
        # only chip hang observed so far was inside a scan-method program
        # (server-side wedge, 30+ min; tools/tpu_timing_probe.py).  Each
        # result is emitted the moment it exists, so if a later method
        # wedges this worker the orchestrator still harvests the banked
        # lines from the output file.
        # "mxscan" (ISSUE 11): the blocked MXU segmented scan joins the
        # full-scale race ahead of "pallas" (both are Pallas kernels;
        # the wedge-prone associative_scan stays quarantined last)
        methods = (
            ["scatter", "cumsum", "mxsum", "mxscan", "pallas"]
            if on_tpu
            else ["scan", "scatter"]
        )
        if (route_gather or route_pf) and "pallas" in methods:
            # the pallas runner never sees route_plan — timing it here
            # would bank an unrouted number under the _route suffix
            methods.remove("pallas")
        if route_fused or route_fused_pf or route_fused_mx:
            # one line: the fused pipeline IS the method
            methods = ["fused"]
        risky_tail = ["scan"] if on_tpu else []
    else:
        methods = (["fused"] if route_fused or route_fused_pf
                   or route_fused_mx else [method_env])
        risky_tail = []
    results = {}

    apps = [
        a.strip()
        for a in os.environ.get(
            "LUX_BENCH_APPS",
            "pagerank,sssp,components,colfilter,serve,ba,refresh,live,"
            "bfs,labelprop",
        ).split(",")
        if a.strip()
    ]

    # kick the routed-race plan build NOW, on background host threads
    # (ops/expand.plan_async — per-part fan-out + per-part disk cache):
    # it overlaps the whole unrouted race, so by the time the routed
    # line's turn comes the plan is warm instead of costing ~3 min of
    # chip window (VERDICT r5 #6).  TPU-only: the routed line itself is.
    rp_future = None
    rp_state = {"warm": None}
    if ("pagerank" in apps and on_tpu
            and not (route_gather or route_fused or route_pf
                     or route_fused_pf or route_fused_mx or compact
                     or sort_seg)):
        from lux_tpu.ops import expand

        def _build_rp():
            # hash/probe INSIDE the background thread (hundreds of MB of
            # sha1 at scale 20 must not delay the first chip measurement)
            paths = expand.has_cached_expand_plan(shards)
            rp_state["warm"] = paths is not None
            base = expand.plan_expand_shards_cached(shards,
                                                    cache_path=paths)
            # the pass-fused twin: load it when the pf cache is warm
            # (prewarm writes it), else a pure in-memory numpy transform
            # of `base` — going through the cached pf planner here would
            # re-hash and re-read the unfused entries just loaded,
            # doubling the background wait the race's budget-aware
            # timeout is spent on
            pf_paths = expand.has_cached_expand_plan(shards, pf=True)
            if pf_paths is not None:
                pf = expand.plan_expand_shards_cached(
                    shards, pf=True, cache_path=pf_paths)
            else:
                pf = expand.to_pf(base)
            return base, pf

        rp_future = expand.plan_async(_build_rp)

    from lux_tpu.utils import roofline

    def measure(m, dt):
        elapsed, _ = timed(m, dt)
        results[(m, dt)] = elapsed
        gteps = iters * g.ne / elapsed / 1e9
        suffix = "" if on_tpu else f"_{platform}_fallback"
        if dt == "bfloat16":
            suffix = "_bf16" + suffix
        if sort_seg:
            suffix = "_sortseg" + suffix
        if compact:
            suffix = "_compact" + suffix
        if _layout["route_tag"]:
            suffix = _layout["route_tag"] + suffix
        print(
            f"# method {m} ({dt}): {elapsed:.4f}s -> {gteps:.4f} GTEPS",
            file=sys.stderr,
            flush=True,
        )
        if _layout["route"] is not None:
            model = roofline.routed_pull_iter_model(
                _layout["route"][0], g.ne, g.nv,
                state_bytes=2 if dt == "bfloat16" else 4,
                method="scan" if m == "fused" else m,
            ).scale(iters)
            # HBM-sweep accounting next to the byte model: the
            # pass-fusion acceptance metric (r1/ff/r2/reduce sweeps per
            # iteration; a pf plan's total is ~half the unfused one's)
            passes = roofline.routed_hbm_passes(
                _layout["route"][0], "scan" if m == "fused" else m)
        else:
            model = roofline.pull_iter_model(
                g.ne, g.nv, m, state_bytes=2 if dt == "bfloat16" else 4,
                compact_unique=compact_unique,
            ).scale(iters)
            passes = (roofline.pull_hbm_passes(m)
                      if m in roofline.REDUCE_HBM_PASSES else None)
        _emit_row(
            {
                "metric": f"pagerank_gteps_rmat{scale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "dtype": dt,
                **({"hbm_passes": passes} if passes else {}),
                **roofline.summarize(model, elapsed, iters * g.ne),
            }
        )

    def measure_scaleup(m, dt):
        """One pagerank line at scale+2 (4x the edges) on the winning
        method — distinguishes a dispatch-dominated small-graph number
        from a bandwidth-bound one (compare the two scales'
        achieved_GBps; docs/PERF.md roofline).  Same layout (sort_seg)
        and suffix composition as the headline so the cross-scale
        comparison is like-for-like."""
        s2 = scale + 2
        g2 = generate.rmat(s2, ef, seed=0)
        sh2 = build_pull_shards(g2, 1, sort_segments=sort_seg,
                                compact_gather=compact)
        cu2 = _total_unique(sh2) if compact else 0
        prog2 = PageRankProgram(nv=sh2.spec.nv, dtype=dt)
        arr2 = jax.tree.map(jnp.asarray, sh2.arrays)
        s0 = pull.init_state(prog2, arr2)

        def run(n):
            return pull.run_pull_fixed(prog2, sh2.spec, arr2, s0, n, m)

        elapsed, _ = fetch_timed(run)
        gteps = iters * g2.ne / elapsed / 1e9
        suffix = "" if on_tpu else f"_{platform}_fallback"
        if dt == "bfloat16":
            suffix = "_bf16" + suffix
        if sort_seg:
            suffix = "_sortseg" + suffix
        if compact:
            suffix = "_compact" + suffix
        model = roofline.pull_iter_model(
            g2.ne, g2.nv, m, state_bytes=2 if dt == "bfloat16" else 4,
            compact_unique=cu2,
        ).scale(iters)
        _emit_row(
            {
                "metric": f"pagerank_gteps_rmat{s2}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "dtype": dt,
                # pass-through marker: _relay must not let this line
                # compete with (and hijack) the rmat{scale} headline
                "scale_up": True,
                **({"hbm_passes": roofline.pull_hbm_passes(m)}
                   if m in roofline.REDUCE_HBM_PASSES else {}),
                **roofline.summarize(model, elapsed, iters * g2.ne),
            }
        )

    suffix = "" if on_tpu else f"_{platform}_fallback"

    push_shards_cache = []

    def _timed_push_convergence(prog, m, app=None):
        """Run a frontier app to convergence on the push chunk loop and
        time it with the fetch-differencing discipline: the chunk loop
        takes a DYNAMIC it_stop, so t(full) - t(1) is the honest marginal
        cost of the remaining iterations under one compiled program.
        ``app`` names the row in the flight recorder and enables one
        extra NON-timed telemetry run whose per-round frontier/traversed
        curve lands in the event log (the ring rides the while carry;
        the timed runs stay ring-free so the differencing numbers are
        exactly the shipped hot loop's).
        Returns (n_iters, traversed_edges, elapsed_s, dense_rounds)."""
        from lux_tpu.engine import push as push_eng
        from lux_tpu.graph.push_shards import build_push_shards

        if not push_shards_cache:
            # program-independent O(ne) host build: shared by sssp + CC
            push_shards_cache.append(build_push_shards(g, 1))
        pshards = push_shards_cache[0]
        arrays_p, parrays_p, carry0 = push_eng.push_init(prog, pshards)
        loop = push_eng.compile_push_chunk(
            prog, pshards.pspec, pshards.spec, m
        )

        def run(n):
            # the chunk loop does not donate its arguments: one carry0 is
            # safely reusable across timed runs
            return loop(arrays_p, parrays_p, carry0, jnp.int32(n))

        # compile.warm holds the trace+compile (plus one cheap iteration);
        # the run-to-convergence is ITERATION work and must land under the
        # "iterate" prefix, or the row's phases dict would blame a 60s
        # converge on the compiler
        with obs.span("compile.warm", app=app or "push"):
            float(jax.device_get(run(1).state.ravel()[0]))
        with obs.span("iterate.converge", app=app or "push"):
            full = run(10_000)  # converge
            float(jax.device_get(full.state.ravel()[0]))
            n_iters = int(full.it)
            traversed = push_eng.edges_total(jax.device_get(full.edges))
            dense_rounds = int(full.dense_rounds)

        def once(n):
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                out = run(n)
                float(jax.device_get(out.state.ravel()[0]))
                best = min(best, time.perf_counter() - t0)
            return best

        with obs.span("iterate", app=app or "push", iters=n_iters):
            if n_iters > 1:
                per_iter = max((once(n_iters) - once(1)) / (n_iters - 1),
                               1e-9)
                elapsed = per_iter * n_iters
            else:
                elapsed = once(n_iters)
        if app is not None:
            try:
                from lux_tpu.obs import ring as obs_ring

                with obs.span("telemetry.capture", app=app, method=m):
                    tloop = push_eng.compile_push_chunk(
                        prog, pshards.pspec, pshards.spec, m,
                        telemetry=True)
                    _, rg = tloop(arrays_p, parrays_p, carry0,
                                  jnp.int32(10_000),
                                  obs_ring.new_ring("push"))
                    obs_ring.emit_ring("push", rg, app=app, method=m)
            except Exception as e:  # noqa: BLE001 — telemetry is never
                # load-bearing for a bench row
                print(f"# push telemetry capture failed: {e}",
                      file=sys.stderr, flush=True)
        return n_iters, traversed, elapsed, dense_rounds

    def measure_sssp():
        """Convergence-driven BFS-SSSP; GTEPS over edges ACTUALLY
        traversed (the engine's exact [hi, lo] counter — dense rounds walk
        every edge, sparse rounds only the frontier's; SURVEY.md §6)."""
        import numpy as np

        from lux_tpu.models.sssp import SSSPProgram

        m = resolve_method("auto", "min", platform)
        # start at the max-out-degree vertex: a fixed start (the CLI's
        # default 0) can have zero out-edges on an RMAT draw, making the
        # metric a meaningless 0.0/traversed=0 line
        start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
        n_iters, traversed, elapsed, dr = _timed_push_convergence(
            SSSPProgram(nv=g.nv, start=start), m, app="sssp"
        )
        gteps = traversed / elapsed / 1e9
        model = roofline.push_run_model(g.ne, g.nv, traversed, dr, m)
        _emit_row(
            {
                "metric": f"sssp_gteps_rmat{scale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "start": start,
                "iters": n_iters,
                "dense_rounds": dr,
                "traversed_edges": traversed,
                **roofline.summarize(model, elapsed, traversed),
            }
        )

    def measure_components(m):
        """Max-label CC on the push engine (dense all-active start, the
        reference's components_gpu.cu:733-739 contract); traversed-edges
        GTEPS like sssp."""
        from lux_tpu.models.components import MaxLabelProgram

        n_iters, traversed, elapsed, dr = _timed_push_convergence(
            MaxLabelProgram(), m, app="components"
        )
        gteps = traversed / elapsed / 1e9
        model = roofline.push_run_model(g.ne, g.nv, traversed, dr, m)
        _emit_row(
            {
                "metric": f"components_gteps_rmat{scale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "iters": n_iters,
                "dense_rounds": dr,
                "traversed_edges": traversed,
                **roofline.summarize(model, elapsed, traversed),
            }
        )

    def measure_bfs():
        """Spec-compiled multi-source BFS (ISSUE 13, lux_tpu.program):
        the luxprog payoff workload on the push engine, riding the SAME
        timed convergence harness as sssp — the program object is the
        only difference, which is the point (the compiler, not the
        engines, absorbed the scenario)."""
        import numpy as np

        from lux_tpu.program import workloads as prog_workloads

        m = resolve_method("auto", "min", platform)
        deg = np.bincount(g.col_idx, minlength=g.nv)
        srcs = tuple(int(v) for v in np.argsort(deg)[::-1][:4])
        prog = prog_workloads.bfs_program(g.nv, srcs)
        n_iters, traversed, elapsed, dr = _timed_push_convergence(
            prog, m, app="bfs")
        gteps = traversed / elapsed / 1e9
        model = roofline.push_run_model(g.ne, g.nv, traversed, dr, m)
        _emit_row(
            {
                "metric": f"bfs_gteps_rmat{scale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "sources": list(srcs),
                "iters": n_iters,
                "dense_rounds": dr,
                "traversed_edges": traversed,
                # dense rounds are pull-style in-edge sweeps: the same
                # accounted-sweep family every pull row carries
                "hbm_passes": roofline.pull_hbm_passes(m),
                **roofline.summarize(model, elapsed, traversed),
            }
        )

    def _fetch_timed_iters(run, n_iters, reps=2):
        """fetch_timed's differencing discipline for a secondary app
        with its OWN iteration count (the closure above is bound to the
        headline race's).  Returns honest per-run seconds."""

        def once(n):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                out = run(n)
                float(jax.device_get(out.ravel()[0]))
                best = min(best, time.perf_counter() - t0)
            return best

        with obs.span("compile.warm", iters=n_iters):
            for n in (1, n_iters):
                float(jax.device_get(run(n).ravel()[0]))
        with obs.span("iterate", iters=n_iters, reps=reps):
            t1 = once(1)
            tn = once(n_iters)
        per_iter = (max((tn - t1) / (n_iters - 1), 1e-9)
                    if n_iters > 1 else tn)
        return per_iter * n_iters

    def measure_labelprop():
        """Spec-compiled seeded label propagation (ISSUE 13): the WIDE
        (V, L) dense-pull workload on its own small graph
        (LUX_BENCH_LABELPROP_SCALE, default min(scale, 12)) — GTEPS
        counts edge traversals (each moves L lanes; the row carries
        ``labels`` so the byte volume is reconstructible)."""
        from lux_tpu.graph.shards import build_pull_shards as _bps
        from lux_tpu.program import workloads as prog_workloads

        lscale = _env_int("LUX_BENCH_LABELPROP_SCALE", min(scale, 12))
        labels, stride, n_it = 8, 16, 10
        m = resolve_method("auto", "sum", platform)
        gl = generate.rmat(lscale, ef, seed=0)
        shl = _bps(gl, 1)
        prog = prog_workloads.labelprop_program(labels, stride)
        arr_l = jax.tree.map(jnp.asarray, shl.arrays)
        s0 = pull.init_state(prog, arr_l)

        def run(n):
            return pull.run_pull_fixed(prog, shl.spec, arr_l, s0, n, m)

        elapsed = _fetch_timed_iters(run, n_it)
        gteps = n_it * gl.ne / elapsed / 1e9
        _emit_row(
            {
                "metric": f"labelprop_gteps_rmat{lscale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "labels": labels,
                "seed_stride": stride,
                "iters": n_it,
                "hbm_passes": roofline.pull_hbm_passes(m),
            }
        )

    def measure_kcore():
        """Spec-compiled k-core decomposition (ISSUE 13, OPT-IN via
        LUX_BENCH_APPS): the iterative peel on its own small graph —
        one compiled program per level, warm-started survivors.  GTEPS
        over ne * total peel rounds (each round is one dense in-edge
        sweep)."""
        from lux_tpu.graph.shards import build_pull_shards as _bps
        from lux_tpu.program import workloads as prog_workloads

        kscale = _env_int("LUX_BENCH_KCORE_SCALE", min(scale, 12))
        m = resolve_method("auto", "sum", platform)
        gk = generate.rmat(kscale, ef, seed=0)
        gks = prog_workloads.symmetrize(gk)
        shk = _bps(gks, 1)
        with obs.span("compile.warm", app="kcore"):
            # the peel compiles ONE program per level (kk is a static),
            # so the warm pass must run the FULL decomposition — a
            # partial warm would leave levels >= 2 compiling inside the
            # timed region and the row would report compile time
            prog_workloads.kcore(shk, method=m)
        with obs.span("iterate", app="kcore"):
            t0 = time.perf_counter()
            coreness, kmax, rounds = prog_workloads.kcore(shk, method=m)
            elapsed = time.perf_counter() - t0
        gteps = rounds * gks.ne / elapsed / 1e9
        _emit_row(
            {
                "metric": f"kcore_gteps_rmat{kscale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "k_max": int(kmax),
                "peel_rounds": int(rounds),
                "core_vertices": int((coreness > 0).sum()),
                "hbm_passes": roofline.pull_hbm_passes(m),
            }
        )

    def measure_triangles():
        """Spec-compiled weighted triangle counting (ISSUE 13, OPT-IN):
        the two-phase intersection-heavy program on its own small
        symmetrized graph (bitset state is quadratic in nv by design).
        GTEPS over 2 edge sweeps (one per phase); the row carries the
        exactness cross-check against the NumPy oracle."""
        import numpy as np

        from lux_tpu.program import workloads as prog_workloads

        tscale = _env_int("LUX_BENCH_TRIANGLES_SCALE", 10)
        m = resolve_method("auto", "sum", platform)
        gt = prog_workloads.symmetrize(
            generate.rmat(tscale, ef, seed=0, weighted=True))
        with obs.span("compile.warm", app="triangles"):
            prog_workloads.triangles(gt, method=m)
        with obs.span("iterate", app="triangles"):
            t0 = time.perf_counter()
            incidence, stats = prog_workloads.triangles(gt, method=m)
            elapsed = time.perf_counter() - t0
        sweeps = 2
        gteps = sweeps * gt.ne / elapsed / 1e9
        oracle_ok = bool(
            np.allclose(incidence, prog_workloads.triangles_reference(gt),
                        rtol=1e-5))
        _emit_row(
            {
                "metric": f"triangles_gteps_rmat{tscale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "total_weighted_incidence":
                    stats["total_weighted_incidence"],
                "bitset_words": stats["bitset_words"],
                "oracle_ok": oracle_ok,
                "hbm_passes": {"per_phase": roofline.pull_hbm_passes(m),
                               "phases": sweeps},
            }
        )

    def measure_serve():
        """Batched query-serving row (lux_tpu.serve): warm Q=64 batched
        QPS vs warm Q=1 sequential on the headline graph — the serving
        path's tracked artifact.  Skipped under layout A/B modes (the
        serving engines bind the default pull layout)."""
        from lux_tpu.serve.benchmarks import measure_serving

        res = measure_serving(
            g, shards, app="sssp", q=64, num_seq=4, batched_reps=1,
            method="auto",
        )
        _emit_row(
            {
                "metric": f"sssp_qps_rmat{scale}_1chip{suffix}",
                "value": res["qps_batched"],
                "unit": "QPS",
                # the serving row's baseline IS request-at-a-time
                # serving: batched/sequential is the subsystem's win
                "vs_baseline": res["batched_vs_q1"],
                **res,
            }
        )

    def measure_fleet():
        """Multi-replica serving rows (lux_tpu.serve.fleet, OPT-IN via
        LUX_BENCH_APPS): offered-QPS ramp to the saturation knee at 1/2/4
        worker processes on CPU, one row per width (distinct metric
        families — the best-per-family relay contest must never fold
        widths together), plus the paired interleaved 2w-vs-1w probe on
        the w2 row.  CPU loopback by design: the fleet layer is host
        coordination, and the row must be bankable with no chip."""
        from lux_tpu.serve.fleet.bench import measure_fleet_saturation

        fscale = _env_int("LUX_BENCH_FLEET_SCALE", 12)
        res = measure_fleet_saturation(scale=fscale, workers=(1, 2, 4))
        for row in res["rows"]:
            _emit_row(row)
        oh = res.get("trace_overhead") or {}
        print(f"# fleet knees: {res['knees']} "
              f"paired_2v1={res.get('scaleup_2v1')} "
              f"trace_overhead={oh.get('overhead_frac')}",
              file=sys.stderr, flush=True)

    def measure_pod():
        """Placement-tree weak-scaling rows (ISSUE 19, OPT-IN via
        LUX_BENCH_APPS): 1/2/4 REAL worker processes over loopback TCP
        with private launcher tmpdirs, one row per width, problem size
        grown with the width (rmat16 -> 18 -> 20 by default — the curve
        the chip window re-runs verbatim on process-mode TPU hosts).
        Each width's sharded sssp answer is asserted BITWISE against
        the single-host pull engine before its row can emit; the phases
        dict attributes wall to plan (stream + partial load + warmup) /
        exchange (frames + assembly) / converge (worker compute);
        ``weak_scaling`` is the per-host converge throughput vs the w1
        row.  Emitted via _emit, not _emit_row: the pod phases ARE the
        row's phase attribution — the driver-process span totals would
        overwrite them with the oracle run's load/compile/iterate."""
        import numpy as np

        from lux_tpu.engine.methods import resolve_sum
        from lux_tpu.graph.format import write_lux
        from lux_tpu.models.sssp import SSSPProgram
        from lux_tpu.program.spec import active_changed
        from lux_tpu.serve.fleet.launcher import launch_pod_worker
        from lux_tpu.serve.fleet.pod import run_pull_pod
        from lux_tpu.utils import roofline

        base = _env_int("LUX_BENCH_POD_SCALE", 16)
        pparts = _env_int("LUX_BENCH_POD_PARTS", 8)
        per_host0 = None
        for w in (1, 2, 4):
            sc = base + {1: 0, 2: 2, 4: 4}[w]
            gp = generate.rmat(sc, 8, seed=3)
            snap = f"/tmp/lux_bench_pod_{os.getpid()}_w{w}.lux"
            write_lux(snap, gp)
            shp = build_pull_shards(gp, pparts)
            start = int(np.argmax(gp.out_degrees()))
            prog = SSSPProgram(nv=shp.spec.nv, start=start)
            s0 = pull.init_state(prog, shp.arrays)
            want, _ = pull.run_pull_until(
                prog, shp.spec, shp.arrays, s0, 10_000, active_changed,
                method="auto")
            hs = [launch_pod_worker(f"bench_w{w}_{i}") for i in range(w)]
            try:
                res = run_pull_pod(
                    [("127.0.0.1", h.port) for h in hs], snap, pparts,
                    app="sssp", start=start)
            finally:
                for h in hs:
                    h.terminate()
            os.remove(snap)
            assert np.array_equal(res["state"], np.asarray(want)), (
                f"pod w{w} != single-host")
            tconv = max(res["phases"]["converge"], 1e-9)
            value = gp.ne * res["iters"] / tconv / 1e9
            per_host = value / w
            per_host0 = per_host if per_host0 is None else per_host0
            m = resolve_sum("auto", prog.reduce)
            row = {
                "metric": f"sssp_pod_w{w}_rmat{sc}",
                "value": round(value, 4),
                "unit": "GTEPS",
                "method": m,
                "dtype": "int32",
                "hosts": w,
                "parts": pparts,
                "iters": res["iters"],
                "edges": int(gp.ne),
                "weak_scaling": round(per_host / per_host0, 3),
                "phases": {k: round(v, 3)
                           for k, v in res["phases"].items()},
                "workers": {wid: {"lo": i["lo"], "hi": i["hi"],
                                  "compute_s": round(i["compute_s"], 3)}
                            for wid, i in res["workers"].items()},
                "hbm_passes": roofline.pull_hbm_passes(m),
                "plan_build_seconds": _plan_build_field(),
                "run_id": obs.recorder().run_id,
            }
            obs.point("bench.row", metric=row["metric"],
                      value=row["value"], unit=row["unit"], method=m)
            _emit(row)
            print(f"# pod w{w} rmat{sc}: iters={res['iters']} "
                  f"phases={row['phases']} "
                  f"weak_scaling={row['weak_scaling']}",
                  file=sys.stderr, flush=True)

    def measure_ba():
        """Standing heavy-tail row (VERDICT r5 weak #4: BA existed only
        as a slow test): a Barabási-Albert graph through the FULL
        production path — generator -> .lux round trip -> ROUTED-PF
        pull — so hub skew is measured where routed-plan padding and
        the pass-fused kernels actually bite, not just unit-tested.
        Scale defaults to min(headline scale, 20); CPU fallback rows
        are real (smaller) measurements like every other family.  The
        metric name carries no ``_rmat``, so _relay treats it as its
        own family and it can never contest the headline."""
        from lux_tpu.graph.format import read_lux, write_lux
        from lux_tpu.ops import expand

        # off-TPU the row is an insurance-path extra: cap its default
        # scale so the BA generation + cold plan build can never delay
        # the number the CPU fallback worker exists to bank quickly
        # (LUX_BENCH_BA_SCALE still overrides for deliberate runs)
        bs = _env_int("LUX_BENCH_BA_SCALE",
                      min(scale, 20 if on_tpu else 14))
        mdeg = _env_int("LUX_BENCH_BA_M", 4)
        gb0 = generate.barabasi_albert(1 << bs, mdeg, seed=7)
        path = f"/tmp/lux_bench_ba_{os.getpid()}.lux"
        write_lux(path, gb0)
        gb = read_lux(path)
        try:
            os.remove(path)
        except OSError:
            pass
        assert (gb.nv, gb.ne) == (gb0.nv, gb0.ne)
        shb = build_pull_shards(gb, 1)
        rp = expand.plan_expand_shards_cached(shb, pf=True)
        rp = (rp[0], jax.tree.map(jnp.asarray, rp[1]))
        m = resolve_method("auto", "sum", platform)
        prog = PageRankProgram(nv=shb.spec.nv, dtype=dtype)
        arrb = jax.tree.map(jnp.asarray, shb.arrays)
        s0b = pull.init_state(prog, arrb)
        jax.block_until_ready((arrb, rp[1]))

        def run(n):
            return pull.run_pull_fixed(prog, shb.spec, arrb, s0b, n, m,
                                       route=rp)

        elapsed, _ = fetch_timed(run)
        gteps = iters * gb.ne / elapsed / 1e9
        model = roofline.routed_pull_iter_model(
            rp[0], gb.ne, gb.nv,
            state_bytes=2 if dtype == "bfloat16" else 4, method=m,
        ).scale(iters)
        # same suffix discipline as the headline rows: a bf16 BA run
        # must never contest the f32 BA family in _relay
        ba_suffix = ("_bf16" if dtype == "bfloat16" else "") + suffix
        _emit_row(
            {
                "metric":
                    f"pagerank_gteps_ba{bs}_m{mdeg}_routepf{ba_suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                "dtype": dtype,
                "nv": int(gb.nv),
                "ne": int(gb.ne),
                "hbm_passes": roofline.routed_hbm_passes(rp[0], m),
                **roofline.summarize(model, elapsed, iters * gb.ne),
            }
        )

    def measure_live():
        """Standing mixed read/write serving row (ISSUE 12,
        lux_tpu.serve.live): a 2-worker thread-mode LIVE fleet under
        concurrent writer + closed-loop readers — sustained write
        batches/s through admit->journal->replicate, read QPS, read
        staleness in generations (p50/p99 of journal-gen-at-submit
        minus the answer's generation tag), and the fleet-wide warm
        refresh latency.  CPU loopback by design, like the fleet rows:
        the write path is host coordination + O(delta) overlay
        rebuilds, bankable with no chip window."""
        from lux_tpu.serve.live.bench import measure_live_mixed

        lscale = _env_int("LUX_BENCH_LIVE_SCALE", 12)
        row = measure_live_mixed(scale=lscale, workers=2)
        _emit_row(row)
        slo = {s["name"]: s["verdict"] for s in row.get("slo", [])}
        print(f"# live: {row['value']} read QPS, "
              f"{row['write_batches_per_s']} write batches/s, "
              f"staleness p99 {row['staleness_gen_p99']} gen, "
              f"fleet refresh {row['fleet_refresh_s']}s, "
              f"slo {slo}",
              file=sys.stderr, flush=True)

    def measure_refresh():
        """Standing dynamic-graph rows (ISSUE 10, lux_tpu.mutate):
        ``pagerank_refresh_churn1pct_*`` and ``sssp_refresh_churn1pct_*``
        — after a 1% edge churn batch (0.5% deletes + 0.5% inserts,
        edge count conserved), the warm overlay refresh from the prior
        converged state is raced against a COLD recompute of the
        compacted snapshot (load + shard build + compile + converge;
        ``jax.clear_caches()`` makes the cold leg a process-restart
        equivalent — with a warm persistent XLA disk cache its compile
        is a disk load, still a cost the refresh never pays).  The row
        value is the speedup (the ROADMAP bar is >=10x), with the
        cold-side breakdown, delta-buffer occupancy, the compaction's
        invalidated-bucket fraction, and the bitwise verdict attached.
        Runs on its own graph (LUX_BENCH_REFRESH_SCALE, default
        min(scale, 16)) at 8 parts so the bucket accounting is real."""
        import numpy as np

        from lux_tpu.graph.format import read_lux
        from lux_tpu.graph.push_shards import build_push_shards
        from lux_tpu.graph.shards import build_pull_shards
        from lux_tpu.models.sssp import SSSPProgram
        from lux_tpu.mutate import MutableGraph
        from lux_tpu.mutate import refresh as refresh_mod

        rscale = _env_int("LUX_BENCH_REFRESH_SCALE", min(scale, 16))
        parts = 8
        gr = generate.rmat(rscale, ef, seed=0)
        rng = np.random.default_rng(0)
        snap = f"/tmp/lux_bench_refresh_{os.getpid()}.lux"
        # size the delta capacity for THIS row's churn: 0.5% inserts
        # could all land in one part in the worst case, and a cap
        # overflow raises (by design) instead of silently folding —
        # the row must measure the overlay, not die on a skew draw
        churn_k = max(8, gr.ne // 200)
        mg = MutableGraph(gr, num_parts=parts, snapshot=snap,
                          cap=max(1024, churn_k + 128))

        # prior converged states; a tiny warmup churn+refresh first so
        # the OVERLAY programs are compiled — the timed refresh is the
        # steady-state production path (churn arrives repeatedly)
        start = int(np.argmax(np.bincount(gr.col_idx, minlength=gr.nv)))
        prog = SSSPProgram(nv=gr.nv, start=start)
        from lux_tpu.engine import push as push_eng

        st, _, _ = push_eng.run_push(prog, mg.push_shards)
        dist = mg.push_shards.scatter_to_global(np.asarray(st))
        pr, _ = refresh_mod.converge_pagerank(mg.pull_shards)
        mg.apply([0], [1], [1])  # warmup batch
        pr, _ = refresh_mod.refresh_pagerank(mg, pr)
        dist, _ = refresh_mod.refresh_sssp(mg, dist, start)

        # the 1% churn batch: balanced deletes/inserts, edge-count
        # conserving (the layouts' static shapes absorb it by design)
        k = churn_k
        cur = mg.log.merged_graph()
        dsts = cur.dst_of_edges()
        dele = rng.choice(cur.ne, size=k, replace=False)
        t0 = time.perf_counter()
        mg.apply(cur.col_idx[dele], dsts[dele], np.zeros(k, np.int8))
        mg.apply(rng.integers(0, gr.nv, k), rng.integers(0, gr.nv, k),
                 np.ones(k, np.int8))
        apply_s = time.perf_counter() - t0
        occ = mg.occupancy()

        def best_of(fn, reps=2):
            best, out = float("inf"), None
            for _ in range(reps):
                t = time.perf_counter()
                r = fn()
                best = min(best, time.perf_counter() - t)
                out = r
            return best, out

        # warm refresh legs (reps keep the number honest vs scheduler
        # noise; refresh is idempotent from the same prior state)
        refresh_pr_s, (pr_new, pr_iters) = best_of(
            lambda: refresh_mod.refresh_pagerank(mg, pr))
        refresh_ss_s, (dist_new, ss_iters) = best_of(
            lambda: refresh_mod.refresh_sssp(mg, dist, start))
        dist_new = np.asarray(dist_new)

        # compact: snapshot + bucket-scoped invalidation (reused cuts)
        t0 = time.perf_counter()
        rep = mg.compact(path=snap)
        compact_s = time.perf_counter() - t0
        inval = rep.get("invalidation", {})
        cuts = np.asarray(mg.pull_shards.cuts)

        # cold legs: per-app process-restart equivalent.  "cold
        # load+plan+recompute" (the ROADMAP bar) restores the WHOLE
        # serving state: the snapshot load, the shard build, the routed
        # expand plan (the shipped default engine config is routed-pf,
        # and a 1% GLOBAL churn invalidates every per-bucket cache
        # entry — the ``invalidated_bucket_fraction`` field is exactly
        # that accounting, so the plan leg is the full rebuild), and
        # the trace+compile+converge.  The COMPUTE legs on both sides
        # use the platform-resolved direct method (identical engine
        # config; routed is the TPU winner, and the refresh side keeps
        # the BASE plan serving without rebuilding it — pinned bitwise
        # by tests/test_mutate.py's overlay∘routed-pf test).
        # jax.clear_caches + a disabled persistent compile cache make
        # the cold compile real, not a disk-cache load.
        from lux_tpu.ops import expand as expand_mod

        def cold_leg(app):
            try:
                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:  # noqa: BLE001 — cache knob is advisory
                pass
            jax.clear_caches()
            t0 = time.perf_counter()
            gc = read_lux(snap)
            t_load = time.perf_counter() - t0
            if app == "pagerank":
                shc = build_pull_shards(gc, parts, cuts=cuts)
                pull_sh = shc
            else:
                shc = build_push_shards(gc, parts, cuts=cuts)
                pull_sh = shc.pull
            t_build = time.perf_counter() - t0 - t_load
            expand_mod.plan_expand_shards(pull_sh, pf=True)
            t_plan = time.perf_counter() - t0 - t_load - t_build
            if app == "pagerank":
                out, _ = refresh_mod.converge_pagerank(shc)
                jax.block_until_ready(out)
            else:
                out, _, _ = push_eng.run_push(
                    SSSPProgram(nv=gc.nv, start=start), shc)
                jax.block_until_ready(out)
            return (time.perf_counter() - t0,
                    {"load": round(t_load, 4),
                     "build": round(t_build, 4),
                     "plan": round(t_plan, 4),
                     "compute": round(time.perf_counter() - t0 - t_load
                                      - t_build - t_plan, 4)},
                    shc.scatter_to_global(np.asarray(out)))

        try:
            cold_pr_s, pr_cold_parts, pr_cold = cold_leg("pagerank")
            cold_ss_s, ss_cold_parts, ss_cold = cold_leg("sssp")
        finally:
            # later families get their persistent compile cache back
            try:
                p0 = (os.environ.get("JAX_PLATFORMS",
                                     "default").split(",")[0]
                      or "default")
                jax.config.update("jax_compilation_cache_dir",
                                  f"/tmp/lux_jax_cache_{p0}")
            except Exception:  # noqa: BLE001 — cache knob is advisory
                pass
        pr_global = mg.pull_shards.scatter_to_global(np.asarray(pr_new))
        try:
            os.remove(snap)
        except OSError:
            pass

        def ulp_dist(a, b):
            ai = a.view(np.int32).astype(np.int64)
            bi = b.view(np.int32).astype(np.int64)
            return int(np.abs(ai - bi).max()) if a.size else 0

        common = {
            "unit": "x", "churn_edges": int(2 * k),
            "churn_frac": round(2 * k / gr.ne, 4),
            "delta_occupancy": occ,
            "invalidated_bucket_fraction": inval.get("fraction"),
            "apply_s": round(apply_s, 4),
            "compact_s": round(compact_s, 4), "parts": parts,
        }
        for app, r_s, c_s, c_parts, iters_, mine, cold in (
            ("pagerank", refresh_pr_s, cold_pr_s, pr_cold_parts,
             pr_iters, pr_global, pr_cold),
            ("sssp", refresh_ss_s, cold_ss_s, ss_cold_parts,
             ss_iters, dist_new, ss_cold),
        ):
            bitwise = bool(np.array_equal(mine, cold))
            speedup = c_s / max(r_s, 1e-9)
            row = {
                "metric":
                    f"{app}_refresh_churn1pct_rmat{rscale}{suffix}",
                "value": round(speedup, 2),
                # the bar this family exists to clear: >=10x over cold
                "vs_baseline": round(speedup / 10.0, 3),
                "refresh_s": round(r_s, 4),
                "cold_s": round(c_s, 4),
                "cold_breakdown": c_parts,
                "refresh_iters": int(iters_),
                "bitwise_equal": bitwise,
                **common,
            }
            if app == "pagerank":
                # f32 fixpoints of two deterministic maps (overlay
                # decomposition vs cold-rebuilt layout): bitwise in
                # practice under the alpha contraction, but the honest
                # cross-association bound is ulps — report it
                # (docs/DYNAMIC.md); sssp/cc are bitwise by
                # construction (unique integer fixpoints)
                row["max_ulp_diff"] = ulp_dist(mine, cold)
                # accounted HBM sweeps per warm-refresh iteration by
                # route family (ISSUE 17): the timed leg above ran the
                # platform default; a serving deployment rides fused-pf
                # (overlays tombstone in group space), whose routed
                # total is the banked win
                fst_acc, _ = expand_mod.plan_fused_shards_cached(
                    mg.pull_shards, "sum", pf=True, mx=False)
                est_acc, _ = expand_mod.plan_expand_shards_cached(
                    mg.pull_shards, pf=True)
                row["hbm_passes"] = {
                    "direct": roofline.pull_hbm_passes("scan"),
                    "expand_pf": roofline.routed_hbm_passes(est_acc,
                                                            "scan"),
                    "fused_pf": roofline.routed_hbm_passes(fst_acc,
                                                           "scan"),
                }
            _emit_row(row)
            print(f"# refresh {app}: {r_s:.3f}s vs cold {c_s:.3f}s "
                  f"= {speedup:.1f}x (bitwise={bitwise})",
                  file=sys.stderr, flush=True)

    def measure_mx_micro():
        """Standing MXU-vs-VPU fused-reduce micro row (ISSUE 7): the
        SAME tiny fused plan in both flavors — "group" (PR 4's masked
        group reshape-reduce on the VPU) vs "mxreduce" (the segmented
        reduction inside the final routed kernel as an MXU one-hot
        contraction) — so the ``tpu:reduce_mode`` default is measured,
        not assumed.  Exactness-gated: each flavor must match the
        NumPy segment-sum oracle (rtol 1e-4 — each has its own
        deterministic f32 association) before its time counts.  On TPU
        the winner is banked in the overlay; the row itself is emitted
        everywhere (CPU rows are real interpret-mode measurements,
        clearly suffixed like every other fallback family)."""
        import numpy as np

        from lux_tpu.ops import expand

        ms = _env_int("LUX_BENCH_MX_MICRO_SCALE", 12)
        gm = generate.rmat(ms, 8, seed=0)
        src_pos = np.asarray(gm.col_idx).astype(np.int64)
        dst_local = gm.dst_of_edges().astype(np.int64)
        rng = np.random.default_rng(0)
        x0_np = rng.random(gm.nv).astype(np.float32)
        want = np.zeros(gm.nv, np.float32)
        np.add.at(want, dst_local, x0_np[src_pos])
        interp = not on_tpu
        flavor_ms = {}
        for name, mx in (("group", False), ("mxreduce", True)):
            st, arr = expand.plan_fused(
                src_pos, dst_local, gm.ne, gm.nv, gm.nv, "sum", mx=mx)
            ra = tuple(jnp.asarray(a) for a in arr)
            x0 = jnp.asarray(x0_np)
            jax.block_until_ready((x0,) + ra)
            got = np.asarray(jax.jit(
                lambda x, st=st, ra=ra: expand.apply_fused(
                    x, st, ra, interpret=interp))(x0))[: gm.nv]
            if not np.allclose(got, want, rtol=1e-4, atol=1e-6):
                print(f"# mx micro: {name} failed the exactness gate "
                      f"(maxdiff {np.abs(got - want).max():.3e}); row "
                      "skipped", file=sys.stderr, flush=True)
                return

            def run(n, st=st, ra=ra):
                def body(_, x):
                    acc = expand.apply_fused(x, st, ra, interpret=interp)
                    return acc[: gm.nv] * 1e-3

                return jax.lax.fori_loop(0, n, body, x0)

            elapsed, _ = fetch_timed(run)
            # floor at 0.1 us: the differencing can land at the timer's
            # resolution on tiny CPU runs, and a 0.0 row would read as
            # "unmeasured" downstream (every bench value is > 0)
            flavor_ms[name] = max(round(elapsed / iters * 1e3, 4), 1e-4)
            print(f"# mx micro {name}: {flavor_ms[name]} ms/iter",
                  file=sys.stderr, flush=True)
        winner = min(flavor_ms, key=flavor_ms.get)
        _emit_row({
            "metric": f"reduce_micro_mx_vs_group_rmat{ms}{suffix}",
            "value": flavor_ms[winner],
            "unit": "ms/iter",
            "winner": winner,
            "flavor_ms": flavor_ms,
            "ne": int(gm.ne),
        })
        if on_tpu:
            from lux_tpu.engine.methods import (REDUCE_MODE_KEY,
                                                record_overlay_entry)

            record_overlay_entry(REDUCE_MODE_KEY, winner)
            record_overlay_entry("tpu:micro_reduce",
                                 {"scale": ms, "ms_per_iter": flavor_ms,
                                  "winner": winner})

    def measure_scan_micro():
        """Standing MXU-vs-VPU segmented-SCAN micro row (ISSUE 11): the
        SAME tiny csc census through all three scan-family flavors —
        "scan" (the shipped VPU ``lax.associative_scan`` ladder),
        "mxsum" (prefix-diff blocked triangular matmul) and "mxscan"
        (the segmented scan itself as masked triangular MXU
        contractions, ops/pallas_scan) — so the ``tpu:sum`` scan-family
        default is measured, not assumed.  Exactness-gated: each flavor
        must match the NumPy f64 segment-sum oracle (atol scaled by the
        prefix-diff strategies' documented ne*eps cancellation bound)
        before its time counts.  On TPU the winner is banked under
        ``tpu:sum`` (consumed by engine/methods.sum_mode on the csc
        gather-apply paths); the row itself is emitted everywhere (CPU
        rows are real interpret-mode measurements, clearly suffixed
        like every other fallback family)."""
        import numpy as np

        from lux_tpu.ops import segment

        ms = _env_int("LUX_BENCH_SCAN_MICRO_SCALE", 12)
        gm = generate.rmat(ms, 8, seed=0)
        shm = build_pull_shards(gm, 1)
        rng = np.random.default_rng(0)
        e_pad = shm.arrays.src_pos.shape[1]
        vals_np = np.zeros(e_pad, np.float32)
        vals_np[: gm.ne] = rng.random(gm.ne).astype(np.float32)
        dst = gm.dst_of_edges()
        want = np.zeros(gm.nv, np.float64)
        np.add.at(want, dst, vals_np[: gm.ne].astype(np.float64))
        vals = jnp.asarray(vals_np)
        rp = jnp.asarray(shm.arrays.row_ptr[0])
        hf = jnp.asarray(shm.arrays.head_flag[0])
        dl = jnp.asarray(shm.arrays.dst_local[0])
        jax.block_until_ready((vals, rp, hf, dl))
        atol = max(1e-5, gm.ne * 6e-7)
        flavor_ms = {}
        for name in ("scan", "mxsum", "mxscan"):
            got = np.asarray(jax.jit(
                lambda v, name=name: segment.segment_sum_csc(
                    v, rp, hf, dl, method=name))(vals))
            if not np.allclose(got[: gm.nv], want, rtol=1e-3, atol=atol):
                print(f"# scan micro: {name} failed the exactness gate "
                      f"(maxdiff {np.abs(got[: gm.nv] - want).max():.3e})"
                      "; row skipped", file=sys.stderr, flush=True)
                return

            def run(n, name=name):
                def body(_, v):
                    acc = segment.segment_sum_csc(v, rp, hf, dl,
                                                  method=name)
                    return vals * (1.0 + acc[0] * 1e-9)

                return jax.lax.fori_loop(0, n, body, vals)

            elapsed, _ = fetch_timed(run)
            # same 0.1 us floor as the mx micro row: a 0.0 value would
            # read as "unmeasured" downstream
            flavor_ms[name] = max(round(elapsed / iters * 1e3, 4), 1e-4)
            print(f"# scan micro {name}: {flavor_ms[name]} ms/iter",
                  file=sys.stderr, flush=True)
        winner = min(flavor_ms, key=flavor_ms.get)
        _emit_row({
            "metric": f"scan_micro_mx_vs_vpu_rmat{ms}{suffix}",
            "value": flavor_ms[winner],
            "unit": "ms/iter",
            "winner": winner,
            "flavor_ms": flavor_ms,
            "ne": int(gm.ne),
        })
        if on_tpu:
            from lux_tpu.engine.methods import (record_overlay_entry,
                                                record_sum_family_winner)

            # never clobbers a measured blanket 'scatter' winner (this
            # row does not time scatter; the full race may)
            record_sum_family_winner(winner)
            record_overlay_entry("tpu:micro_scan",
                                 {"scale": ms, "ms_per_iter": flavor_ms,
                                  "winner": winner})

    def measure_merge_micro():
        """Standing TREE-vs-BULK cross-part merge micro row (ISSUE 17):
        the SAME small multi-part SSSP push run through both cross-part
        merge modes — "bulk" (concatenate-and-scatter, the serialized
        all-to-one dependence) and "tree" (the static asynchronous
        reduction tree of ops/merge_tree.py) — so the ``tpu:merge_mode``
        default is measured, not assumed.  Oracle-gated twice: each
        mode must land bitwise on the NumPy BFS hop oracle (the int-min
        monoid is associative+commutative+idempotent, so ANY merge
        order is exact — the luxmerge precision contract), and tree
        must equal bulk bitwise before either time counts.  On TPU the
        winner is banked under ``tpu:merge_mode`` (consumed by
        engine/push._resolve_merge); the row is emitted everywhere."""
        import numpy as np

        from lux_tpu.engine import push as push_eng
        from lux_tpu.graph.push_shards import build_push_shards
        from lux_tpu.models.sssp import SSSPProgram, bfs_reference

        ms = _env_int("LUX_BENCH_MERGE_MICRO_SCALE", 12)
        mparts = _env_int("LUX_BENCH_MERGE_MICRO_PARTS", 4)
        gm = generate.rmat(ms, 8, seed=0)
        shm = build_push_shards(gm, mparts)
        start = int(np.argmax(np.bincount(gm.col_idx, minlength=gm.nv)))
        progm = SSSPProgram(nv=gm.nv, start=start)
        want = bfs_reference(gm, start)
        mode_ms, dists = {}, {}
        for mode in ("bulk", "tree"):
            st, _, _ = push_eng.run_push(progm, shm, merge=mode)
            got = shm.scatter_to_global(np.asarray(st))
            dists[mode] = got
            # bfs_reference marks unreachable with nv; push with inf
            if not np.array_equal(
                    np.where(got >= progm.inf, gm.nv, got), want):
                print(f"# merge micro: {mode} failed the BFS oracle "
                      "gate; row skipped", file=sys.stderr, flush=True)
                return
            t_best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                st, _, _ = push_eng.run_push(progm, shm, merge=mode)
                jax.block_until_ready(st)
                t_best = min(t_best, time.perf_counter() - t0)
            mode_ms[mode] = max(round(t_best * 1e3, 4), 1e-4)
            print(f"# merge micro {mode}: {mode_ms[mode]} ms/run",
                  file=sys.stderr, flush=True)
        if not np.array_equal(dists["bulk"], dists["tree"]):
            print("# merge micro: tree != bulk bitwise (int monoid "
                  "contract violated); row skipped", file=sys.stderr,
                  flush=True)
            return
        winner = min(mode_ms, key=mode_ms.get)
        _emit_row({
            "metric": f"merge_micro_tree_vs_bulk_rmat{ms}{suffix}",
            "value": mode_ms[winner],
            "unit": "ms/run",
            "winner": winner,
            "mode_ms": mode_ms,
            "bitwise_equal": True,
            "parts": mparts,
            "ne": int(gm.ne),
        })
        if on_tpu:
            from lux_tpu.engine.methods import (MERGE_MODE_KEY,
                                                record_overlay_entry)

            record_overlay_entry(MERGE_MODE_KEY, winner)
            record_overlay_entry("tpu:micro_merge",
                                 {"scale": ms, "parts": mparts,
                                  "ms_per_run": mode_ms,
                                  "winner": winner})

    def measure_cf(m):
        """Fixed-iteration CF (K=20 latent state): edge-update GTEPS +
        per-iteration ms + final RMSE (the reference's CF quality metric,
        colfilter_gpu.cu:85-101 math)."""
        from lux_tpu.models.colfilter import CFProgram

        n_half = (1 << scale) // 2
        gw = generate.bipartite_ratings(
            n_half, n_half, (1 << scale) * ef // 2, seed=1
        )
        wshards = build_pull_shards(gw, 1)
        # gamma=1e-3: at the app-default 3.5e-7 ten iterations barely move
        # the state and the RMSE line cannot distinguish a working engine
        # from a no-op; 1e-3 converges on bipartite_ratings graphs (the
        # same setting every CF oracle test uses) so the tracked RMSE is
        # a real quality signal.  Perf (GTEPS/iter_ms) is gamma-invariant.
        from lux_tpu.models.colfilter import _resolve_err_dot

        # the banked tpu:cf_err_dot winner is the shipped config — the
        # bench row measures what the drivers actually run
        prog = CFProgram(gamma=1e-3, err_dot=_resolve_err_dot(None))
        arrays_w = jax.tree.map(jnp.asarray, wshards.arrays)
        s0 = pull.init_state(prog, arrays_w)

        def run(n):
            return pull.run_pull_fixed(
                prog, wshards.spec, arrays_w, s0, n, m
            )

        elapsed, out = fetch_timed(run)
        gteps = iters * gw.ne / elapsed / 1e9

        @jax.jit
        def rmse(state):
            full = state.reshape((wshards.spec.gathered_size,) + state.shape[2:])
            u = full[arrays_w.src_pos]  # (P, E, K)
            dstc = jnp.clip(arrays_w.dst_local, 0, state.shape[1] - 1)
            v = jnp.take_along_axis(
                state, dstc[..., None], axis=1
            )
            err = arrays_w.weights - jnp.sum(u * v, axis=-1)
            # padding edges carry weight 0 and garbage vectors: the shard
            # layout's own edge_mask excludes them (shard-correct at any P)
            return jnp.sqrt(
                jnp.sum(jnp.where(arrays_w.edge_mask, err * err, 0.0)) / gw.ne
            )

        rm = float(jax.device_get(rmse(out)))
        rm0 = float(jax.device_get(rmse(s0)))  # init-state RMSE: the
        # delta rm0-rm proves the engine moved the state, not just ran
        model = roofline.pull_iter_model(
            gw.ne, gw.nv, m, width=prog.k, weighted=True, needs_dst=True
        ).scale(iters)
        _emit_row(
            {
                "metric": f"colfilter_gteps_rmat{scale}_1chip{suffix}",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / BASELINE_GTEPS_PER_CHIP, 4),
                "method": m,
                # 6 decimals: toy-scale CPU runs measure sub-microsecond
                # per-iteration costs that a 3-decimal round floors to 0
                "iter_ms": round(elapsed / iters * 1e3, 6),
                "rmse": round(rm, 6),
                "rmse_init": round(rm0, 6),
                **roofline.summarize(model, elapsed, iters * gw.ne),
            }
        )

    def capture_pull_telemetry():
        """One NON-timed pagerank run on the race winner with the
        telemetry ring riding the fori carry: the per-iteration residual
        curve into the event log.  The timed race stays ring-free so the
        banked GTEPS are exactly the shipped hot loop's; this run costs
        one extra compile + ``iters`` iterations."""
        from lux_tpu.engine.methods import CONCRETE
        from lux_tpu.obs import ring as obs_ring

        concrete = {kv: t for kv, t in results.items() if kv[0] in CONCRETE}
        if not concrete:
            return
        m, dt = min(concrete, key=concrete.get)
        prog = PageRankProgram(nv=shards.spec.nv, dtype=dt)
        s0 = pull.init_state(prog, arrays)
        with obs.span("telemetry.capture", app="pagerank", method=m):
            out, rg = pull.run_pull_fixed(
                prog, shards.spec, arrays, s0, iters, m,
                route=_layout["route"],
                telemetry=obs_ring.new_ring("pull_fixed"))
            jax.block_until_ready(out)
            obs_ring.emit_ring("pull_fixed", rg, app="pagerank",
                               method=m, iters=iters)

    if "pagerank" in apps:
        for m in methods:
            try:
                measure(m, dtype)
            except Exception as e:  # noqa: BLE001 — a method may be unsupported
                print(f"# method {m} failed: {e}", file=sys.stderr, flush=True)
        if results:
            try:
                capture_pull_telemetry()
            except Exception as e:  # noqa: BLE001 — never costs a row
                print(f"# pull telemetry capture failed: {e}",
                      file=sys.stderr, flush=True)
        if results and on_tpu and dtype_env is None:
            # bf16 datapoint on the best method BEFORE the risky tail:
            # halved HBM gather + exchange traffic is the interesting
            # hardware number
            best_m = min(results.items(), key=lambda kv: kv[1])[0][0]
            try:
                measure(best_m, "bfloat16")
            except Exception as e:  # noqa: BLE001
                print(f"# bf16 variant failed: {e}", file=sys.stderr, flush=True)
        if results and on_tpu and rp_future is not None:
            # the routed hot loop (ops/expand.py; measured 49x the flat
            # gather at the load phase) joins the DEFAULT race so the
            # headline reflects the best shipped config — BOTH flavors:
            # the unfused _route line and the pass-fused _routepf line
            # (same coloring + a numpy transform; ops/expand.to_pf),
            # whose winner is recorded under "tpu:route_mode".  The
            # plans were building on background host threads for the
            # WHOLE unrouted race (rp_future, submitted before the
            # first measure) — by now they are usually done; wait only
            # when enough TPU budget remains to make the residual build
            # worth it.
            rp = None
            rp_pair = None
            saved_results = dict(results)
            routed_elapsed = {}
            try:
                from lux_tpu.engine.methods import CONCRETE

                concrete = {kv: t for kv, t in results.items()
                            if kv[0] in CONCRETE}
                tpu_budget = _env_int("LUX_BENCH_TPU_S", 600)
                spent = time.monotonic() - t_worker0
                if not concrete:
                    print("# routed line skipped: no concrete reduce "
                          "method measured", file=sys.stderr, flush=True)
                elif rp_future.ready() or spent < 0.5 * tpu_budget:
                    t_plan = time.time()
                    # budget-aware wait: a residual build may not eat
                    # past ~70% of the TPU window — on timeout the
                    # banked unrouted rows stand and the routed lines
                    # are skipped, never the whole worker
                    rp_pair = rp_future.result(
                        timeout=max(5.0, 0.7 * tpu_budget - spent))
                    print(f"# routed plans "
                          f"({'cache' if rp_state['warm'] else 'built, overlapped'}"
                          f"; waited {time.time() - t_plan:.1f}s) — "
                          f"measuring routed lines", file=sys.stderr,
                          flush=True)
                    best_m = min(concrete, key=concrete.get)[0]
                    for tag, host_plan in (("_route", rp_pair[0]),
                                           ("_routepf", rp_pair[1])):
                        if (tag == "_routepf" and time.monotonic()
                                - t_worker0 > 0.8 * tpu_budget):
                            print("# routed-pf line skipped: budget "
                                  "mostly spent", file=sys.stderr,
                                  flush=True)
                            break
                        rp = (host_plan[0],
                              jax.tree.map(jnp.asarray, host_plan[1]))
                        jax.block_until_ready(rp[1])
                        _layout["route"] = rp
                        _layout["route_tag"] = tag
                        measure(best_m, dtype)
                        routed_elapsed[tag] = results.get((best_m, dtype))
                        # free this flavor's device copy before the next
                        _layout["route"] = None
                        rp = None
                    host_plan = None  # last flavor's host copy
                    _record_route_mode(routed_elapsed)
                else:
                    print("# routed lines skipped: plan still building and "
                          "budget mostly spent", file=sys.stderr, flush=True)
            except (TimeoutError, _FUTURE_TIMEOUT):
                # 3.10: futures.TimeoutError is NOT the builtin alias yet
                print("# routed lines skipped: plan build exceeded the "
                      "budget-aware wait", file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001
                print(f"# routed line failed: {e}", file=sys.stderr,
                      flush=True)
            finally:
                _layout["route"] = None
                _layout["route_tag"] = ""
                del rp  # free the ~1 GB device-resident plan pre-scale-up
                # drop the HOST plan copies too: rp_pair holds BOTH
                # flavors' stacked pass arrays (~2 GB at scale 20) and
                # the scale-up + secondary apps still run after this.
                # A build still in flight cannot be cancelled (daemon
                # threads run on; later TPU rows are device-bound, so
                # the contention costs dispatch noise, not timed device
                # work) — but a COMPLETED build's copies free here.
                del rp_pair
                rp_future = None
                # the routed elapsed must not pollute the unrouted
                # results the winner recording and scale-up pick from
                results.clear()
                results.update(saved_results)
    # secondary apps run AFTER the headline race banks its lines (each is
    # emitted the moment it exists) and BEFORE the risky tail, so a tail
    # wedge cannot cost the multi-app signal
    if "colfilter" in apps:
        try:
            best_m = (
                min(results.items(), key=lambda kv: kv[1])[0][0]
                if results else None
            )
            from lux_tpu.engine.methods import CONCRETE

            cf_m = (
                best_m
                if best_m in CONCRETE
                else resolve_method("auto", "sum", platform)
            )
            measure_cf(cf_m)
        except Exception as e:  # noqa: BLE001
            print(f"# colfilter failed: {e}", file=sys.stderr, flush=True)
    if "sssp" in apps:
        try:
            measure_sssp()
        except Exception as e:  # noqa: BLE001
            print(f"# sssp failed: {e}", file=sys.stderr, flush=True)
    if "components" in apps:
        try:
            measure_components(resolve_method("auto", "max", platform))
        except Exception as e:  # noqa: BLE001
            print(f"# components failed: {e}", file=sys.stderr, flush=True)
    if "bfs" in apps:
        # spec-compiled workload rows (ISSUE 13).  bfs rides the
        # headline graph's push layout, so it runs under layout A/B too
        # (the dense rounds honor sort_seg/compact exactly like sssp).
        try:
            measure_bfs()
        except Exception as e:  # noqa: BLE001
            print(f"# bfs failed: {e}", file=sys.stderr, flush=True)
    layout_ab = (sort_seg or compact or route_gather or route_fused
                 or route_pf or route_fused_pf or route_fused_mx)
    if "serve" in apps:
        if layout_ab:
            print("# serve row skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        else:
            try:
                measure_serve()
            except Exception as e:  # noqa: BLE001
                print(f"# serve failed: {e}", file=sys.stderr, flush=True)
    if "labelprop" in apps:
        # spec-compiled wide-state dense-pull row (ISSUE 13); own small
        # graph on the default layout — skipped under layout A/B for
        # isolation, like serve
        if layout_ab:
            print("# labelprop row skipped: layout A/B run",
                  file=sys.stderr, flush=True)
        else:
            try:
                measure_labelprop()
            except Exception as e:  # noqa: BLE001
                print(f"# labelprop failed: {e}", file=sys.stderr,
                      flush=True)
    if "kcore" in apps:
        # OPT-IN (LUX_BENCH_APPS=...,kcore): the iterative peel compiles
        # one program per level — minutes of compile on purpose
        if layout_ab:
            print("# kcore row skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        else:
            try:
                measure_kcore()
            except Exception as e:  # noqa: BLE001
                print(f"# kcore failed: {e}", file=sys.stderr, flush=True)
    if "triangles" in apps:
        # OPT-IN: quadratic bitset state, small graph by design
        if layout_ab:
            print("# triangles row skipped: layout A/B run",
                  file=sys.stderr, flush=True)
        else:
            try:
                measure_triangles()
            except Exception as e:  # noqa: BLE001
                print(f"# triangles failed: {e}", file=sys.stderr,
                      flush=True)
    if "ba" in apps:
        # the standing heavy-tail row is itself a routed-pf measurement;
        # skip it under layout A/B runs (isolation, like serve) and when
        # the TPU budget is mostly spent (its graph gen + plan build are
        # host-side but the timed line still needs chip minutes)
        if layout_ab:
            print("# ba row skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        elif (on_tpu and time.monotonic() - t_worker0
                > 0.75 * _env_int("LUX_BENCH_TPU_S", 600)):
            print("# ba row skipped: budget mostly spent", file=sys.stderr,
                  flush=True)
        else:
            try:
                measure_ba()
            except Exception as e:  # noqa: BLE001
                print(f"# ba row failed: {e}", file=sys.stderr, flush=True)
    if "fleet" in apps:
        # opt-in multi-replica serving rows; same isolation rule as
        # serve (the fleet workers bind the default pull layout)
        if layout_ab:
            print("# fleet rows skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        else:
            try:
                measure_fleet()
            except Exception as e:  # noqa: BLE001
                print(f"# fleet failed: {e}", file=sys.stderr, flush=True)
    if "pod" in apps:
        # opt-in placement-tree weak-scaling rows (ISSUE 19): 1/2/4
        # REAL worker processes, snapshot over the wire; CPU loopback
        # by design like fleet (the pod layer is host coordination)
        if layout_ab:
            print("# pod rows skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        else:
            try:
                measure_pod()
            except Exception as e:  # noqa: BLE001
                print(f"# pod rows failed: {e}", file=sys.stderr,
                      flush=True)
    if "live" in apps:
        # the mutation-aware serving row (ISSUE 12): its own thread-mode
        # fleet on its own graph; same isolation rule as serve/fleet
        # (live workers bind the default pull layout) and the same
        # budget gate as ba/refresh (host-side, but minutes are minutes)
        if layout_ab:
            print("# live row skipped: layout A/B run", file=sys.stderr,
                  flush=True)
        elif (on_tpu and time.monotonic() - t_worker0
                > 0.75 * _env_int("LUX_BENCH_TPU_S", 600)):
            print("# live row skipped: budget mostly spent",
                  file=sys.stderr, flush=True)
        else:
            try:
                measure_live()
            except Exception as e:  # noqa: BLE001
                print(f"# live row failed: {e}", file=sys.stderr,
                      flush=True)
    if "refresh" in apps:
        # dynamic-graph refresh rows (ISSUE 10): own graph + 8-part
        # layout; jax.clear_caches() inside the cold legs recompiles
        # later families' programs, so this runs after the other
        # secondary apps and only the headline tail follows.  Same
        # isolation/budget gates as ba.
        if layout_ab:
            print("# refresh rows skipped: layout A/B run",
                  file=sys.stderr, flush=True)
        elif (on_tpu and time.monotonic() - t_worker0
                > 0.75 * _env_int("LUX_BENCH_TPU_S", 600)):
            print("# refresh rows skipped: budget mostly spent",
                  file=sys.stderr, flush=True)
        else:
            try:
                measure_refresh()
            except Exception as e:  # noqa: BLE001
                print(f"# refresh rows failed: {e}", file=sys.stderr,
                      flush=True)
    if "pagerank" in apps:
        # standing mxu-vs-vpu reduce micro row (tiny graph, both fused
        # flavors); skipped under layout A/B runs like serve/ba so the
        # isolation property of those runs holds
        if layout_ab:
            print("# mx micro row skipped: layout A/B run",
                  file=sys.stderr, flush=True)
        else:
            try:
                measure_mx_micro()
            except Exception as e:  # noqa: BLE001
                print(f"# mx micro row failed: {e}", file=sys.stderr,
                      flush=True)
            # standing scan-family micro row (ISSUE 11): scan vs mxsum
            # vs mxscan on one tiny csc census, winner banked under
            # tpu:sum on TPU (engine/methods.sum_mode consumes it)
            try:
                measure_scan_micro()
            except Exception as e:  # noqa: BLE001
                print(f"# scan micro row failed: {e}", file=sys.stderr,
                      flush=True)
            # standing tree-vs-bulk cross-part merge micro row (ISSUE
            # 17): oracle-gated SSSP race, winner banked under
            # tpu:merge_mode on TPU (engine/push._resolve_merge)
            try:
                measure_merge_micro()
            except Exception as e:  # noqa: BLE001
                print(f"# merge micro row failed: {e}", file=sys.stderr,
                      flush=True)
    if "pagerank" in apps and results and (
        on_tpu or os.environ.get("LUX_BENCH_FORCE_SCALEUP") == "1"
    ):
        # scale-up datapoint (VERDICT r3 weak #4: a small headline graph
        # risks a dispatch-dominated number): one more pagerank line at
        # scale+2 on the race winner, only while less than half the TPU
        # budget is spent, and BEFORE the risky tail (a scan wedge must
        # not cost it)
        tpu_budget = _env_int("LUX_BENCH_TPU_S", 600)
        if (route_gather or route_fused or route_pf or route_fused_pf
                or route_fused_mx):
            print("# scale-up skipped: routed-expand A/B plans exist only "
                  "for the headline graph", file=sys.stderr, flush=True)
        elif time.monotonic() - t_worker0 < 0.5 * tpu_budget:
            try:
                from lux_tpu.engine.methods import CONCRETE

                # run_pull_fixed needs a segment-reduce method; a pallas
                # race winner (separate runner) falls back to the best
                # concrete method, like the colfilter block does
                concrete = {
                    k: v for k, v in results.items() if k[0] in CONCRETE
                }
                if concrete:
                    m_up, dt_up = min(concrete, key=concrete.get)
                    measure_scaleup(m_up, dt_up)
            except Exception as e:  # noqa: BLE001
                print(f"# scale-up failed: {e}", file=sys.stderr, flush=True)
        else:
            print("# scale-up skipped: budget half-spent", file=sys.stderr,
                  flush=True)
    if "pagerank" in apps:
        for m in risky_tail:
            try:
                measure(m, dtype)
            except Exception as e:  # noqa: BLE001
                print(f"# method {m} failed: {e}", file=sys.stderr, flush=True)
        if not results:
            raise RuntimeError(f"all benchmark methods failed: {methods}")
        if on_tpu:
            _record_winner(results)


def _record_route_mode(routed_elapsed):
    """Persist the routed-vs-routed-pf winner ("tpu:route_mode" overlay
    entry) when the default race measured BOTH flavors — both are
    bitwise-identical to the direct gather, so the recorded mode is a
    pure perf decision the next process follows via
    engine.methods.route_mode()."""
    t_route = routed_elapsed.get("_route")
    t_pf = routed_elapsed.get("_routepf")
    if not t_route or not t_pf:
        return
    winner = "routed-pf" if t_pf <= t_route else "routed"
    from lux_tpu.engine.methods import ROUTE_MODE_KEY, record_overlay_entry

    record_overlay_entry(ROUTE_MODE_KEY, winner)


def _record_winner(results):
    """Persist the TPU race winner so `--method auto` follows the
    measurement from the NEXT process on (engine/methods reads
    .lux_winners.json) — an unattended chip window updates the default
    without a code edit.  Only the sum row: the race is PageRank; min/max
    rows change via the chip battery + PERF.md."""
    if (os.environ.get("LUX_BENCH_SORT_SEGMENTS") == "1"
            or os.environ.get("LUX_BENCH_COMPACT_GATHER") == "1"
            or os.environ.get("LUX_BENCH_ROUTE_GATHER") == "1"
            or os.environ.get("LUX_BENCH_ROUTE_FUSED") == "1"
            or os.environ.get("LUX_BENCH_ROUTE_PF") == "1"
            or os.environ.get("LUX_BENCH_ROUTE_FUSED_PF") == "1"
            or os.environ.get("LUX_BENCH_ROUTE_FUSED_MX") == "1"):
        # an A/B run under a non-default layout must not mutate the
        # default-layout winner (it would silently change every later
        # allgather run); the human folds A/B results in via PERF.md
        print("# layout A/B run: winner NOT recorded",
              file=sys.stderr, flush=True)
        return
    f32 = {m: t for (m, dt), t in results.items() if dt == "float32"}
    if not f32:
        return
    overall = min(f32, key=f32.get)
    # a recorded tpu:sum must hold on every engine path AND be
    # numerically verified.  scan/scatter are blanket-valid; the
    # scan-family strategies (mxsum/mxscan, ISSUE 11) are safe to
    # record — engine/methods.sum_mode follows them on the csc
    # gather-apply paths while the bucketed layouts downgrade to
    # 'scan' — but ONLY when this same machine's oracle-gated micro
    # race already verified them (the full-scale race times, it never
    # checks numerics; a banked winner must always be a verified one).
    # Anything else (pallas/cumsum/fused) is still reported for the
    # human + PERF.md instead of banked.
    from lux_tpu.engine import methods as _methods

    gated: set = set()
    try:
        with open(_methods.overlay_path()) as f:
            raw = json.load(f)
        micro = raw.get("tpu:micro_scan") or {}
        gated = (set(micro.get("ms_per_iter") or ())
                 | set(micro.get("ms_per_rep") or ()))
    except (OSError, ValueError, AttributeError):
        pass
    safe = {m: t for m, t in f32.items()
            if m in ("scan", "scatter")
            or (m in ("mxsum", "mxscan") and m in gated)}
    if not safe:
        return
    best = min(safe, key=safe.get)
    if overall != best:
        print(
            f"# NOTE: {overall} won the sum race outright but is not a "
            f"safe blanket default; recording {best} — consider a PERF.md "
            f"row + explicit --method {overall} for allgather runs",
            file=sys.stderr, flush=True,
        )
    from lux_tpu.engine.methods import record_overlay_entry

    record_overlay_entry("tpu:sum", best)


def _spawn_worker(env, out_path, nice=0):
    # stderr goes to a FILE, not our fd: an abandoned (stuck) worker must
    # not hold the orchestrator's stderr pipe open past our exit, or a
    # driver reading it to EOF hangs.  start_new_session keeps a group-kill
    # of the orchestrator from SIGKILLing a tunnel-claim-holder.
    out = open(out_path, "wb")
    try:
        err = open(out_path + ".err", "wb")
    except OSError:
        out.close()
        raise
    preexec = (lambda: os.nice(nice)) if nice else None
    try:
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            stdout=out,
            stderr=err,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            start_new_session=True,
            preexec_fn=preexec,
        )
    finally:
        # Popen dup'd both descriptors into the child; the parent's
        # copies would otherwise leak one fd pair per spawned worker
        out.close()
        err.close()


def _wait(proc, deadline):
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return True
        time.sleep(2)
    return proc.poll() is not None


def _relay_probe(assume):
    """One relay-liveness sample, honoring the LUX_BENCH_ASSUME_RELAY
    test hook ('up'/'down' pin the answer)."""
    if assume == "down":
        return False
    if assume == "up":
        return True
    return _relay_listening()


def _wait_tpu(proc, t_start, wait_full, down_grace, relay_up0, assume,
              probe_s=20.0):
    """Adaptive TPU-claim wait (VERDICT r5 weak #3 / next-round #1: the
    one-shot spawn-time relay gate sent a live chip day to the CPU
    insurance path).  While the relay's TCP endpoint accepts, wait out
    the FULL budgeted window; when it stops accepting, ride out only
    ``down_grace`` seconds from the last time it was seen alive — the
    timeout-of-last-resort that hands the run to the insurance worker.
    The probe re-samples every ``probe_s``, so a relay that comes up
    mid-wait EXTENDS the wait back to the full window instead of losing
    the chip day to a stale snapshot.  Returns True iff the worker
    exited before the adaptive deadline."""
    up = relay_up0
    last_up = time.monotonic() if up else t_start
    next_probe = time.monotonic() + probe_s
    while True:
        if proc.poll() is not None:
            return True
        now = time.monotonic()
        # probe BEFORE the deadline check: a relay that came alive since
        # the last sample must extend the deadline it is about to trip
        if now >= next_probe:
            was_up = up
            up = _relay_probe(assume)
            next_probe = now + probe_s
            if up:
                last_up = now
                if not was_up:
                    print(
                        "# relay came alive — extending TPU wait to the "
                        "full window",
                        file=sys.stderr, flush=True,
                    )
            elif was_up:
                print(
                    f"# relay stopped listening — TPU wait now capped "
                    f"{down_grace:.0f}s past last-alive",
                    file=sys.stderr, flush=True,
                )
        deadline = t_start + wait_full
        if not up:
            deadline = min(deadline, last_up + down_grace)
        if now >= deadline:
            return proc.poll() is not None
        time.sleep(min(2.0, probe_s))


def _relay(out_path) -> bool:
    """Forward the BEST of the worker's JSON lines PER APP FAMILY to
    stdout (and its stderr diagnostics to ours); True if any line was
    found.  The worker emits one line per measured (app, method, dtype)
    as soon as it exists, best-effort: even a worker that later wedged
    inside a risky method has its completed measurements harvested here.
    One line per family — the metric stem up to ``_rmat``, so the sssp
    ENGINE row (sssp_gteps) and the sssp SERVING row (sssp_qps) are
    distinct families whose values (GTEPS vs QPS) never contest each
    other — each the highest-value one; the pagerank HEADLINE prints
    LAST — the driver and the tests read the final stdout line."""
    try:
        with open(out_path + ".err", "rb") as f:
            sys.stderr.write(f.read().decode(errors="replace"))
            sys.stderr.flush()
    except OSError:
        pass
    best, extras = {}, []
    try:
        with open(out_path, "rb") as f:
            for line in f.read().decode(errors="replace").splitlines():
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if obj.get("scale_up"):
                    # pass-through datapoints (the rmat{scale+2} line):
                    # must neither hijack the headline nor be dropped by
                    # the best-per-family contest
                    extras.append(obj)
                    continue
                fam = str(obj.get("metric", "")).split("_rmat")[0]
                if fam not in best or obj.get("value", 0.0) > best[fam].get(
                    "value", 0.0
                ):
                    best[fam] = obj
    except OSError:
        pass
    if not best and not extras:
        return False
    for obj in extras:
        print(json.dumps(obj), flush=True)
    if not best:
        return True
    # fixed fallback priority (not max(): that picks the lexicographically
    # largest family — an arbitrary headline when pagerank is excluded)
    for fam in ("pagerank_gteps", "sssp_gteps", "components_gteps",
                "colfilter_gteps", "sssp_qps"):
        if fam in best:
            headline = fam
            break
    else:
        headline = max(best)  # unknown families only: deterministic pick
    for fam in sorted(best):
        if fam != headline:
            print(json.dumps(best[fam]), flush=True)
    print(json.dumps(best[headline]), flush=True)
    return True


def _relay_listening(port=None, timeout=3.0) -> bool:
    """TCP probe of the axon relay's remote_compile endpoint.  Refused =
    relay down: a jax client would burn ~55 min of C-level retries to
    learn the same thing (docs/NOTES_ROUND2.md tunnel diagnostics #5).
    The port is configurable (LUX_BENCH_RELAY_PORT) so an unrelated local
    service on 8083 can't fake a 'relay up' forever — move the probe."""
    import socket

    if port is None:
        port = _env_int("LUX_BENCH_RELAY_PORT", 8083)
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def _new_run_id():
    """Orchestrator-side run id: both workers inherit it via
    LUX_OBS_RUN_ID, so the TPU primary and the CPU insurance land in ONE
    flight-recorder timeline and every row they emit links back to it.
    The id format has exactly one owner — obs/recorder.new_run_id —
    loaded from its file so the orchestrator stays jax-free WITHOUT
    registering a package stub (workers forked from this process must
    still import the real lux_tpu)."""
    import importlib.util

    try:
        spec = importlib.util.spec_from_file_location(
            "_lux_obs_recorder",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lux_tpu", "obs", "recorder.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.new_run_id()
    except Exception:  # noqa: BLE001 — observability must never fail bench
        return f"{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}_0"


def main():
    budget = _env_int("LUX_BENCH_WATCHDOG_S", 900)
    if budget <= 0:  # 0 = unbounded (documented knob semantics)
        budget = 1 << 30
    # one run id for the whole bench invocation (chip_day exports its own
    # battery-wide id; standalone runs mint one here)
    os.environ.setdefault("LUX_OBS_RUN_ID", _new_run_id())
    t_start = time.monotonic()
    scale = _env_int("LUX_BENCH_SCALE", 20)
    tpu_wait = _env_int("LUX_BENCH_TPU_S", budget - 120)
    # relay gate: only meaningful when the primary actually targets the
    # tunnel — a pure-CPU run (tests, CI, dev hosts) has no relay and must
    # not have its wait shortened.  The gate is ADAPTIVE (_wait_tpu): the
    # spawn-time probe below only decides the initial posture and the
    # worker's exported budget; liveness is re-sampled throughout the
    # wait, so a relay that dies mid-claim stops burning budget and one
    # that comes alive re-extends to the full window (VERDICT r5: the
    # old one-shot cap sent a live chip day to the insurance path).
    gate_relay = os.environ.get("JAX_PLATFORMS", "") != "cpu"
    assume = os.environ.get("LUX_BENCH_ASSUME_RELAY")  # test hook
    relay_cap = _env_int("LUX_BENCH_RELAY_CAP_S", 240)
    # grace past last-seen-alive while the relay is down: the
    # timeout-of-last-resort, leaving insurance-wait headroom
    down_grace = max(0, min(tpu_wait, relay_cap, budget - 180))
    relay_up0 = True
    if gate_relay:
        relay_up0 = _relay_probe(assume)
        if not relay_up0:
            # still spawn the TPU worker (a warm AOT cache could dodge
            # remote_compile); the adaptive wait re-extends if the relay
            # comes up
            why = "assumed down (test hook)" if assume == "down" else "not listening"
            print(
                f"# relay 127.0.0.1:8083 {why} — TPU wait capped at "
                f"{down_grace}s, insurance favored (re-probed during the "
                "wait; a live relay re-extends)",
                file=sys.stderr,
                flush=True,
            )

    # unique per-run paths: an abandoned worker from a PREVIOUS run still
    # holds its old fd and may eventually write its (differently-configured)
    # JSON there — it must never be mistaken for this run's result
    tag = f"{os.getpid()}_{int(time.time())}"
    tpu_out = f"/tmp/lux_bench_tpu_worker_{tag}.json"
    # the TPU worker is the niced + staggered one: its CPU-bound phase
    # (graph gen) is not its timed region (device-bound), while the CPU
    # insurance's timed region IS CPU-bound and must not share the core
    env_primary = dict(os.environ)
    # export the FULL wait even when the relay looks down at spawn: the
    # worker's budget gates (routed line, scale-up) only execute once it
    # actually holds a device — which means the relay recovered and the
    # adaptive wait extended to the full window.  Exporting the capped
    # grace here would make a recovered chip day skip the routed
    # headline against a stale 240s budget (the r5 loss, worker-side).
    env_primary["LUX_BENCH_TPU_S"] = str(tpu_wait)
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        env_primary.setdefault("LUX_BENCH_PRIMARY_DELAY_S", "180")
    tpu_proc = _spawn_worker(env_primary, tpu_out, nice=10)

    # CPU insurance starts IMMEDIATELY (smaller graph): a stuck TPU worker
    # sleeps in device init, so the single host core is effectively free —
    # by the TPU deadline the fallback number is already banked rather
    # than just starting.  A 1-core CPU needs a smaller graph to finish
    # inside the budget at all.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("LUX_BENCH_FAKE_HANG", None)  # the hang hook and the stagger
    env.pop("LUX_BENCH_PRIMARY_DELAY_S", None)  # target the primary only
    env["LUX_BENCH_SCALE"] = os.environ.get(
        "LUX_BENCH_CPU_SCALE", str(min(scale, 18))
    )
    # strip the axon sitecustomize: when the relay is wedged it can hang
    # even CPU interpreters at startup
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    ) or os.path.dirname(os.path.abspath(__file__))
    cpu_out = f"/tmp/lux_bench_cpu_worker_{tag}.json"
    # no insurance needed when the primary is already CPU-targeted (it
    # would only contend for the single host core)
    cpu_proc = (
        None
        if os.environ.get("JAX_PLATFORMS", "") == "cpu"
        else _spawn_worker(env, cpu_out)
    )

    tpu_done = (
        _wait_tpu(tpu_proc, t_start, tpu_wait, down_grace, relay_up0, assume)
        if gate_relay
        else _wait(tpu_proc, t_start + tpu_wait)
    )
    if tpu_done and tpu_proc.returncode == 0 and _relay(tpu_out):
        if cpu_proc is not None:
            try:
                cpu_proc.kill()  # insurance unneeded; holds no tunnel claim
            except OSError:
                pass
        return

    if tpu_proc.poll() is None:
        # Do NOT kill it: a SIGKILLed claim-holder wedges the tunnel relay
        # for every later process (docs/NOTES_ROUND1.md).  Leave it running;
        # if the grant ever arrives it finishes and exits on its own.
        print(
            f"# TPU worker (pid {tpu_proc.pid}) still stuck after "
            f"{time.monotonic() - t_start:.0f}s; using CPU insurance result "
            "(worker left running, not killed)",
            file=sys.stderr,
            flush=True,
        )
        if _relay(tpu_out):
            # methods completed BEFORE the wedge are real chip numbers —
            # strictly better than any CPU insurance value
            if cpu_proc is not None:
                try:
                    cpu_proc.kill()
                except OSError:
                    pass
            return
    else:
        print(
            f"# TPU worker exited rc={tpu_proc.returncode}; "
            "harvesting any banked lines",
            file=sys.stderr,
            flush=True,
        )
        if _relay(tpu_out):  # partial results survive a late crash too
            if cpu_proc is not None:
                try:
                    cpu_proc.kill()
                except OSError:
                    pass
            return

    if cpu_proc is None:
        cpu_proc = _spawn_worker(env, cpu_out)  # primary WAS cpu and failed
    # leave ~60s of the budget for this parent's own bookkeeping
    if _wait(cpu_proc, t_start + budget - 60) and cpu_proc.returncode == 0 and _relay(cpu_out):
        return
    try:
        cpu_proc.kill()  # CPU worker holds no tunnel claim; safe to kill
    except OSError:
        pass
    if _relay(cpu_out):
        # banked partial lines ARE the result; appending the zero line
        # after them would put 0.0 in the headline (last-line) slot the
        # driver records
        return
    _emit(_zero(f"pagerank_gteps_rmat{scale}_all_workers_failed"))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker_main()
    else:
        main()
