"""Serving benchmark driver: warm batched throughput vs Q=1 sequential.

Measures the lux_tpu.serve path on one chip (or the CPU fallback) and
emits bench.py-parsable JSON lines:

  * ``<app>_qps_rmat<scale>_1chip[<suffix>]`` — warm batched QPS at the
    throughput bucket (value), with the warm Q=1 sequential QPS, the
    batched-vs-sequential speedup, end-to-end scheduler latency
    percentiles (p50/p95/p99 ms), batch occupancy, queue stats, and the
    warm-vs-cold engine hit ratio as extra fields.

The acceptance bar this driver tracks: warm Q=64 batched throughput
>= 5x warm Q=1 sequential throughput on rmat16 sssp (CPU fallback) —
the batching win of the trailing-query-axis engines
(lux_tpu/serve/batched.py) over request-at-a-time serving.

Usage:
  python tools/serve_bench.py [--rmat-scale 16] [--rmat-ef 16] [--q 64]
      [--app sssp|ppr] [--num-seq 8] [--reps 2] [--method auto]
      [--min-speedup 0] [--seed 0]

A nonzero --min-speedup turns the run into a gate: exit 1 when
batched/sequential falls below it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rmat-scale", type=int, default=16)
    ap.add_argument("--rmat-ef", type=int, default=16)
    ap.add_argument("--app", default="sssp", choices=["sssp", "ppr"])
    ap.add_argument("--q", type=int, default=64,
                    help="throughput bucket size")
    ap.add_argument("--num-seq", type=int, default=8,
                    help="queries in the warm Q=1 sequential baseline")
    ap.add_argument("--reps", type=int, default=2,
                    help="full Q-batches in the batched measurement")
    ap.add_argument("--num-parts", type=int, default=1)
    ap.add_argument("--method", default="auto")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-speedup", type=float, default=0.0,
                    help="exit 1 if batched/sequential < this (CI gate)")
    args = ap.parse_args(argv)

    import jax

    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.serve.benchmarks import measure_serving

    g = generate.rmat(args.rmat_scale, args.rmat_ef, seed=0)
    shards = build_pull_shards(g, args.num_parts)
    print(f"# serve_bench: nv={g.nv} ne={g.ne} app={args.app} q={args.q} "
          f"platform={jax.default_backend()}", file=sys.stderr, flush=True)
    res = measure_serving(
        g, shards, app=args.app, q=args.q, num_seq=args.num_seq,
        batched_reps=args.reps, method=args.method, seed=args.seed,
    )
    on_tpu = jax.default_backend() in ("tpu", "axon")
    suffix = "" if on_tpu else f"_{jax.default_backend()}_fallback"
    line = {
        "metric": f"{args.app}_qps_rmat{args.rmat_scale}_1chip{suffix}",
        "value": res["qps_batched"],
        "unit": "QPS",
        # baseline for the serving row IS request-at-a-time serving:
        # the batched/sequential ratio is the number that justifies the
        # subsystem
        "vs_baseline": res["batched_vs_q1"],
        **res,
    }
    print(json.dumps(line), flush=True)
    if args.min_speedup and res["batched_vs_q1"] < args.min_speedup:
        print(f"# FAIL: batched/sequential {res['batched_vs_q1']} < "
              f"{args.min_speedup}", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
