"""Big-graph-from-disk proof (VERDICT r3 #4).

Generates an rmat{scale}/ef{ef} `.lux` on disk (once; ~1.3 GB at
scale 24), then drives the FILE-BASED pipeline the reference exercises
with Twitter-2010 (partial per-part reads, core/pull_model.inl:253-320):

  1. streaming out-degree scan + `sharded_load.load_pull_shards` (all
     parts AND a parts_subset residency demo),
  2. per-exchange preflight estimates (with the k-resident scaling),
  3. PageRank on the 8-device virtual mesh via the ring and
     reduce_scatter exchanges (k = P/8 resident parts per device),
  4. SSSP (direction-optimized push, allgather exchange) to convergence,
  5. peak-RSS checkpoints after every phase vs the preflight estimates.

Run on the 1-core CPU host (no chip needed):

  env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/biggraph_check.py --scale 24 --parts 16

Results are recorded in docs/BIGGRAPH.md.
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time


def rss_gib() -> float:
    """Peak RSS of this process so far, GiB (ru_maxrss is KiB on Linux)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1 << 20)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=int, default=24)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--parts", type=int, default=16)
    ap.add_argument("--iters", type=int, default=2, help="pagerank iters")
    ap.add_argument("--file", default=None, help=".lux path (default /tmp)")
    ap.add_argument("--skip-sssp", action="store_true")
    ap.add_argument("--sssp-exchange", default="allgather",
                    choices=["allgather", "ring"],
                    help="dense-round exchange for the SSSP phase")
    ap.add_argument(
        "--bucket-cap-gib", type=float, default=40.0,
        help="skip a bucket exchange whose padded arrays would exceed this",
    )
    ap.add_argument(
        "--stream-hbm-gib", type=float, default=0.0,
        help="also run single-device host-offload streamed PageRank "
             "under this device-byte budget (must be below the edge "
             "arrays' total; engine/stream.py)",
    )
    args = ap.parse_args(argv)
    t_all = time.monotonic()

    def note(phase, **kw):
        print(json.dumps({"phase": phase, "rss_gib": round(rss_gib(), 2),
                          "t_s": round(time.monotonic() - t_all, 1), **kw}),
              flush=True)

    import numpy as np

    from lux_tpu.graph import format as fmt
    from lux_tpu.graph import generate, sharded_load

    path = args.file or f"/tmp/lux_rmat{args.scale}_ef{args.ef}.lux"
    if not os.path.exists(path):
        t0 = time.monotonic()
        g = generate.rmat(args.scale, args.ef, seed=0)
        note("generated", gen_s=round(time.monotonic() - t0, 1),
             nv=g.nv, ne=g.ne)
        t0 = time.monotonic()
        fmt.write_lux(path, g)
        note("written", write_s=round(time.monotonic() - t0, 1),
             file_gib=round(os.path.getsize(path) / (1 << 30), 3))
        del g
    else:
        note("reusing", file=path,
             file_gib=round(os.path.getsize(path) / (1 << 30), 3))

    P = args.parts
    header = fmt.read_lux(path, mmap=True)
    nv, ne = header.nv, header.ne

    # --- streaming degree scan (the pull_scan_task analog) ---
    t0 = time.monotonic()
    degrees = sharded_load.out_degrees_from_file(path, header=header)
    note("degree_scan", scan_s=round(time.monotonic() - t0, 1))

    # --- O(local edges) residency demo: load only 2 of P parts ---
    t0 = time.monotonic()
    sub = sharded_load.load_pull_shards(
        path, P, parts_subset=[0, 1], degrees=degrees
    )
    sub_bytes = sum(a.nbytes for a in sub.arrays)
    note("subset_load", parts=2, sub_gib=round(sub_bytes / (1 << 30), 3),
         load_s=round(time.monotonic() - t0, 1))
    del sub

    if args.stream_hbm_gib:
        # --- host-offload streaming: ONE device whose edge arrays exceed
        #     the configured HBM budget (the ZC-memory analog,
        #     core/lux_mapper.cc:146-165; engine/stream.py).  Runs BEFORE
        #     the P-part full load so the single-part copy + chunk copies
        #     never coexist with the monolithic arrays (peak-RSS honesty).
        import jax

        from lux_tpu.engine import pull as pull_eng
        from lux_tpu.engine import stream as stream_eng
        from lux_tpu.models.pagerank import PageRankProgram

        t0 = time.monotonic()
        p1 = sharded_load.load_pull_shards(path, 1, degrees=degrees)
        budget = int(args.stream_hbm_gib * (1 << 30))
        total_edge = stream_eng.edge_bytes_total(p1.spec)
        chunk_e = stream_eng.chunk_edges_for_budget(p1.spec, budget)
        resident = stream_eng.streamed_hbm_bytes(p1.spec, chunk_e)
        if not resident <= budget < total_edge:
            raise SystemExit(
                f"--stream-hbm-gib {args.stream_hbm_gib}: budget "
                f"({budget} B) must sit between the streamed footprint "
                f"({resident} B at chunk_e={chunk_e}) and the full edge "
                f"arrays ({total_edge} B) for the capacity proof to "
                f"mean anything — pick a smaller budget or bigger scale"
            )
        ssh = stream_eng.build_streamed_pull(p1, chunk_e)
        prog1 = PageRankProgram(nv=nv)
        state0 = pull_eng.init_state(
            prog1, jax.tree.map(np.asarray, p1.arrays))
        del p1  # chunks hold copies; drop the monolithic edge arrays
        note("stream_built", chunk_e=chunk_e,
             n_chunks=len(ssh.chunks[0]),
             resident_gib=round(resident / (1 << 30), 3),
             edge_total_gib=round(total_edge / (1 << 30), 3),
             build_s=round(time.monotonic() - t0, 1))
        # warm the compiles so the A/B times transfers, not tracing
        jax.block_until_ready(stream_eng.run_pull_fixed_streamed(
            prog1, ssh, state0, 1))
        times = {}
        for prefetch in (True, False):
            t0 = time.monotonic()
            out = stream_eng.run_pull_fixed_streamed(
                prog1, ssh, state0, args.iters, prefetch=prefetch)
            out = jax.device_get(out)
            times[prefetch] = time.monotonic() - t0
            note("stream_pagerank", prefetch=prefetch, iters=args.iters,
                 run_s=round(times[prefetch], 1),
                 gteps=round(args.iters * ne / times[prefetch] / 1e9, 4),
                 top_rank=float(np.max(out)))
        note("stream_overlap",
             speedup=round(times[False] / max(times[True], 1e-9), 3))
        del ssh, state0, out

    # --- full load from file (every part via partial range reads) ---
    t0 = time.monotonic()
    pull = sharded_load.load_pull_shards(path, P, degrees=degrees)
    full_bytes = sum(a.nbytes for a in pull.arrays)
    note("full_load", parts=P, full_gib=round(full_bytes / (1 << 30), 3),
         load_s=round(time.monotonic() - t0, 1),
         subset_frac=round(sub_bytes / full_bytes, 4))

    import jax

    from lux_tpu.engine import pull as pull_eng
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.parallel.mesh import make_mesh_for_parts
    from lux_tpu.utils import preflight

    mesh = make_mesh_for_parts(P)
    k = P // mesh.devices.size
    prog = PageRankProgram(nv=nv)
    note("mesh", devices=int(mesh.devices.size), k_resident=k)

    # --- ring + scatter exchanges (bucket builds reuse the pull build) ---
    from lux_tpu.parallel.ring import bucket_counts

    counts = bucket_counts(header, pull.cuts, P)
    B_est = int(counts.max())
    bucket_gib = P * P * B_est * 13 / (1 << 30)
    note("bucket_geometry", max_bucket=B_est,
         pad_inflation=round(P * P * B_est / max(ne, 1), 2),
         bucket_gib=round(bucket_gib, 2))

    # both bucket exchanges run the SAME P (k = P/8 resident parts per
    # device) and share the streamed pull build
    for kind in ("ring", "scatter"):
        if bucket_gib > args.bucket_cap_gib:
            note(f"{kind}_skipped", reason="bucket padding exceeds cap",
                 bucket_gib=round(bucket_gib, 2))
            continue
        t0 = time.monotonic()
        if kind == "ring":
            from lux_tpu.parallel.ring import (
                build_ring_shards, run_pull_fixed_ring,
            )

            sh = build_ring_shards(header, P, pull=pull, counts=counts)
            est = preflight.estimate_ring(sh.spec, sh.e_bucket_pad)
        else:
            from lux_tpu.parallel.scatter import (
                build_scatter_shards, run_pull_fixed_scatter,
            )

            sh = build_scatter_shards(header, P, pull=pull, counts=counts)
            est = preflight.estimate_scatter(sh.spec, sh.e_bucket_pad)
        est = preflight.scale_residency(est, k)
        note(f"{kind}_built", parts=P, k_resident=k,
             build_s=round(time.monotonic() - t0, 1),
             preflight_gib=round(est.total_bytes / (1 << 30), 3))
        t0 = time.monotonic()
        state0 = pull_eng.init_state(prog, jax.tree.map(np.asarray, pull.arrays))
        run = run_pull_fixed_ring if kind == "ring" else run_pull_fixed_scatter
        out = run(prog, sh, state0, args.iters, mesh)
        out = jax.device_get(out)
        dt = time.monotonic() - t0
        top = float(np.max(out))
        note(f"pagerank_{kind}", iters=args.iters,
             run_s=round(dt, 1),
             gteps=round(args.iters * ne / dt / 1e9, 4), top_rank=top)
        del sh, out, state0

    if not args.skip_sssp:
        from lux_tpu.models.sssp import inf_value, sssp

        t0 = time.monotonic()
        if args.sssp_exchange == "ring":
            from lux_tpu.parallel.ring import build_push_ring_shards

            psh = build_push_ring_shards(header, P)
            pest = preflight.estimate_push_ring(
                psh.spec, psh.pspec, psh.e_bucket_pad
            )
        else:
            from lux_tpu.graph.push_shards import build_push_shards

            psh = build_push_shards(header, P)
            pest = preflight.estimate_push(psh.spec, psh.pspec)
        pest = preflight.scale_residency(pest, k)
        note("push_built", exchange=args.sssp_exchange,
             build_s=round(time.monotonic() - t0, 1),
             preflight_gib=round(pest.total_bytes / (1 << 30), 3))
        start = int(np.argmax(degrees))
        t0 = time.monotonic()
        dist = sssp(psh, start=start, mesh=mesh,
                    exchange=args.sssp_exchange)
        dt = time.monotonic() - t0
        reached = int((np.asarray(dist) < inf_value(nv)).sum())
        note(f"sssp_{args.sssp_exchange}", start=start, reached=reached,
             run_s=round(dt, 1))

    note("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
