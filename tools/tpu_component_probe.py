#!/usr/bin/env python
"""Honest per-component timing of the pull hot loop on the real chip.

The pull iteration is gather(state by src) -> segmented-reduce(by dst) ->
apply.  This probe times each component in isolation with fetch-based
timing (device->host transfer of a scalar derived from the result — the
only timing the axon tunnel cannot fake; see tools/tpu_timing_probe.py),
so we learn WHICH primitive is slow on TPU instead of guessing:

  gather      vals = state[src_pos]                (HLO gather)
  scan        segmented associative_scan reduce    (log-depth, vectorized)
  scatter     jax.ops.segment_sum                  (HLO scatter)
  pallas      spmv_blockcsr one-hot MXU kernel     (Mosaic)
  pallas+g    gather feeding the pallas kernel     (the full comp phase)

Each row reports ms per repetition from a linear fit over rep counts
(intercept absorbs the constant tunnel latency).  Numerics of the Mosaic
kernel are checked against the scatter result first.

Usage: python tools/tpu_component_probe.py [--scale 20] [--ef 16]
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fit(xs, ys):
    """Least-squares slope/intercept."""
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    den = sum((x - mx) ** 2 for x in xs)
    return num / den, my - (num / den) * mx


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--reps", type=int, nargs="+", default=[1, 4, 16])
    ap.add_argument("--skip", nargs="*", default=[],
                    help="component names to skip")
    ap.add_argument("--sort-segments", action="store_true",
                    help="apply the gather-locality relayout (sort edges "
                         "within each dst segment by gather index) before "
                         "probing — measures the docs/PERF.md "
                         "gather-amplification lever")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lux_tpu.graph import generate
    from lux_tpu.ops import pallas_spmv as ps
    from lux_tpu.ops import segment

    print(f"# platform={jax.devices()[0].platform}", flush=True)
    g = generate.rmat(args.scale, args.ef, seed=0)
    print(f"# nv={g.nv} ne={g.ne}", flush=True)

    rng = np.random.default_rng(0)
    state = jnp.asarray(rng.random(g.nv, np.float32))
    col = np.asarray(g.col_idx)
    if args.sort_segments:
        # dst sequence is the lexsort's primary key, so only the gather
        # indices move (graph/shards.sort_segments_inplace semantics)
        col = col[np.lexsort((col, g.dst_of_edges()))]
        print("# layout: sort-segments (gather-locality)", flush=True)
    src_pos = jnp.asarray(col.astype(np.int32))
    row_ptr = jnp.asarray(g.row_ptr.astype(np.int32))
    head = np.zeros(g.ne, np.int32)
    head[g.row_ptr[:-1][g.row_ptr[:-1] < g.ne]] = 1
    head_flag = jnp.asarray(head.astype(bool))
    dst_local = jnp.asarray(g.dst_of_edges().astype(np.int32))
    vals_fixed = jnp.asarray(rng.random(g.ne, np.float32))

    bc = ps.build_blockcsr(g)
    bc_dst = jnp.asarray(bc.e_dst_rel)
    bc_cb = jnp.asarray(bc.chunk_block)
    bc_cf = jnp.asarray(bc.chunk_first)
    bc_src = jnp.asarray(bc.e_src_pos)
    bc_vals = jnp.asarray(rng.random(bc.e_src_pos.shape, np.float32))
    jax.block_until_ready((state, src_pos, row_ptr, head_flag, dst_local,
                           vals_fixed, bc_dst, bc_cb, bc_cf, bc_src, bc_vals))

    # rep-loop: x_{k+1} = f(x_k)-style chaining so XLA cannot collapse reps.
    # n is TRACED (dynamic trip count) — one compile per component total;
    # over the tunnel each compile costs minutes, so this matters more than
    # the marginally better static-loop codegen.
    def chain(f, seed_like):
        @jax.jit
        def run(x0, n):
            def body(_, x):
                return f(x)
            return jax.lax.fori_loop(0, n, body, x0)
        return run

    # each component maps a state-shaped (nv,) vector to another one
    def c_gather(x):
        # fold the gathered edge vector back to (nv,) with a lane-dim sum —
        # consumes every gathered element (nothing for XLA to DCE) but is
        # bandwidth-trivial next to the ne random reads
        return x[src_pos].reshape(g.nv, args.ef).sum(axis=1) * 1e-3

    # compact-gather A/B (graph/shards.build_compact_mirror semantics,
    # whole graph as one part): sorted unique sources + per-edge remap —
    # the two-stage load_kernel staging vs the direct random gather
    uniq = np.unique(col)
    mirror_pos = jnp.asarray(uniq.astype(np.int32))
    mirror_rel = jnp.asarray(
        np.searchsorted(uniq, col).astype(np.int32))
    jax.block_until_ready((mirror_pos, mirror_rel))
    print(f"# compact mirror: U={len(uniq)} ({len(uniq)/g.nv:.2f} of nv)",
          flush=True)

    def c_gather_c(x):
        mirror = x[mirror_pos]
        return mirror[mirror_rel].reshape(g.nv, args.ef).sum(axis=1) * 1e-3

    def c_scan(x):
        vals = vals_fixed * x[0]
        acc = segment.segment_sum_csc(vals, row_ptr, head_flag, dst_local,
                                      method="scan")
        return acc * 0.999

    def c_scatter(x):
        vals = vals_fixed * x[0]
        acc = segment.segment_sum_csc(vals, row_ptr, head_flag, dst_local,
                                      method="scatter")
        return acc * 0.999

    def c_cumsum(x):
        vals = vals_fixed * x[0]
        acc = segment.segment_sum_csc(vals, row_ptr, head_flag, dst_local,
                                      method="cumsum")
        return acc * 0.999

    def c_mxsum(x):
        vals = vals_fixed * x[0]
        acc = segment.segment_sum_csc(vals, row_ptr, head_flag, dst_local,
                                      method="mxsum")
        return acc * 0.999

    npad = bc.num_vblocks * bc.v_blk

    def c_pallas(x):
        vals = bc_vals * x[0]
        acc = ps.spmv_blockcsr(vals, bc_dst, bc_cb, bc_cf, op="sum",
                               v_blk=bc.v_blk, num_vblocks=bc.num_vblocks)
        return acc[: g.nv] * 0.999

    def c_pallas_g(x):
        xp = jnp.pad(x, (0, max(0, npad - g.nv)))
        vals = xp[bc_src]
        acc = ps.spmv_blockcsr(vals, bc_dst, bc_cb, bc_cf, op="sum",
                               v_blk=bc.v_blk, num_vblocks=bc.num_vblocks)
        return acc[: g.nv] * 0.999

    # numerics first: pallas vs scatter on identical inputs
    if "pallas" not in args.skip:
        ref = segment.segment_sum_csc(
            state[src_pos], row_ptr, head_flag, dst_local, method="scan")
        got = ps.spmv_blockcsr(
            state[jnp.asarray(bc.e_src_pos)], bc_dst, bc_cb, bc_cf,
            op="sum", v_blk=bc.v_blk, num_vblocks=bc.num_vblocks)[: g.nv]
        err = float(jnp.max(jnp.abs(ref - got)))
        print(f"# pallas-vs-scan max abs err: {err:.3e}", flush=True)

    # scan LAST: the one chip-session hang so far happened inside a
    # scan-method program (tools/tpu_timing_probe.py --method scan wedged
    # the server side for 30+ min); keep the safe components' data banked
    # before risking it
    comps = {
        "gather": c_gather,
        "gather_c": c_gather_c,
        "scatter": c_scatter,
        "cumsum": c_cumsum,
        "mxsum": c_mxsum,
        "pallas": c_pallas,
        "pallas+g": c_pallas_g,
        "scan": c_scan,
    }
    for name, f in comps.items():
        if name in args.skip:
            continue
        try:
            run = chain(f, state)
            for n in args.reps:  # one compile (n is traced); warm the path
                float(jax.device_get(run(state, n).ravel()[0]))
            xs, ts = [], []
            for n in args.reps:
                t0 = time.perf_counter()
                float(jax.device_get(run(state, n).ravel()[0]))
                ts.append(time.perf_counter() - t0)
                xs.append(n)
            slope, icpt = _fit(xs, ts)
            gteps = g.ne / slope / 1e9 if slope > 0 else float("nan")
            print(
                f"{name:9s} {slope*1e3:10.3f} ms/rep  ({gteps:8.2f} GTEPS-equiv)"
                f"  [intercept {icpt*1e3:.1f} ms; raw "
                + " ".join(f"{n}:{t*1e3:.1f}" for n, t in zip(xs, ts)) + "]",
                flush=True,
            )
        except Exception as e:  # noqa: BLE001 — print and keep probing
            print(f"{name:9s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
