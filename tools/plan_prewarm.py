#!/usr/bin/env python
"""Routed-plan cache prewarmer — build the Benes expand/fused plans for a
benchmark configuration BEFORE a chip window opens, so chip-day never
pays plan construction inside a TPU budget (VERDICT r5 #6; chip_day.sh
invokes this ahead of the relay gate because it needs only host cores).

The plans are written to the same per-part/per-bucket disk cache
(ops/expand, /tmp/lux_expand_plans_<uid> by default) that bench.py and
the apps read, keyed on the exact shard layout bytes — so this MUST use
the same generator seed/layout as the target run (bench.py: rmat(scale,
ef, seed=0), build_pull_shards(g, 1), default layout).

Examples:
    python tools/plan_prewarm.py --scale 20 --ef 16            # expand+fused
    python tools/plan_prewarm.py --scale 18 --kinds expand     # one family
    python tools/plan_prewarm.py --scale 20 --check-only       # warm?

Prints one JSON line: per-kind cold/warm build seconds, thread counts,
and whether each cache was already warm.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# host-only tool: never let the planner's jax import touch the tunnel
# (the axon sitecustomize registers the TPU plugin at interpreter start,
# so the env var must be overridden AND the live config forced)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="prewarm routed-plan disk caches for a bench config"
    )
    ap.add_argument("--scale", type=int, default=20, help="RMAT scale")
    ap.add_argument("--ef", type=int, default=16, help="edge factor")
    ap.add_argument("--parts", type=int, default=1,
                    help="pull-shard part count (bench.py uses 1)")
    ap.add_argument("--kinds",
                    default="expand,expand-pf,fused,fused-pf,fused-mx",
                    help="comma list from {expand,expand-pf,fused,"
                         "fused-pf,fused-mx,cf,cf-pf} — the -pf families "
                         "are the "
                         "pass-fused twins (derived from the unfused "
                         "entries by the numpy transform, so warming "
                         "them after the base family costs seconds)")
    ap.add_argument("--reduce", default="sum",
                    help="fused-plan reduce op (joins the cache tag)")
    ap.add_argument("--threads", type=int, default=0,
                    help="override LUX_ROUTE_THREADS/LUX_PLAN_THREADS "
                         "(0 = leave env/cpu_count defaults)")
    ap.add_argument("--cache-dir", default=None,
                    help="override the plan cache dir (default per-user tmp)")
    ap.add_argument("--check-only", action="store_true",
                    help="report cache warmth without building")
    args = ap.parse_args(argv)

    if args.threads > 0:
        os.environ["LUX_ROUTE_THREADS"] = str(args.threads)
        os.environ["LUX_PLAN_THREADS"] = str(args.threads)

    from lux_tpu import native
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.ops import expand

    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    bad = set(kinds) - {"expand", "expand-pf", "fused", "fused-pf",
                        "fused-mx", "cf", "cf-pf"}
    if bad:
        ap.error(f"unknown plan kinds: {sorted(bad)}")

    t0 = time.time()
    g = generate.rmat(args.scale, args.ef, seed=0)
    shards = build_pull_shards(g, args.parts)
    gen_s = time.time() - t0

    out = {
        "scale": args.scale, "ef": args.ef, "parts": args.parts,
        "graph_build_seconds": round(gen_s, 1),
        "route_threads": native.route_threads(),
        "plan_threads": expand._plan_threads(),
        "kinds": {},
    }
    if args.check_only:
        probes = {
            "expand": lambda: expand.has_cached_expand_plan(
                shards, cache_dir=args.cache_dir),
            "expand-pf": lambda: expand.has_cached_expand_plan(
                shards, cache_dir=args.cache_dir, pf=True),
            "fused": lambda: expand.has_cached_fused_plan(
                shards, args.reduce, cache_dir=args.cache_dir),
            "fused-pf": lambda: expand.has_cached_fused_plan(
                shards, args.reduce, cache_dir=args.cache_dir, pf=True),
            "fused-mx": lambda: expand.has_cached_fused_plan(
                shards, args.reduce, cache_dir=args.cache_dir, mx=True),
            "cf": lambda: expand.has_cached_cf_plan(
                shards, cache_dir=args.cache_dir),
            "cf-pf": lambda: expand.has_cached_cf_plan(
                shards, cache_dir=args.cache_dir, pf=True),
        }
        for kind in kinds:
            out["kinds"][kind] = {"warm": probes[kind]() is not None}
        print(json.dumps(out), flush=True)
        return 0

    builders = {
        "expand": lambda: expand.plan_expand_shards_cached(
            shards, cache_dir=args.cache_dir),
        "expand-pf": lambda: expand.plan_expand_shards_cached(
            shards, cache_dir=args.cache_dir, pf=True),
        "fused": lambda: expand.plan_fused_shards_cached(
            shards, args.reduce, cache_dir=args.cache_dir),
        "fused-pf": lambda: expand.plan_fused_shards_cached(
            shards, args.reduce, cache_dir=args.cache_dir, pf=True),
        "fused-mx": lambda: expand.plan_fused_shards_cached(
            shards, args.reduce, cache_dir=args.cache_dir, mx=True),
        "cf": lambda: expand.plan_cf_route_shards_cached(
            shards, cache_dir=args.cache_dir),
        "cf-pf": lambda: expand.plan_cf_route_shards_cached(
            shards, cache_dir=args.cache_dir, pf=True),
    }
    for kind in kinds:
        expand.reset_plan_stats()
        t0 = time.time()
        static, arrays = builders[kind]()
        wall = time.time() - t0
        st = expand.plan_stats_snapshot()
        out["kinds"][kind] = {
            "wall_seconds": round(wall, 1),
            "cold_seconds": round(st["cold_s"], 1),
            "warm_seconds": round(st["warm_s"], 1),
            "entries_built": st["built"],
            "entries_loaded": st["loaded"],
            "plan_bytes": int(sum(a.nbytes for a in arrays)),
        }
        print(f"# {kind}: {wall:.1f}s wall "
              f"({st['built']} built / {st['loaded']} loaded)",
              file=sys.stderr, flush=True)
        del static, arrays
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
