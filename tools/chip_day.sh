#!/bin/bash
# Full chip-session measurement battery, in dependency order, each step
# logged separately and continuing on failure.  Run when the axon tunnel
# is up (a quick probe gate aborts early if it is not).  See
# docs/NOTES_ROUND2.md "First things when a chip IS reachable".
#
# Usage: bash tools/chip_day.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/lux_chip_day_$(date +%H%M)}
mkdir -p "$LOG"
echo "logs -> $LOG"

# luxtrace flight recorder: ONE run id for the whole battery — every
# step (and every python worker, via the recorder's env contract) lands
# in the same event-log timeline, so even a window that dies at step 0c
# leaves a complete post-mortem.  window_report.md is written by the
# EXIT trap below on EVERY exit path, abort and timeout included.
export LUX_OBS_RUN_ID=${LUX_OBS_RUN_ID:-$(date +%Y%m%d_%H%M%S)_$$_chipday}
echo "luxtrace run id: $LUX_OBS_RUN_ID"
PREWARM_PID=""
STEP_PID=""
BATTERY_STATUS=aborted

on_exit() {
  local rc=$?
  [ -n "$PREWARM_PID" ] && kill "$PREWARM_PID" 2>/dev/null
  python tools/obs_span.py point battery.exit "rc=$rc" \
      "status=$BATTERY_STATUS" 2>/dev/null
  # the post-mortem artifact: rendered from whatever events made it to
  # disk — an aborted window still gets its waterfall + OPEN spans
  timeout 120 python tools/luxview.py "$LUX_OBS_RUN_ID" \
      --out "$LOG/window_report.md" 2>> "$LOG/luxview.err" \
    && echo "window report -> $LOG/window_report.md"
  printf '{"ts": %s, "tool": "chip_day", "run_id": "%s", "status": "%s", "rc": %s, "log": "%s"}\n' \
      "$(date +%s)" "$LUX_OBS_RUN_ID" "$BATTERY_STATUS" "$rc" "$LOG" \
      >> PROGRESS.jsonl 2>/dev/null
}
trap on_exit EXIT

on_signal() {
  # a mid-step kill (ctrl-C, driver SIGTERM, session timeout) must
  # still reach on_exit: bash defers traps behind a FOREGROUND child
  # and does not run EXIT traps at all for an uncaught fatal signal —
  # so every step runs backgrounded behind an interruptible `wait`
  # (fg_to), the in-flight child is killed here, and the explicit exit
  # fires the EXIT trap that renders window_report.md
  BATTERY_STATUS=killed
  [ -n "$STEP_PID" ] && kill "$STEP_PID" 2>/dev/null
  exit 143
}
trap on_signal INT TERM HUP

fg_to() {  # interruptible foreground step: fg_to <timeout_s> <cmd...>
  timeout "$1" "${@:2}" &
  STEP_PID=$!
  wait "$STEP_PID"
  local rc=$?
  STEP_PID=""
  return $rc
}

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) timeout ${to}s"
  local sid
  sid=$(python tools/obs_span.py begin "step.$name" "timeout_s=$to" \
        2>/dev/null)
  fg_to "$to" "$@" > "$LOG/$name.out" 2> "$LOG/$name.err"
  local rc=$?
  [ -n "$sid" ] && python tools/obs_span.py end "$sid" --rc $rc 2>/dev/null
  echo "    rc=$rc; tail:"; tail -3 "$LOG/$name.out" | sed 's/^/    /'
  return $rc
}

# -3) static preflight: luxcheck over the shipped surface.  Runs BEFORE
#     anything else and ABORTS the window on any finding: the checkers
#     encode exactly the bug classes that waste chip budget (a retrace
#     in the hot loop, a planner-thread race, a nondeterministic
#     ordering poisoning a bitwise A/B) — a finding is cheaper to fix
#     now than to debug mid-window.  No jax import, so this gate costs
#     milliseconds even when the tunnel is wedged.  Suppress only WITH
#     a justification (docs/ANALYSIS.md).
echo "=== luxcheck preflight ($(date +%H:%M:%S))"
SID=$(python tools/obs_span.py begin step.luxcheck 2>/dev/null)
if ! fg_to 120 python tools/luxcheck.py --all \
    > "$LOG/luxcheck.out" 2>&1; then
  [ -n "$SID" ] && python tools/obs_span.py end "$SID" --rc 1 2>/dev/null
  tail -15 "$LOG/luxcheck.out" | sed 's/^/    /'
  echo "luxcheck findings (full list: $LOG/luxcheck.out) — aborting battery"
  exit 1
fi
[ -n "$SID" ] && python tools/obs_span.py end "$SID" 2>/dev/null
echo "luxcheck: clean"

# -3b) IR preflight: luxaudit traces/lowers the REAL engine entry
#      points on CPU and audits the jaxpr/StableHLO — retrace stability
#      (LUX-J1), donation aliases (LUX-J2), collective order under
#      cond/while predicates (LUX-J3), pass-fused VMEM residency
#      (LUX-J4), hbm_passes-vs-kernels accounting (LUX-J5).  ABORTS the
#      window on any finding: a dropped donation or a silently-unfused
#      pf group costs real HBM/compile budget on every iteration of the
#      battery; no tunnel needed, so this runs before the relay gate.
#      The AUDIT json is the round's machine-readable preflight record.
#      PYTHONPATH pinned to the repo root (tests/conftest.forced_cpu_env
#      contract): the axon sitecustomize registers the TPU plugin at
#      interpreter start and would HANG this no-tunnel-needed gate when
#      the relay is wedged.
echo "=== luxaudit preflight ($(date +%H:%M:%S))"
SID=$(python tools/obs_span.py begin step.luxaudit 2>/dev/null)
if ! fg_to 600 env PYTHONPATH="$PWD" python tools/luxaudit.py --all \
    --json "$LOG/AUDIT.json" \
    --progress PROGRESS.jsonl > "$LOG/luxaudit.out" 2>&1; then
  [ -n "$SID" ] && python tools/obs_span.py end "$SID" --rc 1 2>/dev/null
  tail -15 "$LOG/luxaudit.out" | sed 's/^/    /'
  echo "luxaudit findings (full list: $LOG/luxaudit.out) — aborting battery"
  exit 1
fi
[ -n "$SID" ] && python tools/obs_span.py end "$SID" 2>/dev/null
tail -1 "$LOG/luxaudit.out"

# -3c) protocol preflight: luxproto checks the distributed protocols
#      (election fencing, two-phase publish, generation line, journal
#      crash-atomicity) to exhaustion and requires the broken twins to
#      still fail.  ABORTS on any finding: a protocol counterexample
#      means the fleet half of the battery (failover/soak steps) would
#      burn its budget reproducing a bug the model already has the
#      shortest trace for — and that trace EXPORTS as the FaultPlan
#      reproduction (tools/luxproto.py --export <protocol>).  Jax-free
#      like -3, so this costs under a second even tunnel-wedged.
echo "=== luxproto preflight ($(date +%H:%M:%S))"
SID=$(python tools/obs_span.py begin step.luxproto 2>/dev/null)
if ! fg_to 120 python tools/luxproto.py --all --twins \
    > "$LOG/luxproto.out" 2>&1; then
  [ -n "$SID" ] && python tools/obs_span.py end "$SID" --rc 1 2>/dev/null
  tail -15 "$LOG/luxproto.out" | sed 's/^/    /'
  echo "luxproto findings (full list: $LOG/luxproto.out) — aborting battery"
  exit 1
fi
[ -n "$SID" ] && python tools/obs_span.py end "$SID" 2>/dev/null
tail -1 "$LOG/luxproto.out"

# -3d) guard preflight: the LUX-G/LUX-R twins (known-bad snippets that
#      MUST fire — a clean twin means the guarded-by/resource checkers
#      rotted while step -3 kept passing) plus the baseline staleness
#      tripwire for both suppression files.  The families' real sweep
#      already ran inside step -3's luxcheck --all; this pins the
#      checkers themselves.  Jax-free, sub-second.
echo "=== luxguard preflight ($(date +%H:%M:%S))"
SID=$(python tools/obs_span.py begin step.luxguard 2>/dev/null)
if ! { fg_to 120 python tools/luxcheck.py --twins && \
       fg_to 120 python tools/luxcheck.py --check-baselines; } \
    > "$LOG/luxguard.out" 2>&1; then
  [ -n "$SID" ] && python tools/obs_span.py end "$SID" --rc 1 2>/dev/null
  tail -15 "$LOG/luxguard.out" | sed 's/^/    /'
  echo "luxguard twins/baselines failed (full list: $LOG/luxguard.out) — aborting battery"
  exit 1
fi
[ -n "$SID" ] && python tools/obs_span.py end "$SID" 2>/dev/null
tail -1 "$LOG/luxguard.out"

# -2) routed-plan prewarm in the BACKGROUND (host cores only, no chip
#     needed): builds/refreshes the headline-scale expand+fused plan
#     caches so no battery step pays plan construction inside a TPU
#     budget.  Backgrounded so an ALREADY-OPEN window banks the
#     plan-free micro rows (steps 0/0b) immediately instead of idling
#     behind up to ~2h of cold host planning; the first plan-consuming
#     step (0c) waits on it below.  Warm rerun: seconds.
#     nice -n 19: steps 0/0b bank timed micro rows concurrently — the
#     prewarm must not inflate them (bench nices competing workers too)
echo "=== plan_prewarm (background, $(date +%H:%M:%S))"
PREWARM_SID=$(python tools/obs_span.py begin step.plan_prewarm 2>/dev/null)
nice -n 19 timeout 7200 python tools/plan_prewarm.py \
    --scale "${LUX_PREWARM_SCALE:-20}" --ef 16 \
    --kinds expand,expand-pf,fused,fused-pf \
    > "$LOG/plan_prewarm.out" 2> "$LOG/plan_prewarm.err" &
# abort paths (relay gate, dead-tunnel gate) must not orphan 2h of
# all-core host work; on_exit kills the pid while it is nonempty — the
# success path empties it after step 0c's wait
PREWARM_PID=$!

# -1) fast relay gate: the axon remote_compile endpoint is a local HTTP
#     server (127.0.0.1:8083).  Connection-refused = relay down — a plain
#     TCP connect detects that in milliseconds, where a jax probe burns
#     its whole timeout in C-level claim retries (observed: 59 min).
if ! timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
  python tools/obs_span.py point battery.abort reason=relay_down 2>/dev/null
  echo "relay down (127.0.0.1:8083 refused) — aborting battery"; exit 1
fi
echo "relay gate: 8083 accepts"

# 0) chip-window insurance (VERDICT r4 #8): sub-minute scan-vs-mxsum
#    micro race at scale 17 — one tiny compile per method, mxsum banked
#    before scan is risked, result auto-recorded to the winners overlay
#    ("tpu:micro_sum").  Doubles as the tunnel gate: a live tunnel
#    produces the mxsum row in minutes where the old scale-20 probe
#    gate could burn 90 min of a 7-min window.
#    Also races the gather halves (direct vs compact mirror) — the
#    roofline's dominant unknown, banked at micro scale.
#    Round-5 addition: "route" (Benes lane-shuffle expand) and "fused"
#    (routed expand + group reduce) race the same window — the measured
#    design bet of the round.  Round-6 addition: "routepf"/"fusedpf",
#    the PASS-FUSED variants (2-3 passes per kernel, VMEM-resident
#    intermediates) — the fused-vs-unfused A/B banked right after each
#    unfused row so even a short window records the pass-fusion bet.
#    Order: mxsum banks the reduce baseline, gather the flat baseline,
#    then route/routepf/fused/fusedpf; scan stays last.
#    Round-7 addition (ISSUE 7): "fusedmx" — the MXREDUCE in-kernel
#    MXU reduction — races right after fusedpf (their pair banks
#    tpu:reduce_mode), and the "cfdotvpu"/"cfdotmxu" pair races the CF
#    error-dot as VPU lane-sum vs a true MXU matmul tile (banks
#    tpu:cf_err_dot).  All exactness-gated against their oracles.
#    Round-8 addition (ISSUE 11): "mxscan" — the blocked MXU segmented
#    scan (ops/pallas_scan) — completes the three-way scan-family race;
#    scan+mxsum+mxscan together bank tpu:sum (the sum_mode winner the
#    csc engines follow).  mxscan runs second-to-last (new Pallas
#    kernel); scan stays last (the observed tunnel-wedger).
run micro_race 3600 python tools/tpu_micro_race.py \
    --methods mxsum gather route routepf fused fusedpf fusedmx \
              cfdotvpu cfdotmxu gatherc mxscan scan \
    --outdir "$LOG/micro"
grep -q '"ms_per_rep"' "$LOG/micro_race.out" || {
  python tools/obs_span.py point battery.abort reason=tunnel_dead 2>/dev/null
  echo "tunnel dead (no micro rows) — aborting battery"; exit 1; }

# 0b) uint8 vs int32 pass indices (LUX_ROUTE_IDX8): the 4x index-traffic
#     lever; a Mosaic rejection of u8 gather operands shows up here, not
#     mid-battery
LUX_ROUTE_IDX8=0 run micro_route_i32 900 python tools/tpu_micro_race.py \
    --methods route --outdir "$LOG/micro_i32"

# 0c) routed end-to-end pagerank at headline scale: the round's headline
#     bet, banked before the long component probes.  First plan-consuming
#     step — wait for the background prewarm (no-op when already warm).
#     Round 6: the PASS-FUSED rows run FIRST (the round's bet — pf plans
#     derive from the same cached coloring, so prewarm covers them), then
#     the unfused twins for the end-to-end fused-vs-unfused A/B the
#     winners overlay folds in (_record_route_mode runs in the default
#     race of step 1; these explicit rows are the per-flavor artifacts).
echo "waiting for plan_prewarm (pid $PREWARM_PID)"; wait "$PREWARM_PID" || true
[ -n "$PREWARM_SID" ] && python tools/obs_span.py end "$PREWARM_SID" 2>/dev/null
PREWARM_PID=""
tail -1 "$LOG/plan_prewarm.out" 2>/dev/null | sed 's/^/    prewarm: /'
LUX_BENCH_WATCHDOG_S=1500 LUX_BENCH_TPU_S=1300 \
  LUX_BENCH_ROUTE_PF=1 LUX_BENCH_APPS=pagerank \
  LUX_BENCH_METHOD=mxsum LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_routepf 1600 python bench.py
LUX_BENCH_WATCHDOG_S=1500 LUX_BENCH_TPU_S=1300 \
  LUX_BENCH_ROUTE_FUSED_PF=1 LUX_BENCH_APPS=pagerank \
  LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_routefusedpf 1600 python bench.py
LUX_BENCH_WATCHDOG_S=1500 LUX_BENCH_TPU_S=1300 \
  LUX_BENCH_ROUTE_FUSED_MX=1 LUX_BENCH_APPS=pagerank \
  LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_routefusedmx 1600 python bench.py
LUX_BENCH_WATCHDOG_S=1500 LUX_BENCH_TPU_S=1300 \
  LUX_BENCH_ROUTE_FUSED=1 LUX_BENCH_APPS=pagerank \
  LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_routefused 1600 python bench.py
LUX_BENCH_WATCHDOG_S=1500 LUX_BENCH_TPU_S=1300 \
  LUX_BENCH_ROUTE_GATHER=1 LUX_BENCH_APPS=pagerank \
  LUX_BENCH_METHOD=mxsum LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_route 1600 python bench.py

# 1) the driver-format bench race FIRST after the gate (VERDICT r3 #1:
#    the no-suffix TPU datapoint is the top ask — a short window must
#    bank it before the long Pallas sweep).  scatter/cumsum/mxsum/pallas
#    + bf16 + the scale-up line; scan quarantined last; partial results
#    harvested either way.
#    LUX_PEAK_GBPS: the tunnel hides the chip model; 819 GB/s (v5e-class
#    spec) makes frac_bw_roof a lower-bound honesty figure — rescale
#    against docs/PERF.md's roofline table if the chip is bigger.
LUX_BENCH_WATCHDOG_S=3600 LUX_BENCH_TPU_S=3300 \
  LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_race 3700 python bench.py

# 2) per-component timing at headline scale (the old gate, now after
#    the short-window essentials are banked)
run probe_components 5400 python tools/tpu_component_probe.py \
    --scale 20 --ef 16 --reps 1 4 16

# 2a) Mosaic compile check + tile sweep (VERDICT r1 #3)
run pallas_sweep 5400 python tools/tpu_pallas_check.py --scale 18 --sweep

# 2b) gather-locality A/B: the same component battery on the
#     sort-segments relayout — the roofline's gather-amplification lever
#     (docs/PERF.md); compare the gather/spmv rows against step 0
run probe_sortseg 3600 python tools/tpu_component_probe.py \
    --scale 20 --ef 16 --reps 1 4 16 --sort-segments

# 2c) compact-gather A/B (VERDICT r4 #3): the unique-in-source mirror
#     vs the direct gather, same method both sides (scatter completes
#     reliably on-chip; probe rows gather vs gather_c give the
#     component-level answer, this gives the end-to-end one).  Pagerank
#     only: the other bench apps ignore the compact env and would just
#     re-measure default-layout numbers on A/B time.
LUX_BENCH_WATCHDOG_S=1100 LUX_BENCH_TPU_S=900 \
  LUX_BENCH_COMPACT_GATHER=1 LUX_BENCH_APPS=pagerank \
  LUX_BENCH_METHOD=${LUX_COMPACT_AB_METHOD:-scatter} \
  LUX_PEAK_GBPS=${LUX_PEAK_GBPS:-819} \
  run bench_compact 1200 python bench.py

# 2d) multi-part compact A/B: P=16 vmapped on the one chip — each
#     part's unique in-neighborhood is far below nv, so this is the
#     configuration where the mirror SHOULD win most (the bench A/B at
#     P=1 understates it); compare the two ELAPSED TIME lines
run app_p16_direct 1500 python -m lux_tpu.apps.pagerank \
    --rmat-scale 20 -ng 16 -ni 10
run app_p16_compact 1500 python -m lux_tpu.apps.pagerank \
    --rmat-scale 20 -ng 16 -ni 10 --compact-gather

# 3) single-chip HBM ceiling vs preflight (VERDICT r1 #7)
run scale_check 5400 python tools/tpu_scale_check.py --min-scale 18 --max-scale 24

# 4) four-app table
run bench_all 4500 python tools/bench_all.py --scale 18 --iters 10 --routed

# 5) host-offload streaming on the real chip (capacity feature: edge
#    arrays exceed the budget, streamed through HBM in chunks; the
#    host->device link through the tunnel is the unknown being measured
#    — kept last and small: scale 20 with a budget forcing ~4 chunks)
run stream_check 2400 python tools/biggraph_check.py --scale 20 \
    --parts 8 --iters 2 --skip-sssp --stream-hbm-gib 0.15

BATTERY_STATUS=done
echo "battery done ($(date +%H:%M:%S)); fold results into BASELINE.md"
