#!/usr/bin/env python
"""Generate a synthetic graph as a `.lux` file (RMAT / uniform / bipartite
ratings).  The reference points at externally-hosted datasets
(README.md:77-86) that a sealed environment cannot fetch; this tool makes
workload-shaped substitutes.

    python tools/gen_graph.py rmat --scale 20 --ef 16 -o rmat20.lux
    python tools/gen_graph.py ratings --users 500000 --items 18000 \
        --ratings 2000000 -o netflixish.lux
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="kind", required=True)
    r = sub.add_parser("rmat")
    r.add_argument("--scale", type=int, required=True)
    r.add_argument("--ef", type=int, default=16)
    r.add_argument("--weighted", action="store_true")
    u = sub.add_parser("uniform")
    u.add_argument("--nv", type=int, required=True)
    u.add_argument("--ne", type=int, required=True)
    u.add_argument("--weighted", action="store_true")
    b = sub.add_parser("ratings")
    b.add_argument("--users", type=int, required=True)
    b.add_argument("--items", type=int, required=True)
    b.add_argument("--ratings", type=int, required=True)
    for p in (r, u, b):
        p.add_argument("-o", "--output", required=True)
        p.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from lux_tpu.graph import generate
    from lux_tpu.graph.format import write_lux

    if args.kind == "rmat":
        g = generate.rmat(args.scale, args.ef, seed=args.seed,
                          weighted=args.weighted)
    elif args.kind == "uniform":
        g = generate.uniform_random(args.nv, args.ne, seed=args.seed,
                                    weighted=args.weighted)
    else:
        g = generate.bipartite_ratings(args.users, args.items, args.ratings,
                                       seed=args.seed)
    write_lux(args.output, g)
    print(f"wrote {args.output}: nv={g.nv} ne={g.ne}"
          + (" (weighted)" if g.weighted else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
