#!/usr/bin/env python
"""Single-chip scale test: find the HBM ceiling and validate preflight.

Runs PageRank on growing RMAT graphs (2^22 .. 2^26 edges by default) on
the real chip, comparing `utils.preflight.estimate_pull` against the
device's actual `memory_stats()`, and exercising buffer donation at
scale (VERDICT r1 #7; reference dataset-scale table README.md:77-86).

Each size runs in a SUBPROCESS so an OOM kills the child, not the
harness; the parent records the last size that fit.  Results go to
stdout as a markdown table for BASELINE.md.

Usage (on TPU):  python tools/tpu_scale_check.py [--max-scale 23]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def child(scale: int, ef: int, iters: int, method: str) -> int:
    import time

    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.utils import preflight

    g = generate.rmat(scale, ef, seed=0)
    shards = build_pull_shards(g, 1)
    est = preflight.estimate_pull(shards.spec)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    state0 = pull.init_state(prog, arrays)

    # method default is scatter, NOT scan: the one observed chip hang was
    # a scan-method program, and memory numbers are this tool's point.
    # Timing ends in a scalar fetch (block_until_ready lies through the
    # tunnel); 1-vs-N slope removes the constant dispatch+fetch latency.
    def timed(n):
        t0 = time.perf_counter()
        out = pull.run_pull_fixed(prog, shards.spec, arrays, state0, n, method)
        float(jax.device_get(out.ravel()[0]))
        return time.perf_counter() - t0, out

    timed(1)  # compile + warm both programs
    timed(iters)
    t1, _ = timed(1)
    tn, out = timed(iters)
    per_iter = max((tn - t1) / max(iters - 1, 1), 1e-9)
    dt = per_iter * iters
    stats = jax.devices()[0].memory_stats() or {}
    from lux_tpu.utils import roofline

    model = roofline.pull_iter_model(g.ne, g.nv, method).scale(iters)
    print(
        json.dumps(
            {
                "scale": scale,
                "ne": g.ne,
                "est_bytes": est.total_bytes,
                "peak_bytes": stats.get("peak_bytes_in_use", 0),
                "limit_bytes": stats.get("bytes_limit", 0),
                "gteps": iters * g.ne / dt / 1e9,
                # flat achieved_GBps across scales = bandwidth-bound;
                # rising with scale = the small sizes were
                # dispatch-dominated (docs/PERF.md roofline)
                **roofline.summarize(model, dt, iters * g.ne),
            }
        ),
        flush=True,
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-scale", type=int, default=18)
    ap.add_argument("--max-scale", type=int, default=23)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--method", default="scatter")
    ap.add_argument("--child-scale", type=int, default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.child_scale is not None:
        return child(args.child_scale, args.ef, args.iters, args.method)

    rows = []
    for scale in range(args.min_scale, args.max_scale + 1):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--child-scale", str(scale), "--ef", str(args.ef),
             "--iters", str(args.iters), "--method", args.method],
            capture_output=True, text=True, timeout=3600,
        )
        line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
        if r.returncode != 0 or not line:
            print(f"# scale {scale}: FAILED (rc={r.returncode}) — "
                  f"{r.stderr.strip().splitlines()[-1] if r.stderr.strip() else 'no output'}",
                  flush=True)
            break
        rows.append(json.loads(line[0]))
        d = rows[-1]
        print(f"# scale {scale}: est {d['est_bytes']/2**30:.2f} GiB, "
              f"peak {d['peak_bytes']/2**30:.2f} GiB, "
              f"{d['gteps']:.3f} GTEPS", flush=True)

    print("\n| scale | ne | preflight est | device peak | GTEPS | GB/s |")
    print("|---|---|---|---|---|---|")
    for d in rows:
        print(f"| 2^{d['scale']} | {d['ne']:,} | "
              f"{d['est_bytes']/2**30:.2f} GiB | "
              f"{d['peak_bytes']/2**30:.2f} GiB | {d['gteps']:.3f} | "
              f"{d.get('achieved_GBps', 0):.2f} |")
    print("# flat GB/s across scales = bandwidth-bound; rising = small "
          "sizes dispatch-dominated (docs/PERF.md roofline)", flush=True)
    # raw rows for the chip-day artifact
    for d in rows:
        print(json.dumps(d), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
