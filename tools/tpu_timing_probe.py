#!/usr/bin/env python
"""Measure whether device timing is trustworthy on this backend.

On a healthy PJRT backend, `block_until_ready()` returns only after the
computation has finished, so elapsed wall time scales linearly with the
iteration count.  Through the axon tunnel we observed the opposite (100
fori_loop iterations "finishing" faster than 10), i.e. readiness is acked
before execution.  A device->host transfer of the RESULT cannot lie: the
bytes exist only after the computation ran.  This probe times
run(N iters) + 4-byte fetch for several N and prints the per-iteration
slope — the honest number — next to the naive block_until_ready time.

Usage: python tools/tpu_timing_probe.py [--scale 20] [--ef 16] [--method scatter]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--method", default="scatter")
    ap.add_argument("--iters", type=int, nargs="+", default=[10, 50, 100, 200])
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    print(f"# platform={jax.devices()[0].platform}", flush=True)
    g = generate.rmat(args.scale, args.ef, seed=0)
    if args.method == "pallas":
        from lux_tpu.models.pagerank import make_pallas_runner

        prun, ps0 = make_pallas_runner(g, dtype="float32", dynamic_iters=True)

        def run(n):
            return prun(ps0, n)
    else:
        shards = build_pull_shards(g, 1)
        arrays = jax.tree.map(jnp.asarray, shards.arrays)
        jax.block_until_ready(arrays)
        prog = PageRankProgram(nv=shards.spec.nv, dtype="float32")
        s0 = pull.init_state(prog, arrays)

        def run(n):
            return pull.run_pull_fixed(
                prog, shards.spec, arrays, s0, n, args.method
            )

    # warm-compile every N first so the timed region is execute-only
    for n in args.iters:
        np.asarray(jax.device_get(run(n).ravel()[0]))

    rows = []
    for n in args.iters:
        t0 = time.perf_counter()
        out = run(n)
        out.block_until_ready()
        t_block = time.perf_counter() - t0
        t0 = time.perf_counter()
        out2 = run(n)
        val = float(jax.device_get(out2.ravel()[0]))  # 4-byte fetch: can't lie
        t_fetch = time.perf_counter() - t0
        rows.append((n, t_block, t_fetch, val))
        print(
            f"iters={n:5d}  block_until_ready={t_block*1e3:9.3f} ms"
            f"  fetch={t_fetch*1e3:9.3f} ms  out[0,0]={val:.3e}",
            flush=True,
        )

    if len(rows) >= 2:
        (n0, _, f0, _), (n1, _, f1, _) = rows[0], rows[-1]
        per_iter = (f1 - f0) / (n1 - n0)
        gteps = g.ne / per_iter / 1e9 if per_iter > 0 else float("nan")
        print(
            f"# slope: {per_iter*1e3:.3f} ms/iter -> {gteps:.2f} GTEPS "
            f"(ne={g.ne}); fetch-intercept ~{f0 - n0*per_iter:.4f}s",
            flush=True,
        )


if __name__ == "__main__":
    main()
