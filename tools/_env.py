"""Jax-free env-knob parsing for the orchestrator-side scripts.

Mirrors the ``lux_tpu.utils.config.env_int`` contract (error NAMES the
variable; luxcheck LUX-P002) for processes that must never import
lux_tpu — the package __init__ pulls in jax, and bench.py's watchdog /
the tpu tools' parents have to stay healthy when the jax install or the
device tunnel is wedged.  Package code uses the canonical helper; this
is its only sanctioned twin (keep the two in sync).

Import from a script (repo root OR tools/ as cwd):

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _env import env_int
"""
from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: Optional[int] = None, *,
            minimum: Optional[int] = None) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}") from None
    if minimum is not None and val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    return val
