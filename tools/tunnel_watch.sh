#!/bin/bash
# Poll the axon relay's remote_compile endpoint (127.0.0.1:8083) and launch
# the chip-day battery once it accepts connections.  One battery per watch;
# cheap TCP connects only (no jax, no claim) while waiting.
# Usage: bash tools/tunnel_watch.sh [max_wait_s] [logdir]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-36000}
LOG=${2:-/tmp/lux_chip_day_watch}
t0=$(date +%s)
while :; do
  if timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    echo "$(date +%H:%M:%S) relay up — settling 60s then launching battery"
    sleep 60
    # re-check: a flapping relay should not trigger a battery
    if timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
      bash tools/chip_day.sh "$LOG"
      exit $?
    fi
    echo "$(date +%H:%M:%S) relay flapped back down; resuming watch"
  fi
  [ $(( $(date +%s) - t0 )) -ge "$MAX" ] && { echo "watch expired"; exit 1; }
  sleep 300
done
