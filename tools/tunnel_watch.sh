#!/bin/bash
# Poll the axon relay's remote_compile endpoint (127.0.0.1:8083) and launch
# the chip-day battery each time it accepts connections.  The watch
# RE-ARMS after every battery (round-2 evidence: windows can last ~7 min
# and flap — one battery attempt per round would waste later windows);
# per-window logdirs keep partial artifacts separate.  Cheap TCP connects
# only (no jax, no claim) while waiting.
# Usage: bash tools/tunnel_watch.sh [max_wait_s] [logdir]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-36000}
LOG=${2:-/tmp/lux_chip_day_watch}
t0=$(date +%s)
n=0
while :; do
  if timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    echo "$(date +%H:%M:%S) relay up — settling 60s then launching battery"
    sleep 60
    # re-check: a flapping relay should not trigger a battery
    if timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
      n=$((n + 1))
      bash tools/chip_day.sh "${LOG}_w${n}"
      echo "$(date +%H:%M:%S) battery #${n} done (rc=$?); re-arming watch"
      # quiesce before re-probing: the battery's last client must release
      # its claim, and a dying relay needs time to settle
      sleep 600
    else
      echo "$(date +%H:%M:%S) relay flapped back down; resuming watch"
    fi
  fi
  [ $(( $(date +%s) - t0 )) -ge "$MAX" ] && { echo "watch expired ($n batteries ran)"; exit $(( n == 0 )); }
  sleep 300
done
